"""Live weight rollout: verified hot-swap + canary auto-rollback.

The online training->serving pipe, in two halves:

* **engine side** — :class:`CheckpointWatcher` polls a watch directory
  for published checkpoint prefixes (``<version>.model.npz`` +
  ``<version>.manifest.json``).  Every candidate runs
  :func:`~bigdl_tpu.utils.serializer.verify_checkpoint` *before* any
  serving state is touched: a torn, truncated, bit-flipped or
  sha-mismatched publish is counted
  (``bigdl_rollout_rejected_total{reason}``), event-stamped and never
  loaded.  A verified checkpoint is loaded off the decode path and
  handed to ``LMEngine.swap_weights`` — one device_put + pointer flip
  between decode steps, so page tables, slots and in-flight decodes
  survive the swap (int8 twins are re-quantized as part of the same
  swap; the jitted step that closed over the old scales is rebuilt);
* **router side** — :class:`CanaryController` promotes a new version to
  a configurable fraction of replicas and watches two signals: the
  ``serve_latency_slo_burn`` alert and a token-level output-divergence
  probe (the canary replays pinned prompts at temperature 0; the
  mismatch fraction vs the incumbent is published as
  ``bigdl_rollout_canary_divergence``).  Both signals go through the
  autoscaler's hysteresis idiom — consecutive-breach streaks gated by
  ``for_count``, a cooldown after every rollback — so one noisy window
  can neither roll back a good version nor flap promote/rollback.
  Rollback drains each canary first (the drain/handoff machinery
  replays its in-flight requests elsewhere, version-pinned), so a
  rollback drops no requests.

The controller is deliberately I/O-free: it drives injected callables
(``set_version`` / ``drain`` / ``undrain`` / ``alerts`` /
``measure_divergence``) and an injectable clock, so the same object
runs against live :class:`~bigdl_tpu.serving.Router` replicas behind
HTTP and against the serving simulator's virtual clock in the
promote/rollback chaos scenario.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.rollout")

#: the router-tier alert the canary watches next to divergence
SLO_BURN_ALERT = "serve_latency_slo_burn"


# ----------------------------------------------------------------- helpers
def manifest_digest(path_prefix: str) -> Optional[str]:
    """Short sha256 of the checkpoint's manifest file.  The manifest
    already records size + sha256 of every file in the pair, so its own
    digest pins the *entire* checkpoint; the engine exposes it from
    ``/healthz`` so skew triage can tell two same-named publishes
    apart."""
    p = path_prefix + ".manifest.json"
    if not os.path.exists(p):
        return None
    h = hashlib.sha256()
    with open(p, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()[:12]


def _reason_class(reason: str) -> str:
    """Collapse verify_checkpoint's free-form reason strings into the
    bounded label set ``bigdl_rollout_rejected_total`` carries."""
    r = (reason or "").lower()
    if "checksum" in r:
        return "checksum"
    if "size" in r:
        return "size"
    if "missing" in r:
        return "missing"
    if "interrupted" in r or "leftover" in r:
        return "torn"
    return "unreadable"


def token_divergence(reference: Sequence[int],
                     candidate: Sequence[int]) -> float:
    """Fraction of mismatched tokens between two decodes of the same
    pinned prompt (position-wise; a length difference counts every
    missing position as a mismatch).  0.0 = bit-equal, 1.0 = nothing
    agrees."""
    a = [int(t) for t in reference]
    b = [int(t) for t in candidate]
    n = max(len(a), len(b))
    if n == 0:
        return 0.0
    bad = sum(1 for x, y in zip(a, b) if x != y) + abs(len(a) - len(b))
    return bad / float(n)


def divergence_probe(canary_generate: Callable[[List[int], int],
                                               Sequence[int]],
                     incumbent_generate: Callable[[List[int], int],
                                                  Sequence[int]],
                     prompts: Sequence[Sequence[int]],
                     max_new_tokens: int) -> Callable[[], float]:
    """Build the canary's ``measure_divergence`` callable: replay every
    pinned prompt at temperature 0 through both versions and return the
    WORST per-prompt :func:`token_divergence` (max, not mean — one
    badly divergent prompt is a real regression even if the rest
    agree)."""
    pinned = [[int(t) for t in p] for p in prompts]
    n = int(max_new_tokens)

    def measure() -> float:
        worst = 0.0
        for p in pinned:
            ref = incumbent_generate(list(p), n)
            got = canary_generate(list(p), n)
            worst = max(worst, token_divergence(ref, got))
        return worst

    return measure


# ----------------------------------------------------------------- publish
def publish_checkpoint(module, directory: str, version: str) -> str:
    """Publish ``module``'s weights into a watch directory as one
    checkpoint prefix: ``<version>.model.npz`` first, then the
    manifest — the manifest lands last, so a watcher that sees a
    manifest knows the pair preceding it was durable (a crash mid-
    publish leaves a manifest-less prefix the watcher simply ignores).
    Runs the fault injector's ``publish`` site afterwards so chaos
    plans can damage a published checkpoint post-manifest — exactly the
    corruption the watcher's verify-before-swap gate must catch."""
    from bigdl_tpu.resilience.faults import get_injector
    from bigdl_tpu.utils.serializer import save_module, write_manifest

    os.makedirs(directory, exist_ok=True)
    prefix = os.path.join(directory, str(version))
    save_module(module, prefix + ".model")
    write_manifest(prefix)
    get_injector().on_checkpoint_publish(prefix)
    return prefix


# ----------------------------------------------------------------- watcher
class CheckpointWatcher:
    """Engine-side half: poll a directory, verify, hot-swap.

    ``poll_once()`` is the whole policy (the background thread just
    calls it on a timer): walk the directory's checkpoint prefixes
    oldest-first, skip anything already seen, skip prefixes whose
    manifest has not landed yet (still publishing), reject-and-count
    anything that fails verification, and swap everything that
    passes — so a burst of publishes applies in order and the engine
    ends on the newest verified version."""

    def __init__(self, engine, watch_dir: Optional[str] = None, *,
                 poll_s: Optional[float] = None):
        from bigdl_tpu import obs
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().rollout
        self.engine = engine
        self.watch_dir = watch_dir or cfg.watch_dir
        if not self.watch_dir:
            raise ValueError(
                "CheckpointWatcher needs a watch directory "
                "(watch_dir= or BIGDL_ROLLOUT_WATCH)")
        self.poll_s = float(cfg.poll_s if poll_s is None else poll_s)
        self._seen: set = set()
        self.rejected: Dict[str, str] = {}   # prefix -> verify reason
        self.swapped: List[str] = []         # versions, in swap order
        self._rejected_counter = obs.get_registry().counter(
            names.ROLLOUT_REJECTED_TOTAL,
            names.spec(names.ROLLOUT_REJECTED_TOTAL).doc,
            labels=("reason",))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[str]:
        """One watch pass; returns the last version swapped in (None if
        nothing new was applied)."""
        from bigdl_tpu import obs
        from bigdl_tpu.serving import spans
        from bigdl_tpu.utils.serializer import (
            checkpoint_prefixes, load_module, verify_checkpoint)

        try:
            prefixes = checkpoint_prefixes(self.watch_dir)
        except OSError:
            return None   # directory not created yet — nothing to do
        last = None
        for name in prefixes:
            prefix = os.path.join(self.watch_dir, name)
            if prefix in self._seen:
                continue
            if not os.path.exists(prefix + ".manifest.json"):
                # publish in progress: the manifest is written last, so
                # no manifest = the pair may still be landing.  Not
                # "seen" — the next poll re-checks.
                continue
            ok, reason = verify_checkpoint(prefix)
            if not ok:
                # counted, stamped, never loaded — serving state is
                # untouched by a bad publish
                self._seen.add(prefix)
                self.rejected[prefix] = reason
                self._rejected_counter.labels(
                    reason=_reason_class(reason)).inc()
                obs.get_tracer().event(spans.EVENT_ROLLOUT_REJECT,
                                       version=name, reason=reason)
                log.warning("rollout: refused checkpoint %s (%s)",
                            prefix, reason)
                continue
            module = load_module(prefix + ".model")
            self.engine.swap_weights(module.params(), version=name,
                                     manifest_sha=manifest_digest(prefix))
            self._seen.add(prefix)
            self.swapped.append(name)
            last = name
        return last

    # ------------------------------------------------------ thread plumbing
    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="bigdl-rollout-watch", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — watcher must survive
                log.exception("rollout: watch pass failed")
            self._stop.wait(self.poll_s)

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def stats(self) -> dict:
        return {"watch_dir": self.watch_dir,
                "swapped": list(self.swapped),
                "rejected": dict(self.rejected),
                "engine_version": getattr(self.engine, "weight_version",
                                          None)}


# ------------------------------------------------------------------ canary
class CanaryController:
    """Router-side half: canary a version, watch, roll back or promote.

    States: ``idle`` (everything serves the incumbent) -> ``canary``
    (``offer()`` put the candidate on a fraction of replicas) -> back
    to ``idle`` via either a promote (``hold_evals`` consecutive clean
    ``evaluate()`` rounds -> candidate becomes the incumbent
    everywhere) or a rollback (``for_count`` consecutive breaches of
    either signal -> canaries drain, revert, undrain; a cooldown then
    refuses new offers so the same bad version cannot flap)."""

    def __init__(self, replicas: Sequence[str], *,
                 set_version: Callable[[str, str], None],
                 incumbent: str,
                 measure_divergence: Optional[Callable[[], float]] = None,
                 alerts: Optional[Callable[[], Sequence[str]]] = None,
                 drain: Optional[Callable[[str], None]] = None,
                 undrain: Optional[Callable[[str], None]] = None,
                 fraction: Optional[float] = None,
                 divergence_threshold: Optional[float] = None,
                 for_count: Optional[int] = None,
                 hold_evals: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        from bigdl_tpu import obs
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().rollout
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("CanaryController needs at least 1 replica")
        self._set_version = set_version
        self._measure = measure_divergence
        self._alerts = alerts
        self._drain = drain
        self._undrain = undrain
        self.fraction = float(cfg.canary_fraction if fraction is None
                              else fraction)
        self.divergence_threshold = float(
            cfg.divergence_threshold if divergence_threshold is None
            else divergence_threshold)
        self.for_count = max(1, int(cfg.for_count if for_count is None
                                    else for_count))
        self.hold_evals = max(1, int(cfg.hold_evals if hold_evals is None
                                     else hold_evals))
        self.cooldown_s = float(cfg.cooldown_s if cooldown_s is None
                                else cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.incumbent = str(incumbent)
        self.candidate: Optional[str] = None
        self.canaries: List[str] = []
        self._state = "idle"
        self._streaks = {"slo_burn": 0, "divergence": 0}
        self._clean_streak = 0
        self._last_rollback_t: Optional[float] = None
        self.rollbacks: List[dict] = []
        self.promotions: List[str] = []
        self.refused_offers = 0
        reg = obs.get_registry()
        self._div_gauge = reg.gauge(
            names.ROLLOUT_CANARY_DIVERGENCE,
            names.spec(names.ROLLOUT_CANARY_DIVERGENCE).doc)
        self._state_gauge = reg.gauge(
            names.ROLLOUT_CANARY_STATE,
            names.spec(names.ROLLOUT_CANARY_STATE).doc)
        self._rollback_counter = reg.counter(
            names.ROLLOUT_ROLLBACKS_TOTAL,
            names.spec(names.ROLLOUT_ROLLBACKS_TOTAL).doc,
            labels=("reason",))
        self._state_gauge.set(0)

    # ------------------------------------------------------------- offering
    def offer(self, version: str, now: Optional[float] = None) -> bool:
        """Offer a new version for canarying.  Refused (False) while a
        canary is already running or inside the post-rollback cooldown;
        on acceptance the candidate is applied to the canary fraction
        (at least one replica, deterministic pick: sorted-name prefix)
        and evaluation begins."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            if self._state != "idle":
                self.refused_offers += 1
                return False
            if (self._last_rollback_t is not None
                    and now - self._last_rollback_t < self.cooldown_s):
                self.refused_offers += 1
                log.warning("rollout: offer of %s refused — %0.1fs left "
                            "in rollback cooldown", version,
                            self.cooldown_s - (now - self._last_rollback_t))
                return False
            n = max(1, int(self.fraction * len(self.replicas)))
            self.canaries = sorted(self.replicas)[:n]
            self.candidate = str(version)
            self._state = "canary"
            self._streaks = {"slo_burn": 0, "divergence": 0}
            self._clean_streak = 0
        for name in self.canaries:
            self._set_version(name, str(version))
        self._state_gauge.set(1)
        self._decision_event("canary", str(version))
        return True

    # ----------------------------------------------------------- evaluation
    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation round.  Reads both signals, advances the
        breach/clean streaks, and fires a rollback or a promote when a
        streak crosses its threshold.  Returns what it saw (for logs,
        the sim and the smoke)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            if self._state != "canary":
                return {"state": self._state}
        active = set(self._alerts() or ()) if self._alerts else set()
        burn = SLO_BURN_ALERT in active
        div = float(self._measure()) if self._measure else 0.0
        self._div_gauge.set(div)
        div_breach = div > self.divergence_threshold
        with self._lock:
            self._streaks["slo_burn"] = (
                self._streaks["slo_burn"] + 1 if burn else 0)
            self._streaks["divergence"] = (
                self._streaks["divergence"] + 1 if div_breach else 0)
            reason = next((r for r in ("slo_burn", "divergence")
                           if self._streaks[r] >= self.for_count), None)
            if reason is None:
                self._clean_streak = (0 if (burn or div_breach)
                                      else self._clean_streak + 1)
                promote = self._clean_streak >= self.hold_evals
            else:
                promote = False
        out = {"state": "canary", "slo_burn": burn, "divergence": div,
               "streaks": dict(self._streaks)}
        if reason is not None:
            self._rollback(reason, now)
            out.update(state="rollback", rollback=reason)
        elif promote:
            self._promote()
            out.update(state="promoted")
        return out

    def _rollback(self, reason: str, now: float):
        """Revert every canary to the incumbent, dropping nothing: each
        canary drains first (its in-flight requests checkpoint into
        version-pinned handoffs the router replays elsewhere), reverts,
        then rejoins placement."""
        with self._lock:
            version = self.candidate
            canaries = list(self.canaries)
            self._state = "rollback"
        self._state_gauge.set(2)
        for name in canaries:
            if self._drain is not None:
                self._drain(name)
            self._set_version(name, self.incumbent)
            if self._undrain is not None:
                self._undrain(name)
        self._rollback_counter.labels(reason=reason).inc()
        with self._lock:
            self.rollbacks.append({"version": version, "reason": reason,
                                   "t": now})
            self._last_rollback_t = now
            self.candidate = None
            self.canaries = []
            self._state = "idle"
        self._state_gauge.set(0)
        self._decision_event("rollback", version, reason=reason)
        log.warning("rollout: rolled back %s (%s), cooldown %.0fs",
                    version, reason, self.cooldown_s)

    def _promote(self):
        """Candidate held clean for ``hold_evals`` rounds: apply it to
        the rest of the fleet and make it the incumbent."""
        with self._lock:
            version = self.candidate
            rest = [n for n in self.replicas if n not in self.canaries]
        for name in rest:
            self._set_version(name, str(version))
        with self._lock:
            self.incumbent = str(version)
            self.candidate = None
            self.canaries = []
            self.promotions.append(str(version))
            self._state = "idle"
        self._state_gauge.set(0)
        self._decision_event("promote", version)
        log.info("rollout: promoted %s fleet-wide", version)

    def _decision_event(self, decision: str, version: Optional[str],
                        **kw):
        from bigdl_tpu import obs
        from bigdl_tpu.serving import spans

        obs.get_tracer().event(spans.EVENT_ROLLOUT_DECISION,
                               decision=decision, version=version or "",
                               incumbent=self.incumbent, **kw)

    # -------------------------------------------------------------- reading
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "incumbent": self.incumbent,
                    "candidate": self.candidate,
                    "canaries": list(self.canaries),
                    "streaks": dict(self._streaks),
                    "clean_streak": self._clean_streak,
                    "rollbacks": len(self.rollbacks),
                    "promotions": list(self.promotions),
                    "refused_offers": self.refused_offers}


__all__ = ["CanaryController", "CheckpointWatcher", "SLO_BURN_ALERT",
           "divergence_probe", "manifest_digest", "publish_checkpoint",
           "token_divergence"]
