"""Span-name constants for the serving data plane.

Every span or event the serving tier emits through the tracer is named
HERE, once — mint sites reference these constants instead of string
literals, exactly like metric names live in ``obs/names.py``.  A typo'd
span name is then an AttributeError, not a silently-forked timeline,
and graftlint rule RD006 (``bigdl_tpu/analysis/registry_rules.py``)
flags any ``tracer.span(...)`` / ``.event(...)`` / ``.complete(...)``
call in ``bigdl_tpu/serving/`` (or in a module importing this one)
whose first argument is a string literal.

Two families:

* ``SPAN_*`` — the per-request lifecycle hops of the distributed
  request trace (``obs/reqtrace.py``).  Each kept request trace is one
  set of these spans sharing a ``trace`` attribute; ``report.py``'s
  "request traces" section groups them by the hop key (the part after
  ``req.``) for p99 attribution.
* ``EVENT_*`` — point events the engine/simulator stamp regardless of
  request tracing.
"""

from __future__ import annotations

# ---------------------------------------------------- request-trace hops
#: whole routed request, router-side (placement -> final answer)
SPAN_ROUTE = "req.route"
#: one placement decision (PlacementPolicy.choose + signals snapshot)
SPAN_PLACEMENT = "req.placement"
#: one budget-gated retry: the backoff wait before re-placement
SPAN_RETRY = "req.retry"
#: a drain-handoff replay being absorbed (claim + prompt refold)
SPAN_HANDOFF = "req.handoff"
#: submit -> first slot admission (queue wait in batcher.py)
SPAN_QUEUE = "req.queue"
#: one batched prefill forward (per admission, attrs carry the bucket)
SPAN_PREFILL = "req.prefill"
#: preemption refold: pages lost -> re-admitted (KV-pressure eviction)
SPAN_PREEMPT = "req.preempt"
#: aggregated per-token decode time (everything not queue/prefill/
#: preempt inside the engine's e2e — exact partition, see engine.py)
SPAN_DECODE = "req.decode"

#: the hop keys the report attributes, in render order
HOP_ORDER = ("queue", "placement", "retry", "prefill", "decode",
             "preempt", "handoff", "route")

# ----------------------------------------------------------- live phases
#: one live batched decode step (dispatch -> resolved next tokens) —
#: stamped by Engine._step as a REAL tracer span (not a retroactive
#: reqtrace hop) so the continuous profiler (obs/prof.py) attributes
#: decode-time samples to it
SPAN_STEP_DECODE = "serve.decode_step"

# ------------------------------------------------------------ point events
#: a request entered a decode slot (engine admission)
EVENT_ADMIT = "serve.admit"
#: a request was preempted off its slot (pages reclaimed)
EVENT_PREEMPT = "serve.preempt"
#: one chaos-scenario verdict (sim/serve.py)
EVENT_SCENARIO = "serve.scenario"
#: a live weight hot-swap completed (pointer flip between decode steps)
EVENT_WEIGHT_SWAP = "serve.weight_swap"
#: the rollout watcher refused a published checkpoint (verify failed)
EVENT_ROLLOUT_REJECT = "rollout.reject"
#: one canary decision (offer / promote / rollback / suppressed)
EVENT_ROLLOUT_DECISION = "rollout.decision"


def hop_key(span_name: str) -> str:
    """The attribution key of one request-trace span name
    (``"req.prefill"`` -> ``"prefill"``; foreign names pass through)."""
    return span_name[4:] if span_name.startswith("req.") else span_name


__all__ = ["SPAN_ROUTE", "SPAN_PLACEMENT", "SPAN_RETRY", "SPAN_HANDOFF",
           "SPAN_QUEUE", "SPAN_PREFILL", "SPAN_PREEMPT", "SPAN_DECODE",
           "SPAN_STEP_DECODE", "HOP_ORDER", "EVENT_ADMIT",
           "EVENT_PREEMPT", "EVENT_SCENARIO", "EVENT_WEIGHT_SWAP",
           "EVENT_ROLLOUT_REJECT", "EVENT_ROLLOUT_DECISION", "hop_key"]
