"""Continuous-batching LM decode engine.

``TransformerLM.generate`` decodes one fixed batch to completion — the
whole batch waits for its slowest member (head-of-line blocking), and a
new request waits for the whole batch to drain.  This engine replaces
that with the production shape:

* **slots**: up to ``max_batch`` requests decode together in one jitted
  step over the paged KV cache (serving/cache.py);
* **continuous admission**: at every step boundary, free slots are
  refilled from the request queue (serving/batcher.py) — a finished
  request's slot and pages are reused immediately, not when the batch
  drains (``admission="static"`` keeps the drain-first behavior as the
  A/B baseline the serve smoke measures against);
* **prefill/decode split**: a new request's prompt runs one batched
  forward (``TransformerBlock.prefill`` — the identical attention path
  training uses) padded to a page-aligned bucket, writing its K/V pages
  and producing its first token; the shared decode step then advances
  every active slot one token;
* **int8 decode** (``int8=True``): the decode matmuls run on
  pre-quantized per-output-channel int8 weights via the existing
  ``ops.quantized_matmul`` path (the same math ``module.quantize()``
  rides) — decode is memory-bound, so halving/quartering weight bytes
  is the lever; prefill stays float (it is compute-bound);
* **TP-sharded decode** (``tp=N``): the step runs under shard_map with
  Megatron row/col-split weights and the block reductions on
  ``parallel/wire.py``'s compressed collectives (serving/tp.py);
* **preemption**: if the page pool is exhausted mid-decode, the
  youngest request is preempted — pages freed, the request re-queued
  with its generated prefix as prompt — instead of deadlocking the
  batch.

Telemetry closes the serving loop: ``bigdl_request_latency_seconds
{engine,kind=ttft|per_token|e2e}`` histograms, token/request counters,
batch-occupancy and queue-depth gauges (the autoscaler's signals), a
``bigdl_serve_latency_slo_ratio`` gauge the p99 burn-rate alert rule
watches, and the live ``/healthz`` step stamp via ``obs.server``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional

import numpy as np

from bigdl_tpu import obs
from bigdl_tpu.serving.batcher import RequestQueue, ServeRequest
from bigdl_tpu.serving.cache import PagedKVCache
from bigdl_tpu.serving.drain import HANDOFF_ERROR
from bigdl_tpu.serving import spans
from bigdl_tpu.obs import names

LAT_META = (names.REQUEST_LATENCY_SECONDS,
            "Request latency by engine and kind (ttft = time to first "
            "token, per_token = mean inter-token, e2e = submit to done)")


def _quantize_tree(params, n_layer):
    """Per-output-channel int8 twins of every decode matmul weight —
    the ``quantize_per_channel`` path ``module.quantize()`` uses."""
    from bigdl_tpu.ops.quantized_matmul import quantize_per_channel

    q = {}
    for i in range(n_layer):
        pa = params[f"h{i}"]["attn"]
        blk = {"attn": {}, "fc1": None, "fc2": None}
        for w in ("wq", "wk", "wv", "wo"):
            blk["attn"][w] = quantize_per_channel(pa[w], axis=0)
        blk["fc1"] = quantize_per_channel(
            params[f"h{i}"]["fc1"]["weight"], axis=0)
        blk["fc2"] = quantize_per_channel(
            params[f"h{i}"]["fc2"]["weight"], axis=0)
        q[f"h{i}"] = blk
    q["head"] = quantize_per_channel(params["head"]["weight"], axis=0)
    return q


def paged_decode_math(children, n_layer, page_size, params, qparams,
                      kp, vp, tables, lengths, tokens, temps, active,
                      key, *, n_head=None, psum=None, attn_impl="dense",
                      attn_block_pages=0):
    """One decode step over the paged cache — the single source of
    truth shared by the jitted single-host step and the TP shard_map
    body (``n_head`` is the LOCAL head count there, ``psum`` the
    compressed block reduction).  Mirrors
    ``TransformerBlock.decode_step`` exactly in the float path so paged
    decode bit-matches ``generate()`` at temperature 0.

    The attention body is ``ops.decode_attention.paged_decode_attention``
    — ``attn_impl="dense"`` is the bit-match gather path, "auto" lets
    the cached ``decode_attn`` tuner site dispatch the flash-decode
    fused/Pallas kernels per (shape, dtype, platform); ``tables`` may
    be the engine's used-page prefix bucket rather than the full table
    width (same mask contract either way)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.decode_attention import paged_decode_attention
    from bigdl_tpu.ops.quantized_matmul import int8_matmul

    attn0 = children["h0"]._children["attn"]
    heads = attn0.n_head if n_head is None else int(n_head)
    head_dim = attn0.head_dim
    bsz = tokens.shape[0]
    scale = 1.0 / float(np.sqrt(head_dim))

    def mm(x, w, qw):
        if qparams is not None and qw is not None:
            return int8_matmul(x, qw[0], qw[1], impl="auto")
        return jnp.matmul(x, w.T)

    x = jnp.take(params["wte"]["weight"], tokens, axis=0)[:, None, :]
    x = x + jnp.take(params["wpe"]["weight"], lengths, axis=0)[:, None, :]
    for i in range(n_layer):
        block = children[f"h{i}"]
        p = params[f"h{i}"]
        pa = p["attn"]
        qb = None if qparams is None else qparams[f"h{i}"]
        h, _ = block._children["ln1"].apply(p["ln1"], {}, x)
        if qb is None:
            q, k, v = block._project_qkv(pa, h)
        else:
            q = mm(h, pa["wq"], qb["attn"]["wq"])
            k = mm(h, pa["wk"], qb["attn"]["wk"])
            v = mm(h, pa["wv"], qb["attn"]["wv"])
            if pa.get("bq") is not None:
                q, k, v = q + pa["bq"], k + pa["bk"], v + pa["bv"]

        def split(t):
            return t.reshape(bsz, 1, heads, head_dim).transpose(0, 2, 1, 3)

        qh = split(q)
        kh = split(k)[:, :, 0, :]            # (B, H, Dh)
        vh = split(v)[:, :, 0, :]
        pidx = jnp.take_along_axis(
            tables, (lengths // page_size)[:, None], axis=1)[:, 0]
        off = lengths % page_size
        kp = kp.at[i, pidx, :, off, :].set(kh.astype(kp.dtype))
        vp = vp.at[i, pidx, :, off, :].set(vh.astype(vp.dtype))
        o = paged_decode_attention(
            qh[:, :, 0, :], kp[i], vp[i], tables, lengths,
            page_size=page_size, scale=scale, impl=attn_impl,
            block_pages=attn_block_pages)       # (B, H, Dh)
        o = o.reshape(bsz, 1, heads * head_dim)
        y = mm(o, pa["wo"], None if qb is None else qb["attn"]["wo"])
        if psum is not None:
            y = psum(y)
        if pa.get("bo") is not None:
            y = y + pa["bo"]
        x = x + y
        # MLP (pre-LN): bias of the row-parallel fc1 is local, the
        # col-parallel fc2's bias is added once, after the reduction
        h, _ = block._children["ln2"].apply(p["ln2"], {}, x)
        h = mm(h, p["fc1"]["weight"],
               None if qb is None else qb["fc1"]) + p["fc1"]["bias"]
        h = jax.nn.gelu(h)
        h = mm(h, p["fc2"]["weight"],
               None if qb is None else qb["fc2"])
        if psum is not None:
            h = psum(h)
        if p["fc2"].get("bias") is not None:
            h = h + p["fc2"]["bias"]
        x = x + h
    h, _ = children["ln_f"].apply(params["ln_f"], {}, x)
    logits = mm(h, params["head"]["weight"],
                None if qparams is None else qparams["head"])[:, 0, :]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sampled = jax.random.categorical(
        key, logits / jnp.maximum(temps, 1e-6)[:, None],
        axis=-1).astype(jnp.int32)
    nxt = jnp.where(temps > 0.0, sampled, greedy)
    nxt = jnp.where(active, nxt, 0)
    return kp, vp, nxt


class _Active:
    """Host bookkeeping for one occupied slot."""

    __slots__ = ("req", "remaining", "last_token", "prompt_len",
                 "t_admit", "order")

    def __init__(self, req, remaining, last_token, prompt_len, order):
        self.req = req
        self.remaining = remaining
        self.last_token = last_token
        self.prompt_len = prompt_len
        self.t_admit = time.monotonic()
        self.order = order


class LMEngine:
    """Continuous-batching decode over a :class:`PagedKVCache`."""

    def __init__(self, model, params=None, *, max_batch: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 queue_capacity: Optional[int] = None,
                 int8: Optional[bool] = None, tp: int = 1, wire=None,
                 cache_dtype=None, eos_id: Optional[int] = None,
                 slo_s: Optional[float] = None,
                 admission: Optional[str] = None,
                 decode_attn: Optional[str] = None,
                 decode_bucket: Optional[bool] = None, seed: int = 0,
                 weight_version: str = "v0"):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().serve
        self.model = model
        self.params = model.params() if params is None else params
        self.max_batch = int(max_batch or cfg.max_batch)
        self.page_size = int(page_size or cfg.page_size)
        self.int8 = cfg.int8 if int8 is None else bool(int8)
        self.tp = int(tp or 1)
        self.decode_attn = decode_attn or cfg.decode_attn
        if self.decode_attn not in ("auto", "dense", "fused", "pallas",
                                    "pallas_interpret"):
            raise ValueError(
                f"decode_attn must be auto|dense|fused|pallas, got "
                f"{self.decode_attn!r}")
        self.decode_bucket = (cfg.decode_bucket if decode_bucket is None
                              else bool(decode_bucket))
        self.eos_id = eos_id
        self.slo_s = cfg.slo_s if slo_s is None else float(slo_s)
        self.admission = admission or cfg.admission
        if self.admission not in ("continuous", "static"):
            raise ValueError(
                f"admission must be continuous|static, got "
                f"{self.admission!r}")
        if self.int8 and self.tp > 1:
            raise ValueError("int8 decode and tp-sharded decode are "
                             "currently exclusive")
        mc = model._config
        self.max_len = int(mc["max_len"])
        self.n_layer = model.n_layer
        self.n_head = int(mc["n_head"])
        self.head_dim = model.dim // self.n_head
        if cache_dtype is None:
            cache_dtype = self.params["wte"]["weight"].dtype
        pages = num_pages or cfg.num_pages or (
            1 + self.max_batch * -(-self.max_len // self.page_size))
        self.cache = PagedKVCache(
            self.n_layer, self.n_head, self.head_dim,
            page_size=self.page_size, num_pages=pages,
            max_slots=self.max_batch, max_len=self.max_len,
            dtype=cache_dtype)
        self.queue = RequestQueue(queue_capacity or cfg.queue_capacity)
        self._slots: List[Optional[_Active]] = [None] * self.max_batch
        self._stash: collections.deque = collections.deque()
        self._key = jax.random.key(int(seed))
        self._qparams = (_quantize_tree(self.params, self.n_layer)
                        if self.int8 else None)
        self._order = 0
        self._steps = 0
        self._occ_sum = 0.0
        self._tokens_total = 0
        self._t_first_work: Optional[float] = None
        self._t_last_done: Optional[float] = None
        self.completed: List[dict] = []
        self._slo_window: collections.deque = collections.deque(maxlen=256)
        self.weight_version = str(weight_version)
        self.manifest_sha: Optional[str] = None
        self.swaps = 0
        self.draining = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()

        self._last_bucket = self.cache.max_pages_per_slot
        self._impl_by_bucket: dict = {}
        self._decode_ms_sum = 0.0
        self._weight_bytes = self._decode_weight_bytes()
        if self.tp > 1:
            from bigdl_tpu.serving.tp import build_tp_decode_step

            self._step_fn = build_tp_decode_step(
                model, tp=self.tp, wire=wire, page_size=self.page_size,
                max_batch=self.max_batch,
                positions=self.cache.padded_positions(),
                attn_impl=self.decode_attn)
        else:
            self._step_fn = self._build_step()
            self.params = jax.tree.map(
                jnp.asarray, self.params,
                is_leaf=lambda x: x is None or hasattr(x, "shape"))
        self._prefill_fns: dict = {}
        from bigdl_tpu import obs
        from bigdl_tpu.obs import prof as _obs_prof

        # continuous profiler: starts with the engine when
        # BIGDL_PROF_HZ > 0 (unset: one config read, no thread)
        _obs_prof.get_profiler()
        reg = obs.get_registry()
        self._lat = reg.histogram(*LAT_META, labels=("engine", "kind"))
        self._tokens_counter = reg.counter(
            names.SERVE_TOKENS_TOTAL, "Tokens generated by the LM "
            "decode engine")
        self._req_counter = reg.counter(
            names.SERVE_REQUESTS_TOTAL,
            "Requests completed, by engine and status",
            labels=("engine", "status"))
        self._occ_gauge = reg.gauge(
            names.SERVE_BATCH_OCCUPANCY,
            "Mean fraction of decode slots occupied per step")
        self._tps_gauge = reg.gauge(
            names.SERVE_TOKENS_PER_SECOND,
            "LM decode throughput over the engine's busy wall clock")
        self._slo_gauge = reg.gauge(
            names.SERVE_LATENCY_SLO_RATIO,
            "Fraction of recent requests completing within the "
            "latency SLO (feeds the serve_latency_slo_burn alert)")
        self._preempt_counter = reg.counter(
            names.SERVE_PREEMPTIONS_TOTAL,
            "Requests preempted (pages reclaimed, request re-queued) "
            "on KV-page exhaustion")
        self._decode_ms_gauge = reg.gauge(
            names.SERVE_DECODE_ATTN_MS,
            "Mean wall-clock of the jitted paged-decode step "
            "(attention-dominated, memory-bound) in milliseconds")
        self._decode_bytes_gauge = reg.gauge(
            names.SERVE_DECODE_HBM_BYTES_PER_TOKEN,
            "Analytic HBM bytes streamed per generated token (decode "
            "weights + the KV pages the step's page-table bucket "
            "names)")
        self._swap_counter = reg.counter(
            names.SERVE_WEIGHT_SWAPS_TOTAL,
            "Live weight hot-swaps completed, by promoted version",
            labels=("version",))

    def _decode_weight_bytes(self) -> float:
        """Static per-step weight-stream bytes of the decode matmuls —
        one read of every parameter byte per token (decode is
        memory-bound; int8 engines stream the 1-byte twins instead of
        the float matmul weights)."""
        total = 0.0
        leaves = []

        def walk(t):
            if isinstance(t, dict):
                for v in t.values():
                    walk(v)
            elif t is not None and hasattr(t, "size"):
                leaves.append(t)

        walk(self.params)
        for leaf in leaves:
            item = leaf.dtype.itemsize if hasattr(leaf, "dtype") else 4
            # int8 decode replaces every >=2-D matmul weight with its
            # 1-byte twin (+ negligible per-channel scales)
            if self._qparams is not None and getattr(leaf, "ndim", 0) >= 2:
                item = 1
            total += float(leaf.size) * item
        return total

    # ------------------------------------------------------------ hot swap
    def swap_weights(self, params, *, version: str,
                     manifest_sha: Optional[str] = None) -> None:
        """Hot-swap the served weights between decode steps.

        All the expensive work — the host->device transfer of the new
        tree and (int8) requantizing the per-channel twins — happens on
        the CALLER's thread, outside the engine lock; the swap itself
        is a pointer flip the decode loop observes at its next
        ``pump`` cycle.  Page tables, slots and in-flight decodes
        survive untouched: the step and prefill functions take the
        params tree as an argument, so nothing recompiles on the float
        path.  The int8 step closes over the quantized twins, so that
        engine rebuilds its jitted step under the lock (retraced
        lazily on the next step dispatch).

        Requests already decoding keep their old-weights KV prefix and
        continue on the new weights — they complete, on a mixed
        trajectory; requests admitted after the swap decode bit-equal
        to ``generate()`` on the new weights at temperature 0.
        """
        import jax
        import jax.numpy as jnp

        if self.tp == 1:
            params = jax.tree.map(
                jnp.asarray, params,
                is_leaf=lambda x: x is None or hasattr(x, "shape"))
        qparams = (_quantize_tree(params, self.n_layer)
                   if self.int8 else None)
        with self._lock:
            self.params = params
            self._qparams = qparams
            if self.int8:
                self._step_fn = self._build_step()
            self._weight_bytes = self._decode_weight_bytes()
            self.weight_version = str(version)
            self.manifest_sha = manifest_sha
            self.swaps += 1
        self._swap_counter.labels(version=str(version)).inc()
        obs.get_tracer().event(spans.EVENT_WEIGHT_SWAP,
                               version=str(version),
                               sha=manifest_sha or "",
                               swaps=self.swaps)

    # -------------------------------------------------------- jit builders
    def _build_step(self):
        import jax

        children = self.model._children
        n_layer, page_size = self.n_layer, self.page_size
        qparams = self._qparams
        attn_impl = self.decode_attn

        def step(params, kp, vp, tables, lengths, tokens, temps,
                 active, key):
            return paged_decode_math(
                children, n_layer, page_size, params, qparams, kp, vp,
                tables, lengths, tokens, temps, active, key,
                attn_impl=attn_impl)

        return jax.jit(step, donate_argnums=(1, 2))

    def _decode_impl_for(self, bucket: int) -> str:
        """The decode-attention impl this step's bucket resolves to —
        host-side mirror of the in-trace dispatch, cached per bucket
        (drives the bytes-per-token gauge and ``stats()``; with the
        tuner enabled this is also what pre-populates the
        ``decode_attn`` cache entry the traced step then hits)."""
        impl = self._impl_by_bucket.get(bucket)
        if impl is not None:
            return impl
        impl = self.decode_attn
        if impl == "auto":
            impl = "dense"
            try:
                from bigdl_tpu.ops import autotune

                if autotune.enabled():
                    heads = self.n_head // self.tp
                    q_dtype = self.params["wte"]["weight"].dtype
                    rec = autotune.decide_decode_attn(
                        (self.max_batch, heads, self.head_dim),
                        self.page_size, bucket, q_dtype,
                        kv_dtype=self.cache.dtype)
                    if rec is not None:
                        impl = rec.get("impl", "dense")
            except Exception:  # noqa: BLE001 — a hint, never a sink
                pass
        self._impl_by_bucket[bucket] = impl
        return impl

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax import lax

        children = self.model._children
        n_layer, page_size = self.n_layer, self.page_size
        dim = self.model.dim
        n_write = bucket // page_size

        def prefill(params, kp, vp, prompt, t0, pages, temp, key):
            # prompt is (1, bucket), zero-padded past t0 — causal
            # attention keeps the real prefix exact
            x = jnp.take(params["wte"]["weight"], prompt, axis=0)
            x = x + params["wpe"]["weight"][:bucket][None]
            for i in range(n_layer):
                x, kh, vh = children[f"h{i}"].prefill(params[f"h{i}"], x)
                for j in range(n_write):
                    kp = kp.at[i, pages[j]].set(
                        kh[0, :, j * page_size:(j + 1) * page_size,
                           :].astype(kp.dtype))
                    vp = vp.at[i, pages[j]].set(
                        vh[0, :, j * page_size:(j + 1) * page_size,
                           :].astype(vp.dtype))
            h = lax.dynamic_slice(x, (0, t0 - 1, 0), (1, 1, dim))
            h, _ = children["ln_f"].apply(params["ln_f"], {}, h)
            logits, _ = children["head"].apply(params["head"], {}, h)
            logits = logits[:, 0, :]
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                key, logits / jnp.maximum(temp, 1e-6),
                axis=-1).astype(jnp.int32)
            first = jnp.where(temp > 0.0, sampled, greedy)
            return kp, vp, first[0]

        fn = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefill_fns[bucket] = fn
        return fn

    def _bucket(self, t0: int) -> int:
        b = self.page_size
        while b < t0:
            b *= 2
        return min(b, -(-self.max_len // self.page_size) * self.page_size)

    # ------------------------------------------------------------- clients
    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0,
               timeout: Optional[float] = None,
               trace=None) -> ServeRequest:
        if self.draining:
            raise RuntimeError("engine is draining — admissions closed")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + {max_new_tokens} new tokens "
                f"exceeds max_len {self.max_len}")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # feasibility: a request that can NEVER fit the page pool even
        # alone would preempt-loop forever — reject it at the door
        worst = self.cache.pages_for(len(prompt) + int(max_new_tokens))
        if worst > self.cache.num_pages - 1:
            raise ValueError(
                f"request needs {worst} KV pages but the pool has "
                f"{self.cache.num_pages - 1}")
        # request tracing: attach (or mint) a context only when the
        # collector is on — with BIGDL_REQTRACE_SAMPLE=0 this whole
        # branch is two attribute loads and the engine carries no
        # trace state at all
        from bigdl_tpu.obs import reqtrace
        col = reqtrace.get_collector()
        if col.enabled:
            if trace is None:
                trace = col.new_context()
            col.begin(trace)
        else:
            trace = None
        req = ServeRequest(payload=prompt,
                           max_new_tokens=int(max_new_tokens),
                           temperature=float(temperature),
                           trace=trace)
        if trace is not None:
            req._tr_admits = []    # [{t, dur, bucket, prompt_len, slot}]
            req._tr_preempts = []  # [t_preempted]
        return self.queue.submit(req, timeout=timeout)

    # ----------------------------------------------------------- admission
    def _free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s is None]

    def active_count(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def _admit(self, wait_s: float = 0.0) -> int:
        free = self._free_slots()
        if not free:
            return 0
        if self.admission == "static" and self.active_count():
            return 0  # static batching: drain fully before refilling
        wanted = len(free)
        incoming = list(self._stash)
        self._stash.clear()
        if len(incoming) < wanted:
            incoming.extend(
                self.queue.take(wanted - len(incoming), timeout=wait_s))
        admitted = 0
        for req in incoming:
            slot = None
            for i, s in enumerate(self._slots):
                if s is None:
                    slot = i
                    break
            # pages are allocated for the PROMPT, not the (pow2) compile
            # bucket — the bucket's padded tail writes to the trash page
            if slot is None or not self.cache.can_admit(len(req.payload)):
                self._stash.append(req)  # head-of-line, retried first
                continue
            self._prefill_into(slot, req, self._bucket(len(req.payload)))
            admitted += 1
        return admitted

    def _prefill_into(self, slot: int, req: ServeRequest, bucket: int):
        import jax
        import jax.numpy as jnp

        t_admit = time.monotonic()
        t0 = len(req.payload)
        pages = self.cache.alloc(slot, t0)
        page_arg = np.zeros((bucket // self.page_size,), np.int32)
        page_arg[:len(pages)] = pages
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :t0] = req.payload
        self._key, sub = jax.random.split(self._key)
        kp, vp, first = self._prefill_fn(bucket)(
            self.params, self.cache.kp, self.cache.vp,
            jnp.asarray(prompt), t0, jnp.asarray(page_arg),
            float(req.temperature), sub)
        self.cache.kp, self.cache.vp = kp, vp
        self.cache.lengths[slot] = t0
        tok = int(first)
        if req.trace is not None:
            req._tr_admits.append(
                {"t": t_admit, "dur": time.monotonic() - t_admit,
                 "bucket": bucket, "prompt_len": t0, "slot": slot})
        if req.t_first is None:
            req.t_first = time.monotonic()
            self._lat.labels(engine="lm", kind="ttft").observe(
                req.t_first - req.t_submit)
        req.tokens.append(tok)
        self._tokens_total += 1
        self._tokens_counter.inc()
        if self._t_first_work is None:
            self._t_first_work = time.monotonic()
        self._order += 1
        act = _Active(req, req.max_new_tokens - 1, tok, t0, self._order)
        self._slots[slot] = act
        from bigdl_tpu import obs

        obs.get_tracer().event(spans.EVENT_ADMIT, slot=slot,
                               request=req.id, prompt_len=t0,
                               bucket=bucket)
        if act.remaining <= 0 or tok == self.eos_id:
            self._complete(slot)

    def _preempt_youngest(self) -> Optional[int]:
        """Free the youngest active slot's pages; its request re-queues
        with the generated prefix folded into the prompt."""
        victims = [(s.order, i) for i, s in enumerate(self._slots)
                   if s is not None]
        if not victims:
            return None
        _, slot = max(victims)
        act = self._slots[slot]
        req = act.req
        # generated-since-admission tokens fold into the prompt; the
        # still-owed budget becomes the new max_new_tokens (req.tokens
        # keeps everything, so the client sees one contiguous output)
        gen = req.max_new_tokens - act.remaining
        req.payload = list(req.payload) + [int(t) for t in
                                           req.tokens[-gen:]]
        req.max_new_tokens = act.remaining
        self.cache.release(slot)
        self._slots[slot] = None
        self._stash.appendleft(req)
        self._preempt_counter.inc()
        if req.trace is not None:
            req._tr_preempts.append(time.monotonic())
        from bigdl_tpu import obs

        obs.get_tracer().event(spans.EVENT_PREEMPT, slot=slot,
                               request=req.id, owed=act.remaining)
        return slot

    # ---------------------------------------------------------------- step
    def _complete(self, slot: int, error: Optional[str] = None):
        act = self._slots[slot]
        self.cache.release(slot)
        self._slots[slot] = None
        req = act.req
        now = time.monotonic()
        exemplar = None
        if req.trace is not None:
            # finalize BEFORE finish() wakes the client thread, so the
            # engine's spans reach the collector before a same-process
            # router can race the tail-sampling decision
            kept = self._finalize_trace(req, error, now)
            if kept:
                exemplar = {"trace_id": req.trace.trace_id}
        req.finish(error)
        self._t_last_done = now
        e2e = req.e2e_s
        self._lat.labels(engine="lm", kind="e2e").observe(
            e2e, exemplar=exemplar)
        n_tok = len(req.tokens)
        if n_tok > 1:
            self._lat.labels(engine="lm", kind="per_token").observe(
                (req.t_done - req.t_first) / (n_tok - 1))
        self._req_counter.labels(
            engine="lm", status="error" if error else "ok").inc()
        self.completed.append(
            {"id": req.id, "e2e_s": e2e, "ttft_s": req.ttft_s,
             "tokens": n_tok})
        if self.slo_s > 0:
            self._slo_window.append(1.0 if e2e <= self.slo_s else 0.0)
            self._slo_gauge.set(
                sum(self._slo_window) / len(self._slo_window))
        if self._t_first_work is not None and now > self._t_first_work:
            self._tps_gauge.set(
                self._tokens_total / (now - self._t_first_work))

    def _finalize_trace(self, req: ServeRequest, error: Optional[str],
                        now: float) -> bool:
        """Partition the request's engine-side e2e into lifecycle spans
        and push them through the tail sampler.  The partition is EXACT:
        queue + prefill + preempt + decode == e2e by construction
        (decode is the remainder), which is what makes the report's
        per-hop attribution sum to the measured end-to-end time.
        Returns whether the tail sampler kept the trace."""
        from bigdl_tpu.obs import reqtrace
        col = reqtrace.get_collector()
        ctx = req.trace
        e2e = max(0.0, now - req.t_submit)
        admits = getattr(req, "_tr_admits", [])
        preempts = getattr(req, "_tr_preempts", [])
        queue = (max(0.0, admits[0]["t"] - req.t_submit)
                 if admits else e2e)
        prefill = sum(a["dur"] for a in admits)
        col.span(ctx, spans.SPAN_QUEUE, req.t_submit, queue, engine="lm")
        for a in admits:
            col.span(ctx, spans.SPAN_PREFILL, a["t"], a["dur"],
                     slot=a["slot"], bucket=a["bucket"],
                     prompt_len=a["prompt_len"], engine="lm")
        # each preemption pairs with the NEXT admission: the gap is the
        # refold + re-queue wait the preemption cost this request
        preempt_wait = 0.0
        for i, tp in enumerate(preempts):
            if i + 1 < len(admits):
                gap = max(0.0, admits[i + 1]["t"] - tp)
                preempt_wait += gap
                col.span(ctx, spans.SPAN_PREEMPT, tp, gap, engine="lm")
        decode = max(0.0, e2e - queue - prefill - preempt_wait)
        t_dec = req.t_first if req.t_first is not None else now
        col.span(ctx, spans.SPAN_DECODE, t_dec, decode,
                 tokens=len(req.tokens), engine="lm")
        kept, _ = col.finish(
            ctx,
            request=str(getattr(req, "router_id", None) or req.id),
            error=error, preempted=bool(preempts),
            slo_violation=(self.slo_s > 0 and e2e > self.slo_s),
            handoff=(error == HANDOFF_ERROR), e2e_s=e2e)
        return kept

    def _step(self):
        import jax
        import jax.numpy as jnp

        active_slots = [i for i, s in enumerate(self._slots)
                        if s is not None]
        if not active_slots:
            return False
        # grow pages where the next position crosses a page boundary;
        # exhaustion preempts the youngest request (possibly this one)
        for slot in list(active_slots):
            if self._slots[slot] is None:
                continue
            while self.cache.needs_growth(slot):
                if self.cache.grow(slot):
                    continue
                victim = self._preempt_youngest()
                if victim is None or victim == slot:
                    break
        active_slots = [i for i, s in enumerate(self._slots)
                        if s is not None]
        if not active_slots:
            return False
        tokens = np.zeros((self.max_batch,), np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        active = np.zeros((self.max_batch,), bool)
        for i in active_slots:
            tokens[i] = self._slots[i].last_token
            temps[i] = self._slots[i].req.temperature
            active[i] = True
        # used-page prefix bucket (pow2): even the dense baseline stops
        # gathering the empty pool; each bucket is one compiled variant
        from bigdl_tpu.ops.decode_attention import (decode_hbm_bytes,
                                                    used_page_bucket)

        if self.decode_bucket:
            longest = max(int(self.cache.lengths[i])
                          for i in active_slots)
            bucket = used_page_bucket(longest, self.page_size,
                                      self.cache.max_pages_per_slot)
        else:
            bucket = self.cache.max_pages_per_slot
        self._last_bucket = bucket
        impl = self._decode_impl_for(bucket)
        tables, lengths = self.cache.device_tables(pages=bucket)
        self._key, sub = jax.random.split(self._key)
        t0 = time.perf_counter()
        # a LIVE span around the batched decode dispatch+resolve (not a
        # retroactive reqtrace hop): the continuous profiler attributes
        # samples landing here to the decode phase by name
        with obs.get_tracer().span(spans.SPAN_STEP_DECODE,
                                   bucket=bucket,
                                   active=len(active_slots)):
            kp, vp, nxt = self._step_fn(
                self.params, self.cache.kp, self.cache.vp, tables,
                lengths, jnp.asarray(tokens), jnp.asarray(temps),
                jnp.asarray(active), sub)
            self.cache.kp, self.cache.vp = kp, vp
            nxt = np.asarray(nxt)
        step_ms = (time.perf_counter() - t0) * 1000.0
        self._steps += 1
        self._decode_ms_sum += step_ms
        self._decode_ms_gauge.set(self._decode_ms_sum / self._steps)
        kv_item = self.cache.dtype.itemsize
        step_bytes = self._weight_bytes + self.n_layer * decode_hbm_bytes(
            "dense" if impl == "dense" else "fused", self.max_batch,
            self.n_head, self.head_dim, self.page_size, bucket, kv_item)
        self._decode_bytes_gauge.set(step_bytes / len(active_slots))
        self._occ_sum += len(active_slots) / self.max_batch
        self._occ_gauge.set(self._occ_sum / self._steps)
        for i in active_slots:
            act = self._slots[i]
            tok = int(nxt[i])
            self.cache.lengths[i] += 1
            act.last_token = tok
            act.remaining -= 1
            act.req.tokens.append(tok)
            self._tokens_total += 1
            self._tokens_counter.inc()
            if act.remaining <= 0 or tok == self.eos_id:
                self._complete(i)
        try:
            from bigdl_tpu.obs import server as obs_server

            obs_server.note_step(self._steps)
        except Exception:  # noqa: BLE001 — telemetry must not kill serving
            pass
        return True

    # ---------------------------------------------------------- driving
    def pump(self, wait_s: float = 0.0) -> bool:
        """One admission + decode cycle; True while there is work."""
        with self._lock:
            self._admit(wait_s=wait_s if not self.active_count() else 0.0)
            stepped = self._step()
            return stepped or bool(self._stash) \
                or self.queue.depth() > 0

    def run_until_idle(self, timeout_s: float = 60.0):
        """Drive synchronously until queue + slots drain (tests/smokes)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.pump(wait_s=0.01):
                if self.queue.depth() == 0 and not self.active_count() \
                        and not self._stash:
                    return
        raise TimeoutError(f"engine not idle after {timeout_s:g}s")

    def start(self):
        if self._thread is not None:
            return self
        self._stop = False

        def loop():
            while not self._stop:
                if not self.pump(wait_s=0.02):
                    time.sleep(0.002)

        self._thread = threading.Thread(
            target=loop, name="bigdl-serve-lm", daemon=True)
        self._thread.start()
        return self

    def drain(self, deadline_s: float = 10.0):
        """Stop admissions, finish in-flight decodes within the
        deadline, checkpoint the rest (serving/drain.py).  Returns the
        :class:`~bigdl_tpu.serving.drain.HandoffRecord` list a router
        replays elsewhere exactly once."""
        from bigdl_tpu.serving.drain import drain_engine

        return drain_engine(self, deadline_s=deadline_s)

    def close(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.queue.close()

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        e2e = [c["e2e_s"] for c in self.completed]
        ttft = [c["ttft_s"] for c in self.completed
                if c["ttft_s"] is not None]
        busy = None
        if self._t_first_work is not None and self._t_last_done:
            busy = self._t_last_done - self._t_first_work

        def pct(vals, q):
            return float(np.percentile(vals, q)) if vals else None

        return {
            "requests": len(self.completed),
            "tokens": self._tokens_total,
            "steps": self._steps,
            "busy_s": busy,
            "tokens_per_s": (self._tokens_total / busy
                             if busy else None),
            "occupancy_mean": (self._occ_sum / self._steps
                               if self._steps else None),
            "queue_depth": self.queue.depth(),
            "kv_pages_in_use": self.cache.pages_in_use(),
            "kv_pages_total": self.cache.num_pages - 1,
            "draining": self.draining,
            "weight_version": self.weight_version,
            "manifest_sha": self.manifest_sha,
            "weight_swaps": self.swaps,
            "preemptions": int(self._preempt_counter._solo().value),
            "e2e_p50_s": pct(e2e, 50), "e2e_p99_s": pct(e2e, 99),
            "ttft_p50_s": pct(ttft, 50), "ttft_p99_s": pct(ttft, 99),
            "admission": self.admission,
            "int8": self.int8,
            "tp": self.tp,
            "decode_attn": self.decode_attn,
            "decode_bucket": self.decode_bucket,
            "decode_impl_by_bucket": dict(self._impl_by_bucket),
            "last_bucket_pages": self._last_bucket,
            "decode_ms_mean": (self._decode_ms_sum / self._steps
                               if self._steps else None),
            "decode_hbm_bytes_per_token":
                float(self._decode_bytes_gauge._solo().value)
                if self._steps else None,
        }


__all__ = ["LMEngine", "paged_decode_math"]
