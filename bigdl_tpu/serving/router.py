"""Multi-replica serving router — the fault-tolerant data plane.

One stdlib-HTTP tier (:class:`RouterServer`, in the style of
``obs/server.py``) in front of N :class:`~bigdl_tpu.serving.LMEngine`
replicas, built from four policies that also run standalone under the
serving chaos simulator (``bigdl_tpu/sim/serve.py``):

* **placement** (serving/placement.py) — session affinity keeps a
  multi-turn KV prefix resident; otherwise least-loaded by queue depth
  + router in-flight + KV-page pressure (the signals every replica
  already exports as ``bigdl_serve_queue_depth`` /
  ``bigdl_serve_kv_pages_in_use``);
* **bounded retries** (resilience/retry.py) — a transient replica
  failure (connection refused, timeout, queue-full 503) re-places the
  request on another replica after a jittered backoff, but only while
  the *shared* :class:`~bigdl_tpu.resilience.retry.RetryBudget` grants
  a token: budget exhausted means the fleet is browning out and more
  retries are amplification, so the request is shed with an explicit
  503 + ``Retry-After`` instead of queueing;
* **drain/handoff** (serving/drain.py) — ``begin_drain`` stops
  placements onto a replica, lets it finish in-flight decodes inside
  the drain deadline, and replays whatever it checkpointed elsewhere
  exactly once (claim-gated through the :class:`HandoffLedger`, so a
  replica dying mid-handoff cannot double-land a request);
* **telemetry** — the ``bigdl_router_*`` families in ``obs/names.py``.

Replicas are duck-typed (``generate`` / ``signals`` / ``drain``):
:class:`EngineReplica` wraps an in-process engine,
:class:`HTTPReplica` a remote :class:`~bigdl_tpu.serving.ServingServer`.
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from bigdl_tpu.obs import names, reqtrace
from bigdl_tpu.resilience.retry import RetryBudget, backoff_delay
from bigdl_tpu.serving import spans
from bigdl_tpu.serving.drain import (HANDOFF_ERROR, HandoffLedger,
                                     HandoffRecord)
from bigdl_tpu.serving.placement import (NoReplicaAvailable,
                                         PlacementPolicy, ReplicaView)

log = logging.getLogger("bigdl_tpu.serving")

_rids = itertools.count()


class ReplicaUnavailable(RuntimeError):
    """Transient replica failure — retry elsewhere (budget permitting)."""


class ReplicaDraining(RuntimeError):
    """The replica checkpointed this request mid-drain; ``handoff``
    carries the resume point."""

    def __init__(self, handoff: HandoffRecord):
        super().__init__(f"checkpointed by draining replica "
                         f"{handoff.source}")
        self.handoff = handoff


class RouterShed(RuntimeError):
    """Load shed: retry budget exhausted or no eligible replica.  The
    HTTP tier maps this to 503 + ``Retry-After``; ``budget`` (the
    shared retry budget's stats snapshot, when the router had one)
    rides the 503 body so clients can see *why* they were shed."""

    def __init__(self, reason: str, retry_after_s: float = 1.0,
                 budget: Optional[dict] = None):
        super().__init__(reason)
        self.retry_after_s = float(retry_after_s)
        self.budget = budget


def _claim_key(hd: HandoffRecord) -> str:
    """Exactly-once claim key for one handoff *event*: the same record
    surfacing on two recovery paths (per-request retry loop vs the
    drain sweep) builds the same key, while a later re-handoff of the
    same request (longer refolded prompt) builds a fresh one."""
    return f"{hd.request_id}@{hd.source}#{len(hd.prompt)}"


# ---------------------------------------------------------------- replicas
class EngineReplica:
    """In-process replica: one LMEngine (started or pumped by tests)."""

    def __init__(self, name: str, engine):
        self.name = str(name)
        self.engine = engine

    def generate(self, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, timeout_s: float = 30.0,
                 request_id: Optional[str] = None,
                 trace=None) -> dict:
        try:
            req = self.engine.submit(prompt, max_new_tokens,
                                     temperature=temperature,
                                     timeout=timeout_s, trace=trace)
        except TimeoutError as e:       # queue full past the timeout
            raise ReplicaUnavailable(f"{self.name}: {e}") from e
        except RuntimeError as e:       # draining / closed queue
            raise ReplicaUnavailable(f"{self.name}: {e}") from e
        req.router_id = request_id
        try:
            req.wait(timeout_s)
        except TimeoutError as e:
            raise ReplicaUnavailable(f"{self.name}: {e}") from e
        if req.error == HANDOFF_ERROR:
            ctx = getattr(req, "trace", None)
            raise ReplicaDraining(HandoffRecord(
                prompt=[int(t) for t in req.payload],
                max_new_tokens=int(req.max_new_tokens),
                temperature=float(req.temperature),
                tokens_done=[int(t) for t in req.tokens],
                request_id=request_id, source=self.name,
                trace=ctx.to_header() if ctx is not None else None,
                weight_version=getattr(self.engine, "weight_version",
                                       None)))
        if req.error:
            raise ReplicaUnavailable(f"{self.name}: {req.error}")
        return {"tokens": [int(t) for t in req.tokens],
                "ttft_s": req.ttft_s, "e2e_s": req.e2e_s}

    def signals(self) -> dict:
        eng = self.engine
        total = max(1, eng.cache.num_pages - 1)
        return {"up": True, "draining": bool(eng.draining),
                "queue_depth": float(eng.queue.depth()),
                "kv_frac": eng.cache.pages_in_use() / total,
                "weight_version": getattr(eng, "weight_version", None)}

    def drain(self, deadline_s: float = 10.0) -> List[HandoffRecord]:
        records = self.engine.drain(deadline_s)
        for hd in records:
            hd.source = self.name
        return records

    def undrain(self):
        self.engine.draining = False


class HTTPReplica:
    """Remote replica behind a :class:`~bigdl_tpu.serving.ServingServer`
    (``fetch`` is injectable for tests — same seam as FleetAggregator)."""

    def __init__(self, name: str, base_url: str, fetch=None):
        self.name = str(name)
        self.base = base_url.rstrip("/")
        if not self.base.startswith("http"):
            self.base = "http://" + self.base
        self._fetch = fetch or self._http_fetch

    def _http_fetch(self, url: str, body: Optional[dict] = None,
                    timeout_s: float = 30.0,
                    headers: Optional[dict] = None):
        import urllib.error
        import urllib.request

        data = None if body is None else json.dumps(body).encode()
        hdrs = {"Content-Type": "application/json"} if data else {}
        hdrs.update(headers or {})
        req = urllib.request.Request(url, data=data, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001 — a torn error body is data
                payload = {}
            return e.code, payload
        except Exception as e:  # noqa: BLE001 — transport error
            raise ReplicaUnavailable(f"{self.name}: {type(e).__name__}: "
                                     f"{e}") from e

    def generate(self, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, timeout_s: float = 30.0,
                 request_id: Optional[str] = None,
                 trace=None) -> dict:
        # the trace context crosses the hop as the X-Bigdl-Trace
        # header; the kwarg reaches the injectable fetch seam only when
        # a context exists, so untraced runs hit test fakes unchanged
        kw = {"timeout_s": timeout_s}
        if trace is not None:
            kw["headers"] = {reqtrace.TRACE_HEADER: trace.to_header()}
        status, out = self._fetch(
            self.base + "/v1/generate",
            {"prompt": [int(t) for t in prompt],
             "max_new_tokens": int(max_new_tokens),
             "temperature": float(temperature),
             "request_id": request_id},
            **kw)
        if status == 200:
            return {"tokens": [int(t) for t in out["tokens"]],
                    "ttft_s": out.get("ttft_s"),
                    "e2e_s": out.get("e2e_s")}
        if status == 503 and isinstance(out.get("handoff"), dict):
            hd = HandoffRecord.from_dict(out["handoff"])
            hd.request_id, hd.source = request_id, self.name
            raise ReplicaDraining(hd)
        if status in (429, 500, 502, 503, 504):
            raise ReplicaUnavailable(
                f"{self.name}: HTTP {status}: {out.get('error')}")
        raise ValueError(f"{self.name}: HTTP {status}: "
                         f"{out.get('error')}")

    def signals(self) -> dict:
        status, out = self._fetch(self.base + "/stats", timeout_s=2.0)
        if status != 200:
            raise ReplicaUnavailable(f"{self.name}: stats HTTP {status}")
        lm = (out or {}).get("lm") or {}
        total = max(1, int(lm.get("kv_pages_total") or 1))
        return {"up": True, "draining": bool(lm.get("draining")),
                "queue_depth": float(lm.get("queue_depth") or 0.0),
                "kv_frac": float(lm.get("kv_pages_in_use") or 0.0)
                / total,
                "weight_version": lm.get("weight_version")}

    def drain(self, deadline_s: float = 10.0) -> List[HandoffRecord]:
        status, out = self._fetch(self.base + "/admin/drain",
                                  {"deadline_s": float(deadline_s)},
                                  timeout_s=deadline_s + 10.0)
        if status != 200:
            raise ReplicaUnavailable(f"{self.name}: drain HTTP {status}")
        records = [HandoffRecord.from_dict(d)
                   for d in out.get("handoffs") or []]
        for hd in records:
            hd.source = self.name
        return records


# ------------------------------------------------------------------ router
class Router:
    """Placement + budgeted retry + drain/handoff over N replicas."""

    def __init__(self, replicas=None, *,
                 affinity_ttl_s: Optional[float] = None,
                 kv_weight: Optional[float] = None,
                 retry_budget_ratio: Optional[float] = None,
                 retry_budget_burst: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 drain_deadline_s: Optional[float] = None,
                 backoff_base_s: Optional[float] = None,
                 retry_after_s: Optional[float] = None,
                 clock=time.monotonic, sleep=time.sleep, seed: int = 0):
        from bigdl_tpu.config import refresh_from_env

        full = refresh_from_env()
        cfg = full.router
        pick = lambda v, d: d if v is None else v  # noqa: E731
        # skewed-clock routing: a replica whose exported host staleness
        # exceeds the fleet threshold is excluded from placement
        self.stale_exclude = bool(cfg.stale_exclude)
        self.stale_after_s = float(full.obs.stale_after_s)
        self.max_retries = int(pick(max_retries, cfg.max_retries))
        self.request_timeout_s = float(
            pick(request_timeout_s, cfg.request_timeout_s))
        self.drain_deadline_s = float(
            pick(drain_deadline_s, cfg.drain_deadline_s))
        self.backoff_base_s = float(
            pick(backoff_base_s, cfg.backoff_base_s))
        self.retry_after_s = float(pick(retry_after_s, cfg.retry_after_s))
        self.placement = PlacementPolicy(
            affinity_ttl_s=float(pick(affinity_ttl_s, cfg.affinity_ttl_s)),
            kv_weight=float(pick(kv_weight, cfg.kv_weight)), clock=clock)
        self.budget = RetryBudget(
            ratio=float(pick(retry_budget_ratio, cfg.retry_budget_ratio)),
            burst=float(pick(retry_budget_burst, cfg.retry_budget_burst)))
        self.ledger = HandoffLedger()
        self._clock = clock
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.replicas: Dict[str, object] = {}
        self._in_flight: Dict[str, int] = {}
        self._down: set = set()
        self._draining: set = set()
        for r in (replicas or []):
            self.add_replica(r)

        from bigdl_tpu import obs

        reg = obs.get_registry()
        self._req_counter = reg.counter(
            names.ROUTER_REQUESTS_TOTAL,
            "Routed requests by final outcome", labels=("outcome",))
        self._retry_counter = reg.counter(
            names.ROUTER_RETRIES_TOTAL,
            "Budget-gated re-placements after transient replica "
            "failures")
        self._shed_counter = reg.counter(
            names.ROUTER_SHED_TOTAL,
            "Requests shed 503 + Retry-After (budget exhausted or no "
            "eligible replica)")
        self._handoff_counter = reg.counter(
            names.ROUTER_HANDOFFS_TOTAL,
            "Checkpointed decodes replayed off draining replicas")
        self._drain_counter = reg.counter(
            names.ROUTER_DRAINS_TOTAL,
            "Replica drain cycles completed")
        self._affinity_counter = reg.counter(
            names.ROUTER_AFFINITY_HITS_TOTAL,
            "Placements that kept a session on its bound replica")
        self._replica_gauge = reg.gauge(
            names.ROUTER_REPLICAS,
            "Replicas by router-observed state", labels=("state",))
        self._budget_gauge = reg.gauge(
            names.ROUTER_RETRY_BUDGET_TOKENS,
            "Tokens left in the shared retry-budget bucket")
        self._stale_counter = reg.counter(
            names.ROUTER_STALE_EXCLUDED_TOTAL,
            "Placement snapshots that excluded a replica for host-"
            "clock staleness past the fleet threshold")
        self._mismatch_counter = reg.counter(
            names.ROLLOUT_VERSION_MISMATCH_TOTAL,
            "Handoff replays refused on a weight-version mismatch "
            "(re-queued toward a version-exact replica)")

    # -------------------------------------------------------- replica set
    def add_replica(self, replica) -> None:
        with self._lock:
            self.replicas[replica.name] = replica
            self._in_flight.setdefault(replica.name, 0)
            self._down.discard(replica.name)
            self._draining.discard(replica.name)

    def remove_replica(self, name: str) -> List[str]:
        """Drop a replica (death, deprovision).  Returns the sessions
        whose affinity binding was torn up — they rebind on their next
        request."""
        with self._lock:
            self.replicas.pop(name, None)
            self._in_flight.pop(name, None)
            self._down.discard(name)
            self._draining.discard(name)
        return self.placement.unbind_replica(name)

    def _note(self, name: str, delta: int) -> None:
        with self._lock:
            if name in self._in_flight:
                self._in_flight[name] = max(
                    0, self._in_flight[name] + delta)

    def views(self) -> Dict[str, ReplicaView]:
        """One placement snapshot: each replica's exported signals
        merged with the router's own in-flight counts and drain/down
        marks.  A replica whose signals probe fails is scored down
        (and recovers the moment a probe succeeds again)."""
        with self._lock:
            replicas = dict(self.replicas)
            in_flight = dict(self._in_flight)
            draining = set(self._draining)
            down = set(self._down)
        views: Dict[str, ReplicaView] = {}
        for name, replica in replicas.items():
            try:
                sig = replica.signals()
            except Exception:  # noqa: BLE001 — a dead replica is data
                views[name] = ReplicaView(name, up=False)
                with self._lock:
                    self._down.add(name)
                continue
            with self._lock:
                self._down.discard(name)
            stale = (self.stale_exclude and self.stale_after_s > 0
                     and float(sig.get("staleness_s") or 0.0)
                     > self.stale_after_s)
            if stale:
                self._stale_counter.inc()
            views[name] = ReplicaView(
                name, up=bool(sig.get("up", True)) and name not in down,
                draining=bool(sig.get("draining")) or name in draining,
                queue_depth=float(sig.get("queue_depth") or 0.0),
                in_flight=int(in_flight.get(name, 0)),
                kv_frac=float(sig.get("kv_frac") or 0.0),
                stale=stale,
                version=sig.get("weight_version"))
        counts = {"up": 0, "draining": 0, "down": 0, "stale": 0}
        for v in views.values():
            counts["down" if not v.up else
                   "draining" if v.draining else
                   "stale" if v.stale else "up"] += 1
        for state, n in counts.items():
            self._replica_gauge.labels(state=state).set(float(n))
        return views

    # ------------------------------------------------------------ routing
    def _shed(self, rid: str, reason: str, ctx=None):
        self._shed_counter.inc()
        self._req_counter.labels(outcome="shed").inc()
        if ctx is not None:
            reqtrace.get_collector().finish(
                ctx, request=rid, error=f"shed: {reason}")
        raise RouterShed(reason, retry_after_s=self.retry_after_s,
                         budget=self.budget.stats())

    def route(self, prompt, max_new_tokens: int, *,
              temperature: float = 0.0, session: Optional[str] = None,
              request_id: Optional[str] = None, trace=None) -> dict:
        """Route one request to completion.  Returns ``{id, tokens,
        replica, retries, handoffs}``; raises :class:`RouterShed` when
        load must be shed, ValueError on a fatal client error."""
        rid = request_id or f"r{next(_rids)}"
        col = reqtrace.get_collector()
        ctx = trace
        if col.enabled:
            if ctx is None:
                ctx = col.new_context()
            col.begin(ctx)
        else:
            ctx = None
        t_route = time.monotonic()
        self.budget.record_request()
        self._budget_gauge.set(self.budget.tokens())
        prompt_cur = [int(t) for t in prompt]
        owed = int(max_new_tokens)
        prefix: List[int] = []
        tried: set = set()
        retries = 0
        handoffs = 0
        pinned: Optional[str] = None   # weight version a handoff pinned
        affinity0 = self.placement.affinity_hits
        while True:
            t_place = time.monotonic()
            views = self.views()
            try:
                name = self.placement.choose(views, session,
                                             exclude=tried)
            except NoReplicaAvailable as e:
                self._shed(rid, str(e), ctx)
            if pinned is not None:
                view = views.get(name)
                if view is not None and view.version is not None \
                        and view.version != pinned:
                    # the absorber serves a different weight version
                    # than the checkpointed prefix was decoded under —
                    # replaying here would break the bit-equal replay
                    # contract.  Refuse and re-queue toward a
                    # version-exact replica.
                    self._mismatch_counter.inc()
                    tried.add(name)
                    continue
            col.span(ctx, spans.SPAN_PLACEMENT, t_place,
                     time.monotonic() - t_place, replica=name,
                     attempt=retries + handoffs)
            if self.placement.affinity_hits > affinity0:
                affinity0 = self.placement.affinity_hits
                self._affinity_counter.inc()
            with self._lock:
                replica = self.replicas.get(name)
            if replica is None:
                tried.add(name)
                continue
            self._note(name, +1)
            try:
                kw = {} if ctx is None else {"trace": ctx}
                out = replica.generate(
                    prompt_cur, owed, temperature=temperature,
                    timeout_s=self.request_timeout_s, request_id=rid,
                    **kw)
            except ReplicaDraining as e:
                hd = e.handoff
                if not self.ledger.claim(_claim_key(hd)):
                    # another recovery path already replays this
                    # checkpoint — standing down is what keeps the
                    # request landing exactly once
                    self._req_counter.labels(outcome="failed").inc()
                    if ctx is not None:
                        col.finish(ctx, request=rid,
                                   error=f"shed: request {rid} already "
                                         f"replayed elsewhere",
                                   handoff=True)
                    raise RouterShed(
                        f"request {rid} already replayed elsewhere",
                        retry_after_s=self.retry_after_s,
                        budget=self.budget.stats()) from e
                if ctx is not None:
                    # handoffs are exactly what tail sampling must
                    # keep — force the decision before the replay hop
                    ctx.keep = True
                    col.span(ctx, spans.SPAN_HANDOFF,
                             time.monotonic(), 0.0, source=name,
                             tokens_done=len(hd.tokens_done),
                             owed=int(hd.max_new_tokens),
                             side="router")
                prefix.extend(hd.tokens_done)
                prompt_cur = list(hd.prompt)
                owed = int(hd.max_new_tokens)
                pinned = hd.weight_version or pinned
                handoffs += 1
                self._handoff_counter.inc()
                with self._lock:
                    self._draining.add(name)
                self.placement.unbind_replica(name)
                tried = set()       # fresh placement epoch post-handoff
                continue
            except ReplicaUnavailable:
                tried.add(name)
                with self._lock:
                    self._down.add(name)
                if retries >= self.max_retries:
                    self._req_counter.labels(outcome="failed").inc()
                    self._shed(rid, f"request {rid}: "
                                    f"{retries + 1} attempts failed",
                               ctx)
                if not self.budget.try_spend():
                    self._budget_gauge.set(self.budget.tokens())
                    self._shed(rid, "retry budget exhausted — fleet is "
                                    "browning out", ctx)
                retries += 1
                self._retry_counter.inc()
                self._budget_gauge.set(self.budget.tokens())
                t_retry = time.monotonic()
                delay = backoff_delay(
                    retries, base=self.backoff_base_s, cap=1.0,
                    rng=self._rng)
                self._sleep(delay)
                if ctx is not None:
                    # a retried request is an anomaly: keep its trace
                    ctx.keep = True
                    col.span(ctx, spans.SPAN_RETRY, t_retry, delay,
                             replica=name, attempt=retries,
                             budget_tokens=round(
                                 self.budget.tokens(), 2))
                continue
            finally:
                self._note(name, -1)
            tokens = prefix + out["tokens"]
            self.ledger.deliver(rid)
            self._req_counter.labels(outcome="ok").inc()
            resp = {"id": rid, "tokens": tokens, "replica": name,
                    "retries": retries, "handoffs": handoffs,
                    "ttft_s": out.get("ttft_s"),
                    "e2e_s": out.get("e2e_s")}
            if ctx is not None:
                col.span(ctx, spans.SPAN_ROUTE, t_route,
                         time.monotonic() - t_route, replica=name,
                         retries=retries, handoffs=handoffs)
                col.finish(ctx, request=rid, retries=retries,
                           handoff=handoffs > 0,
                           e2e_s=time.monotonic() - t_route)
                resp["trace"] = ctx.trace_id
            return resp

    # -------------------------------------------------------------- drain
    def begin_drain(self, name: str,
                    deadline_s: Optional[float] = None) -> dict:
        """Drain one replica: placements stop immediately, the replica
        finishes what it can inside the deadline, and checkpointed
        router-owned requests replay through their own waiting route()
        calls (claim-gated).  Orphan checkpoints (submitted to the
        replica directly, not through this router) are returned for
        the operator — the router has no client to answer for them."""
        with self._lock:
            replica = self.replicas.get(name)
            if replica is None:
                raise KeyError(f"unknown replica {name!r}")
            self._draining.add(name)
        sessions = self.placement.unbind_replica(name)
        records = replica.drain(deadline_s if deadline_s is not None
                                else self.drain_deadline_s)
        self._drain_counter.inc()
        owned = [hd for hd in records if hd.request_id is not None]
        orphans = [hd.to_dict() for hd in records
                   if hd.request_id is None]
        return {"replica": name, "handoffs": len(records),
                "router_owned": len(owned), "orphans": orphans,
                "sessions_unbound": len(sessions)}

    def undrain(self, name: str) -> None:
        with self._lock:
            replica = self.replicas.get(name)
            self._draining.discard(name)
        if replica is not None and hasattr(replica, "undrain"):
            replica.undrain()

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        views = self.views()
        return {
            "replicas": {n: _view_dict(v)
                         for n, v in sorted(views.items())},
            "budget": self.budget.stats(),
            "placement": self.placement.stats(),
            "ledger": self.ledger.stats(),
        }


def _view_dict(v: ReplicaView) -> dict:
    return {"up": v.up, "draining": v.draining,
            "queue_depth": v.queue_depth, "in_flight": v.in_flight,
            "kv_frac": round(v.kv_frac, 4), "stale": v.stale,
            "weight_version": v.version}


# ------------------------------------------------------------- HTTP front
class RouterServer:
    """stdlib HTTP front-end for :class:`Router` (obs/server.py style).

    * ``POST /v1/generate`` ``{"prompt": [...], "max_new_tokens": N,
      "temperature": t, "session": "abc"}`` — routed, retried,
      handed off as needed; sheds with 503 + ``Retry-After``;
    * ``POST /admin/drain`` ``{"replica": name, "deadline_s": s}``;
    * ``GET /stats`` / ``GET /healthz``.
    """

    def __init__(self, router: Router, *, port: Optional[int] = None,
                 host: str = "127.0.0.1"):
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().router
        if port is None:
            port = cfg.port if cfg.port is not None else 0
        self.router = router
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                log.debug("router: " + fmt, *args)

            def _send(self, obj, code=200, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    views = outer.router.views()
                    return self._send({
                        "status": "ok",
                        "replicas": {n: ("draining" if v.draining
                                         else "stale" if v.stale
                                         else "up" if v.up else "down")
                                     for n, v in views.items()},
                        "weight_versions": {n: v.version
                                            for n, v in views.items()}})
                if self.path == "/stats":
                    return self._send(outer.router.stats())
                return self._send({"error": "not found"}, 404)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0) or 0)
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/v1/generate":
                        # a traced upstream hands us its context in the
                        # X-Bigdl-Trace header; otherwise route() mints
                        # one itself when the collector is on
                        ctx = None
                        if reqtrace.get_collector().enabled:
                            ctx = reqtrace.RequestTraceContext \
                                .from_header(self.headers.get(
                                    reqtrace.TRACE_HEADER))
                        out = outer.router.route(
                            payload["prompt"],
                            int(payload.get("max_new_tokens", 16)),
                            temperature=float(
                                payload.get("temperature", 0.0)),
                            session=payload.get("session"),
                            trace=ctx)
                        return self._send(out)
                    if self.path == "/admin/drain":
                        return self._send(outer.router.begin_drain(
                            payload["replica"],
                            deadline_s=payload.get("deadline_s")))
                    return self._send({"error": "not found"}, 404)
                except RouterShed as e:
                    # the shed body carries the retry-budget snapshot
                    # so a shed client can tell "replica brownout"
                    # from "I personally retried too much"
                    body = {"error": str(e),
                            "retry_after_s": e.retry_after_s}
                    if e.budget is not None:
                        body["retry_budget"] = e.budget
                    return self._send(
                        body, 503,
                        headers={"Retry-After":
                                 f"{max(1, round(e.retry_after_s))}"})
                except KeyError as e:
                    return self._send(
                        {"error": f"missing field {e}"}, 400)
                except (TypeError, ValueError) as e:
                    return self._send(
                        {"error": f"{type(e).__name__}: {e}"}, 400)
                except Exception as e:  # noqa: BLE001 — router bug
                    return self._send(
                        {"error": f"{type(e).__name__}: {e}"}, 500)

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="bigdl-router-http", daemon=True)
        self._thread.start()
        log.info("serving router on %s:%d over %d replica(s)",
                 host, self.port, len(router.replicas))

    def url(self, path: str = "/stats") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


__all__ = ["EngineReplica", "HTTPReplica", "ReplicaDraining",
           "ReplicaUnavailable", "Router", "RouterServer", "RouterShed"]
