"""Continuous-batching inference serving tier (ISSUE 12).

The trained models become a traffic-serving system: a paged KV cache
with per-request page tables (serving/cache.py), a continuous-batching
decode engine that admits requests at step boundaries instead of
waiting for a batch to drain (serving/engine.py), a bounded request
queue riding the streaming tier's backpressure machinery
(serving/batcher.py), a micro-batching classifier engine on the
existing int8 ``quantize()`` path (serving/classifier.py), optional
TP-sharded decode over the compressed-collective wire (serving/tp.py),
and a stdlib HTTP front-end (serving/server.py).

The fault-tolerant data plane (ISSUE 16) stacks a router tier on top:
session-affine, KV-pressure-aware placement over N replicas
(serving/placement.py), graceful drain with exactly-once handoff
(serving/drain.py), and the router + its stdlib HTTP front-end with
budget-gated retries and explicit 503 + Retry-After load shedding
(serving/router.py).

End-to-end request tracing (ISSUE 17) threads one trace_id through
every hop of that stack — router placement/retry/handoff, engine
queue/prefill/preempt/decode — across process boundaries on the
``X-Bigdl-Trace`` header, with tail-based sampling that always keeps
anomalous requests (obs/reqtrace.py; span names are the constants in
serving/spans.py, enforced by graftlint RD006).

The loop closes through the observability planes: request-latency
histograms with trace exemplars + SLO burn-rate alerting
(obs/alerts.py), "serving" and "request traces" report sections
(obs/report.py), and request-driven autoscaling signals — queue depth
and p99 — in resilience/autoscale.py.

Live weight rollout (ISSUE 20) closes the training->serving pipe:
a checkpoint watcher hot-swaps manifest-verified weights into a live
engine between decode steps, and a canary controller promotes new
versions to a fraction of replicas with SLO-burn/divergence
auto-rollback (serving/rollout.py).
"""

from bigdl_tpu.serving import spans
from bigdl_tpu.serving.batcher import RequestQueue, ServeRequest
from bigdl_tpu.serving.cache import PagedKVCache, gather_pages
from bigdl_tpu.serving.classifier import ClassifierEngine
from bigdl_tpu.serving.drain import (HANDOFF_ERROR, HandoffLedger,
                                     HandoffRecord, drain_engine)
from bigdl_tpu.serving.engine import LMEngine
from bigdl_tpu.serving.placement import (NoReplicaAvailable,
                                         PlacementPolicy, ReplicaView)
from bigdl_tpu.serving.rollout import (CanaryController, CheckpointWatcher,
                                       publish_checkpoint, token_divergence)
from bigdl_tpu.serving.router import (EngineReplica, HTTPReplica,
                                      ReplicaDraining, ReplicaUnavailable,
                                      Router, RouterServer, RouterShed)
from bigdl_tpu.serving.server import ServingServer

__all__ = [
    "CanaryController",
    "CheckpointWatcher",
    "ClassifierEngine",
    "EngineReplica",
    "HANDOFF_ERROR",
    "HTTPReplica",
    "HandoffLedger",
    "HandoffRecord",
    "LMEngine",
    "NoReplicaAvailable",
    "PagedKVCache",
    "PlacementPolicy",
    "ReplicaDraining",
    "ReplicaUnavailable",
    "ReplicaView",
    "RequestQueue",
    "Router",
    "RouterServer",
    "RouterShed",
    "ServeRequest",
    "ServingServer",
    "drain_engine",
    "gather_pages",
    "publish_checkpoint",
    "spans",
    "token_divergence",
]
