"""Keras-style layers with shape inference.

Rebuild of «bigdl»/nn/keras/ — each layer mirrors the Keras-1.2.2
constructor surface («py»/nn/keras/layer.py spellings), infers its
output shape from the input shape (batch dim excluded, like the
reference's ``Shape``), and *builds* a core bigdl_tpu.nn module once the
input shape is known.  Image layout is NCHW ("th" dim ordering, the
reference's default).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.nn import layers as L
from bigdl_tpu.nn import module as M
from bigdl_tpu.nn import recurrent as R


__all__ = [
    "KerasLayer", "InputLayer", "Dense", "Activation", "Dropout",
    "Flatten", "Reshape", "Permute", "RepeatVector", "Convolution2D",
    "MaxPooling2D", "AveragePooling2D", "GlobalAveragePooling2D",
    "GlobalMaxPooling2D", "ZeroPadding2D", "BatchNormalization",
    "Embedding", "LSTM", "GRU", "SimpleRNN", "Bidirectional",
    "TimeDistributedDense",
]

_ACTIVATIONS = {
    "relu": L.ReLU,
    "tanh": L.Tanh,
    "sigmoid": L.Sigmoid,
    "hard_sigmoid": L.HardSigmoid,
    "softmax": L.SoftMax,
    "log_softmax": L.LogSoftMax,
    "softplus": L.SoftPlus,
    "softsign": L.SoftSign,
    "elu": L.ELU,
    "linear": M.Identity,
}


def _activation_module(name):
    if name is None or name == "linear":
        return None
    if isinstance(name, str):
        return _ACTIVATIONS[name]()
    return name


class KerasLayer:
    """Base: ``build(input_shape) -> core module`` +
    ``compute_output_shape(input_shape)``; shapes are tuples WITHOUT the
    batch dim (reference Shape semantics)."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None, name=None):
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name
        self.output_shape: Optional[Tuple[int, ...]] = None
        self.core = None

    def build(self, input_shape):
        raise NotImplementedError

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    def _built(self, input_shape):
        self.core = self.build(tuple(input_shape))
        if self.name:
            self.core.set_name(self.name)
        self.output_shape = self.compute_output_shape(tuple(input_shape))
        return self.core


class InputLayer(KerasLayer):
    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape, name)

    def build(self, input_shape):
        return M.Identity()


class Dense(KerasLayer):
    """keras.layers.Dense — W x + b with optional activation."""

    def __init__(self, output_dim: int, activation=None, input_dim=None,
                 input_shape=None, b_regularizer=None, W_regularizer=None,
                 bias=True, name=None):
        if input_dim is not None:
            input_shape = (input_dim,)
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias
        self.W_regularizer, self.b_regularizer = W_regularizer, b_regularizer

    def build(self, input_shape):
        core = M.Sequential()
        core.add(L.Linear(int(input_shape[-1]), self.output_dim,
                          with_bias=self.bias,
                          w_regularizer=self.W_regularizer,
                          b_regularizer=self.b_regularizer))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.activation = activation

    def build(self, input_shape):
        return _activation_module(self.activation) or M.Identity()


class Dropout(KerasLayer):
    def __init__(self, p: float, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.p = p

    def build(self, input_shape):
        return L.Dropout(self.p)


class Flatten(KerasLayer):
    def build(self, input_shape):
        return L.Reshape([int(np.prod(input_shape))], batch_mode=True)

    def compute_output_shape(self, input_shape):
        return (int(np.prod(input_shape)),)


class Reshape(KerasLayer):
    def __init__(self, target_shape, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        return L.Reshape(list(self.target_shape), batch_mode=True)

    def compute_output_shape(self, input_shape):
        return self.target_shape


class Permute(KerasLayer):
    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.dims = tuple(dims)  # 1-based over non-batch dims (keras)

    def build(self, input_shape):
        # express permutation as a sequence of swaps on 1-based dims
        # counting the batch dim (core Transpose convention)
        perm = [d + 1 for d in self.dims]
        current = list(range(2, len(self.dims) + 2))
        swaps = []
        for i, want in enumerate(perm):
            j = current.index(want)
            if j != i:
                swaps.append((i + 2, j + 2))
                current[i], current[j] = current[j], current[i]
        return L.Transpose(swaps) if swaps else M.Identity()

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[d - 1] for d in self.dims)


class RepeatVector(KerasLayer):
    def __init__(self, n: int, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.n = n

    def build(self, input_shape):
        # dim counts the batch dim (core convention): insert at dim 2
        return L.Replicate(self.n, dim=2)

    def compute_output_shape(self, input_shape):
        return (self.n,) + tuple(input_shape)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class Convolution2D(KerasLayer):
    """keras.layers.Convolution2D — NCHW ("th") layout."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, border_mode: str = "valid",
                 subsample=(1, 1), input_shape=None, bias=True,
                 W_regularizer=None, b_regularizer=None, name=None):
        super().__init__(input_shape, name)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.bias = bias
        self.W_regularizer, self.b_regularizer = W_regularizer, b_regularizer

    def build(self, input_shape):
        n_in = int(input_shape[0])
        if self.border_mode == "same":
            pw = ph = -1
        else:
            pw = ph = 0
        core = M.Sequential()
        core.add(L.SpatialConvolution(
            n_in, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], pw, ph,
            with_bias=self.bias, w_regularizer=self.W_regularizer,
            b_regularizer=self.b_regularizer,
        ))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        if self.border_mode == "same":
            oh = -(-h // sh)
            ow = -(-w // sw)
        else:
            oh = (h - self.nb_row) // sh + 1
            ow = (w - self.nb_col) // sw + 1
        return (self.nb_filter, oh, ow)


class MaxPooling2D(KerasLayer):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides else self.pool_size
        self.border_mode = border_mode

    def _core_cls(self):
        return L.SpatialMaxPooling

    def build(self, input_shape):
        ph, pw = self.pool_size
        sh, sw = self.strides
        if self._core_cls() is L.SpatialMaxPooling:
            return L.SpatialMaxPooling(pw, ph, sw, sh)
        return L.SpatialAveragePooling(pw, ph, sw, sh)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        ph, pw = self.pool_size
        sh, sw = self.strides
        return (c, (h - ph) // sh + 1, (w - pw) // sw + 1)


class AveragePooling2D(MaxPooling2D):
    def _core_cls(self):
        return L.SpatialAveragePooling


class GlobalAveragePooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = (int(s) for s in input_shape)
        return M.Sequential() \
            .add(L.SpatialAveragePooling(w, h, 1, 1)) \
            .add(L.Reshape([c], batch_mode=True))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class GlobalMaxPooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = (int(s) for s in input_shape)
        return M.Sequential() \
            .add(L.SpatialMaxPooling(w, h, 1, 1)) \
            .add(L.Reshape([c], batch_mode=True))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.padding = _pair(padding)

    def build(self, input_shape):
        ph, pw = self.padding
        return L.SpatialZeroPadding(pw, pw, ph, ph)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h + 2 * self.padding[0], w + 2 * self.padding[1])


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon=1e-3, momentum=0.99, axis=1,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.epsilon = epsilon
        self.momentum = momentum
        # keras-1.2.2 "th" models normalize the channel axis (1); any
        # other axis would need a transpose sandwich — reject loudly
        if axis not in (1, -1):
            raise ValueError(f"BatchNormalization axis {axis} unsupported")
        self.axis = axis

    def build(self, input_shape):
        # keras momentum is the running-average keep rate; the core layer
        # uses the update rate
        update = 1.0 - self.momentum
        if len(input_shape) == 3:
            if self.axis == -1:
                raise ValueError(
                    "BatchNormalization axis=-1 on an image tensor "
                    "implies tf dim_ordering — unsupported")
            return L.SpatialBatchNormalization(int(input_shape[0]),
                                               eps=self.epsilon,
                                               momentum=update)
        return L.BatchNormalization(int(input_shape[-1]), eps=self.epsilon,
                                    momentum=update)


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int, input_length=None,
                 input_shape=None, name=None):
        if input_shape is None and input_length is not None:
            input_shape = (input_length,)
        super().__init__(input_shape, name)
        self.input_dim, self.output_dim = input_dim, output_dim

    def build(self, input_shape):
        # keras indices are 0-based; core LookupTable is 1-based
        return M.Sequential() \
            .add(L.AddConstant(1.0)) \
            .add(L.LookupTable(self.input_dim, self.output_dim))

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _KerasRecurrent(KerasLayer):
    cell_cls = None

    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 input_shape=None, input_dim=None, input_length=None,
                 stateful=False, go_backwards=False,
                 dropout_W=0.0, dropout_U=0.0,
                 W_regularizer=None, U_regularizer=None, b_regularizer=None,
                 name=None):
        if input_shape is None and input_dim is not None:
            input_shape = (input_length, input_dim)
        super().__init__(input_shape, name)
        if stateful:
            # cross-batch carried state needs a stateful recurrence the
            # jit-pure Recurrent deliberately does not keep; fail loudly
            # rather than silently resetting state every batch
            raise ValueError(
                "stateful=True recurrent layers are not supported: the "
                "recurrence is jit-pure and resets state per batch "
                "(Keras-1.2.2 stateful semantics carry it across batches)")
        if dropout_U:
            raise ValueError(
                "dropout_U (recurrent-state dropout) is not supported; "
                "dropout_W maps to the cell's per-gate input dropout")
        self.output_dim = output_dim
        self.activation = activation
        self.inner_activation = inner_activation
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.dropout_W = dropout_W
        self.W_regularizer = W_regularizer
        self.U_regularizer = U_regularizer
        self.b_regularizer = b_regularizer

    def _cell(self, n_in):
        raise NotImplementedError

    def build(self, input_shape):
        n_in = int(input_shape[-1])
        core = M.Sequential()
        if self.go_backwards:
            # Keras-1.2.2 go_backwards: iterate the sequence reversed;
            # returned sequences stay in PROCESSING order (keras does
            # not re-flip them), so one time-axis Reverse before the
            # scan reproduces both return_sequences modes
            from bigdl_tpu.nn.layers_extra import Reverse as _Rev

            core.add(_Rev(2))
        core.add(R.Recurrent().add(self._cell(n_in)))
        if not self.return_sequences:
            core.add(R.Select(2, -1))
        return core

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], self.output_dim)
        return (self.output_dim,)


class LSTM(_KerasRecurrent):
    def _cell(self, n_in):
        return R.LSTM(n_in, self.output_dim, p=self.dropout_W,
                      activation=_activation_module(self.activation),
                      inner_activation=_activation_module(self.inner_activation),
                      w_regularizer=self.W_regularizer,
                      u_regularizer=self.U_regularizer,
                      b_regularizer=self.b_regularizer)


class GRU(_KerasRecurrent):
    def _cell(self, n_in):
        return R.GRU(n_in, self.output_dim, p=self.dropout_W,
                     activation=_activation_module(self.activation),
                     inner_activation=_activation_module(self.inner_activation),
                     w_regularizer=self.W_regularizer,
                     u_regularizer=self.U_regularizer,
                     b_regularizer=self.b_regularizer)


class SimpleRNN(_KerasRecurrent):
    def _cell(self, n_in):
        return R.RnnCell(n_in, self.output_dim,
                         activation=_activation_module(self.activation)
                         or L.Tanh())


class Bidirectional(KerasLayer):
    """keras.layers.wrappers.Bidirectional.

    ``BiRecurrent`` emits the last-dim CONCAT of the forward pass and
    the (re-flipped to input order) backward pass.  Keras semantics:

    * ``return_sequences=False`` takes each direction's FINAL state —
      forward's sits at the last timestep, backward's at the FIRST
      (it consumed the sequence reversed);
    * non-concat ``merge_mode`` (sum/mul/ave) combines the two halves
      elementwise.
    """

    def __init__(self, layer: _KerasRecurrent, merge_mode="concat",
                 input_shape=None, name=None):
        super().__init__(input_shape or layer.input_shape, name)
        if merge_mode not in ("concat", "sum", "mul", "ave"):
            raise ValueError(f"Bidirectional merge_mode {merge_mode!r} "
                             "unsupported")
        if getattr(layer, "go_backwards", False):
            # BiRecurrent drives both directions itself; building from
            # layer._cell would silently ignore the inner flag (which in
            # keras swaps which wrapped copy sees the reversed sequence)
            raise ValueError(
                "Bidirectional(go_backwards=True) unsupported: the "
                "direction pair is already covered by BiRecurrent")
        self.layer = layer
        self.merge_mode = merge_mode

    def build(self, input_shape):
        from bigdl_tpu.nn.table_ops import (
            CAddTable, CMulTable, ConcatTable, JoinTable,
        )

        n_in = int(input_shape[-1])
        H = self.layer.output_dim
        core = M.Sequential()
        core.add(R.BiRecurrent().add(self.layer._cell(n_in)))
        if not self.layer.return_sequences:
            # forward final = last step's first H dims; backward final =
            # FIRST step's last H dims (backward saw the whole sequence
            # there; the last step saw one element)
            fwd = M.Sequential().add(R.Select(2, -1)).add(L.Narrow(2, 1, H))
            bwd = M.Sequential().add(R.Select(2, 1)) \
                .add(L.Narrow(2, H + 1, H))
            core.add(ConcatTable().add(fwd).add(bwd))
            combine_dim = 2
        else:
            if self.merge_mode == "concat":
                return core
            halves = ConcatTable() \
                .add(L.Narrow(3, 1, H)).add(L.Narrow(3, H + 1, H))
            core.add(halves)
            combine_dim = 3
        if self.merge_mode == "concat":
            # n_input_dims == tensor ndim: `combine_dim` is the absolute
            # 1-based axis (the ncf JoinTable(2, 2) convention)
            core.add(JoinTable(combine_dim, combine_dim))
        elif self.merge_mode == "sum":
            core.add(CAddTable())
        elif self.merge_mode == "mul":
            core.add(CMulTable())
        else:  # ave
            core.add(CAddTable()).add(L.MulConstant(0.5))
        return core

    def compute_output_shape(self, input_shape):
        d = self.layer.output_dim * (2 if self.merge_mode == "concat" else 1)
        if self.layer.return_sequences:
            return (input_shape[0], d)
        return (d,)


class TimeDistributedDense(KerasLayer):
    def __init__(self, output_dim: int, activation=None, input_shape=None,
                 name=None):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation

    def build(self, input_shape):
        inner = M.Sequential().add(L.Linear(int(input_shape[-1]),
                                            self.output_dim))
        act = _activation_module(self.activation)
        if act is not None:
            inner.add(act)
        return R.TimeDistributed(inner)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


# ---------------------------------------------------------------------------
# VERDICT r3 item 4: Keras-1.2.2 core-vocabulary breadth
# ---------------------------------------------------------------------------


class Convolution1D(KerasLayer):
    """keras.layers.Convolution1D over (steps, dim) inputs."""

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 input_shape=None, bias=True, W_regularizer=None,
                 b_regularizer=None, name=None):
        super().__init__(input_shape, name)
        self.nb_filter, self.filter_length = nb_filter, filter_length
        self.activation = activation
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.bias = bias
        self.W_regularizer, self.b_regularizer = W_regularizer, b_regularizer

    def build(self, input_shape):
        dim = int(input_shape[-1])
        core = M.Sequential()
        if self.border_mode == "same":
            k = self.filter_length
            left, right = (k - 1) // 2, k - 1 - (k - 1) // 2
            if left:
                core.add(L.Padding(1, -left, 2))
            if right:
                core.add(L.Padding(1, right, 2))
        core.add(L.TemporalConvolution(
            dim, self.nb_filter, self.filter_length, self.subsample_length,
            with_bias=self.bias))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        steps = input_shape[0]
        if self.border_mode == "same":
            out = -(-steps // self.subsample_length)
        else:
            out = (steps - self.filter_length) // self.subsample_length + 1
        return (out, self.nb_filter)


class MaxPooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride=None,
                 border_mode="valid", input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool_length = pool_length
        self.stride = stride if stride is not None else pool_length

    def _core(self):
        from bigdl_tpu.nn.layers_extra import TemporalMaxPooling

        return TemporalMaxPooling(self.pool_length, self.stride)

    def build(self, input_shape):
        return self._core()

    def compute_output_shape(self, input_shape):
        steps, dim = input_shape
        return ((steps - self.pool_length) // self.stride + 1, dim)


class AveragePooling1D(MaxPooling1D):
    def _core(self):
        from bigdl_tpu.nn.layers_extra import TemporalAveragePooling

        return TemporalAveragePooling(self.pool_length, self.stride)


class GlobalMaxPooling1D(KerasLayer):
    def build(self, input_shape):
        # L.Max reduces its 1-based dim over the FULL batched tensor:
        # dim 2 is the time axis of (B, T, F)
        return L.Max(2)

    def compute_output_shape(self, input_shape):
        return (input_shape[-1],)


class GlobalAveragePooling1D(KerasLayer):
    def build(self, input_shape):
        return L.Mean(2)

    def compute_output_shape(self, input_shape):
        return (input_shape[-1],)


class AtrousConvolution2D(KerasLayer):
    """keras.layers.AtrousConvolution2D — dilated conv, NCHW layout."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 atrous_rate=(1, 1), activation=None,
                 border_mode: str = "valid", subsample=(1, 1),
                 input_shape=None, bias=True, W_regularizer=None,
                 b_regularizer=None, name=None):
        super().__init__(input_shape, name)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.atrous_rate = _pair(atrous_rate)
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.bias = bias
        self.W_regularizer, self.b_regularizer = W_regularizer, b_regularizer

    def _effective_kernel(self):
        dh, dw = self.atrous_rate
        return (dh * (self.nb_row - 1) + 1, dw * (self.nb_col - 1) + 1)

    def build(self, input_shape):
        n_in = int(input_shape[0])
        eh, ew = self._effective_kernel()
        if self.border_mode == "same":
            ph, pw = (eh - 1) // 2, (ew - 1) // 2
        else:
            ph = pw = 0
        core = M.Sequential()
        core.add(L.SpatialDilatedConvolution(
            n_in, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], pw, ph,
            self.atrous_rate[1], self.atrous_rate[0],
            with_bias=self.bias))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        eh, ew = self._effective_kernel()
        if self.border_mode == "same":
            ph, pw = (eh - 1) // 2, (ew - 1) // 2
        else:
            ph = pw = 0
        return (self.nb_filter,
                (h + 2 * ph - eh) // sh + 1,
                (w + 2 * pw - ew) // sw + 1)


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding: int = 1, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.padding = padding

    def build(self, input_shape):
        p = self.padding
        return M.Sequential().add(L.Padding(1, -p, 2)).add(L.Padding(1, p, 2))

    def compute_output_shape(self, input_shape):
        steps, dim = input_shape
        return (steps + 2 * self.padding, dim)


class ZeroPadding3D(KerasLayer):
    def __init__(self, padding=(1, 1, 1), input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.padding = tuple(padding) if not isinstance(padding, int) \
            else (padding,) * 3

    def build(self, input_shape):
        seq = M.Sequential()
        for axis, p in enumerate(self.padding):  # (C, D, H, W): pad D/H/W
            if p:
                seq.add(L.Padding(axis + 2, -p, 4))
                seq.add(L.Padding(axis + 2, p, 4))
        return seq if seq.modules else M.Identity()

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        pd, ph, pw = self.padding
        return (c, d + 2 * pd, h + 2 * ph, w + 2 * pw)


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)), input_shape=None,
                 name=None):
        super().__init__(input_shape, name)
        self.cropping = tuple(tuple(c) for c in cropping)

    def build(self, input_shape):
        from bigdl_tpu.nn.layers_extra import Cropping2D as _C2D

        return _C2D(self.cropping[0], self.cropping[1])

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        (t, b), (l, r) = self.cropping
        return (c, h - t - b, w - l - r)


class UpSampling2D(KerasLayer):
    def __init__(self, size=(2, 2), input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.size = _pair(size)

    def build(self, input_shape):
        from bigdl_tpu.nn.layers_extra import UpSampling2D as _U2D

        return _U2D(self.size)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h * self.size[0], w * self.size[1])


class LeakyReLU(KerasLayer):
    """keras.layers.advanced_activations.LeakyReLU (1.2.2 alpha=0.3)."""

    def __init__(self, alpha: float = 0.3, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.alpha = alpha

    def build(self, input_shape):
        return L.LeakyReLU(self.alpha)


class ELU(KerasLayer):
    def __init__(self, alpha: float = 1.0, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.alpha = alpha

    def build(self, input_shape):
        return L.ELU(self.alpha)


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.theta = theta

    def build(self, input_shape):
        return L.Threshold(self.theta, 0.0)


class Masking(KerasLayer):
    def __init__(self, mask_value: float = 0.0, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.mask_value = mask_value

    def build(self, input_shape):
        return L.Masking(self.mask_value)


class GaussianNoise(KerasLayer):
    def __init__(self, sigma: float = 0.1, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.sigma = sigma

    def build(self, input_shape):
        from bigdl_tpu.nn.layers_extra import GaussianNoise as _GN

        return _GN(self.sigma)


class GaussianDropout(KerasLayer):
    def __init__(self, p: float = 0.5, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.p = p

    def build(self, input_shape):
        from bigdl_tpu.nn.layers_extra import GaussianDropout as _GD

        return _GD(self.p)


class MaxoutDense(KerasLayer):
    """keras.layers.MaxoutDense — max over nb_feature affine pieces."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.nb_feature = nb_feature

    def build(self, input_shape):
        from bigdl_tpu.nn.layers_extra import Maxout as _MX

        return _MX(int(input_shape[-1]), self.output_dim, self.nb_feature)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


def _triple(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


class Convolution3D(KerasLayer):
    """keras.layers.Convolution3D — NCDHW ("th") layout; kernel_dim1/2/3
    map to the volumetric (T, H, W) axes."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, activation=None,
                 border_mode: str = "valid", subsample=(1, 1, 1),
                 input_shape=None, bias=True, name=None):
        super().__init__(input_shape, name)
        self.nb_filter = nb_filter
        self.kdims = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = _triple(subsample)
        self.bias = bias

    def build(self, input_shape):
        from bigdl_tpu.nn.volumetric import VolumetricConvolution

        n_in = int(input_shape[0])
        pad = -1 if self.border_mode == "same" else 0
        k1, k2, k3 = self.kdims
        s1, s2, s3 = self.subsample
        core = M.Sequential()
        core.add(VolumetricConvolution(
            n_in, self.nb_filter, k1, k3, k2, s1, s3, s2,
            pad, pad, pad, with_bias=self.bias,
        ))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        _, d1, d2, d3 = input_shape
        dims = []
        for size, k, s in zip((d1, d2, d3), self.kdims, self.subsample):
            if self.border_mode == "same":
                dims.append(-(-size // s))
            else:
                dims.append((size - k) // s + 1)
        return (self.nb_filter,) + tuple(dims)


class MaxPooling3D(KerasLayer):
    """keras.layers.MaxPooling3D — NCDHW."""

    _pool_cls_name = "VolumetricMaxPooling"

    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 border_mode: str = "valid", input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool_size = _triple(pool_size)
        self.strides = _triple(strides) if strides else self.pool_size
        self.border_mode = border_mode

    def build(self, input_shape):
        import bigdl_tpu.nn.volumetric as V

        cls = getattr(V, self._pool_cls_name)
        k1, k2, k3 = self.pool_size
        s1, s2, s3 = self.strides
        pad = -1 if self.border_mode == "same" else 0
        return cls(k1, k3, k2, s1, s3, s2, pad, pad, pad)

    def compute_output_shape(self, input_shape):
        c = input_shape[0]
        dims = []
        for size, k, s in zip(input_shape[1:], self.pool_size,
                              self.strides):
            if self.border_mode == "same":
                dims.append(-(-size // s))
            else:
                dims.append((size - k) // s + 1)
        return (c,) + tuple(dims)


class AveragePooling3D(MaxPooling3D):
    _pool_cls_name = "VolumetricAveragePooling"


class Highway(KerasLayer):
    """keras.layers.core.Highway — gated identity-skip dense block."""

    def __init__(self, activation=None, input_shape=None, bias=True,
                 name=None):
        super().__init__(input_shape, name)
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        from bigdl_tpu.nn.layers_extra import Highway as _HW

        return _HW(int(input_shape[-1]), with_bias=self.bias,
                   activation=_activation_module(self.activation))

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


__all__ += [
    "Convolution1D", "MaxPooling1D", "AveragePooling1D",
    "GlobalMaxPooling1D", "GlobalAveragePooling1D", "AtrousConvolution2D",
    "ZeroPadding1D", "ZeroPadding3D", "Cropping2D", "UpSampling2D",
    "LeakyReLU", "ELU", "ThresholdedReLU", "Masking",
    "GaussianNoise", "GaussianDropout", "MaxoutDense",
    "Convolution3D", "MaxPooling3D", "AveragePooling3D", "Highway",
]
