"""Keras-style layers with shape inference.

Rebuild of «bigdl»/nn/keras/ — each layer mirrors the Keras-1.2.2
constructor surface («py»/nn/keras/layer.py spellings), infers its
output shape from the input shape (batch dim excluded, like the
reference's ``Shape``), and *builds* a core bigdl_tpu.nn module once the
input shape is known.  Image layout is NCHW ("th" dim ordering, the
reference's default).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.nn import layers as L
from bigdl_tpu.nn import module as M
from bigdl_tpu.nn import recurrent as R


__all__ = [
    "KerasLayer", "InputLayer", "Dense", "Activation", "Dropout",
    "Flatten", "Reshape", "Permute", "RepeatVector", "Convolution2D",
    "MaxPooling2D", "AveragePooling2D", "GlobalAveragePooling2D",
    "GlobalMaxPooling2D", "ZeroPadding2D", "BatchNormalization",
    "Embedding", "LSTM", "GRU", "SimpleRNN", "Bidirectional",
    "TimeDistributedDense",
]

_ACTIVATIONS = {
    "relu": L.ReLU,
    "tanh": L.Tanh,
    "sigmoid": L.Sigmoid,
    "hard_sigmoid": L.HardSigmoid,
    "softmax": L.SoftMax,
    "log_softmax": L.LogSoftMax,
    "softplus": L.SoftPlus,
    "softsign": L.SoftSign,
    "elu": L.ELU,
    "linear": M.Identity,
}


def _activation_module(name):
    if name is None or name == "linear":
        return None
    if isinstance(name, str):
        return _ACTIVATIONS[name]()
    return name


class KerasLayer:
    """Base: ``build(input_shape) -> core module`` +
    ``compute_output_shape(input_shape)``; shapes are tuples WITHOUT the
    batch dim (reference Shape semantics)."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None, name=None):
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name
        self.output_shape: Optional[Tuple[int, ...]] = None
        self.core = None

    def build(self, input_shape):
        raise NotImplementedError

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    def _built(self, input_shape):
        self.core = self.build(tuple(input_shape))
        if self.name:
            self.core.set_name(self.name)
        self.output_shape = self.compute_output_shape(tuple(input_shape))
        return self.core


class InputLayer(KerasLayer):
    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape, name)

    def build(self, input_shape):
        return M.Identity()


class Dense(KerasLayer):
    """keras.layers.Dense — W x + b with optional activation."""

    def __init__(self, output_dim: int, activation=None, input_dim=None,
                 input_shape=None, b_regularizer=None, W_regularizer=None,
                 bias=True, name=None):
        if input_dim is not None:
            input_shape = (input_dim,)
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias
        self.W_regularizer, self.b_regularizer = W_regularizer, b_regularizer

    def build(self, input_shape):
        core = M.Sequential()
        core.add(L.Linear(int(input_shape[-1]), self.output_dim,
                          with_bias=self.bias,
                          w_regularizer=self.W_regularizer,
                          b_regularizer=self.b_regularizer))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.activation = activation

    def build(self, input_shape):
        return _activation_module(self.activation) or M.Identity()


class Dropout(KerasLayer):
    def __init__(self, p: float, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.p = p

    def build(self, input_shape):
        return L.Dropout(self.p)


class Flatten(KerasLayer):
    def build(self, input_shape):
        return L.Reshape([int(np.prod(input_shape))], batch_mode=True)

    def compute_output_shape(self, input_shape):
        return (int(np.prod(input_shape)),)


class Reshape(KerasLayer):
    def __init__(self, target_shape, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        return L.Reshape(list(self.target_shape), batch_mode=True)

    def compute_output_shape(self, input_shape):
        return self.target_shape


class Permute(KerasLayer):
    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.dims = tuple(dims)  # 1-based over non-batch dims (keras)

    def build(self, input_shape):
        # express permutation as a sequence of swaps on 1-based dims
        # counting the batch dim (core Transpose convention)
        perm = [d + 1 for d in self.dims]
        current = list(range(2, len(self.dims) + 2))
        swaps = []
        for i, want in enumerate(perm):
            j = current.index(want)
            if j != i:
                swaps.append((i + 2, j + 2))
                current[i], current[j] = current[j], current[i]
        return L.Transpose(swaps) if swaps else M.Identity()

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[d - 1] for d in self.dims)


class RepeatVector(KerasLayer):
    def __init__(self, n: int, input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.n = n

    def build(self, input_shape):
        # dim counts the batch dim (core convention): insert at dim 2
        return L.Replicate(self.n, dim=2)

    def compute_output_shape(self, input_shape):
        return (self.n,) + tuple(input_shape)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class Convolution2D(KerasLayer):
    """keras.layers.Convolution2D — NCHW ("th") layout."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, border_mode: str = "valid",
                 subsample=(1, 1), input_shape=None, bias=True,
                 W_regularizer=None, b_regularizer=None, name=None):
        super().__init__(input_shape, name)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.bias = bias
        self.W_regularizer, self.b_regularizer = W_regularizer, b_regularizer

    def build(self, input_shape):
        n_in = int(input_shape[0])
        if self.border_mode == "same":
            pw = ph = -1
        else:
            pw = ph = 0
        core = M.Sequential()
        core.add(L.SpatialConvolution(
            n_in, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], pw, ph,
            with_bias=self.bias, w_regularizer=self.W_regularizer,
            b_regularizer=self.b_regularizer,
        ))
        act = _activation_module(self.activation)
        if act is not None:
            core.add(act)
        return core

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        if self.border_mode == "same":
            oh = -(-h // sh)
            ow = -(-w // sw)
        else:
            oh = (h - self.nb_row) // sh + 1
            ow = (w - self.nb_col) // sw + 1
        return (self.nb_filter, oh, ow)


class MaxPooling2D(KerasLayer):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides else self.pool_size
        self.border_mode = border_mode

    def _core_cls(self):
        return L.SpatialMaxPooling

    def build(self, input_shape):
        ph, pw = self.pool_size
        sh, sw = self.strides
        if self._core_cls() is L.SpatialMaxPooling:
            return L.SpatialMaxPooling(pw, ph, sw, sh)
        return L.SpatialAveragePooling(pw, ph, sw, sh)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        ph, pw = self.pool_size
        sh, sw = self.strides
        return (c, (h - ph) // sh + 1, (w - pw) // sw + 1)


class AveragePooling2D(MaxPooling2D):
    def _core_cls(self):
        return L.SpatialAveragePooling


class GlobalAveragePooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = (int(s) for s in input_shape)
        return M.Sequential() \
            .add(L.SpatialAveragePooling(w, h, 1, 1)) \
            .add(L.Reshape([c], batch_mode=True))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class GlobalMaxPooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = (int(s) for s in input_shape)
        return M.Sequential() \
            .add(L.SpatialMaxPooling(w, h, 1, 1)) \
            .add(L.Reshape([c], batch_mode=True))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), input_shape=None, name=None):
        super().__init__(input_shape, name)
        self.padding = _pair(padding)

    def build(self, input_shape):
        ph, pw = self.padding
        return L.SpatialZeroPadding(pw, pw, ph, ph)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h + 2 * self.padding[0], w + 2 * self.padding[1])


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon=1e-3, momentum=0.99, input_shape=None,
                 name=None):
        super().__init__(input_shape, name)
        self.epsilon = epsilon
        self.momentum = momentum

    def build(self, input_shape):
        # keras momentum is the running-average keep rate; the core layer
        # uses the update rate
        update = 1.0 - self.momentum
        if len(input_shape) == 3:
            return L.SpatialBatchNormalization(int(input_shape[0]),
                                               eps=self.epsilon,
                                               momentum=update)
        return L.BatchNormalization(int(input_shape[-1]), eps=self.epsilon,
                                    momentum=update)


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int, input_length=None,
                 input_shape=None, name=None):
        if input_shape is None and input_length is not None:
            input_shape = (input_length,)
        super().__init__(input_shape, name)
        self.input_dim, self.output_dim = input_dim, output_dim

    def build(self, input_shape):
        # keras indices are 0-based; core LookupTable is 1-based
        return M.Sequential() \
            .add(L.AddConstant(1.0)) \
            .add(L.LookupTable(self.input_dim, self.output_dim))

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _KerasRecurrent(KerasLayer):
    cell_cls = None

    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 input_shape=None, input_dim=None, input_length=None,
                 name=None):
        if input_shape is None and input_dim is not None:
            input_shape = (input_length, input_dim)
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation
        self.inner_activation = inner_activation
        self.return_sequences = return_sequences

    def _cell(self, n_in):
        raise NotImplementedError

    def build(self, input_shape):
        n_in = int(input_shape[-1])
        core = M.Sequential()
        core.add(R.Recurrent().add(self._cell(n_in)))
        if not self.return_sequences:
            core.add(R.Select(2, -1))
        return core

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], self.output_dim)
        return (self.output_dim,)


class LSTM(_KerasRecurrent):
    def _cell(self, n_in):
        return R.LSTM(n_in, self.output_dim,
                      activation=_activation_module(self.activation),
                      inner_activation=_activation_module(self.inner_activation))


class GRU(_KerasRecurrent):
    def _cell(self, n_in):
        return R.GRU(n_in, self.output_dim)


class SimpleRNN(_KerasRecurrent):
    def _cell(self, n_in):
        return R.RnnCell(n_in, self.output_dim,
                         activation=_activation_module(self.activation)
                         or L.Tanh())


class Bidirectional(KerasLayer):
    """keras.layers.wrappers.Bidirectional(merge_mode='concat')."""

    def __init__(self, layer: _KerasRecurrent, merge_mode="concat",
                 input_shape=None, name=None):
        super().__init__(input_shape or layer.input_shape, name)
        self.layer = layer
        self.merge_mode = merge_mode

    def build(self, input_shape):
        n_in = int(input_shape[-1])
        core = M.Sequential()
        core.add(R.BiRecurrent().add(self.layer._cell(n_in)))
        if not self.layer.return_sequences:
            core.add(R.Select(2, -1))
        return core

    def compute_output_shape(self, input_shape):
        d = self.layer.output_dim * (2 if self.merge_mode == "concat" else 1)
        if self.layer.return_sequences:
            return (input_shape[0], d)
        return (d,)


class TimeDistributedDense(KerasLayer):
    def __init__(self, output_dim: int, activation=None, input_shape=None,
                 name=None):
        super().__init__(input_shape, name)
        self.output_dim = output_dim
        self.activation = activation

    def build(self, input_shape):
        inner = M.Sequential().add(L.Linear(int(input_shape[-1]),
                                            self.output_dim))
        act = _activation_module(self.activation)
        if act is not None:
            inner.add(act)
        return R.TimeDistributed(inner)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)
