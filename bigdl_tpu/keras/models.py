"""Keras Sequential model + compile/fit/evaluate/predict.

Rebuild of «py»/nn/keras/topology.py (Sequential with the Keras training
verbs, dispatching into the bigdl_tpu Optimizer runtime) on top of the
shape-inferring layers.
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.keras.layers import KerasLayer
from bigdl_tpu.nn import module as M


_LOSSES = {
    "categorical_crossentropy": "_categorical",
    "sparse_categorical_crossentropy": "_sparse",
    "mse": "_mse",
    "mean_squared_error": "_mse",
    "mae": "_mae",
    "binary_crossentropy": "_bce",
    "poisson": "_poisson",
    "cosine_proximity": "_cosine",
    "mape": "_mape",
    "mean_absolute_percentage_error": "_mape",
    "msle": "_msle",
    "mean_squared_logarithmic_error": "_msle",
}


def _resolve_loss(loss):
    from bigdl_tpu.nn import (
        AbsCriterion, BCECriterion, CosineProximityCriterion,
        CrossEntropyCriterion, MeanAbsolutePercentageCriterion,
        MeanSquaredLogarithmicCriterion, MSECriterion, PoissonCriterion,
    )

    if not isinstance(loss, str):
        return loss
    kind = _LOSSES[loss]
    if kind in ("_categorical", "_sparse"):
        return CrossEntropyCriterion()
    if kind == "_mse":
        return MSECriterion()
    if kind == "_mae":
        return AbsCriterion()
    if kind == "_poisson":
        return PoissonCriterion()
    if kind == "_cosine":
        return CosineProximityCriterion()
    if kind == "_mape":
        return MeanAbsolutePercentageCriterion()
    if kind == "_msle":
        return MeanSquaredLogarithmicCriterion()
    return BCECriterion()


def _resolve_optimizer(opt):
    from bigdl_tpu.optim import Adam, Adagrad, Adadelta, Adamax, RMSprop, SGD

    if not isinstance(opt, str):
        return opt
    return {
        "sgd": lambda: SGD(learningrate=0.01),
        "adam": Adam,
        "adagrad": Adagrad,
        "adadelta": Adadelta,
        "adamax": Adamax,
        "rmsprop": RMSprop,
    }[opt.lower()]()


class Sequential:
    """keras.models.Sequential — builds a core bigdl_tpu Sequential as
    layers are added, inferring shapes."""

    def __init__(self):
        self.layers: list[KerasLayer] = []
        self.core = M.Sequential()
        self._shape = None  # current output shape (no batch dim)
        self._criterion = None
        self._optim_method = None
        self._metrics = None

    def add(self, layer: KerasLayer):
        if self._shape is None:
            if layer.input_shape is None:
                raise ValueError(
                    "first layer needs input_shape (reference behavior)"
                )
            self._shape = layer.input_shape
        core = layer._built(self._shape)
        self._shape = layer.output_shape
        self.layers.append(layer)
        self.core.add(core)
        return self

    @property
    def output_shape(self):
        return (None,) + tuple(self._shape)

    def summary(self) -> str:
        lines = ["_" * 60]
        lines.append(f"{'Layer (type)':30s}{'Output Shape':20s}")
        for l in self.layers:
            lines.append(
                f"{type(l).__name__:30s}{str((None,) + tuple(l.output_shape)):20s}"
            )
        total = sum(int(np.prod(w.shape)) for w in self.core.get_weights())
        lines.append(f"Total params: {total}")
        lines.append("_" * 60)
        s = "\n".join(lines)
        print(s)
        return s

    # ------------------------------------------------- keras training verbs
    def compile(self, optimizer, loss, metrics=None):
        self._optim_method = _resolve_optimizer(optimizer)
        self._criterion = _resolve_loss(loss)
        self._metrics = metrics
        return self

    def fit(self, x, y, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, distributed: bool = False):
        from bigdl_tpu.optim import (
            LocalOptimizer, Top1Accuracy, Trigger,
        )
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        if self._criterion is None:
            raise RuntimeError("call compile() before fit()")
        y = self._maybe_from_categorical(y)
        cls = DistriOptimizer if distributed else LocalOptimizer
        opt = cls(self.core, (np.asarray(x), y), self._criterion,
                  batch_size=batch_size)
        opt.set_optim_method(self._optim_method)
        opt.set_end_when(Trigger.max_epoch(nb_epoch))
        if validation_data is not None:
            vx, vy = validation_data
            vy = self._maybe_from_categorical(vy)
            methods = [Top1Accuracy()] if self._metrics else None
            if methods:
                opt.set_validation(trigger=Trigger.every_epoch(),
                                   dataset=(np.asarray(vx), vy),
                                   methods=methods)
        opt.optimize()
        self._last_optimizer = opt
        return self

    def _maybe_from_categorical(self, y):
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] > 1 and set(np.unique(y)) <= {0.0, 1.0}:
            # one-hot -> 1-based class ids (keras categorical target)
            return (np.argmax(y, axis=1) + 1).astype(np.float32)
        return y.astype(np.float32)

    def evaluate(self, x, y, batch_size: int = 32):
        from bigdl_tpu.dataset import ArrayDataSet
        from bigdl_tpu.optim import Loss, Top1Accuracy
        from bigdl_tpu.optim.evaluator import evaluate_dataset

        y = self._maybe_from_categorical(y)
        ds = ArrayDataSet(np.asarray(x), y, batch_size)
        methods = [Loss(self._criterion)]
        if self._metrics:
            methods.append(Top1Accuracy())
        results = evaluate_dataset(self.core, ds, methods)
        return [r.result()[0] for r in results]

    def predict(self, x, batch_size: int = 32):
        from bigdl_tpu.optim.evaluator import predict

        # keras multi-input convention: a tuple OR a list of >=2-D
        # branch arrays is a table input (one array per graph input)
        if isinstance(x, list) and x and all(
                getattr(a, "ndim", 0) >= 2 for a in x):
            x = tuple(np.asarray(a) for a in x)
        elif not isinstance(x, tuple):
            x = np.asarray(x)
        return predict(self.core, x, batch_size)

    def predict_classes(self, x, batch_size: int = 32):
        from bigdl_tpu.optim.evaluator import predict_class

        return predict_class(self.core, np.asarray(x), batch_size) - 1

    # persistence through the core serializer
    def save(self, path: str):
        from bigdl_tpu.utils.serializer import save_module

        return save_module(self.core, path)

    def get_weights(self):
        return self.core.get_weights()

    def set_weights(self, weights):
        self.core.set_weights(weights)
        return self


class Model(Sequential):
    """keras.models.Model — the functional-API training surface over a
    built :class:`bigdl_tpu.nn.Graph` (e.g. the converter's output for
    functional JSON configs).  Inherits Sequential's compile/fit/
    evaluate/predict verbs, which only touch ``self.core``; ``add`` is
    disabled (the graph is already wired)."""

    def __init__(self, core_graph):
        super().__init__()
        self.core = core_graph

    def add(self, layer):
        raise TypeError("Model wraps a finished Graph; use Sequential "
                        "to build layer-by-layer")

    def forward(self, x):
        return self.core.forward(x)
