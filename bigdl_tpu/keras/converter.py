"""Keras 1.2.2 model importer — JSON configs + HDF5 weights.

Rebuild of «py»/keras/converter.py (SURVEY.md §2.2: "Keras-1.2.2-
compatible API and JSON/weights importer").

``model_from_json`` handles both ``Sequential`` configs (a list of layer
configs) and functional ``Model`` configs (layers + inbound_nodes wired
into an :class:`bigdl_tpu.nn.Graph`).  ``load_weights_hdf5`` copies
weights from a Keras 1.2.2 ``save_weights`` HDF5 file by layer name
(Dense / Convolution2D / BatchNormalization / Embedding; recurrent
weight import is rejected explicitly rather than silently mis-mapped).

Keras dim ordering: the reference targets "th" (NCHW) ordering, which is
also this framework's layout; "tf"-ordered convolution weights are
transposed on load.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.keras import layers as KL
from bigdl_tpu.keras import models as KM


__all__ = [
    "KerasConversionException", "model_from_json",
    "model_from_json_path", "load_weights_hdf5",
]

class KerasConversionException(Exception):
    pass


def _tuple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else v


def _strip_batch(shape):
    if shape is None:
        return None
    return tuple(int(s) for s in shape[1:])


def _build_layer(class_name: str, cfg: dict) -> Optional[KL.KerasLayer]:
    """One Keras-1.2.2 layer config -> a keras-surface layer (or None for
    layers that vanish, e.g. InputLayer handled by the caller)."""
    name = cfg.get("name")
    input_shape = _strip_batch(cfg.get("batch_input_shape"))

    if class_name in ("InputLayer",):
        return KL.InputLayer(input_shape=input_shape, name=name)
    if class_name == "Dense":
        return KL.Dense(
            cfg["output_dim"],
            activation=cfg.get("activation"),
            input_shape=input_shape,
            bias=cfg.get("bias", True),
            name=name,
        )
    if class_name == "Activation":
        return KL.Activation(cfg["activation"], input_shape=input_shape,
                             name=name)
    if class_name == "Dropout":
        return KL.Dropout(cfg.get("p", 0.5), name=name)
    if class_name == "Flatten":
        return KL.Flatten(input_shape=input_shape, name=name)
    if class_name == "Reshape":
        return KL.Reshape(_tuple(cfg["target_shape"]),
                          input_shape=input_shape, name=name)
    if class_name == "Permute":
        return KL.Permute(_tuple(cfg["dims"]), input_shape=input_shape,
                          name=name)
    if class_name == "RepeatVector":
        return KL.RepeatVector(cfg["n"], input_shape=input_shape, name=name)
    if class_name == "Convolution2D":
        if cfg.get("dim_ordering", "th") == "tf":
            raise KerasConversionException(
                "tf dim_ordering Convolution2D configs are not supported; "
                "re-save the model with dim_ordering='th'"
            )
        sub = _tuple(cfg.get("subsample", (1, 1)))
        return KL.Convolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
            activation=cfg.get("activation"),
            border_mode=cfg.get("border_mode", "valid"),
            subsample=sub,
            input_shape=input_shape,
            name=name,
        )
    if class_name == "MaxPooling2D":
        return KL.MaxPooling2D(
            pool_size=_tuple(cfg.get("pool_size", (2, 2))),
            strides=_tuple(cfg.get("strides")) if cfg.get("strides") else None,
            border_mode=cfg.get("border_mode", "valid"),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "AveragePooling2D":
        return KL.AveragePooling2D(
            pool_size=_tuple(cfg.get("pool_size", (2, 2))),
            strides=_tuple(cfg.get("strides")) if cfg.get("strides") else None,
            border_mode=cfg.get("border_mode", "valid"),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "GlobalAveragePooling2D":
        return KL.GlobalAveragePooling2D(input_shape=input_shape, name=name)
    if class_name == "GlobalMaxPooling2D":
        return KL.GlobalMaxPooling2D(input_shape=input_shape, name=name)
    if class_name == "ZeroPadding2D":
        return KL.ZeroPadding2D(
            padding=_tuple(cfg.get("padding", (1, 1))),
            input_shape=input_shape, name=name,
        )
    if class_name == "BatchNormalization":
        return KL.BatchNormalization(
            epsilon=cfg.get("epsilon", 1e-3),
            momentum=cfg.get("momentum", 0.99),
            axis=cfg.get("axis", 1),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "Embedding":
        return KL.Embedding(
            cfg["input_dim"], cfg["output_dim"],
            input_shape=input_shape
            or ((cfg.get("input_length"),) if cfg.get("input_length")
                else None),
            name=name,
        )
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        cls = getattr(KL, class_name)
        return cls(
            cfg["output_dim"],
            activation=cfg.get("activation", "tanh"),
            return_sequences=cfg.get("return_sequences", False),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "TimeDistributedDense":
        return KL.TimeDistributedDense(
            cfg["output_dim"], activation=cfg.get("activation"),
            input_shape=input_shape, name=name,
        )
    raise KerasConversionException(
        f"unsupported Keras layer class {class_name}"
    )


# ==========================================================================
# JSON entry points
# ==========================================================================


def model_from_json(json_str: str):
    """Reference: keras.models.model_from_json over the BigDL converter.
    Returns a :class:`bigdl_tpu.keras.models.Sequential` for Sequential
    configs, or a core :class:`bigdl_tpu.nn.Graph` for functional Model
    configs."""
    spec = json.loads(json_str)
    class_name = spec.get("class_name")
    if class_name == "Sequential":
        return _sequential_from_config(spec["config"])
    if class_name == "Model":
        return _graph_from_config(spec["config"])
    raise KerasConversionException(f"unsupported model class {class_name}")


def _sequential_from_config(layer_specs: List[dict]) -> KM.Sequential:
    model = KM.Sequential()
    for ls in layer_specs:
        layer = _build_layer(ls["class_name"], ls.get("config", {}))
        if layer is not None:
            model.add(layer)
    return model


def _graph_from_config(cfg: dict):
    """Functional Model: wire built cores into an nn.Graph."""
    from bigdl_tpu.nn.graph import Graph, Input as GInput
    from bigdl_tpu.nn import table_ops as T

    nodes: Dict[str, object] = {}
    shapes: Dict[str, tuple] = {}
    input_nodes = []

    for ls in cfg.get("layers", []):
        cname = ls["class_name"]
        lcfg = ls.get("config", {})
        lname = ls.get("name") or lcfg.get("name")
        inbound = ls.get("inbound_nodes") or []
        in_names = [ref[0] for ref in inbound[0]] if inbound else []

        if cname == "InputLayer":
            node = GInput(lname)
            input_nodes.append(node)
            nodes[lname] = node
            shapes[lname] = _strip_batch(lcfg.get("batch_input_shape"))
            continue
        if cname == "Merge":
            mode = lcfg.get("mode", "concat")
            if mode == "concat":
                axis = lcfg.get("concat_axis", -1)
                in_shape = shapes[in_names[0]]
                if axis == -1:
                    axis = len(in_shape)  # last feature dim (no batch)
                mod = T.JoinTable(dimension=axis + 1, n_input_dims=-1)
                out_shape = list(in_shape)
                out_shape[axis - 1] = sum(
                    shapes[n][axis - 1] for n in in_names
                )
                out_shape = tuple(out_shape)
            elif mode in ("sum", "ave", "max", "mul"):
                if mode == "ave":
                    from bigdl_tpu.nn import layers as KLY
                    from bigdl_tpu.nn.module import Sequential

                    mod = Sequential().add(T.CAddTable()) \
                        .add(KLY.MulConstant(1.0 / len(in_names)))
                else:
                    mod = {"sum": T.CAddTable, "max": T.CMaxTable,
                           "mul": T.CMulTable}[mode]()
                out_shape = shapes[in_names[0]]
            else:
                raise KerasConversionException(f"Merge mode {mode}")
            if lname:
                mod.set_name(lname)
            nodes[lname] = mod(*[nodes[n] for n in in_names])
            shapes[lname] = out_shape
            continue

        layer = _build_layer(cname, lcfg)
        if not in_names:
            # implicit input (rare in 1.2.2 functional configs)
            raise KerasConversionException(
                f"layer {lname} has no inbound nodes"
            )
        in_shape = shapes[in_names[0]]
        core = layer._built(in_shape)
        nodes[lname] = core(*[nodes[n] for n in in_names])
        shapes[lname] = layer.output_shape

    outputs = [nodes[ref[0]] for ref in cfg.get("output_layers", [])]
    return Graph(input_nodes, outputs)


def model_from_json_path(path: str):
    with open(path) as f:
        return model_from_json(f.read())


# ==========================================================================
# HDF5 weights
# ==========================================================================


def load_weights_hdf5(model, h5_path: str, by_name: bool = True):
    """Copy Keras-1.2.2 ``save_weights`` HDF5 weights into a converted
    model by layer name (reference: converter's weight loader)."""
    import h5py
    import jax.numpy as jnp

    core = getattr(model, "core", model)
    modules = {m._name: m for m in _iter_modules(core) if m._name}

    with h5py.File(h5_path, "r") as f:
        grp = f["model_weights"] if "model_weights" in f else f
        layer_names = [
            n.decode() if isinstance(n, bytes) else n
            for n in grp.attrs.get("layer_names", list(grp.keys()))
        ]
        for lname in layer_names:
            if lname not in grp:
                continue
            g = grp[lname]
            weight_names = [
                n.decode() if isinstance(n, bytes) else n
                for n in g.attrs.get("weight_names", list(g.keys()))
            ]
            if not weight_names:
                continue
            mod = modules.get(lname)
            if mod is None:
                if by_name:
                    continue
                raise KerasConversionException(f"no module named {lname}")
            arrays = [np.asarray(g[w]) for w in weight_names]
            _assign_weights(mod, lname, weight_names, arrays)
    return model


def _assign_weights(mod, lname, weight_names, arrays):
    import jax.numpy as jnp

    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn.module import Sequential

    # keras Dense+activation / Conv+activation become a Sequential in the
    # keras layer build; the parameterised core is the first child
    if isinstance(mod, Sequential):
        for child in mod.modules:
            if child.params():
                mod = child
                break
    if any("lstm" in w.lower() or "gru" in w.lower() for w in weight_names) \
            or len(arrays) > 4:
        raise KerasConversionException(
            f"recurrent weight import not supported (layer {lname})"
        )
    if isinstance(mod, L.Linear):
        w = arrays[0]
        mod.weight = jnp.asarray(w.T)  # keras (in,out) -> (out,in)
        if len(arrays) > 1 and mod.bias is not None:
            mod.bias = jnp.asarray(arrays[1])
    elif isinstance(mod, L.SpatialConvolution):
        w = arrays[0]  # th: (nb_filter, in, rows, cols)
        mod.weight = jnp.asarray(w.reshape(np.asarray(mod.weight).shape))
        if len(arrays) > 1 and mod.bias is not None:
            mod.bias = jnp.asarray(arrays[1])
    elif isinstance(mod, (L.BatchNormalization,)):
        mod.weight = jnp.asarray(arrays[0])
        mod.bias = jnp.asarray(arrays[1])
        if len(arrays) > 2:
            mod.running_mean = jnp.asarray(arrays[2])
        if len(arrays) > 3:
            # keras 1.2.2 stores running_std for mode=0 pre-1.0 configs,
            # variance otherwise; both enter as the variance slot
            mod.running_var = jnp.asarray(arrays[3])
    elif isinstance(mod, L.LookupTable):
        mod.weight = jnp.asarray(arrays[0])
    else:
        raise KerasConversionException(
            f"weight import for {type(mod).__name__} (layer {lname}) "
            "not supported"
        )


def _iter_modules(m):
    yield m
    for child in getattr(m, "modules", []):
        yield from _iter_modules(child)
