"""Keras 1.2.2 model importer — JSON configs + HDF5 weights.

Rebuild of «py»/keras/converter.py (SURVEY.md §2.2: "Keras-1.2.2-
compatible API and JSON/weights importer").

``model_from_json`` handles both ``Sequential`` configs (a list of layer
configs) and functional ``Model`` configs (layers + inbound_nodes wired
into an :class:`bigdl_tpu.nn.Graph`).  ``load_weights_hdf5`` copies
weights from a Keras 1.2.2 ``save_weights`` HDF5 file by layer name
(Dense / Convolution2D / BatchNormalization / Embedding; recurrent
weight import is rejected explicitly rather than silently mis-mapped).

Keras dim ordering: the reference targets "th" (NCHW) ordering, which is
also this framework's layout; "tf"-ordered convolution weights are
transposed on load.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.keras import layers as KL
from bigdl_tpu.keras import models as KM


__all__ = [
    "KerasConversionException", "model_from_json",
    "model_from_json_path", "load_weights_hdf5",
]

class KerasConversionException(Exception):
    pass


def _tuple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else v


def _regularizer(spec):
    """Keras-1.2.2 regularizer config -> L1L2Regularizer.
    ``{"name": "WeightRegularizer"/"ActivityRegularizer", "l1": x,
    "l2": y}`` (activity regularizers have no analogue and are
    rejected)."""
    if not spec:
        return None
    from bigdl_tpu.optim.regularizer import L1L2Regularizer

    name = spec.get("name", "WeightRegularizer")
    if "Activity" in name:
        raise KerasConversionException(
            "ActivityRegularizer has no bigdl analogue")
    return L1L2Regularizer(float(spec.get("l1", 0.0)),
                           float(spec.get("l2", 0.0)))


def _strip_batch(shape):
    if shape is None:
        return None
    return tuple(int(s) for s in shape[1:])


def _build_layer(class_name: str, cfg: dict) -> Optional[KL.KerasLayer]:
    """One Keras-1.2.2 layer config -> a keras-surface layer (or None for
    layers that vanish, e.g. InputLayer handled by the caller)."""
    name = cfg.get("name")
    input_shape = _strip_batch(cfg.get("batch_input_shape"))

    if class_name in ("InputLayer",):
        return KL.InputLayer(input_shape=input_shape, name=name)
    if class_name == "Dense":
        return KL.Dense(
            cfg["output_dim"],
            activation=cfg.get("activation"),
            input_shape=input_shape,
            bias=cfg.get("bias", True),
            W_regularizer=_regularizer(cfg.get("W_regularizer")),
            b_regularizer=_regularizer(cfg.get("b_regularizer")),
            name=name,
        )
    if class_name == "Activation":
        return KL.Activation(cfg["activation"], input_shape=input_shape,
                             name=name)
    if class_name == "Dropout":
        return KL.Dropout(cfg.get("p", 0.5), name=name)
    if class_name == "Flatten":
        return KL.Flatten(input_shape=input_shape, name=name)
    if class_name == "Reshape":
        return KL.Reshape(_tuple(cfg["target_shape"]),
                          input_shape=input_shape, name=name)
    if class_name == "Permute":
        return KL.Permute(_tuple(cfg["dims"]), input_shape=input_shape,
                          name=name)
    if class_name == "RepeatVector":
        return KL.RepeatVector(cfg["n"], input_shape=input_shape, name=name)
    if class_name == "Convolution2D":
        if cfg.get("dim_ordering", "th") == "tf":
            raise KerasConversionException(
                "tf dim_ordering Convolution2D configs are not supported; "
                "re-save the model with dim_ordering='th'"
            )
        sub = _tuple(cfg.get("subsample", (1, 1)))
        return KL.Convolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
            activation=cfg.get("activation"),
            border_mode=cfg.get("border_mode", "valid"),
            subsample=sub,
            input_shape=input_shape,
            bias=cfg.get("bias", True),
            W_regularizer=_regularizer(cfg.get("W_regularizer")),
            b_regularizer=_regularizer(cfg.get("b_regularizer")),
            name=name,
        )
    if class_name == "Convolution3D":
        if cfg.get("dim_ordering", "th") == "tf":
            raise KerasConversionException(
                "tf dim_ordering Convolution3D configs are not supported; "
                "re-save the model with dim_ordering='th'"
            )
        return KL.Convolution3D(
            cfg["nb_filter"], cfg["kernel_dim1"], cfg["kernel_dim2"],
            cfg["kernel_dim3"],
            activation=cfg.get("activation"),
            border_mode=cfg.get("border_mode", "valid"),
            subsample=_tuple(cfg.get("subsample", (1, 1, 1))),
            input_shape=input_shape,
            bias=cfg.get("bias", True),
            name=name,
        )
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        if cfg.get("dim_ordering", "th") == "tf":
            raise KerasConversionException(
                f"tf dim_ordering {class_name} unsupported")
        cls = getattr(KL, class_name)
        return cls(
            pool_size=_tuple(cfg.get("pool_size", (2, 2, 2))),
            strides=_tuple(cfg["strides"]) if cfg.get("strides") else None,
            border_mode=cfg.get("border_mode", "valid"),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "Highway":
        return KL.Highway(
            activation=cfg.get("activation"),
            bias=cfg.get("bias", True),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "AtrousConvolution2D":
        if cfg.get("dim_ordering", "th") == "tf":
            raise KerasConversionException(
                "tf dim_ordering AtrousConvolution2D unsupported")
        return KL.AtrousConvolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
            atrous_rate=_tuple(cfg.get("atrous_rate", (1, 1))),
            activation=cfg.get("activation"),
            border_mode=cfg.get("border_mode", "valid"),
            subsample=_tuple(cfg.get("subsample", (1, 1))),
            input_shape=input_shape,
            bias=cfg.get("bias", True),
            name=name,
        )
    if class_name == "Convolution1D":
        return KL.Convolution1D(
            cfg["nb_filter"], cfg["filter_length"],
            activation=cfg.get("activation"),
            border_mode=cfg.get("border_mode", "valid"),
            subsample_length=cfg.get("subsample_length", 1),
            input_shape=input_shape,
            bias=cfg.get("bias", True),
            name=name,
        )
    if class_name == "MaxPooling1D":
        return KL.MaxPooling1D(
            pool_length=cfg.get("pool_length", 2),
            stride=cfg.get("stride"),
            input_shape=input_shape, name=name,
        )
    if class_name == "AveragePooling1D":
        return KL.AveragePooling1D(
            pool_length=cfg.get("pool_length", 2),
            stride=cfg.get("stride"),
            input_shape=input_shape, name=name,
        )
    if class_name == "GlobalMaxPooling1D":
        return KL.GlobalMaxPooling1D(input_shape=input_shape, name=name)
    if class_name == "GlobalAveragePooling1D":
        return KL.GlobalAveragePooling1D(input_shape=input_shape, name=name)
    if class_name == "ZeroPadding1D":
        return KL.ZeroPadding1D(cfg.get("padding", 1),
                                input_shape=input_shape, name=name)
    if class_name == "ZeroPadding3D":
        return KL.ZeroPadding3D(_tuple(cfg.get("padding", (1, 1, 1))),
                                input_shape=input_shape, name=name)
    if class_name == "Cropping2D":
        return KL.Cropping2D(_tuple(cfg.get("cropping", ((0, 0), (0, 0)))),
                             input_shape=input_shape, name=name)
    if class_name == "UpSampling2D":
        return KL.UpSampling2D(_tuple(cfg.get("size", (2, 2))),
                               input_shape=input_shape, name=name)
    if class_name == "LeakyReLU":
        return KL.LeakyReLU(cfg.get("alpha", 0.3),
                            input_shape=input_shape, name=name)
    if class_name == "ELU":
        return KL.ELU(cfg.get("alpha", 1.0), input_shape=input_shape,
                      name=name)
    if class_name == "ThresholdedReLU":
        return KL.ThresholdedReLU(cfg.get("theta", 1.0),
                                  input_shape=input_shape, name=name)
    if class_name == "Masking":
        return KL.Masking(cfg.get("mask_value", 0.0),
                          input_shape=input_shape, name=name)
    if class_name == "MaxPooling2D":
        return KL.MaxPooling2D(
            pool_size=_tuple(cfg.get("pool_size", (2, 2))),
            strides=_tuple(cfg.get("strides")) if cfg.get("strides") else None,
            border_mode=cfg.get("border_mode", "valid"),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "AveragePooling2D":
        return KL.AveragePooling2D(
            pool_size=_tuple(cfg.get("pool_size", (2, 2))),
            strides=_tuple(cfg.get("strides")) if cfg.get("strides") else None,
            border_mode=cfg.get("border_mode", "valid"),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "GlobalAveragePooling2D":
        return KL.GlobalAveragePooling2D(input_shape=input_shape, name=name)
    if class_name == "GlobalMaxPooling2D":
        return KL.GlobalMaxPooling2D(input_shape=input_shape, name=name)
    if class_name == "ZeroPadding2D":
        return KL.ZeroPadding2D(
            padding=_tuple(cfg.get("padding", (1, 1))),
            input_shape=input_shape, name=name,
        )
    if class_name == "BatchNormalization":
        return KL.BatchNormalization(
            epsilon=cfg.get("epsilon", 1e-3),
            momentum=cfg.get("momentum", 0.99),
            axis=cfg.get("axis", 1),
            input_shape=input_shape,
            name=name,
        )
    if class_name == "Embedding":
        return KL.Embedding(
            cfg["input_dim"], cfg["output_dim"],
            input_shape=input_shape
            or ((cfg.get("input_length"),) if cfg.get("input_length")
                else None),
            name=name,
        )
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        cls = getattr(KL, class_name)
        kw = {}
        if class_name != "SimpleRNN":
            kw["inner_activation"] = cfg.get("inner_activation",
                                             "hard_sigmoid")
        if cfg.get("stateful"):
            # documented design decision (not an omission): stateful
            # recurrents carry hidden state ACROSS batches, which the
            # jit-pure per-batch recurrence deliberately resets; failing
            # loudly beats silently training different semantics
            raise KerasConversionException(
                f"stateful {class_name} {name}: cross-batch state is not "
                "supported by the jit-pure recurrence")
        return cls(
            cfg["output_dim"],
            activation=cfg.get("activation", "tanh"),
            return_sequences=cfg.get("return_sequences", False),
            go_backwards=cfg.get("go_backwards", False),
            input_shape=input_shape,
            dropout_W=cfg.get("dropout_W", 0.0) or 0.0,
            dropout_U=cfg.get("dropout_U", 0.0) or 0.0,
            W_regularizer=_regularizer(cfg.get("W_regularizer")),
            U_regularizer=_regularizer(cfg.get("U_regularizer")),
            b_regularizer=_regularizer(cfg.get("b_regularizer")),
            name=name,
            **kw,
        )
    if class_name == "Bidirectional":
        inner_spec = cfg.get("layer", {})
        inner = _build_layer(inner_spec.get("class_name"),
                             inner_spec.get("config", {}))
        return KL.Bidirectional(inner,
                                merge_mode=cfg.get("merge_mode", "concat"),
                                input_shape=input_shape, name=name)
    if class_name == "GaussianNoise":
        return KL.GaussianNoise(cfg.get("sigma", 0.1),
                                input_shape=input_shape, name=name)
    if class_name == "GaussianDropout":
        return KL.GaussianDropout(cfg.get("p", 0.5),
                                  input_shape=input_shape, name=name)
    if class_name == "MaxoutDense":
        return KL.MaxoutDense(cfg["output_dim"],
                              nb_feature=cfg.get("nb_feature", 4),
                              input_shape=input_shape, name=name)
    if class_name == "TimeDistributedDense":
        return KL.TimeDistributedDense(
            cfg["output_dim"], activation=cfg.get("activation"),
            input_shape=input_shape, name=name,
        )
    raise KerasConversionException(
        f"unsupported Keras layer class {class_name}"
    )


# ==========================================================================
# JSON entry points
# ==========================================================================


def model_from_json(json_str: str):
    """Reference: keras.models.model_from_json over the BigDL converter.
    Returns a :class:`bigdl_tpu.keras.models.Sequential` for Sequential
    configs, or a core :class:`bigdl_tpu.nn.Graph` for functional Model
    configs."""
    spec = json.loads(json_str)
    class_name = spec.get("class_name")
    if class_name == "Sequential":
        return _sequential_from_config(spec["config"])
    if class_name == "Model":
        return _graph_from_config(spec["config"])
    raise KerasConversionException(f"unsupported model class {class_name}")


def _sequential_from_config(layer_specs: List[dict]) -> KM.Sequential:
    model = KM.Sequential()
    start = 0
    if layer_specs and layer_specs[0]["class_name"] == "Merge":
        # keras-1.2.2 Sequential([Merge([left, right], mode=...), ...]):
        # the branches are full sub-model configs; the merged table op
        # heads the core and the model takes a TABLE of inputs
        model = _merge_headed_sequential(layer_specs[0].get("config", {}))
        start = 1
    for ls in layer_specs[start:]:
        layer = _build_layer(ls["class_name"], ls.get("config", {}))
        if layer is not None:
            model.add(layer)
    return model


def _merge_headed_sequential(mcfg: dict) -> KM.Sequential:
    from bigdl_tpu.nn import layers as KLY
    from bigdl_tpu.nn import table_ops as T
    from bigdl_tpu.nn.module import Sequential as CoreSeq

    branches = []
    for sub in mcfg.get("layers", []):
        if sub.get("class_name") != "Sequential":
            raise KerasConversionException(
                "Merge branches must be Sequential sub-models")
        branches.append(_sequential_from_config(sub["config"]))
    if not branches:
        raise KerasConversionException("Merge with no branch models")
    mode = mcfg.get("mode", "concat")
    shapes = [tuple(b._shape) for b in branches]

    if mode == "concat":
        axis = mcfg.get("concat_axis", -1)
        if axis == -1:
            axis = len(shapes[0])  # last non-batch dim, 1-based below
        mod = T.JoinTable(dimension=axis + 1, n_input_dims=-1)
        out_shape = list(shapes[0])
        out_shape[axis - 1] = sum(s[axis - 1] for s in shapes)
        out_shape = tuple(out_shape)
    elif mode in ("sum", "ave", "max", "mul"):
        if mode == "ave":
            mod = CoreSeq().add(T.CAddTable()) \
                .add(KLY.MulConstant(1.0 / len(branches)))
        else:
            mod = {"sum": T.CAddTable, "max": T.CMaxTable,
                   "mul": T.CMulTable}[mode]()
        out_shape = shapes[0]
    elif mode in ("dot", "cos"):
        if len(branches) != 2:
            raise KerasConversionException(
                f"Merge mode {mode} needs exactly 2 branches")
        mod = T.DotProduct() if mode == "dot" else T.CosineDistance()
        out_shape = (1,)
    else:
        raise KerasConversionException(f"Merge mode {mode}")

    from bigdl_tpu.nn.table_ops import ParallelTable

    pt = ParallelTable()
    for b in branches:
        pt.add(b.core)
    model = KM.Sequential()
    model.core.add(pt).add(mod)
    model._shape = tuple(out_shape)
    if mcfg.get("name"):
        model.core.set_name(mcfg["name"])
    return model


def _graph_from_config(cfg: dict):
    """Functional Model: wire built cores into an nn.Graph."""
    from bigdl_tpu.nn.graph import Graph, Input as GInput
    from bigdl_tpu.nn import table_ops as T

    nodes: Dict[str, object] = {}
    shapes: Dict[str, tuple] = {}
    input_nodes = []

    for ls in cfg.get("layers", []):
        cname = ls["class_name"]
        lcfg = ls.get("config", {})
        lname = ls.get("name") or lcfg.get("name")
        inbound = ls.get("inbound_nodes") or []
        in_names = [ref[0] for ref in inbound[0]] if inbound else []

        if cname == "InputLayer":
            node = GInput(lname)
            input_nodes.append(node)
            nodes[lname] = node
            shapes[lname] = _strip_batch(lcfg.get("batch_input_shape"))
            continue
        if cname == "Merge":
            mode = lcfg.get("mode", "concat")
            if mode == "concat":
                axis = lcfg.get("concat_axis", -1)
                in_shape = shapes[in_names[0]]
                if axis == -1:
                    axis = len(in_shape)  # last feature dim (no batch)
                mod = T.JoinTable(dimension=axis + 1, n_input_dims=-1)
                out_shape = list(in_shape)
                out_shape[axis - 1] = sum(
                    shapes[n][axis - 1] for n in in_names
                )
                out_shape = tuple(out_shape)
            elif mode in ("sum", "ave", "max", "mul"):
                if mode == "ave":
                    from bigdl_tpu.nn import layers as KLY
                    from bigdl_tpu.nn.module import Sequential

                    mod = Sequential().add(T.CAddTable()) \
                        .add(KLY.MulConstant(1.0 / len(in_names)))
                else:
                    mod = {"sum": T.CAddTable, "max": T.CMaxTable,
                           "mul": T.CMulTable}[mode]()
                out_shape = shapes[in_names[0]]
            elif mode in ("dot", "cos"):
                if len(in_names) != 2:
                    raise KerasConversionException(
                        f"Merge mode {mode} needs exactly 2 inputs")
                mod = T.DotProduct() if mode == "dot" \
                    else T.CosineDistance()
                out_shape = (1,)
            else:
                raise KerasConversionException(f"Merge mode {mode}")
            if lname:
                mod.set_name(lname)
            nodes[lname] = mod(*[nodes[n] for n in in_names])
            shapes[lname] = out_shape
            continue

        layer = _build_layer(cname, lcfg)
        if not in_names:
            # implicit input (rare in 1.2.2 functional configs)
            raise KerasConversionException(
                f"layer {lname} has no inbound nodes"
            )
        in_shape = shapes[in_names[0]]
        core = layer._built(in_shape)
        nodes[lname] = core(*[nodes[n] for n in in_names])
        shapes[lname] = layer.output_shape

    outputs = [nodes[ref[0]] for ref in cfg.get("output_layers", [])]
    return Graph(input_nodes, outputs)


def model_from_json_path(path: str):
    with open(path) as f:
        return model_from_json(f.read())


# ==========================================================================
# HDF5 weights
# ==========================================================================


def load_weights_hdf5(model, h5_path: str, by_name: bool = True):
    """Copy Keras-1.2.2 ``save_weights`` HDF5 weights into a converted
    model by layer name (reference: converter's weight loader)."""
    import h5py
    import jax.numpy as jnp

    core = getattr(model, "core", model)
    modules = {m._name: m for m in _iter_modules(core) if m._name}

    with h5py.File(h5_path, "r") as f:
        grp = f["model_weights"] if "model_weights" in f else f
        layer_names = [
            n.decode() if isinstance(n, bytes) else n
            for n in grp.attrs.get("layer_names", list(grp.keys()))
        ]
        for lname in layer_names:
            if lname not in grp:
                continue
            g = grp[lname]
            weight_names = [
                n.decode() if isinstance(n, bytes) else n
                for n in g.attrs.get("weight_names", list(g.keys()))
            ]
            if not weight_names:
                continue
            mod = modules.get(lname)
            if mod is None:
                if by_name:
                    continue
                raise KerasConversionException(f"no module named {lname}")
            arrays = [np.asarray(g[w]) for w in weight_names]
            _assign_weights(mod, lname, weight_names, arrays)
    return model


def _assign_weights(mod, lname, weight_names, arrays):
    import jax.numpy as jnp

    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn import recurrent as R
    from bigdl_tpu.nn.module import Sequential

    # keras Dense+activation / Conv+activation become a Sequential in the
    # keras layer build; the parameterised core is the first child —
    # for recurrents that child is the Recurrent container whose cell
    # holds the parameters
    if isinstance(mod, Sequential):
        for child in mod.modules:
            if child.params():
                mod = child
                break
    if isinstance(mod, R.BiRecurrent):
        # keras Bidirectional saves forward_* then backward_* weights;
        # positional fallback: first half forward, second half backward
        pairs = list(zip(weight_names, arrays))
        fw = [(n, a) for n, a in pairs if "backward" not in n.lower()]
        bw = [(n, a) for n, a in pairs if "backward" in n.lower()]
        if not bw:
            half = len(pairs) // 2
            fw, bw = pairs[:half], pairs[half:]
        _assign_recurrent(mod.modules[0].modules[0], lname,
                          [n for n, _ in fw], [a for _, a in fw])
        _assign_recurrent(mod.modules[1].modules[0], lname,
                          [n for n, _ in bw], [a for _, a in bw])
        return
    if isinstance(mod, R.Recurrent):
        cell = mod.modules[0]
        return _assign_recurrent(cell, lname, weight_names, arrays)
    if isinstance(mod, R.TimeDistributed):
        inner = mod.modules[0]
        return _assign_weights(inner, lname, weight_names, arrays)
    if isinstance(mod, L.Linear):
        w = arrays[0]
        mod.weight = jnp.asarray(w.T)  # keras (in,out) -> (out,in)
        if len(arrays) > 1 and mod.bias is not None:
            mod.bias = jnp.asarray(arrays[1])
    elif isinstance(mod, L.SpatialConvolution):
        w = arrays[0]  # th: (nb_filter, in, rows, cols)
        mod.weight = jnp.asarray(w.reshape(np.asarray(mod.weight).shape))
        if len(arrays) > 1 and mod.bias is not None:
            mod.bias = jnp.asarray(arrays[1])
    elif type(mod).__name__ == "VolumetricConvolution":
        w = arrays[0]  # th: (nb_filter, in, k1, k2, k3) == OIDHW
        mod.weight = jnp.asarray(w.reshape(np.asarray(mod.weight).shape))
        if len(arrays) > 1 and mod.bias is not None:
            mod.bias = jnp.asarray(arrays[1])
    elif type(mod).__name__ == "Highway":
        # keras-1.2.2 trainable order: W, W_carry, b, b_carry; keras
        # stores (in, out) — transpose for the y = x W^T convention
        named = {}
        for n, a in zip(weight_names, arrays):
            tail = n.rsplit("/", 1)[-1]
            for suffix in ("W_carry", "b_carry", "W", "b"):
                if tail.endswith(suffix):
                    named.setdefault(suffix, a)
                    break
        if len(named) == len(arrays):
            mod.weight = jnp.asarray(named["W"].T)
            mod.carry_weight = jnp.asarray(named["W_carry"].T)
            if "b" in named:
                mod.bias = jnp.asarray(named["b"])
            if "b_carry" in named:
                mod.carry_bias = jnp.asarray(named["b_carry"])
        else:  # positional fallback
            mod.weight = jnp.asarray(arrays[0].T)
            mod.carry_weight = jnp.asarray(arrays[1].T)
            if len(arrays) > 2:
                mod.bias = jnp.asarray(arrays[2])
            if len(arrays) > 3:
                mod.carry_bias = jnp.asarray(arrays[3])
    elif isinstance(mod, (L.BatchNormalization,)):
        mod.weight = jnp.asarray(arrays[0])
        mod.bias = jnp.asarray(arrays[1])
        if len(arrays) > 2:
            mod.running_mean = jnp.asarray(arrays[2])
        if len(arrays) > 3:
            # keras 1.2.2 stores running_std for mode=0 pre-1.0 configs,
            # variance otherwise; both enter as the variance slot
            mod.running_var = jnp.asarray(arrays[3])
    elif isinstance(mod, L.LookupTable):
        mod.weight = jnp.asarray(arrays[0])
    else:
        raise KerasConversionException(
            f"weight import for {type(mod).__name__} (layer {lname}) "
            "not supported"
        )


def _assign_recurrent(cell, lname, weight_names, arrays):
    """Keras-1.2.2 recurrent weights -> cell params.

    consume_less='cpu' saves one array per gate tensor named
    ``<layer>_W_i`` / ``_U_i`` / ``_b_i`` (LSTM gates i/c/f/o, GRU
    z/r/h, SimpleRNN plain W/U/b); consume_less='gpu' saves packed
    W/U/b with keras gate order i,f,c,o (LSTM) / z,r,h (GRU).  Mapping
    is name-based with a positional fallback in the 1.2.2
    trainable_weights order (i,c,f,o / z,r,h)."""
    import re

    import jax.numpy as jnp

    from bigdl_tpu.nn import recurrent as R

    named = {}
    for wn, arr in zip(weight_names, arrays):
        tail = wn.split("/")[-1].split(":")[0]
        m = re.search(r"_(W|U|b)(?:_(i|f|c|o|z|r|h))?$", tail)
        if m:
            named[(m.group(1), m.group(2))] = arr

    def pick(kind, gate):
        if (kind, gate) in named:
            return named[(kind, gate)]
        raise KerasConversionException(
            f"layer {lname}: missing recurrent weight {kind}_{gate}")

    H = cell.hidden_size
    if isinstance(cell, R.LSTM):
        if len(arrays) == 12:
            if not named:  # positional: 1.2.2 order i, c, f, o
                gates = ["i", "c", "f", "o"]
                named.update({("W", g): arrays[3 * k] for k, g in
                              enumerate(gates)})
                named.update({("U", g): arrays[3 * k + 1] for k, g in
                              enumerate(gates)})
                named.update({("b", g): arrays[3 * k + 2] for k, g in
                              enumerate(gates)})
            # our packing: (i, f, g=c, o)
            cell.w = jnp.asarray(np.concatenate(
                [pick("W", g) for g in ("i", "f", "c", "o")], axis=1))
            cell.u = jnp.asarray(np.concatenate(
                [pick("U", g) for g in ("i", "f", "c", "o")], axis=1))
            cell.b = jnp.asarray(np.concatenate(
                [pick("b", g) for g in ("i", "f", "c", "o")]))
        elif len(arrays) == 3:  # gpu mode: packed i, f, c, o — ours too
            cell.w = jnp.asarray(arrays[0])
            cell.u = jnp.asarray(arrays[1])
            cell.b = jnp.asarray(arrays[2])
        else:
            raise KerasConversionException(
                f"layer {lname}: unexpected LSTM weight count "
                f"{len(arrays)}")
    elif isinstance(cell, R.GRU):
        if len(arrays) == 9:
            if not named:  # positional: 1.2.2 order z, r, h
                gates = ["z", "r", "h"]
                named.update({("W", g): arrays[3 * k] for k, g in
                              enumerate(gates)})
                named.update({("U", g): arrays[3 * k + 1] for k, g in
                              enumerate(gates)})
                named.update({("b", g): arrays[3 * k + 2] for k, g in
                              enumerate(gates)})
            # our packing: (r, z) + candidate h
            cell.w_rz = jnp.asarray(np.concatenate(
                [pick("W", "r"), pick("W", "z")], axis=1))
            cell.u_rz = jnp.asarray(np.concatenate(
                [pick("U", "r"), pick("U", "z")], axis=1))
            cell.b_rz = jnp.asarray(np.concatenate(
                [pick("b", "r"), pick("b", "z")]))
            cell.w_h = jnp.asarray(pick("W", "h"))
            cell.u_h = jnp.asarray(pick("U", "h"))
            cell.b_h = jnp.asarray(pick("b", "h"))
        elif len(arrays) == 3:  # gpu mode: packed z, r, h
            W, U, b = (np.asarray(a) for a in arrays)
            cell.w_rz = jnp.asarray(
                np.concatenate([W[:, H:2 * H], W[:, :H]], axis=1))
            cell.u_rz = jnp.asarray(
                np.concatenate([U[:, H:2 * H], U[:, :H]], axis=1))
            cell.b_rz = jnp.asarray(np.concatenate([b[H:2 * H], b[:H]]))
            cell.w_h = jnp.asarray(W[:, 2 * H:])
            cell.u_h = jnp.asarray(U[:, 2 * H:])
            cell.b_h = jnp.asarray(b[2 * H:])
        else:
            raise KerasConversionException(
                f"layer {lname}: unexpected GRU weight count {len(arrays)}")
    elif isinstance(cell, R.RnnCell):
        cell.w = jnp.asarray(arrays[0])
        cell.u = jnp.asarray(arrays[1])
        if len(arrays) > 2:
            cell.b = jnp.asarray(arrays[2])
    else:
        raise KerasConversionException(
            f"recurrent weight import for {type(cell).__name__} "
            f"(layer {lname}) not supported")


def _iter_modules(m):
    yield m
    for child in getattr(m, "modules", []):
        yield from _iter_modules(child)
