"""bigdl_tpu.keras — Keras-1.2.2-compatible API.

Rebuild of «bigdl»/nn/keras/ (Scala shape-inferring wrappers with Shape
propagation) + «py»/nn/keras/ (SURVEY.md §2.1 / §2.2): Sequential model
with ``input_shape`` on the first layer, automatic shape inference layer
to layer, and the Keras training conveniences (compile/fit/evaluate/
predict) bridging into the bigdl_tpu Optimizer runtime.
"""

from bigdl_tpu.keras.layers import (
    Activation,
    AveragePooling2D,
    BatchNormalization,
    Bidirectional,
    Convolution2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    GlobalAveragePooling2D,
    GlobalMaxPooling2D,
    GRU,
    InputLayer,
    KerasLayer,
    LSTM,
    MaxPooling2D,
    Permute,
    RepeatVector,
    Reshape,
    SimpleRNN,
    TimeDistributedDense,
    ZeroPadding2D,
)
from bigdl_tpu.keras.models import Model, Sequential

__all__ = [
    "Model",
    "Sequential", "KerasLayer", "InputLayer", "Dense", "Activation",
    "Dropout", "Flatten", "Reshape", "Permute", "RepeatVector",
    "Convolution2D", "MaxPooling2D", "AveragePooling2D", "ZeroPadding2D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D", "BatchNormalization",
    "Embedding", "LSTM", "GRU", "SimpleRNN", "Bidirectional",
    "TimeDistributedDense",
]
