"""⟦«py»/models/lenet/lenet5.py⟧ — build_model + the training main."""
from bigdl_tpu.models.lenet import build_lenet5, main, train_lenet  # noqa: F401


def build_model(class_num: int = 10):
    """Reference spelling (lenet5.build_model)."""
    return build_lenet5(class_num=class_num)
