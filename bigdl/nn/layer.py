"""«py»/nn/layer.py shim — every layer under its classic name.

The reference file defines one thin ``JavaValue`` subclass per JVM
layer; here the real implementations are re-exported.  ``Model`` is the
graph constructor (functional API), matching Python-BigDL.
"""

from bigdl_tpu.nn import *  # noqa: F401,F403
from bigdl_tpu.nn import Graph, Input, Model, Sequential  # noqa: F401
from bigdl_tpu.nn.module import AbstractModule as Layer  # noqa: F401
