"""⟦«py»/nn/keras/layer.py⟧ — Keras-style layer spellings."""
from bigdl_tpu.keras.layers import *  # noqa: F401,F403
