"""⟦«py»/nn/keras/topology.py⟧ — Sequential Keras-style builder.

The reference also ships a graph-style ``Model(input, output)`` with
Keras shape inference; the rebuild's functional graph API lives at
``bigdl.nn.layer.Model`` (node-based) — use that for graph topologies.
"""
from bigdl_tpu.keras.models import Sequential  # noqa: F401
