"""pyspark-bigdl import path: bigdl.nn.keras (⟦«py»/nn/keras/⟧)."""
from bigdl.nn.keras import topology, layer  # noqa: F401
