"""«py»/nn/criterion.py shim — criterions under their classic names."""

from bigdl_tpu.nn.criterion import *  # noqa: F401,F403
