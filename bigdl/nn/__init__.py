from bigdl.nn import criterion, layer  # noqa: F401
