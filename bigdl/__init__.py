"""``bigdl`` — drop-in Python-BigDL API shim over bigdl_tpu.

The reference's Python package («py»/bigdl, SURVEY.md §2.2) is a thin
Py4J bridge: every layer/optimizer name resolves to a JVM object.  Here
Python *is* the runtime (SURVEY.md §3.4 note), so the shim simply
re-exports the bigdl_tpu implementations under the classic module paths:

    from bigdl.nn.layer import Sequential, Linear, SpatialConvolution
    from bigdl.nn.criterion import ClassNLLCriterion
    from bigdl.optim.optimizer import Optimizer, SGD, MaxEpoch
    from bigdl.util.common import init_engine, Sample

Existing BigDL user code keeps its imports; only the spark-specific
plumbing (JavaCreator, gateway bootstrap) becomes a no-op.
"""

__version__ = "0.1.0+tpu"
