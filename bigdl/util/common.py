"""«py»/util/common.py shim — engine bootstrap + data containers.

The reference's file bootstraps the Py4J gateway (``JavaCreator``,
``callBigDlFunc``) and converts numpy <-> JTensor.  Here there is no
JVM: ``init_engine`` initialises the TPU Engine, ``create_spark_conf``
returns a plain dict of the conf the reference would require, and
``JTensor``/``Sample`` wrap numpy directly.
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.dataset.sample import Sample  # noqa: F401
from bigdl_tpu.engine import Engine


def init_engine(bigdl_type: str = "float"):
    """Reference: ``init_engine()`` -> Engine.init (SURVEY.md §3.1)."""
    Engine.init()


def init_executor_gateway(sc=None):  # pragma: no cover - spark-only shim
    """No JVM gateway exists; kept for import compatibility."""


def create_spark_conf():
    """Reference: Engine.createSparkConf — returns the required conf as
    a dict (usable as ``SparkConf().setAll(conf.items())`` when pyspark
    is present)."""
    return {
        "spark.shuffle.reduceLocality.enabled": "false",
        "spark.scheduler.minRegisteredResourcesRatio": "1.0",
        "spark.speculation": "false",
    }


def get_node_and_core_number():
    from bigdl_tpu.engine import Engine as E

    if not E.is_initialized():
        E.init()
    return E.node_number(), E.core_number()


class JTensor:
    """numpy carrier (reference: JTensor ndarray<->Tensor bridge)."""

    def __init__(self, storage, shape, bigdl_type="float"):
        self.storage = np.asarray(storage, np.float32)
        self.shape = tuple(int(s) for s in shape)

    @classmethod
    def from_ndarray(cls, a):
        a = np.asarray(a, np.float32)
        return cls(a.reshape(-1), a.shape)

    def to_ndarray(self):
        return self.storage.reshape(self.shape)


class JavaValue:  # pragma: no cover - import-compat only
    """Placeholder for reference code that subclasses JavaValue; the
    constructor is a no-op (there is no JVM to call into)."""

    def __init__(self, *args, **kwargs):
        self.value = self
