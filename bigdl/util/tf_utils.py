"""«py»/util/tf_utils.py shim — TF graph import/export entry points."""

from bigdl_tpu.utils.tf_interop import (  # noqa: F401
    BigDLSessionImpl,
    TensorflowLoader,
    TensorflowSaver,
    TFTrainingSession,
    load_tf,
)
