from bigdl.util import common  # noqa: F401
