"""⟦«py»/keras/converter.py⟧ — Keras-1.2.2 JSON/HDF5 model importer."""
from bigdl_tpu.keras.converter import *  # noqa: F401,F403
