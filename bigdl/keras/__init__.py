from bigdl.keras import converter  # noqa: F401
