from bigdl.transform import vision  # noqa: F401
