from bigdl.transform.vision import image  # noqa: F401
