"""«py»/transform/vision/image.py shim — vision transforms."""

from bigdl_tpu.transform.vision import *  # noqa: F401,F403
