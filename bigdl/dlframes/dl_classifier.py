"""⟦«py»/dlframes/dl_classifier.py⟧ — DLEstimator/DLClassifier/DLModel."""
from bigdl_tpu.dlframes.dl_estimator import (  # noqa: F401
    DLClassifier,
    DLClassifierModel,
    DLEstimator,
    DLModel,
)
