from bigdl.dlframes import dl_classifier  # noqa: F401
