from bigdl_tpu.dataset import *  # noqa: F401,F403
from bigdl_tpu.dataset import mnist, text  # noqa: F401
from bigdl_tpu.dataset.sample import MiniBatch, Sample  # noqa: F401
