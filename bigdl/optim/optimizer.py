"""«py»/optim/optimizer.py shim — Optimizer, optim methods, triggers,
summaries under their Python-BigDL names.

Python-BigDL spells triggers as constructors (``MaxEpoch(n)``,
``EveryEpoch()``); the core Trigger factory provides them.
"""

from bigdl_tpu.optim import (  # noqa: F401
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    Ftrl,
    LBFGS,
    LarsSGD,
    LocalOptimizer,
    Loss,
    Optimizer,
    RMSprop,
    SGD,
    Top1Accuracy,
    Top5Accuracy,
    Trigger,
)
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer  # noqa: F401
from bigdl_tpu.optim.optim_method import (  # noqa: F401
    Default,
    EpochDecay,
    Exponential,
    MultiStep,
    Plateau,
    Poly,
    SequentialSchedule,
    Step,
    Warmup,
)
from bigdl_tpu.visualization import (  # noqa: F401
    TrainSummary,
    ValidationSummary,
)


# Python-BigDL trigger spellings are plain constructors
def MaxEpoch(n):  # noqa: N802 - reference spelling
    return Trigger.max_epoch(n)


def MaxIteration(n):  # noqa: N802
    return Trigger.max_iteration(n)


def EveryEpoch():  # noqa: N802
    return Trigger.every_epoch()


def SeveralIteration(n):  # noqa: N802
    return Trigger.several_iteration(n)


def MinLoss(v):  # noqa: N802
    return Trigger.min_loss(v)


def MaxScore(v):  # noqa: N802
    return Trigger.max_score(v)


def TriggerAnd(*ts):  # noqa: N802
    return Trigger.and_(*ts)


def TriggerOr(*ts):  # noqa: N802
    return Trigger.or_(*ts)
