from bigdl.optim import optimizer  # noqa: F401
