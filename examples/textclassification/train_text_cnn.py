"""Text classification — embedding + temporal CNN.

Reference analogue: «bigdl»/example/textclassification (GloVe + CNN on
news20).  With no corpus on disk it builds a deterministic synthetic
keyword-classification task (each class has signature tokens), exercising
the same pipeline: Dictionary -> padded id sequences -> LookupTable ->
TemporalConvolution -> pooling -> Linear.

    python examples/textclassification/train_text_cnn.py --max-epoch 3
"""

import argparse
import logging

import numpy as np


def build_text_cnn(vocab, embed=32, n_classes=4, doc_len=32):
    from bigdl_tpu.nn import (
        Linear, LogSoftMax, LookupTable, Max, ReLU, Sequential,
        TemporalConvolution,
    )

    return (
        Sequential()
        .add(LookupTable(vocab, embed))           # (B, T) -> (B, T, E)
        .add(TemporalConvolution(embed, 64, 5))   # (B, T-4, 64)
        .add(ReLU())
        .add(Max(2))                              # global max over time
        .add(Linear(64, n_classes))
        .add(LogSoftMax())
    )


def encode_texts(texts, dic, doc_len):
    """Raw texts -> padded 1-based id matrix.  The ONE encoding both
    training and serving (examples/udfpredict) must share — any unk/
    offset/tokenization change here reaches both sides."""
    x = np.zeros((len(texts), doc_len), np.float32)
    for i, text in enumerate(texts):
        for j, tok in enumerate(text.lower().split()[:doc_len]):
            # ids are 1-based for LookupTable; 0 stays padding
            x[i, j] = dic.get_index(tok, 0) + 1
    return x


def tokenize_corpus(docs, doc_len=128, vocab_limit=20000):
    """[(text, label)] -> padded id matrix via the Dictionary pipeline
    (reference: news20 GloVe+CNN example preprocessing)."""
    from bigdl_tpu.dataset.text import Dictionary

    tokenized = [d.lower().split() for d, _ in docs]
    dic = Dictionary(tokenized, vocab_size=vocab_limit)
    x = encode_texts([d for d, _ in docs], dic, doc_len)
    y = np.asarray([label for _, label in docs], np.float32)
    return x, y, dic


def load_corpus(data_dir=None, doc_len=128):
    """news20 from disk when present (bigdl_tpu.dataset.news20), else
    the deterministic synthetic stand-in — same pipeline either way."""
    from bigdl_tpu.dataset.news20 import get_news20, synthetic_news20

    try:
        docs = get_news20(data_dir) if data_dir else get_news20()
        n_classes = 20
    except FileNotFoundError:
        logging.getLogger(__name__).info(
            "no news20 corpus on disk; using the synthetic stand-in")
        docs = synthetic_news20(1536, class_num=4)
        n_classes = 4
    x, y, dic = tokenize_corpus(docs, doc_len)
    return x, y, len(dic) + 1, n_classes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("-e", "--max-epoch", type=int, default=3)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("-f", "--data-dir", default=None,
                    help="dir containing 20news-18828 (else synthetic)")
    ap.add_argument("--doc-len", type=int, default=32)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Adam, Optimizer, Top1Accuracy, Trigger

    x, y, vocab, n_classes = load_corpus(args.data_dir, args.doc_len)
    perm = np.random.RandomState(0).permutation(len(x))
    x, y = x[perm], y[perm]
    n_val = max(64, len(x) // 8)
    model = build_text_cnn(vocab=vocab, n_classes=n_classes,
                           doc_len=args.doc_len)
    optimizer = Optimizer(
        model=model,
        training_set=(x[:-n_val], y[:-n_val]),
        criterion=ClassNLLCriterion(),
        batch_size=args.batch_size,
        distributed=False,
    )
    optimizer.set_optim_method(Adam(learningrate=args.learning_rate)) \
        .set_end_when(Trigger.max_epoch(args.max_epoch)) \
        .set_validation(trigger=Trigger.every_epoch(),
                        dataset=(x[-n_val:], y[-n_val:]),
                        methods=[Top1Accuracy()])
    optimizer.optimize()


if __name__ == "__main__":
    main()
