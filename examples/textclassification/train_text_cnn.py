"""Text classification — embedding + temporal CNN.

Reference analogue: «bigdl»/example/textclassification (GloVe + CNN on
news20).  With no corpus on disk it builds a deterministic synthetic
keyword-classification task (each class has signature tokens), exercising
the same pipeline: Dictionary -> padded id sequences -> LookupTable ->
TemporalConvolution -> pooling -> Linear.

    python examples/textclassification/train_text_cnn.py --max-epoch 3
"""

import argparse
import logging

import numpy as np


def synthetic_corpus(n_docs=1536, n_classes=4, vocab=200, doc_len=32, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randint(6, vocab, size=(n_docs, doc_len))
    y = rs.randint(0, n_classes, n_docs)
    for i in range(n_docs):
        # plant 1-based signature tokens (ids 1..n_classes) for the class
        pos = rs.choice(doc_len, size=6, replace=False)
        x[i, pos] = y[i] + 1
    return x.astype(np.float32), (y + 1).astype(np.float32)


def build_text_cnn(vocab, embed=32, n_classes=4, doc_len=32):
    from bigdl_tpu.nn import (
        Linear, LogSoftMax, LookupTable, Max, ReLU, Sequential,
        TemporalConvolution,
    )

    return (
        Sequential()
        .add(LookupTable(vocab, embed))           # (B, T) -> (B, T, E)
        .add(TemporalConvolution(embed, 64, 5))   # (B, T-4, 64)
        .add(ReLU())
        .add(Max(2))                              # global max over time
        .add(Linear(64, n_classes))
        .add(LogSoftMax())
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("-e", "--max-epoch", type=int, default=3)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Adam, Optimizer, Top1Accuracy, Trigger

    x, y = synthetic_corpus()
    n_val = 256
    model = build_text_cnn(vocab=200)
    optimizer = Optimizer(
        model=model,
        training_set=(x[:-n_val], y[:-n_val]),
        criterion=ClassNLLCriterion(),
        batch_size=args.batch_size,
        distributed=False,
    )
    optimizer.set_optim_method(Adam(learningrate=args.learning_rate)) \
        .set_end_when(Trigger.max_epoch(args.max_epoch)) \
        .set_validation(trigger=Trigger.every_epoch(),
                        dataset=(x[-n_val:], y[-n_val:]),
                        methods=[Top1Accuracy()])
    optimizer.optimize()


if __name__ == "__main__":
    main()
