"""UDF prediction — serve a trained text classifier as a DataFrame UDF.

Reference analogue: «bigdl»/example/udfpredict (Spark SQL text
classification: a trained news20 CNN registered as a UDF and applied to
a DataFrame / streaming query column).  The rebuild keeps the shape of
that workflow without a Spark dependency: ``make_predict_udf`` wraps a
trained module into a plain callable over raw text, and the demo applies
it both row-wise (the UDF form) and via ``DLClassifierModel.transform``
over a dict-DataFrame (the DLframes form).

    python examples/udfpredict/udf_predict.py --max-epoch 2
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)
from examples.textclassification.train_text_cnn import (  # noqa: E402
    build_text_cnn, encode_texts, tokenize_corpus,
)

log = logging.getLogger("udfpredict")


def load_docs(data_dir=None):
    """news20 from disk when present, else the synthetic stand-in.  An
    explicitly requested corpus that can't be loaded is an error — the
    silent fallback applies only to the no-argument default."""
    from bigdl_tpu.dataset.news20 import get_news20, synthetic_news20

    if data_dir:
        return get_news20(data_dir), 20
    try:
        return get_news20(), 20
    except FileNotFoundError:
        log.info("no news20 corpus on disk; using the synthetic stand-in")
        return synthetic_news20(1536, class_num=4), 4


def make_predict_udf(model, dictionary, doc_len):
    """Return ``predict(text) -> 1-based class id`` — the UDF.

    Mirrors the reference's registered UDF: tokenize with the training
    Dictionary (via the SAME ``encode_texts`` the training side used),
    pad to ``doc_len``, forward, argmax.  Batched variant accepts a
    list of texts (one device dispatch for the whole column).
    """
    from bigdl_tpu.optim.evaluator import predict as module_predict

    def predict_udf(text_or_texts):
        texts = (
            [text_or_texts]
            if isinstance(text_or_texts, str) else list(text_or_texts)
        )
        logp = module_predict(
            model, encode_texts(texts, dictionary, doc_len)
        )
        cls = np.asarray(logp).argmax(axis=-1) + 1  # 1-based labels
        return int(cls[0]) if isinstance(text_or_texts, str) else cls

    return predict_udf


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--max-epoch", type=int, default=2)
    parser.add_argument("--doc-len", type=int, default=32)
    parser.add_argument("--batch-size", type=int, default=32)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import SGD, Optimizer, Trigger

    docs, n_classes = load_docs(args.data_dir)
    x, y, dic = tokenize_corpus(docs, args.doc_len)
    vocab = len(dic) + 1
    model = build_text_cnn(vocab, n_classes=n_classes, doc_len=args.doc_len)

    opt = Optimizer(
        model=model, training_set=(x, y), criterion=ClassNLLCriterion(),
        batch_size=args.batch_size,
    )
    opt.set_optim_method(SGD(learningrate=0.05))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    model = opt.optimize()

    # --- the UDF form: one callable, applied to a text column -----------
    predict_udf = make_predict_udf(model, dic, args.doc_len)
    texts = [doc for doc, _ in docs[:8]]
    labels = [label for _, label in docs[:8]]
    preds = predict_udf(texts)
    for text, pred, label in zip(texts, preds, labels):
        log.info("pred=%d label=%d  %.60s", pred, label, text)
    acc = float(np.mean(np.asarray(preds) == np.asarray(labels)))
    log.info("UDF head accuracy on %d rows: %.2f", len(texts), acc)

    # --- the DLframes form: same model via DLClassifierModel.transform --
    from bigdl_tpu.dlframes import DLClassifierModel

    df = {"text": texts, "features": [row for row in x[:8]]}
    dlmodel = DLClassifierModel(model, feature_size=[args.doc_len])
    out = dlmodel.transform(df)
    log.info("DLClassifierModel predictions: %s",
             [int(p) for p in out["prediction"]])
    return acc


if __name__ == "__main__":
    main()
