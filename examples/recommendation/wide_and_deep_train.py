"""Wide & Deep recommendation — sparse cross features + deep embeddings.

Reference analogue: the wide-and-deep recommendation path the sparse
stack exists to serve (SURVEY.md §2.1 "Sparse tensor": SparseLinear /
LookupTableSparse feed this family).  With no corpus on disk this
builds a synthetic tabular dataset: each sample carries a handful of
active wide cross-features (COO, packed to the fixed-slot encoding via
``SparseTensor.to_padded``) plus categorical deep columns; the label
mixes a memorization signal (one wide cross) with a generalization
signal (a deep-column interaction) — the textbook reason the two
towers are summed.

    python examples/recommendation/wide_and_deep_train.py --max-epoch 20
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)

log = logging.getLogger("wide_and_deep")


def synthetic_tabular(n=4096, wide_vocab=200, deep_vocabs=(12, 20, 8),
                      wide_active=4, seed=0):
    from bigdl_tpu.nn import SparseTensor

    rs = np.random.RandomState(seed)
    cols = rs.randint(0, wide_vocab, (n, wide_active))
    rows = np.repeat(np.arange(n), wide_active)
    sp = SparseTensor(
        np.stack([rows, cols.reshape(-1)], 1),
        np.ones(n * wide_active, np.float32), (n, wide_vocab))
    deep = np.stack(
        [rs.randint(1, v + 1, n) for v in deep_vocabs], axis=1)
    # label: wide memorization OR deep generalization
    y = (((cols[:, 0] > wide_vocab // 2).astype(int)
          | (deep[:, 0] > deep_vocabs[0] // 2).astype(int)) + 1
         ).astype(np.float32)
    return sp, deep, y


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=20)
    p.add_argument("--learning-rate", type=float, default=1.0)
    p.add_argument("--wide-vocab", type=int, default=200)
    p.add_argument("--wide-slots", type=int, default=8)
    p.add_argument("--distributed", action="store_true",
                   help="DistriOptimizer over the Engine mesh")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from bigdl_tpu.models import build_wide_and_deep, pack_batch
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import evaluate_dataset
    from bigdl_tpu.dataset import ArrayDataSet

    deep_vocabs = (12, 20, 8)
    sp, deep, y = synthetic_tabular(wide_vocab=args.wide_vocab,
                                    deep_vocabs=deep_vocabs)
    x = pack_batch(sp, deep, args.wide_slots)
    model = build_wide_and_deep(args.wide_vocab, deep_vocabs, class_num=2,
                                wide_slots=args.wide_slots)

    if args.distributed:
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.optim import DistriOptimizer

        Engine.init()
        opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                              batch_size=args.batch_size)
    else:
        from bigdl_tpu.optim.optimizer import LocalOptimizer

        opt = LocalOptimizer(model, (x, y), ClassNLLCriterion(),
                             batch_size=args.batch_size)
    opt.set_optim_method(SGD(learningrate=args.learning_rate))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    trained = opt.optimize()

    (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, args.batch_size),
                              [Top1Accuracy()])
    value, _ = acc.result()
    log.info("train-set Top1Accuracy: %.4f", value)
    return value


if __name__ == "__main__":
    main()
