"""NCF recommendation — implicit feedback with HitRatio/NDCG eval.

Reference analogue: the NCF recommendation example (⟦«py»⟧ NCF /
NeuralCF on MovieLens, evaluated with HitRatio@10 and NDCG@10).  With
no corpus on disk this builds a synthetic latent-factor interaction
dataset, trains NeuralCF on positive + sampled-negative pairs
(2-class ClassNLL, the implicit-feedback setup), and evaluates the
leave-one-out ranking protocol: for each user, rank one held-out
positive against 99 sampled negatives.

    python examples/recommendation/ncf_train.py --max-epoch 4
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)

log = logging.getLogger("ncf")


def _group_positives(rows, min_per_user=5):
    """(N, 3) rating rows -> implicit positives (rating >= 4, the
    standard NCF protocol): a list of 0-based item arrays, one per kept
    user.  Users with fewer than ``min_per_user`` positives are dropped
    (need one held-out + training items).  Vectorized — the real ml-1m
    file is a million rows."""
    keep = rows[rows[:, 2] >= 4]
    order = np.argsort(keep[:, 0], kind="stable")
    users, starts = np.unique(keep[order, 0], return_index=True)
    items = keep[order, 1] - 1
    chunks = np.split(items, starts[1:])
    return [c for c in chunks if len(c) >= min_per_user]


def synthetic_interactions(n_users=200, n_items=400, dim=4, per_user=20,
                           seed=0):
    """Latent-factor implicit feedback: each user interacts with their
    top-scoring items under the shared hidden embedding model
    (dataset/movielens.latent_scores)."""
    from bigdl_tpu.dataset.movielens import latent_scores

    scores = latent_scores(n_users, n_items, dim, seed)
    pos = np.argsort(-scores, axis=1)[:, :per_user]  # (U, per_user)
    return pos


def movielens_interactions(data_dir, min_per_user=5):
    """MovieLens ratings -> (positives, n_users, n_items) via the
    shared implicit-feedback grouping."""
    from bigdl_tpu.dataset.movielens import get_id_ratings

    rows = get_id_ratings(data_dir)
    pos = _group_positives(rows, min_per_user)
    return pos, len(pos), int(rows[:, 1].max())


def training_pairs(pos, n_items, neg_per_pos=4, seed=1):
    """(user, item) -> label 2 for positives, 1 for sampled negatives
    (1-based labels for ClassNLLCriterion)."""
    rs = np.random.RandomState(seed)
    users, items, labels = [], [], []
    pos_sets = [set(row) for row in pos]
    for uid, row in enumerate(pos):
        for it in row[1:]:  # item 0 is held out for evaluation
            users.append(uid); items.append(it); labels.append(2)
            for _ in range(neg_per_pos):
                j = rs.randint(n_items)
                while j in pos_sets[uid]:
                    j = rs.randint(n_items)
                users.append(uid); items.append(j); labels.append(1)
    x = np.stack([np.asarray(users) + 1.0, np.asarray(items) + 1.0], 1)
    return x.astype(np.float32), np.asarray(labels, np.float32)


def eval_ranking(model, pos, n_items, neg_num=99, k=10, seed=2):
    """Leave-one-out: score each user's held-out positive against
    neg_num sampled negatives; feed the grouped scores to the
    HitRatio/NDCG ValidationMethods."""
    from bigdl_tpu.optim import HitRatio, NDCG
    from bigdl_tpu.optim.evaluator import predict

    rs = np.random.RandomState(seed)
    pos_sets = [set(row) for row in pos]
    rows = []
    for uid, row in enumerate(pos):
        cands = [row[0]]
        while len(cands) < neg_num + 1:
            j = rs.randint(n_items)
            if j not in pos_sets[uid]:
                cands.append(j)
        for it in cands:
            rows.append((uid + 1, it + 1))
    x = np.asarray(rows, np.float32)
    logp = np.asarray(predict(model, x, batch_size=1000))
    scores = logp[:, 1]  # log P(interacted)
    hr = HitRatio(k=k, neg_num=neg_num).batch_result(scores, None)
    ndcg = NDCG(k=k, neg_num=neg_num).batch_result(scores, None)
    return hr.result()[0], ndcg.result()[0]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-f", "--data-dir", default=None,
                    help="dir containing ml-1m/ratings.dat (else a "
                         "synthetic latent-factor corpus)")
    ap.add_argument("-b", "--batch-size", type=int, default=256)
    ap.add_argument("-e", "--max-epoch", type=int, default=4)
    ap.add_argument("--learning-rate", type=float, default=1e-3)
    ap.add_argument("--n-users", type=int, default=200)
    ap.add_argument("--n-items", type=int, default=400)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    from bigdl_tpu.models.ncf import build_ncf
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    if args.data_dir:
        pos, n_users, n_items = movielens_interactions(args.data_dir)
        log.info("MovieLens: %d users, %d items", n_users, n_items)
    else:
        pos = synthetic_interactions(args.n_users, args.n_items)
        n_users, n_items = args.n_users, args.n_items
    x, y = training_pairs(pos, n_items)
    model = build_ncf(n_users, n_items, class_num=2)

    opt = Optimizer(model=model, training_set=(x, y),
                    criterion=ClassNLLCriterion(),
                    batch_size=args.batch_size)
    opt.set_optim_method(Adam(learningrate=args.learning_rate))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    model = opt.optimize()

    hr, ndcg = eval_ranking(model, pos, n_items)
    log.info("HitRatio@10: %.3f   NDCG@10: %.3f  (random ~ 0.10 / 0.045)",
             hr, ndcg)
    return hr, ndcg


if __name__ == "__main__":
    main()
