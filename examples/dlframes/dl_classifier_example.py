"""DLClassifier in an ML-pipeline flow — DataFrame in, DataFrame out.

Reference analogue: «bigdl»/example/DLframes + DLClassifierSpec usage:
fit a small MLP on a DataFrame of (features, label) columns, transform
adds a prediction column.  Runs on pandas (or a plain dict of columns;
a Spark DataFrame works the same way when pyspark is around).

    python examples/dlframes/dl_classifier_example.py
"""

import logging

import numpy as np


def main():
    logging.basicConfig(level=logging.INFO)
    from bigdl_tpu.dlframes import DLClassifier
    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, \
        Sequential

    rs = np.random.RandomState(0)
    n = 1024
    x = rs.randn(n, 6).astype(np.float32)
    # two interleaved classes, 1-based labels
    y = (1 + ((x[:, 0] + x[:, 1] * 0.5 + 0.1 * rs.randn(n)) > 0)).astype(
        np.float32
    )
    try:
        import pandas as pd

        df = pd.DataFrame({"features": list(x), "label": y})
    except ImportError:
        df = {"features": x, "label": y}

    model = Sequential().add(Linear(6, 32)).add(ReLU()) \
        .add(Linear(32, 2)).add(LogSoftMax())
    clf = DLClassifier(model, ClassNLLCriterion(), [6]) \
        .set_batch_size(64).set_max_epoch(5).set_learning_rate(0.1)
    fitted = clf.fit(df)
    out = fitted.transform(df)
    pred = np.asarray(
        out["prediction"] if isinstance(out, dict) else out["prediction"].tolist()
    )
    acc = (pred.reshape(-1) == y).mean()
    print(f"train accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
