"""Tree-LSTM sentiment — the reference's treeLSTMSentiment example.

Reference analogue: «bigdl»/example/treeLSTMSentiment (BinaryTreeLSTM
over constituency trees, GloVe leaf embeddings, sentiment at the root).
With no SST corpus on disk, a deterministic synthetic task stands in:
random binary trees whose label is the majority sign of a planted leaf
feature — same model, same array encoding, same TreeNNAccuracy metric.

    python examples/treelstm/train_tree_sentiment.py --max-steps 200
"""

import argparse
import logging

import numpy as np


def synthetic_trees(batch, n_leaves, dim, seed=7):
    from bigdl_tpu.nn.tree_lstm import random_binary_trees

    children, leaf_slots = random_binary_trees(batch, n_leaves, seed)
    n = 2 * n_leaves - 1
    rs = np.random.RandomState(seed + 1)
    emb = np.zeros((batch, n, dim), np.float32)
    labels = np.zeros((batch,), np.float32)
    for bi, leaves in enumerate(leaf_slots):
        signs = rs.choice([-1.0, 1.0], len(leaves))
        for slot, s in zip(leaves, signs):
            v = rs.randn(dim) * 0.1
            v[0] = s
            emb[bi, slot] = v
        labels[bi] = 1.0 if signs.sum() > 0 else 2.0
    return emb, children, labels


def main():
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn import BinaryTreeLSTM
    from bigdl_tpu.optim import TreeNNAccuracy

    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("--n-leaves", type=int, default=8)
    ap.add_argument("--embed-dim", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--max-steps", type=int, default=200)
    ap.add_argument("--learning-rate", type=float, default=0.3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("treelstm")

    emb, children, labels = synthetic_trees(
        args.batch_size, args.n_leaves, args.embed_dim)
    m = BinaryTreeLSTM(args.embed_dim, args.hidden)
    rs = np.random.RandomState(0)
    params = {"tree": m.params(),
              "w": jnp.asarray(rs.randn(args.hidden, 2) * 0.1)}
    emb_j, ch_j = jnp.asarray(emb), jnp.asarray(children)
    y = jnp.asarray(labels, jnp.int32) - 1

    def loss_fn(p):
        h, _ = m.apply(p["tree"], {}, (emb_j, ch_j))
        logp = jax.nn.log_softmax(h[:, 0] @ p["w"])
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

    lr = args.learning_rate
    step = jax.jit(lambda p: jax.tree.map(
        lambda w, g: w - lr * g, p, jax.grad(loss_fn)(p)))
    for i in range(args.max_steps):
        params = step(params)
        if (i + 1) % 50 == 0:
            log.info("step %d loss %.4f", i + 1, float(loss_fn(params)))

    h, _ = m.apply(params["tree"], {}, (emb_j, ch_j))
    logits = np.asarray(h[:, 0] @ params["w"])
    acc = TreeNNAccuracy().batch_result(logits[:, None, :], labels)
    log.info("root sentiment accuracy: %.4f", acc.result()[0])


if __name__ == "__main__":
    main()
