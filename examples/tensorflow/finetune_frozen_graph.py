"""Fine-tune a frozen TensorFlow GraphDef (BigDLSession path).

Reference analogue: the TF-interop examples (Module.loadTF + the
BigDLSessionImpl training session, SURVEY.md §2.1 "TensorFlow
interop").  With no model zoo on disk this script first EXPORTS a small
frozen classifier GraphDef (TensorflowSaver), then imports it with
``TFTrainingSession`` and fine-tunes it on a synthetic task under the
chosen optimizer — gradients flow through every imported op.

    python examples/tensorflow/finetune_frozen_graph.py --max-epoch 8
"""

import argparse
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)

log = logging.getLogger("tf_finetune")


def export_frozen_classifier(path, d, k, seed=0):
    """Build + freeze a small MLP classifier as a GraphDef file."""
    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn.graph import Graph, Input
    from bigdl_tpu.utils.tf_interop import TensorflowSaver

    rs = np.random.RandomState(seed)
    inp = Input("x")
    h = L.Linear(d, 32).set_name("fc1")(inp)
    h = L.ReLU().set_name("relu1")(h)
    h = L.Linear(32, k).set_name("fc2")(h)
    h = L.LogSoftMax().set_name("logp")(h)
    g = Graph(inp, h)
    TensorflowSaver.save(g, path)
    return path


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("-e", "--max-epoch", type=int, default=8)
    p.add_argument("--learning-rate", type=float, default=0.5)
    p.add_argument("--graph", default=None,
                   help="existing frozen GraphDef; default: export one")
    p.add_argument("--distributed", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import evaluate_dataset
    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.utils.tf_interop import TFTrainingSession

    d, k, n = 16, 4, 1024
    rs = np.random.RandomState(1)
    w = rs.randn(d, k)
    x = rs.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)

    graph_path = args.graph
    if graph_path is None:
        graph_path = os.path.join(tempfile.gettempdir(),
                                  "bigdl_tpu_frozen_mlp.pb")
        export_frozen_classifier(graph_path, d, k)
        log.info("exported frozen classifier to %s", graph_path)

    if args.distributed:
        from bigdl_tpu.engine import Engine

        Engine.init()
    sess = TFTrainingSession(graph_path, inputs=["x"], outputs=["logp"])
    trained = sess.train(
        (x, y), ClassNLLCriterion(),
        optim_method=SGD(learningrate=args.learning_rate),
        batch_size=args.batch_size,
        end_trigger=Trigger.max_epoch(args.max_epoch),
        distributed=args.distributed)

    (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, args.batch_size),
                              [Top1Accuracy()])
    value, _ = acc.result()
    log.info("fine-tuned Top1Accuracy: %.4f", value)
    return value


if __name__ == "__main__":
    main()
