"""Train from a TF graph that ships its OWN input pipeline.

Reference analogue: the BigDLSessionImpl usage the reference was built
for (SURVEY.md §2.1 "TensorFlow interop": a Session that runs TF graphs
for training data pipelines) — a TF1-era export whose input side is
Const(filenames) -> filename queue -> TFRecordReader -> example queue ->
QueueDequeueMany -> ParseExample, feeding the trainable model ops.

With no model zoo on disk this script first WRITES a synthetic TFRecord
training set and a pipeline-bearing GraphDef, then imports the graph
with ``BigDLSessionImpl``: the reader chain is lifted host-side (the
queue-dequeue boundary becomes an iterator seam, the TPU-native shape
of the reference's executor-side queue runners) and the model
fine-tunes under DistriOptimizer from the graph's own files.

    python examples/tensorflow/train_from_tf_pipeline.py --max-epoch 8
"""

import argparse
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)

log = logging.getLogger("tf_pipeline")


def write_tfrecords(tmpdir, x, y, shards=3):
    from bigdl_tpu.utils.tf_records import TFRecordWriter, encode_example

    files = []
    for si, idx in enumerate(np.array_split(np.arange(len(x)), shards)):
        path = os.path.join(tmpdir, f"train-{si}.tfrecord")
        with TFRecordWriter(path) as w:
            for i in idx:
                w.write(encode_example({
                    "x": x[i],
                    "y": np.asarray([y[i]], np.float32),
                }))
        files.append(path)
    return files


def export_pipeline_graph(path, files, d, k, batch=32, seed=0):
    """A TF1-style GraphDef: reader/queue/ParseExample input side wired
    into a trainable MLP classifier."""
    from bigdl_tpu.utils.tf_interop import (
        _DT_FLOAT,
        _DT_STRING,
        GraphDefBuilder,
    )

    rs = np.random.RandomState(seed)
    b = GraphDefBuilder()
    b.const("files", np.asarray(files, dtype=object))
    b.op("fq", "FIFOQueueV2", [],
         component_types=b.attr_types([_DT_STRING]))
    b.op("enq_files", "QueueEnqueueManyV2", ["fq", "files"])
    b.op("reader", "TFRecordReaderV2", [])
    b.op("read", "ReaderReadV2", ["reader", "fq"])
    b.op("eq", "FIFOQueueV2", [],
         component_types=b.attr_types([_DT_STRING]))
    b.op("enq_ex", "QueueEnqueueV2", ["eq", "read:1"])
    b.const("batch", np.asarray(batch, np.int32))
    b.op("deq", "QueueDequeueManyV2", ["eq", "batch"],
         component_types=b.attr_types([_DT_STRING]))
    b.const("key_x", np.asarray(["x"], dtype=object))
    b.const("key_y", np.asarray(["y"], dtype=object))
    b.const("names", np.asarray([], dtype=object))
    b.const("def_x", np.zeros(0, np.float32))
    b.const("def_y", np.zeros(0, np.float32))
    b.op("parse", "ParseExample",
         ["deq", "names", "key_x", "key_y", "def_x", "def_y"],
         Nsparse=b.attr_i(0), Ndense=b.attr_i(2),
         Tdense=b.attr_types([_DT_FLOAT, _DT_FLOAT]),
         dense_shapes=b.attr_shapes([[d], [1]]))
    b.const("w1", (rs.randn(d, 32) * 0.3).astype(np.float32))
    b.const("w2", (rs.randn(32, k) * 0.3).astype(np.float32))
    b.op("mm1", "MatMul", ["parse", "w1"])
    b.op("relu", "Relu", ["mm1"])
    b.op("mm2", "MatMul", ["relu", "w2"])
    b.op("logp", "LogSoftmax", ["mm2"])
    with open(path, "wb") as f:
        f.write(b.tobytes())
    return path


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--max-epoch", type=int, default=8)
    p.add_argument("--learning-rate", type=float, default=0.5)
    p.add_argument("-n", "--num-samples", type=int, default=256)
    p.add_argument("--local", action="store_true",
                   help="LocalOptimizer instead of DistriOptimizer")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import evaluate_dataset
    from bigdl_tpu.utils.tf_interop import BigDLSessionImpl

    Engine.init()
    rs = np.random.RandomState(11)
    d, k, n = 16, 4, args.num_samples
    wtrue = rs.randn(d, k)
    x = rs.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ wtrue, axis=1) + 1).astype(np.float32)

    tmp = tempfile.mkdtemp(prefix="bigdl_tf_pipeline_")
    files = write_tfrecords(tmp, x, y)
    pb = export_pipeline_graph(
        os.path.join(tmp, "train_graph.pb"), files, d, k)
    log.info("wrote %d TFRecord shards + pipeline graph %s",
             len(files), pb)

    sess = BigDLSessionImpl(path=pb)
    log.info("lifted pipeline: seams=%s batch=%d files=%d",
             sess.pipeline.seam_refs, sess.pipeline.batch_size,
             len(sess.pipeline.dataset.filenames))
    trained = sess.train_with_pipeline(
        ClassNLLCriterion(), label_key="y",
        label_transform=lambda a: a.reshape(-1),
        optim_method=SGD(learningrate=args.learning_rate),
        end_trigger=Trigger.max_epoch(args.max_epoch),
        distributed=not args.local)

    (acc,) = evaluate_dataset(
        trained, ArrayDataSet(x, y, 64), [Top1Accuracy()])
    value, _ = acc.result()
    log.info("fine-tuned Top1Accuracy from the graph's own pipeline: %.4f",
             value)
    return value


if __name__ == "__main__":
    main()
