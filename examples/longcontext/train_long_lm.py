"""Long-context LM training: remat + Ulysses sequence parallelism.

Beyond-reference showcase (SURVEY.md §5 notes the reference has NO
long-context story — sequence length is bounded by one replica's
memory).  This example trains a decoder-only TransformerLM on
synthetic token streams with BOTH long-context levers on:

* ``remat=True`` — per-block gradient checkpointing: backward
  recomputes each block's forward, so activation HBM no longer scales
  with ``n_layer * seq``;
* Ulysses sequence parallelism — each device holds ``T / seq_devices``
  of every sequence; attention reshards sequence->heads through one
  all_to_all pair, so the sequence axis scales with the mesh.

Run (8 virtual devices for the mesh):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/longcontext/train_long_lm.py --seq 1024
"""

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)

log = logging.getLogger("long_lm")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--seq-devices", type=int, default=None,
                   help="mesh size for the sequence axis "
                        "(default: all devices)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bigdl_tpu.engine import Engine
    from bigdl_tpu.models.transformer import build_transformer_lm
    from bigdl_tpu.parallel.ulysses import UlyssesMultiHeadAttention

    n_seq = args.seq_devices or len(jax.devices())
    if args.seq % n_seq:
        raise SystemExit(
            f"--seq {args.seq} must be divisible by the {n_seq}-way "
            "sequence axis")
    if args.heads % n_seq:
        raise SystemExit(
            f"--heads {args.heads} must be divisible by the {n_seq}-way "
            "sequence axis (Ulysses reshards sequence onto heads)")
    mesh = Engine.build_mesh({"seq": n_seq},
                             devices=jax.devices()[:n_seq])
    log.info("mesh: %d-way sequence parallel, seq=%d (%d tokens/device)",
             n_seq, args.seq, args.seq // n_seq)

    # flagship LM with remat'd blocks, attention swapped for the
    # sequence-parallel Ulysses variant (n_head >= seq devices)
    model = build_transformer_lm(
        args.vocab, dim=args.dim, n_head=args.heads, n_layer=args.layers,
        max_len=args.seq, remat=True)
    for i in range(args.layers):
        blk = model._children[f"h{i}"]
        ul = UlyssesMultiHeadAttention(
            args.dim, args.heads, mesh, seq_axis="seq", causal=True)
        # keep the block's initialized projections
        ul.set_params(blk._children["attn"].params())
        blk._children["attn"] = ul

    params = model.params()
    rs = np.random.RandomState(0)
    # synthetic copy-task-ish stream: next token = (token + 1) % vocab,
    # so the LM has a learnable structure and loss must fall
    start = rs.randint(0, args.vocab, (4, 1))
    ids = (start + np.arange(args.seq)[None, :]) % args.vocab
    x = jnp.asarray(ids.astype(np.float32))
    y = jnp.asarray((ids + 1) % args.vocab)
    shard = NamedSharding(mesh, P(None, "seq"))
    x = jax.device_put(x, shard)
    y = jax.device_put(y, shard)

    def loss_fn(p, x, y):
        logits, _ = model.apply(p, model.state(), x, training=True,
                                rng=jax.random.key(0))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, :, None], 2))

    @jax.jit
    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g)
        return p, loss

    first = None
    t0 = time.time()
    for i in range(args.steps):
        params, loss = step(params, x, y)
        if i == 0:
            first = float(loss)
            log.info("step 0 loss %.4f (compile %.1fs)", first,
                     time.time() - t0)
        elif i % 10 == 0 or i == args.steps - 1:
            log.info("step %d loss %.4f", i, float(loss))
    final = float(loss)
    log.info("loss %.4f -> %.4f over %d steps (seq %d, %d-way "
             "sequence-parallel, remat on)", first, final, args.steps,
             args.seq, n_seq)
    assert final < first, (first, final)
    return final


if __name__ == "__main__":
    main()
