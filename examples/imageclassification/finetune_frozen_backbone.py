"""Transfer learning — frozen backbone, trainable head.

Reference analogue: the fine-tune image-classification examples built
on ``model.freeze(names*)``.  A small conv backbone is "pretrained" on
one synthetic task, frozen, and a fresh head is trained on a second
task; the backbone must come out bit-identical while the head learns.

    python examples/imageclassification/finetune_frozen_backbone.py
"""

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
)

log = logging.getLogger("finetune")


def synthetic_images(n, n_class, seed):
    rs = np.random.RandomState(seed)
    y = (rs.randint(0, n_class, n) + 1).astype(np.float32)
    x = rs.rand(n, 3, 16, 16).astype(np.float32) * 0.2
    for i in range(n):
        c = int(y[i]) - 1
        x[i, c % 3, 2 + c:10 + c, 2:10] += 0.8  # class-dependent patch
    return x, y


def build(n_class):
    from bigdl_tpu.nn import (
        Linear, LogSoftMax, ReLU, Reshape, Sequential,
        SpatialConvolution, SpatialMaxPooling,
    )

    backbone = Sequential() \
        .add(SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)) \
        .add(ReLU()) \
        .add(SpatialMaxPooling(2, 2)) \
        .add(SpatialConvolution(8, 16, 3, 3, 1, 1, 1, 1)) \
        .add(ReLU()) \
        .add(SpatialMaxPooling(2, 2)) \
        .add(Reshape([16 * 4 * 4], batch_mode=True))
    backbone.set_name("backbone")
    head = Sequential() \
        .add(Linear(256, n_class)).add(LogSoftMax())
    head.set_name("head")
    return Sequential().add(backbone).add(head), backbone, head


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("-e", "--max-epoch", type=int, default=6)
    p.add_argument("--learning-rate", type=float, default=0.5)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, Sequential
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import evaluate_dataset
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.dataset import ArrayDataSet

    n_class = 3
    model, backbone, head = build(n_class)

    # phase 1: "pretrain" end to end
    x1, y1 = synthetic_images(512, n_class, seed=0)
    opt = LocalOptimizer(model, (x1, y1), ClassNLLCriterion(),
                         batch_size=args.batch_size)
    opt.set_optim_method(SGD(learningrate=args.learning_rate))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    opt.optimize()

    # phase 2: freeze the backbone, swap a fresh head, fine-tune on a
    # new task (same patches, permuted labels)
    model.freeze("backbone")
    w_frozen = [w.copy() for w in backbone.get_weights()]
    model.modules[1] = Sequential() \
        .add(Linear(256, n_class)).add(LogSoftMax())
    x2, y2 = synthetic_images(512, n_class, seed=1)
    y2 = ((y2 % n_class) + 1).astype(np.float32)  # permuted labels
    opt2 = LocalOptimizer(model, (x2, y2), ClassNLLCriterion(),
                          batch_size=args.batch_size)
    opt2.set_optim_method(SGD(learningrate=args.learning_rate))
    opt2.set_end_when(Trigger.max_epoch(args.max_epoch))
    trained = opt2.optimize()

    for before, after in zip(w_frozen, backbone.get_weights()):
        np.testing.assert_array_equal(before, after)
    log.info("backbone bit-identical after fine-tune (frozen)")

    (acc,) = evaluate_dataset(trained, ArrayDataSet(x2, y2,
                                                    args.batch_size),
                              [Top1Accuracy()])
    value, _ = acc.result()
    log.info("fine-tuned head Top1Accuracy: %.4f", value)
    return value


if __name__ == "__main__":
    main()
