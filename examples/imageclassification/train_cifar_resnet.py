"""ResNet on CIFAR-10 — runnable image-classification example.

Reference analogue: «bigdl»/models/resnet/TrainCIFAR10.scala (scopt CLI
in Utils.scala).  With no dataset on disk it trains on a deterministic
synthetic CIFAR-shaped task so the example always runs end to end.

    python examples/imageclassification/train_cifar_resnet.py \
        --depth 20 --batch-size 128 --max-epoch 2
"""

import argparse
import logging

import numpy as np


def synthetic_cifar(n_train=2048, n_val=512, seed=0):
    """Class-dependent colored blobs — learnable, deterministic."""
    rs = np.random.RandomState(seed)
    n = n_train + n_val
    y = rs.randint(0, 10, n)
    x = rs.randn(n, 3, 32, 32).astype(np.float32) * 0.3
    for i in range(n):
        c = y[i]
        x[i, c % 3, (c * 3) % 28 : (c * 3) % 28 + 4, :] += 1.5
        x[i, (c + 1) % 3, :, (c * 2) % 28 : (c * 2) % 28 + 4] -= 1.2
    labels = (y + 1).astype(np.float32)  # 1-based (ClassNLL convention)
    return (x[:n_train], labels[:n_train]), (x[n_train:], labels[n_train:])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=20)
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("-e", "--max-epoch", type=int, default=2)
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--distributed", action="store_true")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    from bigdl_tpu.models import build_resnet_cifar
    from bigdl_tpu.nn import CrossEntropyCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.optim_method import Poly

    (x, y), (vx, vy) = synthetic_cifar()
    model = build_resnet_cifar(depth=args.depth, class_num=10)
    n_iters = args.max_epoch * (len(x) // args.batch_size)
    optimizer = Optimizer(
        model=model,
        training_set=(x, y),
        criterion=CrossEntropyCriterion(),
        batch_size=args.batch_size,
        distributed=args.distributed,
    )
    optimizer.set_optim_method(
        SGD(learningrate=args.learning_rate, momentum=0.9,
            dampening=0.0, nesterov=True, weightdecay=1e-4,
            learningrate_schedule=Poly(2.0, n_iters))
    ).set_end_when(Trigger.max_epoch(args.max_epoch)) \
        .set_validation(trigger=Trigger.every_epoch(), dataset=(vx, vy),
                        methods=[Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint)
    optimizer.optimize()


if __name__ == "__main__":
    main()
