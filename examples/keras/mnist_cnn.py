"""Keras-API MNIST CNN — the reference's keras example.

Reference analogue: «bigdl»/example/keras (the Keras-1.2.2-compatible
API driving BigDL training).  Same shape here: the bigdl_tpu.keras
Sequential builds the model, ``compile``/``fit``/``evaluate`` drive it.
With no MNIST on disk the deterministic synthetic digits stand in.

    python examples/keras/mnist_cnn.py --nb-epoch 2
"""

import argparse
import logging

import numpy as np


def main():
    from bigdl_tpu.dataset.mnist import load_mnist, normalize
    from bigdl_tpu.keras.layers import (
        Activation, Convolution2D, Dense, Dropout, Flatten, MaxPooling2D,
    )
    from bigdl_tpu.keras.models import Sequential

    ap = argparse.ArgumentParser()
    ap.add_argument("-f", "--data-dir", default=None)
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("--nb-epoch", type=int, default=2)
    ap.add_argument("-n", "--num-samples", type=int, default=2048)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("keras_mnist")

    x, y = load_mnist(args.data_dir, "train", synthetic_n=args.num_samples)
    x = normalize(x).reshape(-1, 1, 28, 28)

    model = Sequential()
    model.add(Convolution2D(16, 3, 3, activation="relu",
                            input_shape=(1, 28, 28)))
    model.add(MaxPooling2D((2, 2)))
    model.add(Convolution2D(32, 3, 3, activation="relu"))
    model.add(MaxPooling2D((2, 2)))
    model.add(Flatten())
    model.add(Dense(64, activation="relu"))
    model.add(Dropout(0.25))
    model.add(Dense(10, activation="softmax"))
    log.info("\n%s", model.summary())

    model.compile(optimizer="adam", loss="categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x[256:], y[256:], batch_size=args.batch_size,
              nb_epoch=args.nb_epoch)
    loss, acc = model.evaluate(x[:256], y[:256],
                               batch_size=args.batch_size)
    log.info("held-out loss %.4f accuracy %.4f", loss, acc)


if __name__ == "__main__":
    main()
