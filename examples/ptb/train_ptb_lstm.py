"""PTB language model — LSTM with BPTT windows.

Reference analogue: «bigdl»/models/rnn (SimpleRNN/LSTM PTB trainer with
TimeDistributedCriterion).  Runs on the synthetic Markov token stream
when no PTB file is given; reports perplexity per epoch.

    python examples/ptb/train_ptb_lstm.py --max-epoch 2 --num-steps 20
"""

import argparse
import logging


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="tokenised PTB text file")
    ap.add_argument("-b", "--batch-size", type=int, default=20)
    ap.add_argument("--num-steps", type=int, default=20)
    ap.add_argument("-e", "--max-epoch", type=int, default=2)
    ap.add_argument("--learning-rate", type=float, default=0.5)
    ap.add_argument("--vocab-size", type=int, default=100)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    from bigdl_tpu.dataset.text import Dictionary, synthetic_ptb_stream
    from bigdl_tpu.models.rnn import train_ptb

    tokens = None
    vocab_size = args.vocab_size
    if args.data:
        with open(args.data) as f:
            words = f.read().split()
        d = Dictionary([words], vocab_size=args.vocab_size)
        import numpy as np

        tokens = np.asarray([d.get_index(w) for w in words], np.int64)
        vocab_size = d.vocab_size()
    model, _opt, ppl = train_ptb(
        data_tokens=tokens,
        vocab_size=vocab_size,
        batch_size=args.batch_size,
        num_steps=args.num_steps,
        max_epoch=args.max_epoch,
        learning_rate=args.learning_rate,
    )
    print(f"final perplexity: {ppl:.2f}")


if __name__ == "__main__":
    main()
