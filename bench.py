"""Benchmark — ResNet-50 training throughput on the real chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": R}

The metric is the BASELINE.json headline (ResNet-50 ImageNet
images/sec/chip).  ``vs_baseline`` is measured against a hand-written
plain-JAX ResNet-50 train step defined in this file (independent of the
framework: raw pytree params, inline conv/BN calls, direct SGD tree
update).  The reference repo ships no locally citable numbers
(BASELINE.md), so raw JAX on the same chip is the honest baseline: the
ratio isolates framework overhead — >= 1.0 means the bigdl_tpu module
system, flat-parameter optimizer, and driver loop cost nothing over
hand-rolled JAX.
"""

from __future__ import annotations

import json
import time

import numpy as np

BATCH = 32
IMG = 224
N_CLASSES = 1000
WARMUP = 3
ITERS = 10


# --------------------------------------------------------------------------
# plain-JAX ResNet-50 (the baseline): raw functions + pytree params
# --------------------------------------------------------------------------


def _baseline_resnet50_init(rng):
    import jax

    params = {}

    def conv_p(key, cin, cout, k):
        fan = cin * k * k
        params[key] = {
            "w": jax.random.normal(
                jax.random.fold_in(rng, hash(key) % (2**31)),
                (cout, cin, k, k),
                dtype=np.float32,
            )
            * np.sqrt(2.0 / fan)
        }

    def bn_p(key, c):
        import jax.numpy as jnp

        params[key] = {
            "scale": jnp.ones(c),
            "bias": jnp.zeros(c),
            "mean": jnp.zeros(c),
            "var": jnp.ones(c),
        }

    conv_p("stem", 3, 64, 7)
    bn_p("stem_bn", 64)
    cfg = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
    cin = 64
    for s, (w, n, stride) in enumerate(cfg):
        for i in range(n):
            pfx = f"s{s}b{i}"
            conv_p(pfx + "c1", cin, w, 1)
            bn_p(pfx + "bn1", w)
            conv_p(pfx + "c2", w, w, 3)
            bn_p(pfx + "bn2", w)
            conv_p(pfx + "c3", w, w * 4, 1)
            bn_p(pfx + "bn3", w * 4)
            if i == 0:
                conv_p(pfx + "sc", cin, w * 4, 1)
                bn_p(pfx + "scbn", w * 4)
            cin = w * 4
    import jax.numpy as jnp

    params["fc"] = {
        "w": jax.random.normal(jax.random.fold_in(rng, 77), (cin, N_CLASSES))
        * 0.01,
        "b": jnp.zeros(N_CLASSES),
    }
    return params


def _baseline_forward(params, x):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def conv(p, x, stride=1, pad="SAME"):
        return lax.conv_general_dilated(
            x, p["w"], (stride, stride), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    def bn(p, x):
        # training-mode BN with batch statistics in f32, matching the
        # framework's SpatialBatchNormalization normalization math under
        # both precisions (the framework additionally updates running-
        # stat EMAs — that small extra cost stays attributed to the
        # framework side of the ratio)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 2, 3))
        var = jnp.var(xf, axis=(0, 2, 3))
        inv = jax.lax.rsqrt(var + 1e-5) * p["scale"].astype(jnp.float32)
        y = xf * inv[None, :, None, None] + (
            p["bias"].astype(jnp.float32) - mean * inv
        )[None, :, None, None]
        return y.astype(x.dtype)

    x = conv(params["stem"], x, 2)
    x = jax.nn.relu(bn(params["stem_bn"], x))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
        [(0, 0), (0, 0), (1, 1), (1, 1)],
    )
    cfg = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
    for s, (w, n, stride) in enumerate(cfg):
        for i in range(n):
            pfx = f"s{s}b{i}"
            st = stride if i == 0 else 1
            y = jax.nn.relu(bn(params[pfx + "bn1"], conv(params[pfx + "c1"], x)))
            y = jax.nn.relu(bn(params[pfx + "bn2"], conv(params[pfx + "c2"], y, st)))
            y = bn(params[pfx + "bn3"], conv(params[pfx + "c3"], y))
            if i == 0:
                sc = bn(params[pfx + "scbn"], conv(params[pfx + "sc"], x, st))
            else:
                sc = x
            x = jax.nn.relu(y + sc)
    x = jnp.mean(x, axis=(2, 3))
    return x @ params["fc"]["w"] + params["fc"]["b"]


def _timed_scan_throughput(step_fn, carry, x, y):
    """Run ITERS steps inside ONE jitted lax.scan and time the call: the
    relay between this host and the chip adds per-call and per-buffer
    overheads that would otherwise dominate; a single call with one
    scalar output measures pure device throughput for both contenders.
    ``float()`` on the result is the barrier (block_until_ready returns
    early through the relay)."""
    import jax
    import jax.lax as lax

    @jax.jit
    def run(carry, x, y):
        def body(c, _):
            c, loss = step_fn(c, x, y)
            return c, loss

        _, losses = lax.scan(body, carry, None, length=ITERS)
        return losses[-1]

    float(run(carry, x, y))  # compile + warmup
    t0 = time.perf_counter()
    float(run(carry, x, y))
    dt = time.perf_counter() - t0
    return BATCH * ITERS / dt


def _bench_baseline(x, y, compute_dtype=None):
    import jax
    import jax.numpy as jnp

    params = _baseline_resnet50_init(jax.random.key(0))

    def loss_fn(p, x, y):
        if compute_dtype is not None:
            # same mixed-precision policy as the framework: bf16 fwd/bwd
            # inside the differentiated fn, f32 master params + loss
            ct = jnp.dtype(compute_dtype)
            p = jax.tree.map(
                lambda a: a.astype(ct)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, p
            )
            x = x.astype(ct)
        logits = _baseline_forward(p, x).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        idx = y.astype(jnp.int32) - 1
        return -jnp.mean(jnp.take_along_axis(logp, idx[:, None], 1))

    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g)
        return p, loss

    return _timed_scan_throughput(step, params, jnp.asarray(x), jnp.asarray(y))


def _bench_framework(x, y, compute_dtype=None):
    import jax

    from bigdl_tpu.models import build_resnet_imagenet
    from bigdl_tpu.nn import CrossEntropyCriterion
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    model = build_resnet_imagenet(depth=50, class_num=N_CLASSES)
    # drop the LogSoftMax tail; CrossEntropyCriterion fuses it (same as
    # the baseline's fused log_softmax)
    model.modules = model.modules[:-1]
    crit = CrossEntropyCriterion()
    opt = LocalOptimizer(model, (x, y), crit, batch_size=BATCH)
    opt.set_optim_method(SGD(learningrate=0.1))
    if compute_dtype is not None:
        opt.set_compute_dtype(compute_dtype)

    params = opt._init_params()
    mod_state = model.state()
    opt_state = opt._init_opt_state(params)

    import jax.numpy as jnp

    rng = jax.random.key(0)

    # same scan harness as the baseline: the framework's jitted step body
    # runs unchanged inside the scan
    loss_fn = opt._loss_fn()
    method = opt.optim_method
    clipper = opt._clipper

    def step(carry, x, y):
        p, opt_st, mstate = carry
        (_, (loss, new_mstate)), grad = jax.value_and_grad(
            loss_fn, has_aux=True
        )(p, mstate, rng, x, y)
        grad = clipper(grad)
        new_p, new_opt = method.step(grad, p, opt_st)
        return (new_p, new_opt, new_mstate), loss

    return _timed_scan_throughput(
        step, (params, opt_state, mod_state), jnp.asarray(x), jnp.asarray(y)
    )


def main():
    x = np.random.RandomState(0).randn(BATCH, 3, IMG, IMG).astype(np.float32)
    y = (np.random.RandomState(1).randint(0, N_CLASSES, BATCH) + 1).astype(
        np.float32
    )
    # headline: the TPU-native recipe — bf16 fwd/bwd, f32 master params —
    # on both contenders; the ratio still isolates framework overhead
    fw = _bench_framework(x, y, compute_dtype="bfloat16")
    bl = _bench_baseline(x, y, compute_dtype="bfloat16")
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(fw, 2),
                "unit": "images/sec",
                "vs_baseline": round(fw / bl, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
