"""Benchmark — ResNet-50 training throughput + MFU on the real chip.

Prints ONE JSON line (the LAST line of stdout is always the result):
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": R,
   "mfu": M, "platform": ..., "device_kind": ..., "extras": {...},
   "error": null | "..."}

Robustness contract (VERDICT r3 item 1 — the round must never be blind
again; r03's rc=124 showed the r02 design's worst case exceeded the
driver's kill window):
  * the TOTAL worst-case wall-clock is bounded: a <=120s bring-up PROBE
    child (jax.devices() only) gates the expensive measurement — a hung
    tunnel costs one probe timeout, never a full measurement budget;
  * a probe TIMEOUT is never retried (only a fast error is, once);
  * the measurement child streams a @@BENCH_PARTIAL@@ full-result JSON
    line after EVERY completed segment; the parent tails them live and
    mirrors the latest to BENCH_PARTIAL.json on disk, so a kill at any
    point still leaves a parseable result;
  * the parent traps SIGTERM/SIGINT and prints the best partial as the
    final line before exiting 0 — a driver `timeout` kill yields JSON;
  * the child self-truncates: it stops starting new segments when its
    own deadline nears, labelling skipped segments in extras;
  * a probe timeout no longer forfeits the round (VERDICT r4 item 1a):
    after the CPU fallback the parent re-probes ONCE — a tunnel that
    recovers mid-window still yields the real TPU measurement;
  * worst-case envelope (all defaults): probe 120 + CPU child 240 +
    re-probe 120 + TPU child 900 + slop < BENCH_TIMEOUT 1500s.  Every
    budget is env-overridable; tests/test_bench_envelope.py proves the
    arithmetic and exercises both the hung-bring-up and the
    tunnel-recovers paths with compressed budgets.

The headline metric is BASELINE.json's (ResNet-50 ImageNet images/sec/
chip).  ``vs_baseline`` compares against a hand-written plain-JAX
ResNet-50 train step in this file (raw pytree params, inline conv/BN,
direct SGD tree update): the reference repo ships no locally citable
numbers (BASELINE.md), so raw JAX on the same chip is the honest
baseline and the ratio isolates framework overhead.  ``mfu`` uses an
analytic conv/fc FLOPs model (2*K*K*Cin*Cout*Hout*Wout MACs counted as
2 flops, backward = 2x forward) against the chip's peak bf16 FLOPs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

def _env_flag(name):
    return os.environ.get(name, "").lower() in ("1", "true", "yes")


# BENCH_ALLOW_CPU_STANDIN marks an envelope-test invocation; the
# headline-redefining overrides (image size / iters) are honored ONLY
# then, so a leaked BENCH_IMG can never silently inflate a real round's
# 224px headline series
_TEST_MODE = _env_flag("BENCH_ALLOW_CPU_STANDIN")

BATCH = 32
IMG = int(os.environ.get("BENCH_IMG", "224")) if _TEST_MODE else 224
N_CLASSES = 1000
ITERS = int(os.environ.get("BENCH_ITERS", "10")) if _TEST_MODE else 10

# batch sweep (VERDICT r2 #2): batch 32 underfeeds the MXU; measure a
# sweep and report the best operating point as the headline.  PRIORITY
# ORDER: the child measures left to right and self-truncates near its
# deadline, so the best-known operating point (128, per the r03 sweep)
# goes first — a truncated run must never be left holding only the
# batch-32 number.
SWEEP_BATCHES = tuple(
    int(b) for b in os.environ.get("BENCH_BATCHES", "128,256,64,32").split(",")
) if _TEST_MODE else (128, 256, 64, 32)

# CPU fallback must finish on one core: tiny shapes, clearly labelled
# (env-overridable so the envelope test can compress them further)
CPU_BATCH = int(os.environ.get("BENCH_CPU_BATCH", "4"))
CPU_IMG = int(os.environ.get("BENCH_CPU_IMG", "64"))
CPU_ITERS = int(os.environ.get("BENCH_CPU_ITERS", "3"))

# peak dense bf16 FLOPs/s per chip generation (public spec sheets);
# override with BENCH_PEAK_FLOPS when the kind is missing or wrong
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _peak_flops(device_kind: str):
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    kind = (device_kind or "").lower()
    for k in sorted(_PEAK_BF16, key=len, reverse=True):
        if k in kind:
            return _PEAK_BF16[k]
    return None


def _resnet50_cfg():
    return [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def resnet50_flops_per_image(img: int = IMG) -> float:
    """Analytic forward FLOPs (2*MACs) for the ResNet-50 in this file."""
    flops = 0.0

    def conv(cin, cout, k, h_in, stride):
        nonlocal flops
        h_out = -(-h_in // stride)  # SAME padding
        flops += 2.0 * k * k * cin * cout * h_out * h_out
        return h_out

    h = conv(3, 64, 7, img, 2)          # stem
    h = -(-h // 2)                       # 3x3/2 maxpool
    cin = 64
    for w, n, stride in _resnet50_cfg():
        for i in range(n):
            st = stride if i == 0 else 1
            conv(cin, w, 1, h, 1)
            h2 = conv(w, w, 3, h, st)
            conv(w, w * 4, 1, h2, 1)
            if i == 0:
                conv(cin, w * 4, 1, h, st)
            h = h2
            cin = w * 4
    flops += 2.0 * cin * N_CLASSES       # fc
    return flops


def train_step_flops_per_image(img: int = IMG) -> float:
    """fwd + bwd; backward of a conv/matmul is ~2x its forward."""
    return 3.0 * resnet50_flops_per_image(img)


# --------------------------------------------------------------------------
# plain-JAX ResNet-50 (the baseline): raw functions + pytree params
# --------------------------------------------------------------------------


def _baseline_resnet50_init(rng):
    import jax

    params = {}

    def conv_p(key, cin, cout, k):
        fan = cin * k * k
        params[key] = {
            "w": jax.random.normal(
                jax.random.fold_in(rng, hash(key) % (2**31)),
                (cout, cin, k, k),
                dtype=np.float32,
            )
            * np.sqrt(2.0 / fan)
        }

    def bn_p(key, c):
        import jax.numpy as jnp

        params[key] = {
            "scale": jnp.ones(c),
            "bias": jnp.zeros(c),
            "mean": jnp.zeros(c),
            "var": jnp.ones(c),
        }

    conv_p("stem", 3, 64, 7)
    bn_p("stem_bn", 64)
    cin = 64
    for s, (w, n, stride) in enumerate(_resnet50_cfg()):
        for i in range(n):
            pfx = f"s{s}b{i}"
            conv_p(pfx + "c1", cin, w, 1)
            bn_p(pfx + "bn1", w)
            conv_p(pfx + "c2", w, w, 3)
            bn_p(pfx + "bn2", w)
            conv_p(pfx + "c3", w, w * 4, 1)
            bn_p(pfx + "bn3", w * 4)
            if i == 0:
                conv_p(pfx + "sc", cin, w * 4, 1)
                bn_p(pfx + "scbn", w * 4)
            cin = w * 4
    import jax.numpy as jnp

    params["fc"] = {
        "w": jax.random.normal(jax.random.fold_in(rng, 77), (cin, N_CLASSES))
        * 0.01,
        "b": jnp.zeros(N_CLASSES),
    }
    return params


def _baseline_forward(params, x):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def conv(p, x, stride=1, pad="SAME"):
        return lax.conv_general_dilated(
            x, p["w"], (stride, stride), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    def bn(p, x):
        # training-mode BN as a user would naturally write it: two-pass
        # f32 batch statistics + f32 normalize.  The framework's
        # SpatialBatchNormalization deliberately diverges (shifted
        # single-pass stats, compute-dtype normalize — BASELINE.md r03b),
        # which is exactly the advantage vs_baseline measures; the
        # framework also pays for running-stat EMA updates the baseline
        # skips.
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 2, 3))
        var = jnp.var(xf, axis=(0, 2, 3))
        inv = jax.lax.rsqrt(var + 1e-5) * p["scale"].astype(jnp.float32)
        y = xf * inv[None, :, None, None] + (
            p["bias"].astype(jnp.float32) - mean * inv
        )[None, :, None, None]
        return y.astype(x.dtype)

    x = conv(params["stem"], x, 2)
    x = jax.nn.relu(bn(params["stem_bn"], x))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
        [(0, 0), (0, 0), (1, 1), (1, 1)],
    )
    for s, (w, n, stride) in enumerate(_resnet50_cfg()):
        for i in range(n):
            pfx = f"s{s}b{i}"
            st = stride if i == 0 else 1
            y = jax.nn.relu(bn(params[pfx + "bn1"], conv(params[pfx + "c1"], x)))
            y = jax.nn.relu(bn(params[pfx + "bn2"], conv(params[pfx + "c2"], y, st)))
            y = bn(params[pfx + "bn3"], conv(params[pfx + "c3"], y))
            if i == 0:
                sc = bn(params[pfx + "scbn"], conv(params[pfx + "sc"], x, st))
            else:
                sc = x
            x = jax.nn.relu(y + sc)
    x = jnp.mean(x, axis=(2, 3))
    return x @ params["fc"]["w"] + params["fc"]["b"]


def _timed_scan_throughput(step_fn, carry, x, y, batch, iters):
    """Run ``iters`` steps inside ONE jitted lax.scan and time the call:
    the relay between this host and the chip adds per-call and per-buffer
    overheads that would otherwise dominate; a single call with one
    scalar output measures pure device throughput for both contenders.
    ``float()`` on the result is the barrier (block_until_ready returns
    early through the relay).

    Every segment also feeds the obs runtime profile (compile events
    from the warmup call, per-step times into the reservoir) so the
    BENCH JSON carries step-time percentiles + compile count — the
    trajectory baseline future perf PRs diff against."""
    import jax
    import jax.lax as lax

    from bigdl_tpu import obs

    @jax.jit
    def run(carry, x, y):
        def body(c, _):
            c, loss = step_fn(c, x, y)
            return c, loss

        _, losses = lax.scan(body, carry, None, length=iters)
        return losses[-1]

    runtime = obs.get_runtime()
    # XLA's HloCostAnalysis counts a while-loop body ONCE regardless of
    # trip count, so the scanned N-step program already reports ~one
    # step's FLOPs — no steps_per_call normalization here.  If a
    # backend ever multiplies by the trip count instead, the
    # hlo_vs_analytic_flops ratio in the BENCH JSON flags it as ~N.
    run = obs.instrument_jit(run, "bench_scan", stats=runtime)
    float(run(carry, x, y))  # compile + warmup (recorded: compile event)
    t0 = time.perf_counter()
    float(run(carry, x, y))
    dt = time.perf_counter() - t0
    runtime.record_step(dt / iters)
    return batch * iters / dt, dt / iters


def _bench_baseline(x, y, batch, iters, compute_dtype=None):
    import jax
    import jax.numpy as jnp

    params = _baseline_resnet50_init(jax.random.key(0))

    def loss_fn(p, x, y):
        if compute_dtype is not None:
            # same mixed-precision policy as the framework: bf16 fwd/bwd
            # inside the differentiated fn, f32 master params + loss
            ct = jnp.dtype(compute_dtype)
            p = jax.tree.map(
                lambda a: a.astype(ct)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, p
            )
            x = x.astype(ct)
        logits = _baseline_forward(p, x).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        idx = y.astype(jnp.int32) - 1
        return -jnp.mean(jnp.take_along_axis(logp, idx[:, None], 1))

    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g)
        return p, loss

    return _timed_scan_throughput(
        step, params, jnp.asarray(x), jnp.asarray(y), batch, iters
    )


def _bench_framework(x, y, batch, iters, compute_dtype=None, fuse=False,
                     fuse_kernels=(1, 3)):
    import jax

    from bigdl_tpu.models import build_resnet_imagenet
    from bigdl_tpu.nn import CrossEntropyCriterion
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    model = build_resnet_imagenet(depth=50, class_num=N_CLASSES)
    if fuse:
        # Pallas fused 1x1-conv+BN-stats path (nn/fused.py): BN stats
        # accumulate in the conv epilogue instead of re-reading the
        # activation
        from bigdl_tpu.nn import fuse_conv_bn

        fuse_conv_bn(model, kernels=fuse_kernels)
    # drop the LogSoftMax tail; CrossEntropyCriterion fuses it (same as
    # the baseline's fused log_softmax)
    model.modules = model.modules[:-1]
    crit = CrossEntropyCriterion()
    opt = LocalOptimizer(model, (x, y), crit, batch_size=batch)
    opt.set_optim_method(SGD(learningrate=0.1))
    if compute_dtype is not None:
        opt.set_compute_dtype(compute_dtype)

    params = opt._init_params()
    mod_state = model.state()
    opt_state = opt._init_opt_state(params)

    import jax.numpy as jnp

    rng = jax.random.key(0)

    # same scan harness as the baseline: the framework's jitted step body
    # runs unchanged inside the scan
    loss_fn = opt._loss_fn()
    method = opt.optim_method
    clipper = opt._clipper

    def step(carry, x, y):
        p, opt_st, mstate = carry
        (_, (loss, new_mstate)), grad = jax.value_and_grad(
            loss_fn, has_aux=True
        )(p, mstate, rng, x, y)
        grad = clipper(grad)
        new_p, new_opt = method.step(grad, p, opt_st)
        return (new_p, new_opt, new_mstate), loss

    return _timed_scan_throughput(
        step, (params, opt_state, mod_state), jnp.asarray(x), jnp.asarray(y),
        batch, iters,
    )


def _bench_local_optimizer(model, x, y, criterion, batch, iters, lr=0.05):
    """Shared harness: a LocalOptimizer's exact step recipe timed inside
    one scan (both secondary configs use this so they measure the SAME
    code path)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    opt = LocalOptimizer(model, (x, y), criterion, batch_size=batch)
    opt.set_optim_method(SGD(learningrate=lr))
    params = opt._init_params()
    mod_state = model.state()
    opt_state = opt._init_opt_state(params)
    loss_fn = opt._loss_fn()
    method = opt.optim_method
    clipper = opt._clipper
    rng = jax.random.key(0)

    def step(carry, x, y):
        p, opt_st, mstate = carry
        (_, (loss, new_mstate)), grad = jax.value_and_grad(
            loss_fn, has_aux=True
        )(p, mstate, rng, x, y)
        grad = clipper(grad)
        new_p, new_opt = method.step(grad, p, opt_st)
        return (new_p, new_opt, new_mstate), loss

    ips, _ = _timed_scan_throughput(
        step, (params, opt_state, mod_state), jnp.asarray(x), jnp.asarray(y),
        batch, iters,
    )
    return ips


def _bench_ptb(batch=64, num_steps=20, iters=20):
    """Parity config 4 (BASELINE.md): PTB LSTM LM — tokens/sec/chip."""
    from bigdl_tpu.models.rnn import build_ptb_lm
    from bigdl_tpu.nn import TimeDistributedCriterion, ClassNLLCriterion

    vocab, hidden = 10000, 256
    rs = np.random.RandomState(0)
    x = rs.randint(1, vocab + 1, (batch, num_steps)).astype(np.float32)
    y = rs.randint(1, vocab + 1, (batch, num_steps)).astype(np.float32)
    model = build_ptb_lm(vocab, hidden_size=hidden)
    crit = TimeDistributedCriterion(ClassNLLCriterion(), size_average=True)
    ips = _bench_local_optimizer(model, x, y, crit, batch, iters, lr=0.1)
    return ips * num_steps  # tokens/sec


def _bench_transformer(batch=16, seq=512, iters=10, *, vocab=8192,
                       dim=512, n_head=8, n_layer=8):
    """Beyond-parity flagship: decoder-only TransformerLM (Pallas flash
    attention) — tokens/sec/chip at a long-context operating point.
    The CPU fallback passes a tiny config so the metric is at least
    populated (VERDICT r4 item 4)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import build_transformer_lm

    model = build_transformer_lm(vocab, dim=dim, n_head=n_head,
                                 n_layer=n_layer, max_len=seq)
    rs = np.random.RandomState(0)
    # TokenEmbedding is 0-based (models/transformer.py): ids in [0, vocab)
    x = jnp.asarray(rs.randint(0, vocab, (batch, seq)).astype(np.float32))
    y = rs.randint(0, vocab, (batch, seq))

    params = model.params()
    state = model.state()
    rng = jax.random.key(0)
    yhot = jnp.asarray(y)

    def loss_fn(p, x):
        ct = jnp.bfloat16
        p = jax.tree.map(
            lambda a: a.astype(ct)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
        logits, _ = model.apply(p, state, x, training=True, rng=rng)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(
            jnp.take_along_axis(logp, yhot[:, :, None], 2))

    def step(p, x, _y):
        loss, g = jax.value_and_grad(loss_fn)(p, x)
        p = jax.tree.map(lambda w, gw: w - 1e-3 * gw, p, g)
        return p, loss

    ips, _ = _timed_scan_throughput(step, params, x, jnp.asarray(y), batch,
                                    iters)
    return ips * seq  # tokens/sec


def _bench_dlframes(n_rows=4096, n_feat=64, epochs=2):
    """Parity config 5 (BASELINE.md): DLEstimator fit + DLModel
    transform over a dict DataFrame — rows/sec end-to-end wall time."""
    from bigdl_tpu.dlframes import DLClassifier
    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential

    rs = np.random.RandomState(0)
    x = rs.randn(n_rows, n_feat).astype(np.float32)
    w = rs.randn(n_feat, 4)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    df = {"features": [row for row in x], "label": list(y)}
    model = Sequential().add(Linear(n_feat, 32)).add(ReLU()) \
        .add(Linear(32, 4)).add(LogSoftMax())
    est = DLClassifier(model, ClassNLLCriterion(), [n_feat]) \
        .set_batch_size(256).set_max_epoch(epochs)
    t0 = time.perf_counter()
    fitted = est.fit(df)
    out = fitted.transform(df)
    dt = time.perf_counter() - t0
    assert len(out["prediction"]) == n_rows
    return n_rows * (epochs + 1) / dt  # rows/sec through fit+transform


def _bench_wide_and_deep(n=4096, batch=256, iters=20):
    """Parity config (SURVEY "Sparse tensor"): wide-and-deep over the
    padded fixed-slot sparse encoding — samples/sec/chip."""
    from bigdl_tpu.models import build_wide_and_deep, pack_batch
    from bigdl_tpu.nn import ClassNLLCriterion, SparseTensor

    rs = np.random.RandomState(0)
    WV, slots = 10000, 8
    deep_vocabs = (100, 50, 20)
    cols = rs.randint(0, WV, (n, 4))
    rows = np.repeat(np.arange(n), 4)
    sp = SparseTensor(np.stack([rows, cols.reshape(-1)], 1),
                      np.ones(n * 4, np.float32), (n, WV))
    deep = np.stack([rs.randint(1, v + 1, n) for v in deep_vocabs], 1)
    y = (rs.randint(0, 2, n) + 1).astype(np.float32)
    x = pack_batch(sp, deep, slots)
    model = build_wide_and_deep(WV, deep_vocabs, class_num=2,
                                wide_slots=slots)
    return _bench_local_optimizer(
        model, x[:batch], y[:batch], ClassNLLCriterion(), batch, iters)


def _bench_lenet(platform_batch=256, iters=20):
    """Secondary config (BASELINE.md table): LeNet-5 / LocalOptimizer."""
    from bigdl_tpu.models.lenet import build_lenet5
    from bigdl_tpu.nn import ClassNLLCriterion

    rs = np.random.RandomState(0)
    x = rs.rand(platform_batch, 28, 28).astype(np.float32)
    y = (rs.randint(0, 10, platform_batch) + 1).astype(np.float32)
    return _bench_local_optimizer(
        build_lenet5(), x, y, ClassNLLCriterion(), platform_batch, iters)


# --------------------------------------------------------------------------
# child-process measurement
# --------------------------------------------------------------------------


PARTIAL_MARK = "@@BENCH_PARTIAL@@"


def _obs_runtime_extras():
    """Step-time p50/p95/p99 + compile count from the obs runtime
    reservoirs (fed by _timed_scan_throughput) — best-effort, a broken
    obs layer must never sink the bench."""
    try:
        from bigdl_tpu import obs

        snap = obs.get_runtime().snapshot(memory=False)
        st = snap["step_time_s"]
        return {
            "step_time_p50_s": st["p50"],
            "step_time_p95_s": st["p95"],
            "step_time_p99_s": st["p99"],
            "step_samples": st["count"],
            "compile_count": snap["compile"]["count"],
            "compile_total_s": snap["compile"]["total_s"],
            # compiled.cost_analysis() of the newest scanned segment,
            # normalized per step (obs/runtime.py)
            "hlo_step_flops": snap.get("step_flops"),
        }
    except Exception:
        return None


def _wire_extras():
    """Quantized-collective evidence for the BENCH JSON: the static
    byte model of the wire this run is configured for (config.wire),
    plus the newest ``WIRE_SMOKE.json`` A/B results when the smoke has
    been run (scripts/wire_smoke.py — savings ratios and trajectory
    agreement per wire dtype).  None when nothing is banked and the
    configured wire is the default."""
    try:
        from bigdl_tpu.config import config
        from bigdl_tpu.obs import collectives as C

        out = {}
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "WIRE_SMOKE.json")
        if os.path.exists(smoke):
            with open(smoke, "r", encoding="utf-8") as fh:
                out["smoke"] = json.load(fh)
        w = config.wire
        if w.dtype not in ("bfloat16",) or out:
            from bigdl_tpu.parallel.wire import WIRE_DTYPES

            model = {"dtype": w.dtype, "block": w.block,
                     "error_feedback": w.error_feedback}
            if w.dtype in WIRE_DTYPES:
                # a reference point: 1 MiB of gradient over 8 shards
                name = WIRE_DTYPES[w.dtype][0]
                ex = C.staged_ring_exchange_bytes(1 << 20, 8, w.block,
                                                  name)
                f32 = C.reduce_scatter_bytes(1 << 20, "float32", 8)
                model["model_savings_1mib_8way"] = f32 / sum(ex.values())
            out["configured"] = model
        return out or None
    except Exception:
        return None


def _autoscale_extras():
    """Autoscaling + exactly-once streaming evidence for the BENCH
    JSON: the newest ``AUTOSCALE_SMOKE.json`` banked by
    scripts/autoscale_smoke.py (supervised 1→2→1 resize decisions,
    trajectory error, and the zero-duplicate/zero-drop stream audit).
    None when the smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "AUTOSCALE_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _overlap_extras():
    """Overlapped-step evidence for the BENCH JSON: the newest
    ``OVERLAP_SMOKE.json`` banked by scripts/overlap_smoke.py (the
    on-vs-off A/B — trajectory error, byte parity, comm/input badput
    fractions, checkpoint badput, goodput ratios).  None when the
    smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "OVERLAP_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _serve_extras():
    """Serving-tier evidence for the BENCH JSON: the newest
    ``SERVE_SMOKE.json`` banked by scripts/serve_smoke.py (continuous
    vs static tokens/sec + p99, batcher occupancy, the int8 classifier
    run and the queue-driven autoscale decision).  None when the smoke
    has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "SERVE_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _fleet_extras():
    """Fleet-simulator evidence for the BENCH JSON: the newest
    ``FLEET_SIM.json`` banked by scripts/fleet_sim.py (per-scenario
    invariant verdicts, decision/episode counts, aggregation-scaling
    measurement at 200 synthetic hosts).  None when the smoke has
    never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "FLEET_SIM.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _fleetobs_extras():
    """Fleet-metrics-pipeline evidence for the BENCH JSON: the newest
    ``FLEETOBS_SMOKE.json`` banked by scripts/fleetobs_smoke.py (the
    hierarchical-vs-flat exactness, cardinality/memory-bound and
    staleness-exclusion invariant verdicts at 1000 simulated hosts,
    plus the bounded scrape-pool wall and retention-store replay
    counts).  None when the smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "FLEETOBS_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _router_extras():
    """Serving-router evidence for the BENCH JSON: the newest
    ``ROUTER_SMOKE.json`` banked by scripts/router_smoke.py (the three
    data-plane chaos scenarios' invariant verdicts — conservation,
    retry amplification, SLO stability — plus the real-engine
    bit-equality / drain-handoff / HTTP-topology segment).  None when
    the smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "ROUTER_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _reqtrace_extras():
    """Request-tracing evidence for the BENCH JSON: the newest
    ``REQTRACE_SMOKE.json`` banked by scripts/reqtrace_smoke.py (the
    rigged slow-replica topology's p99 attribution — the slowest
    decile blamed on the queue hop, per-hop coverage of measured e2e,
    token parity with tracing on, and the tail sampler's keep/drop
    counts).  None when the smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "REQTRACE_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _rollout_extras():
    """Live-weight-rollout evidence for the BENCH JSON: the newest
    ``ROLLOUT_SMOKE.json`` banked by scripts/rollout_smoke.py (the
    checkpoint watcher's hot-swap + verify-gate segment, the canary
    promote/rollback segment, and the weight_rollout chaos scenario's
    invariant verdicts).  None when the smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "ROLLOUT_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _prof_extras():
    """Continuous-profiling evidence for the BENCH JSON: the newest
    ``PROF_SMOKE.json`` banked by scripts/prof_smoke.py (the rigged
    hot-span attribution share, the measured sampling overhead vs the
    <1% gate, and the alert-triggered debug bundle's manifest verdict).
    None when the smoke has never been run."""
    try:
        smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "PROF_SMOKE.json")
        if not os.path.exists(smoke):
            return None
        with open(smoke, "r", encoding="utf-8") as fh:
            return {"smoke": json.load(fh)}
    except Exception:
        return None


def _tuner_extras():
    """Auto-tuner evidence for the BENCH JSON (ops/autotune.py): the
    cache stats and every decision with its static baseline, measured
    candidate times and never-lose gate verdict — how the A/B
    comparisons (attn_ab/bn_ab "tuned" rows) are banked across
    chip-unavailable rounds.  None when the tuner is off."""
    try:
        from bigdl_tpu.ops import autotune

        if not autotune.enabled():
            return None
        return autotune.summary()
    except Exception:
        return None


def _child_platform_setup(platform: str):
    """Pin jax to the requested platform and return the device (may
    raise / hang — the parent's probe + deadline own that risk)."""
    import jax

    tpu_platform = None
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        # pin to the accelerator platform: never let a silent CPU
        # fallback run full shapes and report them as the TPU headline
        tpu_platform = os.environ.get("BENCH_TPU_PLATFORM")
        if tpu_platform is None:
            registered = "axon" if os.environ.get(
                "JAX_PLATFORMS", ""
            ).startswith("axon") else "tpu"
            tpu_platform = registered
        jax.config.update("jax_platforms", tpu_platform)

    t0 = time.time()
    dev = jax.devices()[0]
    init_s = round(time.time() - t0, 1)
    # BENCH_TPU_PLATFORM=cpu + BENCH_ALLOW_CPU_STANDIN is the envelope
    # tests' stand-in chip; BOTH are required so a leaked
    # BENCH_TPU_PLATFORM alone can never reinstate the silent-CPU
    # fallback this guard exists to prevent
    standin = tpu_platform == "cpu" and _TEST_MODE
    if platform != "cpu" and not standin and dev.platform == "cpu":
        raise RuntimeError(
            f"requested accelerator platform but got {dev.platform!r}"
        )
    return dev, init_s


def _probe_child(platform: str):
    """--probe mode: bring-up only.  Proves the platform answers fast
    enough to be worth a measurement budget."""
    if os.environ.get("BENCH_FAKE_PROBE_HANG"):  # envelope test hook
        # hang-once variant: a marker file makes only the FIRST probe
        # hang — the flapping-tunnel-recovers scenario (VERDICT r4 1a)
        once = os.environ.get("BENCH_FAKE_PROBE_HANG_ONCE_FILE")
        if once is None or not os.path.exists(once):
            if once is not None:
                with open(once, "w") as f:
                    f.write("1")
            time.sleep(float(os.environ["BENCH_FAKE_PROBE_HANG"]))
    if os.environ.get("BENCH_FAKE_PROBE_ERROR"):  # envelope test hook
        raise RuntimeError("BENCH_FAKE_PROBE_ERROR injected")
    dev, init_s = _child_platform_setup(platform)
    print(PARTIAL_MARK + json.dumps(
        {"probe": True, "platform": dev.platform,
         "device_kind": dev.device_kind, "backend_init_s": init_s}),
        flush=True)


def _run_child(platform: str):
    """--run mode: measure, streaming a full-result JSON partial after
    every completed segment so the parent is never blind.  Segments are
    ordered headline-first and self-truncate near the child deadline."""
    if platform != "cpu" and os.environ.get("BENCH_FAKE_TPU_HANG"):
        time.sleep(float(os.environ["BENCH_FAKE_TPU_HANG"]))  # test hook
    child_t0 = time.time()
    child_budget = float(os.environ.get("BENCH_CHILD_BUDGET", "86400"))
    # don't START a segment when less than this remains: a ResNet-50
    # fwd+bwd compile alone can take ~60-120s on first trace — but the
    # CPU fallback's tiny (batch-4, 64px) headline compiles far faster,
    # and a 150s reserve there would let the secondaries-first reorder
    # starve the headline out of a 225s child budget
    seg_reserve = float(os.environ.get(
        "BENCH_SEG_RESERVE", "150" if platform != "cpu" else "60"))

    if platform == "cpu":
        img, iters = CPU_IMG, CPU_ITERS
        batches = (CPU_BATCH,)
    else:
        img, iters = IMG, ITERS
        batches = SWEEP_BATCHES

    dev, init_s = _child_platform_setup(platform)
    peak = _peak_flops(dev.device_kind)
    if peak:
        # lets obs.publish_runtime derive the bigdl_mfu gauge from the
        # HLO step FLOPs it collects (best-effort — obs must never sink
        # the bench)
        try:
            from bigdl_tpu import obs as _obs

            _obs.get_runtime().peak_flops = peak
        except Exception:
            pass

    result = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "mfu": None,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "extras": {
            "baseline_images_per_sec": None,
            "step_time_s": None,
            "batch": None,
            "image_size": img,
            "backend_init_s": init_s,
            "train_flops_per_image": train_step_flops_per_image(img),
            "headline_config": "standard",
            "fused_conv_bn": None,
            "batch_sweep": {},
            "completed_segments": [],
            "skipped_segments": [],
            "lenet_local_images_per_sec": None,
            "ptb_lstm_tokens_per_sec": None,
            "transformer_lm_tokens_per_sec": None,
            "dlframes_fit_transform_rows_per_sec": None,
            "obs_runtime": None,
        },
        "error": None,
        "partial": True,
    }
    ex = result["extras"]

    def emit(segment):
        ex["completed_segments"].append(segment)
        ex["obs_runtime"] = _obs_runtime_extras()
        print(PARTIAL_MARK + json.dumps(result), flush=True)

    def remaining():
        return child_budget - (time.time() - child_t0)

    def ok_segments():
        return [s for s in ex["completed_segments"]
                if not s.endswith(":failed")]

    def data(b):
        x = np.random.RandomState(0).randn(b, 3, img, img).astype(np.float32)
        y = (np.random.RandomState(1).randint(0, N_CLASSES, b) + 1).astype(
            np.float32)
        return x, y

    best = None  # (ips, step_s, batch) over the STANDARD path only:
    # the headline series stays config-stable round over round (ADVICE
    # r3 #2); the fused path is reported in extras only.

    def refresh_headline():
        if best is None:
            return
        fw, step_s, b = best
        result["value"] = round(fw, 2)
        ex["step_time_s"] = round(step_s, 4)
        ex["batch"] = b
        if peak and dev.platform != "cpu":
            result["mfu"] = round(
                train_step_flops_per_image(img) * fw / peak, 4)
        if ex["baseline_images_per_sec"]:
            result["vs_baseline"] = round(
                fw / ex["baseline_images_per_sec"], 4)

    def run_secondaries():
        # CPU tiny configs are cheap-first: a truncated CPU fallback
        # must still deliver every secondary metric (VERDICT r4 item 4);
        # their reserve is far below seg_reserve because none of them
        # needs a ResNet-50-sized compile
        sec_reserve = float(os.environ.get(
            "BENCH_SEC_RESERVE", "30" if platform == "cpu" else str(
                seg_reserve)))
        if platform == "cpu":
            plan = [
                ("lenet", "lenet_local_images_per_sec",
                 lambda: _bench_lenet(64, iters=4)),
                ("dlframes", "dlframes_fit_transform_rows_per_sec",
                 lambda: _bench_dlframes(1024, 32, 1)),
                ("ptb", "ptb_lstm_tokens_per_sec",
                 lambda: _bench_ptb(batch=16, num_steps=10, iters=4)),
                ("transformer", "transformer_lm_tokens_per_sec",
                 lambda: _bench_transformer(batch=2, seq=64, iters=3,
                                            vocab=512, dim=64, n_head=2,
                                            n_layer=2)),
            ]
        else:
            plan = [
                ("lenet", "lenet_local_images_per_sec", _bench_lenet),
                ("ptb", "ptb_lstm_tokens_per_sec", _bench_ptb),
                ("transformer", "transformer_lm_tokens_per_sec",
                 _bench_transformer),
                ("dlframes", "dlframes_fit_transform_rows_per_sec",
                 _bench_dlframes),
            ]
        for name, key, fn in plan:
            if remaining() < sec_reserve:
                ex["skipped_segments"].append(name)
                continue
            try:
                v = fn()
                ex[key] = round(v, 1) if v else None
                emit(name)
            except Exception as e:  # secondary must not sink the bench
                ex.setdefault("secondary_errors", {})[name] = (
                    f"{type(e).__name__}: {str(e)[:160]}")
                emit(f"{name}:failed")

    # --- segment plan -----------------------------------------------
    # TPU: headline-first — framework std sweep, baseline, fused, then
    # secondaries.  CPU fallback: the cheap secondaries FIRST (they have
    # been null in every driver artifact; the ResNet compile alone can
    # eat a truncated window), then the std headline + baseline.
    ran_secondaries = False
    if platform == "cpu":
        run_secondaries()
        ran_secondaries = True

    failed_streak = 0
    for i, b in enumerate(batches):
        if remaining() < seg_reserve and (i > 0 or ok_segments()):
            ex["skipped_segments"].append(f"std_b{b}")
            continue
        x, y = data(b)
        try:
            fw_b, step_b = _bench_framework(x, y, b, iters,
                                            compute_dtype="bfloat16")
        except Exception as e:  # OOM at large batch: record + continue
            ex["batch_sweep"][str(b)] = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"}
            emit(f"std_b{b}:failed")
            failed_streak += 1
            if failed_streak == 2 and not ran_secondaries:
                # two consecutive headline failures smell like a broken
                # remote-compile service for BIG programs (observed: the
                # relay 500s every ResNet batch, then HANGS one, losing
                # the whole child to the parent's kill) — bank the small
                # cheap segments NOW, then come back for the rest of the
                # sweep
                run_secondaries()
                ran_secondaries = True
            continue
        failed_streak = 0
        entry = {"images_per_sec": round(fw_b, 2),
                 "step_time_s": round(step_b, 4)}
        if peak and dev.platform != "cpu":
            entry["mfu"] = round(
                train_step_flops_per_image(img) * fw_b / peak, 4)
        # HLO-derived FLOPs for THIS segment's compiled program vs the
        # analytic conv/fc model: neither is trusted blindly — the
        # ratio is the headline's error bar (rematerialization, fused
        # BN, padding all move the real count off the analytic one)
        hlo = (_obs_runtime_extras() or {}).get("hlo_step_flops")
        if hlo:
            analytic = train_step_flops_per_image(img) * b
            entry["hlo_flops_per_step"] = hlo
            entry["hlo_vs_analytic_flops"] = round(hlo / analytic, 4)
            if peak and dev.platform != "cpu":
                entry["mfu_hlo"] = round(hlo * fw_b / b / peak, 4)
            print(f"[bench] b{b}: HLO step FLOPs {hlo:.4g} vs analytic "
                  f"{analytic:.4g} (ratio {hlo / analytic:.3f})",
                  file=sys.stderr, flush=True)
        ex["batch_sweep"][str(b)] = entry
        if best is None or fw_b > best[0]:
            best = (fw_b, step_b, b)
        refresh_headline()
        emit(f"std_b{b}")

    if best is None:
        if not ok_segments():
            raise RuntimeError(
                f"all sweep batches failed: {ex['batch_sweep']}")
        # secondaries are banked but the headline never succeeded
        # (truncated CPU fallback, or every TPU compile failed): emit a
        # final value-less result instead of throwing them away
        ex["skipped_segments"].append("baseline")
        result["error"] = ("headline segments failed or truncated; "
                           "secondaries only")
        result["partial"] = False
        ex["obs_runtime"] = _obs_runtime_extras()
        print(PARTIAL_MARK + json.dumps(result), flush=True)
        return
    batch = best[2]

    if remaining() >= seg_reserve:
        x, y = data(batch)
        try:
            bl, _ = _bench_baseline(x, y, batch, iters,
                                    compute_dtype="bfloat16")
            ex["baseline_images_per_sec"] = round(bl, 2)
            refresh_headline()
            emit("baseline")
        except Exception as e:  # a baseline OOM must not sink the rest
            ex["baseline_error"] = f"{type(e).__name__}: {str(e)[:200]}"
            emit("baseline:failed")
    else:
        ex["skipped_segments"].append("baseline")

    if platform != "cpu":
        if remaining() >= seg_reserve:
            x, y = data(batch)
            # full fusion first; if the toolchain rejects the kxk
            # Pallas kernel (scripts/mosaic_probe.py attributes this),
            # still measure the 36-site 1x1-only fusion
            errors = {}
            for kernels in ((1, 3), (1,)):
                try:
                    fw_f, step_f = _bench_framework(
                        x, y, batch, iters, compute_dtype="bfloat16",
                        fuse=True, fuse_kernels=kernels)
                    fused = {"images_per_sec": round(fw_f, 2),
                             "step_time_s": round(step_f, 4),
                             "kernels": list(kernels)}
                    if peak:
                        fused["mfu"] = round(
                            train_step_flops_per_image(img) * fw_f / peak, 4)
                    ex["fused_conv_bn"] = fused
                    break
                except Exception as e:
                    errors[",".join(map(str, kernels))] = (
                        f"{type(e).__name__}: {str(e)[:200]}")
                    ex["fused_conv_bn"] = {"errors": dict(errors)}
                    if remaining() < seg_reserve:
                        break
            if errors and "errors" not in ex["fused_conv_bn"]:
                # a degraded success still records WHY full fusion fell
                # back (per-kernel Mosaic attribution must not be lost)
                ex["fused_conv_bn"]["errors"] = errors
            emit("fused_conv_bn")
        else:
            ex["skipped_segments"].append("fused_conv_bn")

    if platform != "cpu" and not ran_secondaries:
        run_secondaries()

    result["partial"] = False
    ex["obs_runtime"] = _obs_runtime_extras()
    tuner = _tuner_extras()
    if tuner is not None:
        ex["tuner"] = tuner
    wire = _wire_extras()
    if wire is not None:
        ex["wire"] = wire
    autoscale = _autoscale_extras()
    if autoscale is not None:
        ex["autoscale"] = autoscale
    overlap = _overlap_extras()
    if overlap is not None:
        ex["overlap"] = overlap
    serve = _serve_extras()
    if serve is not None:
        ex["serve"] = serve
    fleet = _fleet_extras()
    if fleet is not None:
        ex["fleet"] = fleet
    fleetobs = _fleetobs_extras()
    if fleetobs is not None:
        ex["fleetobs"] = fleetobs
    router = _router_extras()
    if router is not None:
        ex["router"] = router
    reqtrace = _reqtrace_extras()
    if reqtrace is not None:
        ex["reqtrace"] = reqtrace
    prof = _prof_extras()
    if prof is not None:
        ex["prof"] = prof
    rollout = _rollout_extras()
    if rollout is not None:
        ex["rollout"] = rollout
    print(PARTIAL_MARK + json.dumps(result), flush=True)


# --------------------------------------------------------------------------
# parent orchestration: probe → measure (streamed) → CPU fallback
# --------------------------------------------------------------------------

_LATEST: dict = {}  # parent-side best-so-far, dumped on SIGTERM
_ACTIVE_PROC: list = []  # the in-flight child, so a SIGTERM kills it too


def _partial_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_PARTIAL.json")


def _measured(d):
    """A result is 'measured' if it carries a headline value OR any
    successfully completed segment (the secondaries-only CPU fallback
    has value=None by design but is still a banked measurement)."""
    if d.get("value") is not None:
        return True
    return any(not s.endswith(":failed")
               for s in (d.get("extras") or {}).get(
                   "completed_segments", []))


def _record_partial(d):
    # dominance rule: an unmeasured partial never clobbers a measured
    # result already in hand — otherwise the post-fallback TPU re-run's
    # early (possibly failed) partials would overwrite the banked CPU
    # fallback, and a driver SIGTERM would dump an empty artifact
    if not _measured(d) and _LATEST and _measured(_LATEST):
        return
    _LATEST.clear()
    _LATEST.update(d)
    try:
        with open(_partial_path(), "w") as f:
            json.dump(d, f)
    except OSError:
        pass


def _spawn_streaming(mode: str, platform: str, timeout_s: float,
                     extra_env=None):
    """Run a child, tailing stdout live for PARTIAL_MARK lines.  Returns
    (last_partial | None, error | None).  On timeout the child is killed
    but every partial already streamed is kept.  Raw non-blocking fd
    reads (not a buffered readline) so a kill never strands partials in
    a stdio buffer."""
    import select as _select

    cmd = [sys.executable, os.path.abspath(__file__), mode, platform]
    env = dict(os.environ)
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
    )
    _ACTIVE_PROC[:] = [proc]
    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    deadline = time.time() + timeout_s
    buf = b""
    last, tail, timed_out = None, [], False

    def _consume(data):
        nonlocal buf, last
        buf += data
        while b"\n" in buf:
            raw, buf = buf.split(b"\n", 1)
            line = raw.decode("utf-8", "replace").rstrip()
            if line.startswith(PARTIAL_MARK):
                try:
                    d = json.loads(line[len(PARTIAL_MARK):])
                    last = d
                    if "metric" in d:
                        _record_partial(d)
                except json.JSONDecodeError:
                    pass
            elif line:
                tail.append(line)
                del tail[:-8]

    try:
        while True:
            budget = deadline - time.time()
            if budget <= 0:
                timed_out = True
                break
            ready, _, _ = _select.select([fd], [], [], min(budget, 5.0))
            if ready:
                try:
                    chunk = os.read(fd, 65536)
                except BlockingIOError:
                    continue
                if not chunk:
                    break  # EOF
                _consume(chunk)
            elif proc.poll() is not None:
                break
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        # drain whatever the dead child left in the pipe
        try:
            while True:
                chunk = os.read(fd, 65536)
                if not chunk:
                    break
                _consume(chunk)
        except (BlockingIOError, OSError):
            pass
        proc.stdout.close()
        _ACTIVE_PROC[:] = []
    if timed_out:
        err = f"{platform} child timed out after {int(timeout_s)}s"
        return last, err
    if proc.returncode not in (0, None):
        return last, (f"{platform} child rc={proc.returncode}: "
                      + "\n".join(tail)[-800:])
    return last, None


def _empty_result(errors):
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": None, "unit": "images/sec", "vs_baseline": None,
        "mfu": None, "platform": None, "device_kind": None,
        "extras": {}, "error": " | ".join(errors),
    }


# default budgets; the envelope invariant (tests/test_bench_envelope.py):
# PROBE + CPU + RE-PROBE + TPU + 90s orchestration slop <= TIMEOUT, and
# every spawn is additionally capped by remaining() so the sum can never
# overshoot.
DEFAULT_TIMEOUT = 1500.0
DEFAULT_PROBE_TIMEOUT = 120.0
DEFAULT_TPU_TIMEOUT = 900.0
DEFAULT_CPU_TIMEOUT = 240.0


def main():
    deadline = float(os.environ.get("BENCH_TIMEOUT", DEFAULT_TIMEOUT))
    probe_budget = float(
        os.environ.get("BENCH_PROBE_TIMEOUT", DEFAULT_PROBE_TIMEOUT))
    tpu_budget = float(
        os.environ.get("BENCH_TPU_TIMEOUT", DEFAULT_TPU_TIMEOUT))
    cpu_budget = float(
        os.environ.get("BENCH_CPU_TIMEOUT", DEFAULT_CPU_TIMEOUT))
    t0 = time.time()
    errors = []

    def remaining():
        return deadline - (time.time() - t0)

    # never blind, part 1: a driver SIGTERM/SIGINT prints the best
    # partial as the final stdout line and exits 0
    import signal

    def _dump_and_exit(signum, frame):
        # kill the in-flight child first: a hung bring-up grandchild
        # would otherwise linger holding the exclusive TPU device lock
        for p in _ACTIVE_PROC:
            try:
                p.kill()
            except OSError:
                pass
        res = dict(_LATEST) if _LATEST else _empty_result(
            errors + [f"killed by signal {signum}"])
        if res.get("partial"):
            res["error"] = ((res.get("error") or "") +
                            f" truncated by signal {signum}").strip()
        sys.stdout.write("\n" + json.dumps(res) + "\n")
        sys.stdout.flush()
        os._exit(0)

    signal.signal(signal.SIGTERM, _dump_and_exit)
    signal.signal(signal.SIGINT, _dump_and_exit)

    # --- probe: is the chip reachable at all? -----------------------
    # A probe TIMEOUT is terminal (a hung tunnel blocked >9 min in r01;
    # re-trying it would burn the whole window).  A FAST error gets one
    # retry — observed transient UNAVAILABLE from a flapping tunnel.
    tpu_ok = False
    for attempt in (1, 2):
        budget = min(probe_budget, remaining() - cpu_budget - 30)
        if budget < 20:
            errors.append("no time left for TPU probe")
            break
        probe_t0 = time.time()
        probe, err = _spawn_streaming("--probe", "tpu", budget)
        if probe and probe.get("probe"):
            tpu_ok = True
            break
        errors.append(f"probe attempt {attempt}: {err or 'no output'}")
        if err and "timed out" in err:
            break  # hung bring-up: do not retry
        if attempt == 1 and time.time() - probe_t0 < 30:
            time.sleep(10)  # fast transient error: one retry
        else:
            break

    # --- measurement ------------------------------------------------
    # one retry of the measurement itself, but ONLY when the child died
    # QUICKLY with no partials (transient tunnel flap after a good
    # probe) and the remaining window still covers tpu+cpu budgets — a
    # timeout or a mid-run crash with partials is never retried
    result = None
    cpu_res = None
    cpu_child_err = None

    def _cpu_error_label():
        msg = ("TPU unavailable — CPU fallback with tiny shapes "
               "(batch %d, %dpx): " % (CPU_BATCH, CPU_IMG)
               + " | ".join(errors))
        if cpu_child_err:
            msg += " | child: " + cpu_child_err
        return msg

    if tpu_ok:
        for attempt in (1, 2):
            budget = min(tpu_budget, remaining() - cpu_budget - 30)
            if budget < 120:
                errors.append("no time left for TPU measurement")
                break
            run_t0 = time.time()
            result, err = _spawn_streaming(
                "--run", "tpu", budget,
                extra_env={"BENCH_CHILD_BUDGET": max(60.0, budget - 30)})
            if err:
                errors.append(err)
            if result is not None or err is None:
                break
            fast_failure = (time.time() - run_t0 < 90
                            and "timed out" not in (err or ""))
            if not (attempt == 1 and fast_failure
                    and remaining() > tpu_budget + cpu_budget + 60):
                break
            time.sleep(10)

    if result is None or result.get("value") is None:
        # CPU fallback: tiny shapes, labelled, still a full JSON line.
        # Leave headroom for the post-fallback re-probe when the window
        # still covers one (VERDICT r4 item 1a).
        tpu_partial = result  # may hold TPU secondaries w/o a headline
        budget = max(60.0, min(cpu_budget, remaining() - 15))
        cpu_res, err = _spawn_streaming(
            "--run", "cpu", budget,
            extra_env={"BENCH_CHILD_BUDGET": max(45.0, budget - 15)})
        if err:
            errors.append(err)
        if cpu_res is not None and _measured(cpu_res):
            result = cpu_res
            if tpu_partial is not None and _measured(tpu_partial):
                # the chip answered but the headline compiles failed:
                # keep the REAL-chip secondary numbers alongside the
                # CPU-fallback headline instead of discarding them
                tex = tpu_partial.get("extras") or {}
                result["extras"]["tpu_secondaries"] = {
                    k: tex.get(k) for k in (
                        "lenet_local_images_per_sec",
                        "ptb_lstm_tokens_per_sec",
                        "transformer_lm_tokens_per_sec",
                        "dlframes_fit_transform_rows_per_sec")
                    if tex.get(k) is not None}
                result["extras"]["tpu_headline_errors"] = {
                    b: v.get("error") for b, v in
                    (tex.get("batch_sweep") or {}).items()
                    if isinstance(v, dict) and v.get("error")}
            # label IMMEDIATELY (and mirror to _LATEST): a driver
            # SIGTERM during the post-fallback re-probe window must dump
            # a labelled artifact, not a clean-looking CPU number
            cpu_child_err = cpu_res.get("error")
            result["error"] = _cpu_error_label()
            _record_partial(result)

    # --- post-fallback re-probe (VERDICT r4 item 1a) ----------------
    # The tunnel flaps on tens-of-minutes timescales (it recovered
    # mid-round in r03; r04 lost the whole round to ONE early timeout).
    # After the CPU fallback, if the first probe never succeeded and the
    # window still covers a probe + a useful TPU measurement, probe once
    # more and upgrade to the real number.
    if not tpu_ok and remaining() - 180 >= 20:
        budget = min(probe_budget, remaining() - 180)
        reprobe, err = _spawn_streaming("--probe", "tpu", budget)
        if reprobe and reprobe.get("probe"):
            budget = min(tpu_budget, remaining() - 30)
            if budget >= 120:
                tpu_res, err = _spawn_streaming(
                    "--run", "tpu", budget,
                    extra_env={
                        "BENCH_CHILD_BUDGET": max(60.0, budget - 30)})
                if err:
                    errors.append(f"post-fallback run: {err}")
                if tpu_res is not None and tpu_res.get("value") is not None:
                    tpu_res["error"] = None
                    result = tpu_res
        else:
            errors.append(f"re-probe: {err or 'no output'}")

    def _apply_regression_gate(res):
        # opt-in perf-regression gate (obs/regress.py): compare this
        # run's extras.obs_runtime against the BENCH_r*.json trajectory
        # in $BIGDL_REGRESS_TRAJECTORY; the verdict rides in
        # extras.regression and, on violation, a flight-recorder bundle
        # lands in $BIGDL_REGRESS_FLIGHT_DIR.  Best-effort: the gate
        # must never sink the bench or touch its exit code.
        traj = os.environ.get("BIGDL_REGRESS_TRAJECTORY")
        if not traj:
            return
        try:
            from bigdl_tpu.obs import regress

            verdict = regress.gate(
                res, traj,
                flight_dir=os.environ.get("BIGDL_REGRESS_FLIGHT_DIR"),
                trace_dir=os.environ.get("BIGDL_TRACE_DIR"))
            res.setdefault("extras", {})["regression"] = verdict
        except Exception as e:  # noqa: BLE001 — never sink the bench
            res.setdefault("extras", {})["regression"] = {
                "status": "error",
                "error": f"{type(e).__name__}: {str(e)[:200]}"}

    if result is None:
        result = _empty_result(errors)
    elif result is cpu_res:
        # re-bake the label LAST so the re-probe attempt's outcome
        # (failure appended to `errors`; success replaced `result`) and
        # the child's own cause (e.g. "headline truncated") both land
        # in the round artifact
        result["error"] = (_cpu_error_label()
                           + (" [truncated]" if result.get("partial")
                              else ""))
    elif result.get("partial"):
        result["error"] = ((result.get("error") or "") + " truncated: " +
                           " | ".join(errors)).strip()
    result.pop("partial", None)
    _apply_regression_gate(result)
    _record_partial(result)
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--run":
        _run_child(sys.argv[2])
    elif len(sys.argv) >= 3 and sys.argv[1] == "--probe":
        _probe_child(sys.argv[2])
    else:
        main()
