"""A/B the TransformerLM train step's attention impl on chip.

Round-5 measured policy (ops/attention.py ``impl="auto"``): XLA's fused
lax attention beats the Pallas flash forward at every length whose
softmax residuals fit, so auto takes lax below T=4096 and flash beyond.
This script reproduces those numbers — and re-evaluates them now that
the flash path has a true blockwise backward — one subprocess per
(T, impl) so a hung remote compile costs only that cell.

``tuned`` is the auto-tuner row (ops/autotune.py): the child enables
``BIGDL_TUNER``, pre-warms the cell's attention shape with concrete
arrays (so candidates are wall-clock measured, fwd+bwd), and runs the
model with ``attn_impl="auto"`` — dispatch then comes from the cached
decision.  All cells share one cache file, and the tuner's
never-lose gate means the tuned row can only match or beat the best
static row; the decisions ride the output line (and bench.py's
``extras.tuner``) so the evidence is banked across
chip-unavailable rounds.

Usage: python scripts/attn_ab.py [impl ...]   (default: pallas lax)
Cells: (T=512,B=16) (T=1024,B=8) (T=2048,B=4) (T=4096,B=2).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CELLS = [(512, 16), (1024, 8), (2048, 4), (4096, 2)]
IMPLS = sys.argv[1:] or ["pallas", "lax"]
_VALID = {"auto", "lax", "pallas", "pallas_interpret", "tuned"}
_bad = [i for i in IMPLS if i not in _VALID]
if _bad:
    # dot_product_attention silently routes unknown impl strings to the
    # lax reference — a typo would benchmark lax under the wrong label
    sys.exit(f"unknown impl {_bad}; choose from {sorted(_VALID)}")


def _run_cell(t: int, b: int, impl: str):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax

    jax.config.update("jax_platforms", "axon")
    from bigdl_tpu.models.transformer import build_transformer_lm

    attn_impl = impl
    tuner_info = None
    if impl == "tuned":
        os.environ.setdefault("BIGDL_TUNER", "1")
        os.environ.setdefault("BIGDL_TUNER_MEASURE", "1")
        os.environ.setdefault(
            "BIGDL_TUNER_CACHE",
            os.environ.get("ATTN_AB_TUNER_CACHE",
                           "/tmp/bigdl_attn_ab_tuner.json"))
        from bigdl_tpu.ops import autotune

        # pre-warm the cell's shape with concrete arrays so candidates
        # are wall-clock measured; the in-model trace then hits the
        # cache (measurement never runs inside a jit trace)
        autotune.prewarm_attention(b, 8, t, t, 64, causal=True)
        attn_impl = "auto"
        tuner_info = [f"{d['label']}<-{d['source']}"
                      for d in autotune.summary()["decisions"]]
    model = build_transformer_lm(8192, dim=512, n_head=8, n_layer=8,
                                 max_len=t, attn_impl=attn_impl)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randint(0, 8192, (b, t)).astype(np.float32))
    params, state = model.params(), model.state()
    rng = jax.random.key(0)

    def loss_fn(p, x):
        out, _ = model.apply(p, state, x, training=True, rng=rng)
        logp = jax.nn.log_softmax(out.astype(jnp.float32))
        ids = x.astype(jnp.int32)
        tgt = jnp.concatenate([ids[:, 1:], ids[:, :1]], axis=1)
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))

    def step(p, x):
        loss, g = jax.value_and_grad(loss_fn)(p, x)
        return jax.tree.map(lambda w, gw: w - 1e-3 * gw, p, g), loss

    @jax.jit
    def run(p, x):
        def body(c, _):
            c, loss = step(c, x)
            return c, loss

        _, losses = lax.scan(body, p, None, length=10)
        return losses[-1]

    float(run(params, x))  # compile + warmup
    t0 = time.perf_counter()
    float(run(params, x))
    dt = time.perf_counter() - t0
    rec = {
        "T": t, "batch": b, "impl": impl,
        "tokens_per_sec": round(b * t * 10 / dt, 1),
        "step_ms": round(dt / 10 * 1e3, 2),
    }
    if tuner_info is not None:
        rec["tuner"] = tuner_info
    print(json.dumps(rec), flush=True)


def main():
    child = os.environ.get("ATTN_AB_CHILD")
    if child:
        t, b, impl = child.split(",")
        _run_cell(int(t), int(b), impl)
        return
    if "tuned" in IMPLS and "ATTN_AB_TUNER_CACHE" not in os.environ:
        # one shared decision store across all tuned cells of this run
        os.environ["ATTN_AB_TUNER_CACHE"] = \
            f"/tmp/bigdl_attn_ab_tuner.{os.getpid()}.json"
    for t, b in CELLS:
        for impl in IMPLS:
            t0 = time.time()
            env = dict(os.environ, ATTN_AB_CHILD=f"{t},{b},{impl}")
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True, text=True, timeout=420, env=env)
                out = (proc.stdout or "").strip().splitlines()
                line = out[-1] if out else (proc.stderr or "")[-200:]
            except subprocess.TimeoutExpired:
                line = json.dumps({"T": t, "impl": impl,
                                   "error": "TIMEOUT 420s"})
            print(f"{line}   [{time.time()-t0:.0f}s]", flush=True)


if __name__ == "__main__":
    main()
