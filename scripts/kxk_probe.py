"""Which kxk im2col construction does this Mosaic accept?

The pure-2-D kxk kernel (ops/conv_bn.py) builds a tap-major im2col from
k*k lane-shifted slices.  The 2026-07 Mosaic rejects concatenating
vectors whose lane offsets differ ("result/input offset mismatch on
non-concat dimension"), so this probe tries the candidate relayout
mechanisms on the real chip, each in a subprocess, and checks numerics
against the XLA reference:

  scratch — store each tap slice into a VMEM scratch ref (stores
            materialize the ref's layout), then one deep dot
  taps    — k*k separate accumulated dots, no concat (relies on dot
            operand relayout; k*k-fold shallower contraction)
  roll    — jnp.roll the whole block to lane offset 0, slice, concat

    python scripts/kxk_probe.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N, C, H, W, O, K = 8, 64, 16, 16, 64, 3


def _build(variant: str):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    pad = (K - 1) // 2
    hp, wp_ = H + 2 * pad, W + 2 * pad
    ho, wo = H, W
    L = hp * wp_ + K - 1

    def kern(x_ref, w_ref, y_ref, *scratch):
        xp = x_ref[0]                       # (C, L)
        if variant == "scratch":
            xcat_ref, = scratch
            for t in range(K * K):
                dy, dx = t // K, t % K
                s = dy * wp_ + dx
                xcat_ref[t * C:(t + 1) * C, :] = xp[:, s:s + ho * wp_]
            acc = jax.lax.dot_general(
                w_ref[...], xcat_ref[...], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif variant == "taps":
            acc = None
            for t in range(K * K):
                dy, dx = t // K, t % K
                s = dy * wp_ + dx
                part = jax.lax.dot_general(
                    w_ref[:, t * C:(t + 1) * C], xp[:, s:s + ho * wp_],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc = part if acc is None else acc + part
        else:  # roll
            taps = []
            for t in range(K * K):
                dy, dx = t // K, t % K
                s = dy * wp_ + dx
                taps.append(jnp.roll(xp, -s, axis=1)[:, :ho * wp_])
            acc = jax.lax.dot_general(
                w_ref[...], jnp.concatenate(taps, axis=0),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        y_ref[0] = acc.astype(y_ref.dtype)

    def run(x, w):
        xpad = jnp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        xflat = jnp.pad(xpad.reshape(N, C, hp * wp_),
                        ((0, 0), (0, 0), (0, K - 1)))
        wt = jnp.transpose(w, (0, 2, 3, 1)).reshape(O, K * K * C)
        y2 = pl.pallas_call(
            kern,
            grid=(1, N),
            in_specs=[
                pl.BlockSpec((1, C, L), lambda oi, ni: (ni, 0, 0)),
                pl.BlockSpec((O, K * K * C), lambda oi, ni: (oi, 0)),
            ],
            out_specs=pl.BlockSpec((1, O, ho * wp_),
                                   lambda oi, ni: (ni, oi, 0)),
            out_shape=jax.ShapeDtypeStruct((N, O, ho * wp_), x.dtype),
            scratch_shapes=(
                [pltpu.VMEM((K * K * C, ho * wp_), x.dtype)]
                if variant == "scratch" else []),
        )(xflat, wt)
        return y2.reshape(N, O, ho, wp_)[:, :, :, :wo]

    return run


def _run_variant(variant: str):
    import numpy as np

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "axon")
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(N, C, H, W), dtype=jnp.bfloat16)
    w = jnp.asarray(rs.randn(O, C, K, K) * 0.05, dtype=jnp.bfloat16)
    t0 = time.time()
    y = jax.jit(_build(variant))(x, w)
    y.block_until_ready()
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32).astype(jnp.bfloat16)
    err = float(jnp.max(jnp.abs(y.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    print(json.dumps({"variant": variant, "ok": True,
                      "max_err": round(err, 5),
                      "seconds": round(time.time() - t0, 1)}))


def main():
    if os.environ.get("KXK_PROBE_CHILD"):
        _run_variant(os.environ["KXK_PROBE_CHILD"])
        return
    for v in ("scratch", "taps", "roll"):
        t0 = time.time()
        env = dict(os.environ, KXK_PROBE_CHILD=v)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=240, env=env)
            ok = proc.returncode == 0
            tail = (proc.stdout or proc.stderr or "").strip().splitlines()
            detail = tail[-1][:220] if tail else ""
        except subprocess.TimeoutExpired:
            ok, detail = False, "TIMEOUT 240s"
        print(f"{v:8s} {'OK' if ok else 'FAIL'} "
              f"{time.time()-t0:6.1f}s  {detail}", flush=True)


if __name__ == "__main__":
    main()
