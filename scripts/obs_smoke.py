#!/usr/bin/env python
"""--obs-report smoke: the distributed-observability loop, end to end.

Driven by ``scripts/run-tests.sh --obs-report``.  Four stages, each a
hard assert:

1. two simulated hosts (separate OS processes, ``BIGDL_PROCESS_ID``
   0/1, CPU backend) each run a 10-step traced DistriOptimizer job —
   with health telemetry on (``BIGDL_HEALTH_EVERY=2``) — into ONE
   shared trace/metrics volume;
2. ``python -m bigdl_tpu.obs.aggregate`` merges the shards into a
   single Perfetto-loadable timeline — both hosts tagged, barriers
   clock-aligned;
3. ``python -m bigdl_tpu.obs.report`` renders the run report (step
   times, collective bytes, slowest spans, the training-health section
   with per-layer grad norms) from the same dirs, and ``--json``
   carries the same health dict machine-readably;
4. ``python -m bigdl_tpu.obs.regress`` gates a synthetic 2x step-time
   slowdown against a synthetic trajectory (must FAIL and dump a
   flight-recorder bundle) and the unchanged result (must PASS).

Exit 0 only when all four hold.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys
sys.path.insert(0, os.environ["BIGDL_REPO"])
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") \\
    + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from bigdl_tpu import obs
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import (ClassNLLCriterion, Linear, LogSoftMax, ReLU,
                          Sequential)
from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

Engine.init()
rng = np.random.RandomState(0)
w = rng.randn(16, 4)
x = rng.randn(320, 16).astype(np.float32)
y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
model = Sequential().add(Linear(16, 32)).add(ReLU()) \\
    .add(Linear(32, 4)).add(LogSoftMax())
opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=32)
opt.set_optim_method(SGD(learningrate=0.1))
opt.set_end_when(Trigger.max_iteration(10))
opt.optimize()
assert opt.state["neval"] == 11, opt.state["neval"]
"""


def run(cmd, **env):
    e = dict(os.environ)
    e.update({k: str(v) for k, v in env.items()})
    e["BIGDL_REPO"] = REPO
    return subprocess.run(cmd, env=e, cwd=REPO, capture_output=True,
                          text=True, timeout=300)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="bigdl_obs_smoke_")
    trace_dir = os.path.join(tmp, "trace")
    metrics_dir = os.path.join(tmp, "metrics")

    # -- 1: two simulated hosts, one shared volume --------------------
    for host in (0, 1):
        p = run([sys.executable, "-c", _WORKER],
                BIGDL_PROCESS_ID=host, BIGDL_TRACE_DIR=trace_dir,
                BIGDL_METRICS_DIR=metrics_dir, BIGDL_HEALTH_EVERY=2)
        assert p.returncode == 0, f"host {host} worker failed:\n{p.stdout}\n{p.stderr}"
        print(f"[obs-smoke] host {host}: 10-step traced run ok")

    # -- 2: merge ------------------------------------------------------
    merged = os.path.join(tmp, "merged.trace.json")
    p = run([sys.executable, "-m", "bigdl_tpu.obs.aggregate", trace_dir,
             "-o", merged])
    assert p.returncode == 0, p.stdout + p.stderr
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["hosts"] == [0, 1], summary
    assert not summary["unaligned"], summary
    doc = json.load(open(merged))
    evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    assert evs and all(
        evs[i]["ts"] <= evs[i + 1]["ts"] for i in range(len(evs) - 1)), \
        "merged timeline not monotone"
    assert {e["args"].get("host") for e in evs} == {0, 1}
    print(f"[obs-smoke] merged {summary['shards']} shards, "
          f"{len(evs)} events, offsets {summary['offsets_s']}")

    # -- 3: report -----------------------------------------------------
    p = run([sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
             "--metrics-dir", metrics_dir])
    assert p.returncode == 0, p.stdout + p.stderr
    for needle in ("step times", "collective wire bytes", "psum_scatter",
                   "slowest spans", "training health", "grad=",
                   "upd/w="):
        assert needle in p.stdout, f"report missing {needle!r}:\n{p.stdout}"
    print("[obs-smoke] report renders (step times + collective bytes "
          "+ training health)")

    # --json: the same report machine-readably, health section included
    p = run([sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
             "--metrics-dir", metrics_dir, "--json"])
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    assert rep["health"]["grad_norm"], rep["health"]
    assert rep["health"]["update_ratio"], rep["health"]
    print("[obs-smoke] --json report carries the health section")

    # -- 4: regression gate -------------------------------------------
    traj = os.path.join(tmp, "traj")
    os.makedirs(traj)
    base = {"metric": "m", "value": 100.0, "platform": "cpu",
            "extras": {"step_time_s": 0.05,
                       "obs_runtime": {"step_time_p50_s": 0.05}}}
    with open(os.path.join(traj, "BENCH_r01.json"), "w") as fh:
        json.dump({"parsed": base}, fh)
    slow = json.loads(json.dumps(base))
    slow["extras"]["obs_runtime"]["step_time_p50_s"] = 0.10  # 2x slower
    slow["value"] = 50.0
    fresh_slow = os.path.join(tmp, "fresh_slow.json")
    with open(fresh_slow, "w") as fh:
        json.dump(slow, fh)
    flight = os.path.join(tmp, "flight")
    p = run([sys.executable, "-m", "bigdl_tpu.obs.regress", "--fresh",
             fresh_slow, "--trajectory", traj, "--flight-dir", flight,
             "--trace-dir", trace_dir, "--metrics-dir", metrics_dir,
             "--json"])
    assert p.returncode == 1, f"2x slowdown not flagged: {p.stdout}"
    verdict = json.loads(p.stdout.strip().splitlines()[-1])
    assert verdict["status"] == "violation", verdict
    bundle_path = verdict.get("flight_recorder")
    assert bundle_path and os.path.exists(bundle_path), verdict
    bundle = json.load(open(bundle_path))
    assert bundle["spans"], "flight bundle has no spans"
    assert "bigdl_collective_bytes_total" in bundle["metrics"]["metrics"]
    print(f"[obs-smoke] gate flags 2x slowdown; bundle at {bundle_path}")

    fresh_ok = os.path.join(tmp, "fresh_ok.json")
    with open(fresh_ok, "w") as fh:
        json.dump(base, fh)
    p = run([sys.executable, "-m", "bigdl_tpu.obs.regress", "--fresh",
             fresh_ok, "--trajectory", traj, "--json"])
    assert p.returncode == 0, f"unchanged result flagged: {p.stdout}"
    print("[obs-smoke] gate passes the unchanged result")
    print("[obs-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
