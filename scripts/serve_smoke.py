#!/usr/bin/env python
"""--serve smoke: the continuous-batching serving tier, end to end.

Driven by ``scripts/run-tests.sh --serve``.  Six stages, each a hard
assert:

1. **continuous vs static A/B** — the same bursty request trace (mixed
   prompt lengths, short and long decodes interleaved so static
   batching head-of-line blocks) is decoded by two engines sharing one
   model: ``admission="static"`` (drain the whole batch before
   refilling — the ``generate()`` baseline behavior) vs
   ``admission="continuous"`` (refill freed slots at step boundaries).
   Continuous must win on tokens/sec at equal-or-better p99.
2. **decode-kernel A/B (ISSUE 13)** — the same long-decode trace on a
   serving-sized model, decoded by the PR 12 dense-gather baseline
   (``decode_attn="dense"``, full-width tables) vs the tuner-
   dispatched flash-decode path (``BIGDL_TUNER=1``, used-page prefix
   buckets).  The fused path must win >= 1.15x tokens/sec at
   equal-or-better p99, with ``decode_attn`` tuner decisions visible,
   byte-identical greedy tokens across arms (and vs ``generate()``),
   and fused-vs-dense op output within 1e-5.
3. **concurrent clients over HTTP** — a ResNet classifier (int8 via the
   existing ``quantize()``/folded-BN path) and the LM decoder behind
   one stdlib front-end, hammered by concurrent client threads mixing
   ``/v1/generate`` and ``/v1/classify``; every response must be
   well-formed.
4. **queue-driven autoscale decision** — a burst is parked in the
   request queue while the policy loop scrapes the process's own live
   ``/metrics`` endpoint (the real ``EndpointScraper`` path); the
   ``queue_high`` rule must emit a scale-up decision (dry-run).
5. **report** — ``obs.report`` must render the serving section (now
   incl. the decode ms/step + HBM bytes/token line) in text and carry
   the request-latency histograms + the autoscale decision in
   ``--json``.
6. **bank** — ``SERVE_SMOKE.json`` (incl. ``decode_kernel``) for BENCH
   ``extras.serve``.

NOTE: the parent pins JAX_PLATFORMS=cpu for itself — importing
bigdl_tpu pulls jax, which otherwise probes this container's TPU
plugin forever.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

TMP = None  # set in main


def _trace(prompts_seed: int = 7, n: int = 24):
    """The shared A/B request trace: short/long decodes interleaved so
    a drained-batch scheduler head-of-line blocks."""
    import numpy as np

    rs = np.random.RandomState(prompts_seed)
    lens = [20, 3, 16, 2, 12, 4] * (n // 6 + 1)
    return [(rs.randint(0, 48, (3 + i % 5,)).tolist(), lens[i])
            for i in range(n)]


def _reset_measures(eng):
    """Zero the engine's throughput/latency accounting after compile
    warmup so the measured window is pure steady-state decode."""
    eng.completed.clear()
    eng._tokens_total = 0
    eng._occ_sum = eng._steps = 0
    eng._decode_ms_sum = 0.0
    eng._t_first_work = eng._t_last_done = None


def _ab_arm(model, admission: str):
    from bigdl_tpu.serving import LMEngine

    eng = LMEngine(model, max_batch=4, page_size=8, admission=admission,
                   queue_capacity=64, slo_s=30.0, seed=3)
    # warm every compile OUTSIDE the measured window: one request per
    # prefill bucket, plus one long decode that walks the step through
    # every used-page table bucket the trace will touch (the decode
    # step is compiled per pow2 bucket since ISSUE 13)
    for t0 in (4, 12):
        eng.submit(list(range(1, t0 + 1)), 2)
    eng.submit(list(range(1, 5)), 30)
    eng.run_until_idle(120)
    _reset_measures(eng)
    reqs = [eng.submit(p, m) for p, m in _trace()]
    eng.run_until_idle(180)
    assert all(r.done and len(r.tokens) == m
               for r, (_, m) in zip(reqs, _trace())), "incomplete requests"
    st = eng.stats()
    eng.close()
    return st


# -------------------------------------------------- decode-kernel A/B
def _decode_trace(n: int = 16):
    """Long-decode trace for the kernel A/B: short prompts, 40-56
    generated tokens each, so the step count is decode-dominated and
    slot lengths stay under 64 (= the 4-page bucket at page 16)."""
    import numpy as np

    rs = np.random.RandomState(11)
    decodes = [48, 40, 56, 44, 52, 40, 54, 46] * (n // 8 + 1)
    return [(rs.randint(0, 64, (4 + i % 5,)).tolist(), decodes[i])
            for i in range(n)]


def _decode_arm(model, label, **engine_kw):
    from bigdl_tpu.serving import LMEngine

    eng = LMEngine(model, max_batch=8, page_size=16, num_pages=64,
                   queue_capacity=64, slo_s=30.0, seed=7, **engine_kw)
    # warmup drives one slot through every decode bucket the trace
    # touches (lengths 4 -> 60: 1-, 2- and 4-page tables) plus the
    # prefill bucket, so the measured window has zero compiles
    eng.submit([1, 2, 3, 4], 56)
    eng.run_until_idle(300)
    _reset_measures(eng)
    reqs = [eng.submit(p, m) for p, m in _decode_trace()]
    eng.run_until_idle(600)
    assert all(r.done and len(r.tokens) == m
               for r, (_, m) in zip(reqs, _decode_trace())), \
        f"incomplete requests in {label} arm"
    st = eng.stats()
    eng.close()
    return st, [list(r.tokens) for r in reqs]


def main() -> int:
    global TMP
    import tempfile

    TMP = tempfile.mkdtemp(prefix="bigdl_serve_smoke_")
    os.environ["BIGDL_TRACE_DIR"] = os.path.join(TMP, "trace")
    os.environ["BIGDL_METRICS_DIR"] = os.path.join(TMP, "metrics")
    os.environ["BIGDL_OBS_PORT"] = "0"
    port_file = os.path.join(TMP, "obs_port")
    os.environ["BIGDL_OBS_PORT_FILE"] = port_file

    import numpy as np

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.engine import Engine

    RandomGenerator.RNG.set_seed(13)
    Engine.init()
    from bigdl_tpu.models.transformer import build_transformer_lm

    model = build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                 max_len=64, attn_impl="xla")

    # -- 1: continuous vs static A/B ----------------------------------
    stat = _ab_arm(model, "static")
    cont = _ab_arm(model, "continuous")
    speedup = cont["tokens_per_s"] / stat["tokens_per_s"]
    print(f"[serve-smoke] static:     {stat['tokens_per_s']:.1f} tok/s, "
          f"p99 {stat['e2e_p99_s'] * 1000:.0f}ms, occupancy "
          f"{stat['occupancy_mean'] * 100:.0f}%")
    print(f"[serve-smoke] continuous: {cont['tokens_per_s']:.1f} tok/s, "
          f"p99 {cont['e2e_p99_s'] * 1000:.0f}ms, occupancy "
          f"{cont['occupancy_mean'] * 100:.0f}%")
    assert cont["tokens_per_s"] > stat["tokens_per_s"], \
        f"continuous {cont['tokens_per_s']:.1f} tok/s did not beat " \
        f"static {stat['tokens_per_s']:.1f}"
    assert cont["e2e_p99_s"] <= stat["e2e_p99_s"], \
        f"continuous p99 {cont['e2e_p99_s']:.3f}s worse than static " \
        f"{stat['e2e_p99_s']:.3f}s"
    print(f"[serve-smoke] continuous batching: {speedup:.2f}x tokens/s "
          "at equal-or-better p99 — PASS")

    # -- 2: decode-kernel A/B (flash-decode vs the dense gather) ------
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import build_transformer_lm
    from bigdl_tpu.ops import autotune
    from bigdl_tpu.ops.decode_attention import paged_decode_attention

    RandomGenerator.RNG.set_seed(29)
    # max_len 512 / page 16 = a 32-page table per slot, of which the
    # trace only ever fills 4 — the PR 12 baseline gathers all 32 per
    # layer per step (the gather tax the fused path deletes)
    model2 = build_transformer_lm(64, dim=128, n_head=8, n_layer=4,
                                  max_len=512, attn_impl="xla")
    params2 = model2.params()
    base_st, base_toks = _decode_arm(
        model2, "dense-gather baseline", decode_attn="dense",
        decode_bucket=False)
    os.environ["BIGDL_TUNER"] = "1"
    os.environ["BIGDL_TUNER_CACHE"] = os.path.join(TMP, "tuner.json")
    autotune.reset()
    fused_st, fused_toks = _decode_arm(model2, "tuner-dispatched")
    dspeed = fused_st["tokens_per_s"] / base_st["tokens_per_s"]
    impls = fused_st["decode_impl_by_bucket"]
    decisions = [d for d in autotune.summary()["decisions"]
                 if d["site"] == "decode_attn"]
    print(f"[serve-smoke] decode dense-full:  "
          f"{base_st['tokens_per_s']:.0f} tok/s, p99 "
          f"{base_st['e2e_p99_s'] * 1000:.0f}ms, "
          f"{base_st['decode_ms_mean']:.2f}ms/step, "
          f"{base_st['decode_hbm_bytes_per_token'] / 1e6:.2f} MB/token")
    print(f"[serve-smoke] decode tuned:       "
          f"{fused_st['tokens_per_s']:.0f} tok/s, p99 "
          f"{fused_st['e2e_p99_s'] * 1000:.0f}ms, "
          f"{fused_st['decode_ms_mean']:.2f}ms/step, "
          f"{fused_st['decode_hbm_bytes_per_token'] / 1e6:.2f} MB/token")
    print(f"[serve-smoke] decode_attn tuner decisions: "
          + ", ".join(f"{d['key'].split('|')[1]}->{d['label']}"
                      f"({d['source']})" for d in decisions))
    assert decisions, "no decode_attn tuner decisions recorded"
    assert impls and all(v == "fused" for v in impls.values()), impls
    assert fused_toks == base_toks, \
        "tuned arm diverged from the dense baseline's greedy tokens"
    p0, m0 = _decode_trace()[0]
    ref0 = list(np.asarray(model2.generate(
        params2, np.asarray(p0)[None, :], m0))[0])
    assert [int(t) for t in p0 + base_toks[0]] == ref0, \
        "dense baseline lost temperature-0 parity vs generate()"
    assert dspeed >= 1.15, \
        f"flash-decode speedup {dspeed:.2f}x < 1.15x"
    assert fused_st["e2e_p99_s"] <= base_st["e2e_p99_s"] * 1.02, \
        f"tuned p99 {fused_st['e2e_p99_s']:.3f}s worse than dense " \
        f"{base_st['e2e_p99_s']:.3f}s"
    # op-level fused-vs-dense parity at the serving shape
    rs2 = np.random.RandomState(2)
    pool = 33
    qo = jnp.asarray(rs2.randn(8, 8, 16).astype(np.float32))
    kpo = jnp.asarray(rs2.randn(pool, 8, 16, 16).astype(np.float32))
    vpo = jnp.asarray(rs2.randn(pool, 8, 16, 16).astype(np.float32))
    lens = jnp.asarray(rs2.randint(1, 63, (8,)).astype(np.int32))
    tbls = jnp.asarray(rs2.randint(1, pool, (8, 4)).astype(np.int32))
    od = paged_decode_attention(qo, kpo, vpo, tbls, lens, page_size=16,
                                impl="dense")
    of = paged_decode_attention(qo, kpo, vpo, tbls, lens, page_size=16,
                                impl="fused")
    op_diff = float(jnp.max(jnp.abs(od - of)))
    assert op_diff < 1e-5, f"fused-vs-dense op diff {op_diff:g}"
    print(f"[serve-smoke] flash-decode: {dspeed:.2f}x tokens/s at "
          f"equal-or-better p99, token-identical, op diff "
          f"{op_diff:.1e} — PASS")

    # -- 3: concurrent clients vs ResNet + LM over HTTP ---------------
    from bigdl_tpu.models.resnet import build_resnet_cifar
    from bigdl_tpu.serving import (ClassifierEngine, LMEngine,
                                   ServingServer)

    lm = LMEngine(model, max_batch=4, page_size=8, slo_s=30.0,
                  seed=5).start()
    resnet = build_resnet_cifar(depth=8, class_num=10)
    clf = ClassifierEngine(resnet, max_batch=4, int8=True).start()
    assert clf.int8, "classifier must ride the int8 quantize() path"
    srv = ServingServer(lm=lm, classifier=clf, port=0)
    url = f"http://127.0.0.1:{srv.port}"

    def post(path, payload, timeout=120):
        req = urllib.request.Request(
            url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(
            req, timeout=timeout).read())

    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, 48, (3 + i % 4,)).tolist() for i in range(8)]
    images = rs.randn(8, 2, 3, 32, 32).astype(np.float32)
    errors = []

    def client(i):
        try:
            g = post("/v1/generate", {"prompt": prompts[i],
                                      "max_new_tokens": 4 + i % 3})
            assert len(g["tokens"]) == 4 + i % 3, g
            assert g["ttft_s"] is not None and g["e2e_s"] > 0, g
            c = post("/v1/classify", {"inputs": images[i].tolist()})
            assert len(c["classes"]) == 2, c
            assert all(0 <= k < 10 for k in c["classes"]), c
        except Exception as e:  # noqa: BLE001 — joined below
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    assert not errors, "\n".join(errors)
    stats = json.loads(urllib.request.urlopen(
        url + "/stats", timeout=10).read())
    assert stats["lm"]["requests"] >= 8, stats["lm"]
    assert stats["classifier"]["requests"] >= 8, stats["classifier"]
    srv.close()
    clf.close()
    print("[serve-smoke] 8 concurrent HTTP clients vs int8 ResNet-8 + "
          "LM decoder: all responses well-formed — PASS")

    # -- 4: queue-driven autoscale decision off the live /metrics -----
    os.environ.update({
        "BIGDL_AUTOSCALE_QUEUE_HIGH": "8",
        "BIGDL_AUTOSCALE_HYSTERESIS": "1",
        "BIGDL_AUTOSCALE_WARMUP": "0",
        "BIGDL_AUTOSCALE_DRY_RUN": "1",
    })
    from bigdl_tpu.config import refresh_from_env
    from bigdl_tpu.resilience.autoscale import (AutoscaleController,
                                                EndpointScraper,
                                                derive_signals)

    # park a burst in the queue: the engine thread is stopped, so the
    # backlog (and its gauge) is real at scrape time
    lm.close()
    burst_lm = LMEngine(model, max_batch=4, page_size=8,
                        queue_capacity=64, seed=9)
    for i in range(12):
        burst_lm.submit(prompts[i % len(prompts)], 4)
    depth = burst_lm.queue.depth()
    assert depth > 8, f"expected a parked backlog, got depth {depth}"
    scraper = EndpointScraper(port_file=port_file)
    ctl = AutoscaleController(cfg=refresh_from_env().autoscale, world=1,
                              scrape=scraper)
    scraped = scraper()
    assert scraped and scraped[0].get("ok"), scraped
    sig = derive_signals(scraped, {}, 1)
    assert sig.get("queue_depth", 0) > 8, sig
    decision = ctl.evaluate(sig)
    assert decision is not None and decision.direction == "up" \
        and decision.reason == "queue_high", decision
    burst_lm.run_until_idle(120)  # drain so nothing leaks
    burst_lm.close()
    print(f"[serve-smoke] queue depth {sig['queue_depth']:g} scraped "
          f"from the live endpoint -> autoscale decision "
          f"{decision.direction} ({decision.reason}, dry-run) — PASS")

    from bigdl_tpu import obs

    obs.flush()

    # -- 5: the report renders the serving loop -----------------------
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report",
         os.environ["BIGDL_TRACE_DIR"], "--metrics-dir",
         os.environ["BIGDL_METRICS_DIR"]],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    for needle in ("-- serving --", "latency lm:e2e",
                   "latency classifier:e2e", "tok/s", "decode: ",
                   "MB/token"):
        assert needle in p.stdout, f"report missing {needle!r}:\n{p.stdout}"
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report",
         os.environ["BIGDL_TRACE_DIR"], "--metrics-dir",
         os.environ["BIGDL_METRICS_DIR"], "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    sv = rep["serving"]
    assert sv and sv["latency"]["lm:e2e"]["count"] >= 8, sv
    assert sv["latency"]["lm:ttft"]["p99_s"] is not None, sv
    assert sv["latency"]["classifier:e2e"]["count"] >= 8, sv
    assert sv["tokens_per_second"] and sv["tokens_per_second"] > 0, sv
    assert sv["decode_attn_ms"] and sv["decode_attn_ms"] > 0, sv
    assert sv["decode_hbm_bytes_per_token"] > 0, sv
    decs = rep["autoscale"]["decisions_total"]
    assert decs.get("up:queue_high", 0) >= 1, decs
    tn = rep.get("tuner")
    assert tn and any(s.startswith("decode_attn")
                      for s in tn["decisions_total"]), tn
    print("[serve-smoke] report: serving section + latency histograms "
          "+ the queue-driven decision all present (text + --json) — "
          "PASS")

    # -- 6: bank for BENCH extras.serve -------------------------------
    bank = {
        "static": {k: stat[k] for k in
                   ("tokens_per_s", "e2e_p99_s", "e2e_p50_s",
                    "occupancy_mean", "requests", "tokens", "steps")},
        "continuous": {k: cont[k] for k in
                       ("tokens_per_s", "e2e_p99_s", "e2e_p50_s",
                        "occupancy_mean", "requests", "tokens",
                        "steps")},
        "tokens_per_s_speedup": speedup,
        "p99_ratio": cont["e2e_p99_s"] / stat["e2e_p99_s"],
        "decode_kernel": {
            "dense_full": {k: base_st[k] for k in
                           ("tokens_per_s", "e2e_p99_s", "e2e_p50_s",
                            "decode_ms_mean",
                            "decode_hbm_bytes_per_token", "steps",
                            "tokens")},
            "tuned": {k: fused_st[k] for k in
                      ("tokens_per_s", "e2e_p99_s", "e2e_p50_s",
                       "decode_ms_mean", "decode_hbm_bytes_per_token",
                       "steps", "tokens")},
            "tokens_per_s_speedup": dspeed,
            "p99_ratio": fused_st["e2e_p99_s"] / base_st["e2e_p99_s"],
            "impl_by_bucket": impls,
            "fused_vs_dense_max_abs_diff": op_diff,
            "tuner_decisions": decisions,
        },
        "classifier": {"requests": stats["classifier"]["requests"],
                       "int8": True},
        "autoscale_decision": {"direction": decision.direction,
                               "reason": decision.reason,
                               "queue_depth": sig["queue_depth"]},
        "ts": time.time(),
    }
    out = os.path.join(REPO, "SERVE_SMOKE.json")
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2)
    print(f"[serve-smoke] banked {out}")
    print("[serve-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
