#!/usr/bin/env python
"""--serve smoke: the continuous-batching serving tier, end to end.

Driven by ``scripts/run-tests.sh --serve``.  Five stages, each a hard
assert:

1. **continuous vs static A/B** — the same bursty request trace (mixed
   prompt lengths, short and long decodes interleaved so static
   batching head-of-line blocks) is decoded by two engines sharing one
   model: ``admission="static"`` (drain the whole batch before
   refilling — the ``generate()`` baseline behavior) vs
   ``admission="continuous"`` (refill freed slots at step boundaries).
   Continuous must win on tokens/sec at equal-or-better p99.
2. **concurrent clients over HTTP** — a ResNet classifier (int8 via the
   existing ``quantize()``/folded-BN path) and the LM decoder behind
   one stdlib front-end, hammered by concurrent client threads mixing
   ``/v1/generate`` and ``/v1/classify``; every response must be
   well-formed.
3. **queue-driven autoscale decision** — a burst is parked in the
   request queue while the policy loop scrapes the process's own live
   ``/metrics`` endpoint (the real ``EndpointScraper`` path); the
   ``queue_high`` rule must emit a scale-up decision (dry-run).
4. **report** — ``obs.report`` must render the serving section in text
   and carry the request-latency histograms + the autoscale decision
   in ``--json``.
5. **bank** — ``SERVE_SMOKE.json`` for BENCH ``extras.serve``.

NOTE: the parent pins JAX_PLATFORMS=cpu for itself — importing
bigdl_tpu pulls jax, which otherwise probes this container's TPU
plugin forever.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

TMP = None  # set in main


def _trace(prompts_seed: int = 7, n: int = 24):
    """The shared A/B request trace: short/long decodes interleaved so
    a drained-batch scheduler head-of-line blocks."""
    import numpy as np

    rs = np.random.RandomState(prompts_seed)
    lens = [20, 3, 16, 2, 12, 4] * (n // 6 + 1)
    return [(rs.randint(0, 48, (3 + i % 5,)).tolist(), lens[i])
            for i in range(n)]


def _ab_arm(model, admission: str):
    from bigdl_tpu.serving import LMEngine

    eng = LMEngine(model, max_batch=4, page_size=8, admission=admission,
                   queue_capacity=64, slo_s=30.0, seed=3)
    # warm every compile OUTSIDE the measured window: one request per
    # prefill bucket plus the shared decode step
    for t0 in (4, 12):
        eng.submit(list(range(1, t0 + 1)), 2)
    eng.run_until_idle(120)
    eng.completed.clear()
    eng._tokens_total = 0
    eng._occ_sum = eng._steps = 0
    eng._t_first_work = eng._t_last_done = None
    reqs = [eng.submit(p, m) for p, m in _trace()]
    eng.run_until_idle(180)
    assert all(r.done and len(r.tokens) == m
               for r, (_, m) in zip(reqs, _trace())), "incomplete requests"
    st = eng.stats()
    eng.close()
    return st


def main() -> int:
    global TMP
    import tempfile

    TMP = tempfile.mkdtemp(prefix="bigdl_serve_smoke_")
    os.environ["BIGDL_TRACE_DIR"] = os.path.join(TMP, "trace")
    os.environ["BIGDL_METRICS_DIR"] = os.path.join(TMP, "metrics")
    os.environ["BIGDL_OBS_PORT"] = "0"
    port_file = os.path.join(TMP, "obs_port")
    os.environ["BIGDL_OBS_PORT_FILE"] = port_file

    import numpy as np

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.engine import Engine

    RandomGenerator.RNG.set_seed(13)
    Engine.init()
    from bigdl_tpu.models.transformer import build_transformer_lm

    model = build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                 max_len=64, attn_impl="xla")

    # -- 1: continuous vs static A/B ----------------------------------
    stat = _ab_arm(model, "static")
    cont = _ab_arm(model, "continuous")
    speedup = cont["tokens_per_s"] / stat["tokens_per_s"]
    print(f"[serve-smoke] static:     {stat['tokens_per_s']:.1f} tok/s, "
          f"p99 {stat['e2e_p99_s'] * 1000:.0f}ms, occupancy "
          f"{stat['occupancy_mean'] * 100:.0f}%")
    print(f"[serve-smoke] continuous: {cont['tokens_per_s']:.1f} tok/s, "
          f"p99 {cont['e2e_p99_s'] * 1000:.0f}ms, occupancy "
          f"{cont['occupancy_mean'] * 100:.0f}%")
    assert cont["tokens_per_s"] > stat["tokens_per_s"], \
        f"continuous {cont['tokens_per_s']:.1f} tok/s did not beat " \
        f"static {stat['tokens_per_s']:.1f}"
    assert cont["e2e_p99_s"] <= stat["e2e_p99_s"], \
        f"continuous p99 {cont['e2e_p99_s']:.3f}s worse than static " \
        f"{stat['e2e_p99_s']:.3f}s"
    print(f"[serve-smoke] continuous batching: {speedup:.2f}x tokens/s "
          "at equal-or-better p99 — PASS")

    # -- 2: concurrent clients vs ResNet + LM over HTTP ---------------
    from bigdl_tpu.models.resnet import build_resnet_cifar
    from bigdl_tpu.serving import (ClassifierEngine, LMEngine,
                                   ServingServer)

    lm = LMEngine(model, max_batch=4, page_size=8, slo_s=30.0,
                  seed=5).start()
    resnet = build_resnet_cifar(depth=8, class_num=10)
    clf = ClassifierEngine(resnet, max_batch=4, int8=True).start()
    assert clf.int8, "classifier must ride the int8 quantize() path"
    srv = ServingServer(lm=lm, classifier=clf, port=0)
    url = f"http://127.0.0.1:{srv.port}"

    def post(path, payload, timeout=120):
        req = urllib.request.Request(
            url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(
            req, timeout=timeout).read())

    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, 48, (3 + i % 4,)).tolist() for i in range(8)]
    images = rs.randn(8, 2, 3, 32, 32).astype(np.float32)
    errors = []

    def client(i):
        try:
            g = post("/v1/generate", {"prompt": prompts[i],
                                      "max_new_tokens": 4 + i % 3})
            assert len(g["tokens"]) == 4 + i % 3, g
            assert g["ttft_s"] is not None and g["e2e_s"] > 0, g
            c = post("/v1/classify", {"inputs": images[i].tolist()})
            assert len(c["classes"]) == 2, c
            assert all(0 <= k < 10 for k in c["classes"]), c
        except Exception as e:  # noqa: BLE001 — joined below
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    assert not errors, "\n".join(errors)
    stats = json.loads(urllib.request.urlopen(
        url + "/stats", timeout=10).read())
    assert stats["lm"]["requests"] >= 8, stats["lm"]
    assert stats["classifier"]["requests"] >= 8, stats["classifier"]
    srv.close()
    clf.close()
    print("[serve-smoke] 8 concurrent HTTP clients vs int8 ResNet-8 + "
          "LM decoder: all responses well-formed — PASS")

    # -- 3: queue-driven autoscale decision off the live /metrics -----
    os.environ.update({
        "BIGDL_AUTOSCALE_QUEUE_HIGH": "8",
        "BIGDL_AUTOSCALE_HYSTERESIS": "1",
        "BIGDL_AUTOSCALE_WARMUP": "0",
        "BIGDL_AUTOSCALE_DRY_RUN": "1",
    })
    from bigdl_tpu.config import refresh_from_env
    from bigdl_tpu.resilience.autoscale import (AutoscaleController,
                                                EndpointScraper,
                                                derive_signals)

    # park a burst in the queue: the engine thread is stopped, so the
    # backlog (and its gauge) is real at scrape time
    lm.close()
    burst_lm = LMEngine(model, max_batch=4, page_size=8,
                        queue_capacity=64, seed=9)
    for i in range(12):
        burst_lm.submit(prompts[i % len(prompts)], 4)
    depth = burst_lm.queue.depth()
    assert depth > 8, f"expected a parked backlog, got depth {depth}"
    scraper = EndpointScraper(port_file=port_file)
    ctl = AutoscaleController(cfg=refresh_from_env().autoscale, world=1,
                              scrape=scraper)
    scraped = scraper()
    assert scraped and scraped[0].get("ok"), scraped
    sig = derive_signals(scraped, {}, 1)
    assert sig.get("queue_depth", 0) > 8, sig
    decision = ctl.evaluate(sig)
    assert decision is not None and decision.direction == "up" \
        and decision.reason == "queue_high", decision
    burst_lm.run_until_idle(120)  # drain so nothing leaks
    burst_lm.close()
    print(f"[serve-smoke] queue depth {sig['queue_depth']:g} scraped "
          f"from the live endpoint -> autoscale decision "
          f"{decision.direction} ({decision.reason}, dry-run) — PASS")

    from bigdl_tpu import obs

    obs.flush()

    # -- 4: the report renders the serving loop -----------------------
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report",
         os.environ["BIGDL_TRACE_DIR"], "--metrics-dir",
         os.environ["BIGDL_METRICS_DIR"]],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    for needle in ("-- serving --", "latency lm:e2e",
                   "latency classifier:e2e", "tok/s"):
        assert needle in p.stdout, f"report missing {needle!r}:\n{p.stdout}"
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report",
         os.environ["BIGDL_TRACE_DIR"], "--metrics-dir",
         os.environ["BIGDL_METRICS_DIR"], "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    sv = rep["serving"]
    assert sv and sv["latency"]["lm:e2e"]["count"] >= 8, sv
    assert sv["latency"]["lm:ttft"]["p99_s"] is not None, sv
    assert sv["latency"]["classifier:e2e"]["count"] >= 8, sv
    assert sv["tokens_per_second"] and sv["tokens_per_second"] > 0, sv
    decs = rep["autoscale"]["decisions_total"]
    assert decs.get("up:queue_high", 0) >= 1, decs
    print("[serve-smoke] report: serving section + latency histograms "
          "+ the queue-driven decision all present (text + --json) — "
          "PASS")

    # -- 5: bank for BENCH extras.serve -------------------------------
    bank = {
        "static": {k: stat[k] for k in
                   ("tokens_per_s", "e2e_p99_s", "e2e_p50_s",
                    "occupancy_mean", "requests", "tokens", "steps")},
        "continuous": {k: cont[k] for k in
                       ("tokens_per_s", "e2e_p99_s", "e2e_p50_s",
                        "occupancy_mean", "requests", "tokens",
                        "steps")},
        "tokens_per_s_speedup": speedup,
        "p99_ratio": cont["e2e_p99_s"] / stat["e2e_p99_s"],
        "classifier": {"requests": stats["classifier"]["requests"],
                       "int8": True},
        "autoscale_decision": {"direction": decision.direction,
                               "reason": decision.reason,
                               "queue_depth": sig["queue_depth"]},
        "ts": time.time(),
    }
    out = os.path.join(REPO, "SERVE_SMOKE.json")
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2)
    print(f"[serve-smoke] banked {out}")
    print("[serve-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
