#!/usr/bin/env python
"""--live smoke: the live telemetry plane, end to end.

Driven by ``scripts/run-tests.sh --live``.  Five stages, each a hard
assert:

1. two simulated hosts (separate OS processes, ``BIGDL_PROCESS_ID``
   0/1) run a 40-step DistriOptimizer job with live servers on
   **ephemeral** ports (``BIGDL_OBS_PORT=0`` + port files), the input
   pipeline synthetically starved for the first ~24 steps and healthy
   after — so the ``goodput_slo_burn`` alert must fire, then resolve;
2. while both are RUNNING, the driver scrapes each host's ``/metrics``
   (must parse completely, with ``# HELP``/``# TYPE`` on every family)
   and ``/healthz`` (an advancing step stamp), and a peer-mode
   ``FleetAggregator`` snapshot must merge both hosts;
3. after the run, the alert lifecycle is checked: ``alert.firing`` AND
   ``alert.resolved`` trace events for ``goodput_slo_burn``, with
   matching ``bigdl_alerts_total``/``bigdl_alerts_resolved_total``;
4. ``report --watch --once`` renders the alerts section in text and
   carries it (plus the fleet snapshot) in ``--json``;
5. the supervisor hang watchdog: a deliberately stalled child (stamps
   one step, then wedges) is killed and restarted, the restarted
   attempt completes — and a control run with ``BIGDL_OBS_PORT`` unset
   holds no server thread, no socket, and no step stamp (the seed
   off-path; the compiled-signature pin itself lives in
   tests/test_obs_health.py's disabled-signature spec).

Exit 0 only when all five hold.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# importing bigdl_tpu pulls jax, which otherwise probes for a TPU and
# hangs on /tmp/libtpu_lockfile on relay-equipped machines
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_WORKER = """
import os, sys, time, threading
sys.path.insert(0, os.environ["BIGDL_REPO"])
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") \\
    + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import bigdl_tpu.native as native
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import (ClassNLLCriterion, Linear, LogSoftMax, ReLU,
                          Sequential)
from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

# synthetic SLO violation: the first STARVE_BATCHES batches arrive
# late (window goodput ratio collapses -> burn-rate breach), the rest
# arrive promptly (the breach resolves before the run ends)
_P = native.PrefetchIterator
_DELIVERED = [0]

class HalfStarved:
    def __init__(self, iterable, depth=2):
        self._it = iter(_P(iterable, depth))

    def __iter__(self):
        return self

    def __next__(self):
        if _DELIVERED[0] < int(os.environ.get("SMOKE_STARVE_BATCHES",
                                              "24")):
            time.sleep(float(os.environ.get("SMOKE_BATCH_DELAY",
                                            "0.05")))
        _DELIVERED[0] += 1
        return next(self._it)

if os.environ.get("SMOKE_NO_OBS") != "1":
    native.PrefetchIterator = HalfStarved

Engine.init()
rng = np.random.RandomState(0)
w = rng.randn(16, 4)
x = rng.randn(320, 16).astype(np.float32)
y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
model = Sequential().add(Linear(16, 32)).add(ReLU()) \\
    .add(Linear(32, 4)).add(LogSoftMax())
opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=32)
opt.set_optim_method(SGD(learningrate=0.1))
opt.set_end_when(Trigger.max_iteration(40))
opt.optimize()
assert opt.state["neval"] == 41, opt.state["neval"]

from bigdl_tpu.obs import server
if os.environ.get("SMOKE_NO_OBS") == "1":
    # the off-path pin: no server object, no daemon thread, no stamp
    assert opt._obs_server is None, "server built without BIGDL_OBS_PORT"
    assert server.get_server() is None
    assert not [t for t in threading.enumerate()
                if t.name == "bigdl-obs-server"], "stray server thread"
    assert server.last_step() == (None, None), "stamp without a server"
    print("NO_OBS_PIN_OK")
else:
    assert server.get_server() is not None
    assert server.last_step()[0] == 40
"""

_STALLER = """
import os, sys, time
sys.path.insert(0, os.environ["BIGDL_REPO"])
from bigdl_tpu.obs import server
s = server.ensure_server()
assert s is not None, "staller must bind its ephemeral endpoint"
if int(os.environ.get("BIGDL_ELASTIC_ATTEMPT", "0")) >= 1:
    sys.exit(0)                 # the restarted attempt completes
server.note_step(1)
time.sleep(300)                 # wedged: alive, never advances
"""


def _env(**extra):
    e = dict(os.environ)
    e.update({k: str(v) for k, v in extra.items()})
    e["BIGDL_REPO"] = REPO
    e["JAX_PLATFORMS"] = "cpu"
    return e


def _get(url, timeout=3.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


def _wait_port(port_file, deadline):
    while time.time() < deadline:
        try:
            with open(port_file, encoding="utf-8") as fh:
                port = int(fh.read().strip() or 0)
            if port:
                return port
        except (OSError, ValueError):
            pass
        time.sleep(0.1)
    raise AssertionError(f"no port file at {port_file}")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="bigdl_live_smoke_")
    trace_dir = os.path.join(tmp, "trace")
    metrics_dir = os.path.join(tmp, "metrics")

    # -- 1: two live hosts on ephemeral ports -------------------------
    workers, port_files = [], []
    for host in (0, 1):
        pf = os.path.join(tmp, f"port.h{host}")
        port_files.append(pf)
        workers.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER],
            env=_env(BIGDL_PROCESS_ID=host, BIGDL_TRACE_DIR=trace_dir,
                     BIGDL_METRICS_DIR=metrics_dir,
                     BIGDL_GOODPUT_WINDOW=4, BIGDL_OBS_PORT=0,
                     BIGDL_OBS_PORT_FILE=pf),
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    deadline = time.time() + 120
    ports = [_wait_port(pf, deadline) for pf in port_files]
    print(f"[live-smoke] two hosts up on ephemeral ports {ports}")

    # -- 2: live scrapes + fleet merge, mid-run -----------------------
    from bigdl_tpu.obs.aggregate import FleetAggregator
    from bigdl_tpu.obs.metrics import parse_prometheus, sample_value

    for host, port in enumerate(ports):
        # wait until the host resolved its first step (live, not idle)
        while time.time() < deadline:
            h = json.loads(_get(f"http://127.0.0.1:{port}/healthz"))
            if h.get("step"):
                break
            assert workers[host].poll() is None, "worker died early"
            time.sleep(0.2)
        assert h["host"] == host and h["status"] == "ok", h
        assert h["step"] >= 1 and h["step_age_s"] is not None, h
        text = _get(f"http://127.0.0.1:{port}/metrics")
        parsed = parse_prometheus(text)  # loud on any malformed line
        assert "# TYPE bigdl_engine_inits_total counter" in text
        assert "# HELP bigdl_engine_inits_total" in text
        assert sample_value(parsed, "bigdl_engine_inits_total") == 1
        tail = json.loads(_get(f"http://127.0.0.1:{port}/trace?last=16"))
        assert tail, "flight-recorder tail empty with tracing on"
        print(f"[live-smoke] host {host}: live /metrics "
              f"({len(parsed['samples'])} samples, HELP/TYPE ok), "
              f"/healthz step {h['step']}, /trace tail {len(tail)}")

    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    fleet = FleetAggregator(peers=peers).snapshot()
    assert fleet["mode"] == "peers" and not fleet["errors"], fleet
    assert set(fleet["hosts"]) == {"0", "1"}, fleet["hosts"].keys()
    for host, row in fleet["hosts"].items():
        # the autoscaler's queue signal rides every host row (None on
        # a non-streaming run like this one — the key must exist)
        assert "queue_depth" in row and row["queue_depth"] is None, row
    print(f"[live-smoke] fleet snapshot merged hosts "
          f"{sorted(fleet['hosts'])} from {peers}")

    for host, w in enumerate(workers):
        out, err = w.communicate(timeout=300)
        assert w.returncode == 0, \
            f"host {host} worker failed:\n{out}\n{err}"

    # -- 3: alert fired AND resolved, with matching counters ----------
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
         "--metrics-dir", metrics_dir, "--json"],
        env=_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    al = rep["alerts"]
    states = {e["state"] for e in al["events"]
              if e.get("rule") == "goodput_slo_burn"}
    assert states == {"firing", "resolved"}, al["events"]
    fired = al["fired_total"].get("goodput_slo_burn[warning]", 0)
    resolved = al["resolved_total"].get("goodput_slo_burn", 0)
    assert fired >= 1 and fired == resolved, \
        f"fired {fired} != resolved {resolved}"
    assert "goodput_slo_burn" not in al["active"], al["active"]
    print(f"[live-smoke] goodput_slo_burn fired {int(fired)}x and "
          f"resolved {int(resolved)}x (matching counts)")

    # -- 4: report --watch --once renders alerts, text + --json -------
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
         "--metrics-dir", metrics_dir, "--watch", "--once"],
        env=_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    for needle in ("-- live fleet (shards) --", "-- alerts --",
                   "goodput_slo_burn[warning]"):
        assert needle in p.stdout, \
            f"watch frame missing {needle!r}:\n{p.stdout}"
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
         "--metrics-dir", metrics_dir, "--watch", "--once", "--json"],
        env=_env(), cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    wrep = json.loads(p.stdout.strip().splitlines()[-1])
    assert wrep["fleet"]["hosts"], wrep["fleet"]
    assert wrep["alerts"]["fired_total"], wrep["alerts"]
    print("[live-smoke] report --watch --once renders the alerts "
          "section (text + --json, with the fleet header)")

    # -- 5a: supervisor hang watchdog kills + restarts a wedged child -
    staller = os.path.join(tmp, "staller.py")
    with open(staller, "w", encoding="utf-8") as fh:
        fh.write(_STALLER)
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.resilience.supervisor",
         "--max-retries", "2", "--hang-timeout", "2", "--",
         sys.executable, staller],
        env=_env(BIGDL_OBS_PORT=0, BIGDL_RETRY_BACKOFF_BASE=0),
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "killing the hung child" in p.stderr, p.stderr
    assert "(hang)" in p.stderr, p.stderr
    print("[live-smoke] hang watchdog killed the wedged child; the "
          "restarted attempt completed (rc 0)")

    # -- 5b: BIGDL_OBS_PORT unset binds nothing -----------------------
    env_off = _env(BIGDL_PROCESS_ID=0, SMOKE_NO_OBS=1)
    for var in ("BIGDL_OBS_PORT", "BIGDL_OBS_PORT_FILE", "BIGDL_OBS",
                "BIGDL_TRACE_DIR", "BIGDL_METRICS_DIR"):
        env_off.pop(var, None)
    p = subprocess.run(
        [sys.executable, "-c", _WORKER],
        env=env_off, cwd=REPO,
        capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "NO_OBS_PIN_OK" in p.stdout, p.stdout
    print("[live-smoke] control run without BIGDL_OBS_PORT: no thread, "
          "no socket, no step stamp")
    print("[live-smoke] PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
