#!/usr/bin/env python
"""Autoscaling + exactly-once streaming smoke — the whole loop, for
real.

Driven by ``scripts/run-tests.sh --autoscale``.  The parent runs the
REAL restart supervisor with the REAL autoscaling policy loop
(``resilience/autoscale.py``) over real training children, and nothing
ever restarts a child manually:

1. launch 0: a 1-"host" DistriOptimizer trains from an unbounded-style
   :class:`SyntheticStream` with an **infinite backlog** (rate=None) —
   the stream buffer pins at capacity, the controller scrapes the
   child's live ``/metrics`` (``bigdl_stream_buffer_depth``) through
   the port file the supervisor injects, the ``queue_high`` rule
   breaches twice, and the supervisor executes **scale-up 1→2** by
   graceful stop (SIGTERM → in-flight step finishes → emergency
   checkpoint carrying the trained stream offset → exit 170);
2. launch 1: the child re-forms at world 2, ``elastic.restore_latest``
   re-partitions the ZeRO state AND seeks the stream to the trained
   offset (``bigdl_resumes_total{resize="1to2"}``).  The synthetic
   ingest rate is now **below** training throughput — the buffer
   drains, ``queue_low`` breaches past the cooldown, and the
   supervisor executes **scale-down 2→1**;
3. launch 2: world 1 again (``resize="2to1"``); ``queue_low`` keeps
   breaching but the world is at ``min_world`` — the decision is
   suppressed (``at_bound``) and the child trains to completion.

The parent then asserts:

* resumed-vs-uninterrupted **trajectory equivalence**: the union of
  the three attempts' per-step losses covers steps 1..N exactly once
  and matches an uninterrupted 1-host baseline step-for-step;
* the **exactly-once stream audit**: the attempts' trained-range logs
  concatenate to every record id 0..TOTAL exactly once — none dropped,
  none trained twice, across BOTH resizes;
* ``bigdl_resumes_total{resize="1to2"} 1`` and ``{resize="2to1"} 1``
  in the children's metrics shards, and both policy decisions in the
  parent's ``bigdl_autoscale_decisions_total``.

Results are banked as ``AUTOSCALE_SMOKE.json`` (bench.py folds them
into BENCH ``extras.autoscale``).
"""

import dataclasses
import glob
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TOTAL_STEPS = 300
BATCH = 32
TOTAL_RECORDS = TOTAL_STEPS * BATCH
THROTTLE_S = 0.04      # per-step sleep so launches outlive the warmup
DRAIN_RATE = 600.0     # records/s on resumed launches (< consumption)


def child():
    baseline = os.environ.get("BIGDL_SMOKE_BASELINE") == "1"
    attempt = int(os.environ.get("BIGDL_ELASTIC_ATTEMPT", "0"))
    world = 1 if baseline else int(
        os.environ.get("BIGDL_AUTOSCALE_WORLD", "1"))
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count"
                                 f"={world}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F401 — keeps the import graph warm

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.dataset.stream import StreamDataSet, SyntheticStream
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.nn import (
        ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
    )
    from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger
    from bigdl_tpu.resilience import elastic

    smoke_dir = os.environ["BIGDL_SMOKE_DIR"]
    Engine.init()
    assert len(jax.devices()) == world, jax.devices()
    RandomGenerator.RNG.set_seed(7)
    model = Sequential().add(Linear(16, 32)).add(ReLU()) \
        .add(Linear(32, 4)).add(LogSoftMax())
    # launch 0 sees an infinite backlog (the buffer pins at capacity —
    # the scale-UP signal); resumed launches follow a live edge slower
    # than training drains it (depth ~0 — the scale-DOWN signal)
    rate = None if (baseline or attempt == 0) else DRAIN_RATE
    stream = SyntheticStream(feature_dim=16, n_classes=4, seed=3,
                             limit=TOTAL_RECORDS, rate=rate)
    ds = StreamDataSet(stream, batch_size=BATCH, buffer_records=128,
                       audit_log=True)
    opt = DistriOptimizer(model, ds, ClassNLLCriterion(),
                          batch_size=BATCH, wire_dtype="none")
    opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
    opt.set_end_when(Trigger.max_iteration(TOTAL_STEPS))
    opt.set_checkpoint(os.path.join(smoke_dir, "ckpt"),
                       Trigger.several_iteration(50))
    opt.max_retry = 0

    losses = {}
    throttle = 0.0 if baseline else THROTTLE_S

    class Tape:
        def add_scalar(self, tag, value, step):
            if tag == "Loss":
                losses[step] = float(value)
                if throttle:
                    time.sleep(throttle)

        def add_histogram(self, *a, **k):
            pass

        def get_summary_trigger(self, name):
            return None

        def add_resilience(self, *a, **k):
            pass

    opt.set_train_summary(Tape())
    extra = None if baseline else elastic.restore_latest(opt)
    print(f"SMOKE_CHILD attempt={attempt} world={world} "
          f"resumed={extra is not None} "
          f"offset={(extra or {}).get('stream', {}).get('offset')}",
          flush=True)

    def train():
        try:
            opt.optimize()
        finally:
            tag = "baseline" if baseline else f"attempt{attempt}"
            with open(os.path.join(smoke_dir, f"losses.{tag}.json"),
                      "w", encoding="utf-8") as fh:
                json.dump(losses, fh)
            with open(os.path.join(smoke_dir, f"audit.{tag}.json"),
                      "w", encoding="utf-8") as fh:
                json.dump(ds.audit_log, fh)

    sys.exit(elastic.run_main(train))


def run_baseline(smoke_dir, env):
    bdir = os.path.join(smoke_dir, "baseline")
    os.makedirs(bdir, exist_ok=True)
    benv = dict(env)
    benv.update(BIGDL_SMOKE_DIR=bdir, BIGDL_SMOKE_BASELINE="1",
                BIGDL_METRICS_DIR=bdir, BIGDL_TRACE_DIR=bdir)
    benv.pop("BIGDL_OBS_PORT", None)
    subprocess.run([sys.executable, os.path.abspath(__file__),
                    "--child"], env=benv, check=True)
    with open(os.path.join(bdir, "losses.baseline.json"),
              encoding="utf-8") as fh:
        return {int(k): v for k, v in json.load(fh).items()}


def main():
    import tempfile

    from bigdl_tpu.config import AutoscaleConfig
    from bigdl_tpu.resilience.autoscale import AutoscaleController
    from bigdl_tpu.resilience.elastic import EXIT_PREEMPTED
    from bigdl_tpu.resilience.supervisor import Supervisor

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_autoscale_smoke_")
    obs_dir = os.path.join(smoke_dir, "obs")
    os.environ["BIGDL_RETRY_BACKOFF_BASE"] = "0"
    os.environ.update(
        BIGDL_SMOKE_DIR=smoke_dir, BIGDL_METRICS_DIR=obs_dir,
        BIGDL_TRACE_DIR=obs_dir, BIGDL_OBS_PORT="0", PYTHONPATH=REPO,
        # the parent's own atexit obs flush imports jax (device memory
        # stats) — pin CPU or this container's TPU plugin probes the
        # GCP metadata service forever; children pin it themselves too
        JAX_PLATFORMS="cpu")
    # children own their XLA_FLAGS (world-sized device count)
    os.environ.pop("XLA_FLAGS", None)

    cfg = AutoscaleConfig(
        enabled=True, min_world=1, max_world=2, factor=2,
        interval_s=0.4, warmup_s=6.0, cooldown_s=4.0, hysteresis=2,
        queue_high=64.0, queue_low=4.0)
    controller = AutoscaleController(cfg=cfg, world=1)
    rcs = []

    class TapeSupervisor(Supervisor):
        def _spawn(self, cmd, env):
            rc = super()._spawn(cmd, env)
            rcs.append(rc)
            return rc

    sup = TapeSupervisor(
        [sys.executable, os.path.abspath(__file__), "--child"],
        max_retries=2, autoscaler=controller, stop_grace_s=60.0)
    t0 = time.monotonic()
    rc = sup.run()
    wall = time.monotonic() - t0
    assert rc == 0, f"supervisor gave up with rc {rc} (children: {rcs})"
    assert sup.resizes == 2, \
        f"expected 2 resizes (1to2, 2to1), got {sup.resizes}: {rcs}"
    resizes = [d.resize for d in controller.decisions]
    assert resizes == ["1to2", "2to1"], resizes
    reasons = [d.reason for d in controller.decisions]
    assert reasons == ["queue_high", "queue_low"], reasons
    assert rcs[:2] == [EXIT_PREEMPTED, EXIT_PREEMPTED] and rcs[-1] == 0, \
        f"expected graceful resize stops then success, got {rcs}"
    print(f"SMOKE supervisor: launches={sup.attempt} rcs={rcs} "
          f"resizes={resizes} ({wall:.1f}s)")

    # --- exactly-once audit: every record id trained exactly once ----
    ranges = []
    for a in range(sup.attempt):
        with open(os.path.join(smoke_dir, f"audit.attempt{a}.json"),
                  encoding="utf-8") as fh:
            ranges.extend(tuple(r) for r in json.load(fh))
    trained = [o for s, e in ranges for o in range(s, e)]
    dup = len(trained) - len(set(trained))
    missing = TOTAL_RECORDS - len(set(trained))
    assert dup == 0, f"{dup} records trained twice across resizes"
    assert missing == 0 and sorted(trained) == list(
        range(TOTAL_RECORDS)), f"{missing} records dropped"
    print(f"SMOKE exactly-once: {TOTAL_RECORDS} record ids trained "
          f"exactly once across {sup.attempt} launches (0 dup, 0 drop)")

    # --- trajectory equivalence vs an uninterrupted 1-host run -------
    resumed = {}
    for a in range(sup.attempt):
        with open(os.path.join(smoke_dir, f"losses.attempt{a}.json"),
                  encoding="utf-8") as fh:
            for k, v in json.load(fh).items():
                step = int(k)
                assert step not in resumed, f"step {step} trained twice"
                resumed[step] = v
    assert sorted(resumed) == list(range(1, TOTAL_STEPS + 1)), \
        f"step gaps: have {len(resumed)} of {TOTAL_STEPS}"
    base = run_baseline(smoke_dir, dict(os.environ))
    worst = 0.0
    for step, val in sorted(resumed.items()):
        rel = abs(val - base[step]) / max(1.0, abs(base[step]))
        worst = max(worst, rel)
        assert rel < 1e-3, \
            f"loss diverged at step {step}: {val} vs {base[step]}"
    print(f"SMOKE trajectory: {len(resumed)} steps across 3 launches "
          f"match the uninterrupted baseline (worst rel {worst:.2e})")

    # --- resize resumes counted in the children's metrics shards -----
    proms = glob.glob(os.path.join(obs_dir, "metrics.*.prom"))
    blob = "".join(open(p, encoding="utf-8").read() for p in proms)
    for needle in ('bigdl_resumes_total{resize="1to2"} 1',
                   'bigdl_resumes_total{resize="2to1"} 1'):
        assert needle in blob, \
            f"{needle!r} not in metrics shards:\n{blob[-2000:]}"
    print("SMOKE metrics: both resize resumes counted")

    # --- policy decisions counted in the parent's registry -----------
    from bigdl_tpu import obs

    counts = {}
    for fam in obs.get_registry().families():
        if fam.name == "bigdl_autoscale_decisions_total":
            for key, c in fam.child_items():
                counts[dict(zip(fam.labelnames, key))["reason"]] = c.value
    assert counts == {"queue_high": 1.0, "queue_low": 1.0}, counts
    print(f"SMOKE decisions: {counts}")

    bank = {
        "resizes": resizes,
        "decisions": [dataclasses.asdict(d) for d in controller.decisions],
        "child_rcs": rcs,
        "launches": sup.attempt,
        "steps": TOTAL_STEPS,
        "records": TOTAL_RECORDS,
        "duplicate_records": dup,
        "dropped_records": missing,
        "worst_rel_err": worst,
        "wall_s": round(wall, 2),
    }
    with open(os.path.join(REPO, "AUTOSCALE_SMOKE.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True)
    print("AUTOSCALE SMOKE PASS (banked AUTOSCALE_SMOKE.json)")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child()
    else:
        main()
