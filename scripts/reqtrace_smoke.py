#!/usr/bin/env python
"""Request-tracing smoke — p99 attribution on a rigged topology.

Driven by ``scripts/run-tests.sh --reqtrace``.  The scenario: a
:class:`Router` over two live :class:`LMEngine` replicas, one of them
deliberately slow (its single decode slot preloaded with long direct
submissions), with ``BIGDL_REQTRACE_SAMPLE=1.0`` so every request
trace is kept.  Session-affine requests pinned to the slow replica
queue behind the preload; free requests place onto the fast replica.

The assertions are the tentpole's acceptance criteria:

* every routed response is token-identical to the direct
  ``generate()`` reference — tracing moved nothing;
* the report's "request traces" section attributes the slowest decile
  to the *queue* hop (that is where the time actually went), and the
  per-hop attribution sums to within 10% of the measured e2e
  (coverage >= 0.9).

Banks ``REQTRACE_SMOKE.json`` at the repo root; bench.py folds it
into BENCH ``extras.reqtrace``.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/reqtrace_smoke.py",
        description="End-to-end request tracing smoke: rigged "
                    "slow-replica topology, every trace kept, report "
                    "must attribute the slow decile to the queue hop.")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--slow-requests", type=int, default=3,
                    help="session-affine requests pinned behind the "
                         "slow replica's preload (default 3)")
    ap.add_argument("--fast-requests", type=int, default=8,
                    help="unpinned requests for the fast replica "
                         "(default 8)")
    args = ap.parse_args()

    import tempfile

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_reqtrace_smoke_")
    obs_dir = os.path.join(smoke_dir, "obs")
    os.environ["BIGDL_TRACE_DIR"] = obs_dir
    os.environ["BIGDL_METRICS_DIR"] = obs_dir

    import numpy as np

    from bigdl_tpu import obs
    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm
    from bigdl_tpu.obs import reqtrace
    from bigdl_tpu.obs.report import build_report, render_text
    from bigdl_tpu.serving import LMEngine
    from bigdl_tpu.serving.router import EngineReplica, Router

    t0 = time.monotonic()
    RandomGenerator.RNG.set_seed(13)
    model = build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                 max_len=64, attn_impl="xla")
    params = model.params()

    def ref(prompt, n):
        return list(np.asarray(model.generate(
            params, np.asarray(prompt)[None, :], n))[0])

    # single decode slot each: a preloaded slow replica really queues
    e1 = LMEngine(model, max_batch=1, page_size=8).start()
    e2 = LMEngine(model, max_batch=1, page_size=8).start()
    engines = {"r1": e1, "r2": e2}
    router = Router([EngineReplica(n, e) for n, e in engines.items()],
                    request_timeout_s=120.0)
    rs = np.random.RandomState(args.seed)

    def route_checked(n_prompt, n_new, session=None):
        p = rs.randint(0, 48, (n_prompt,)).tolist()
        out = router.route(p, n_new, session=session)
        assert [int(t) for t in list(p) + out["tokens"]] \
            == ref(p, n_new), \
            f"traced routed output diverged from generate() for {p}"
        return out

    # warm both replicas UNTRACED (prefill/decode compile must not
    # pollute the measured traces) and bind the session whose replica
    # we will rig slow
    route_checked(5, 8)
    bound = route_checked(5, 8, session="pinned")["replica"]
    slow_eng = engines[bound]
    print(f"SMOKE reqtrace: session pinned to {bound}; rigging it slow")

    # tracing ON for the measured window (read-at-call-time contract:
    # the collector rebuilds from live config on the next route)
    os.environ["BIGDL_REQTRACE_SAMPLE"] = "1.0"

    # rig: a long direct submission occupies the bound replica's only
    # slot, so every pinned request's time goes to the QUEUE hop
    preload = slow_eng.submit(rs.randint(0, 48, (5,)).tolist(), 24)
    parity = 0
    for _ in range(args.slow_requests):
        route_checked(5, 8, session="pinned")
        parity += 1
    for _ in range(args.fast_requests):
        route_checked(5, 8)
        parity += 1
    preload.wait(120)
    col = reqtrace.get_collector()
    sampler = col.stats()
    assert sampler["kept"] >= parity, sampler

    e1.close()
    e2.close()
    obs.flush()

    rep = build_report(obs_dir)
    rt = rep.get("reqtrace")
    assert rt, "report has no request-traces section"
    assert rt["traces"] >= parity, rt
    sd = rt["slow_decile"]
    hop_means = sd["hop_mean_s"]
    worst_hop = max(hop_means, key=hop_means.get)
    assert worst_hop == "queue", \
        (f"slow decile attributed to {worst_hop!r}, expected 'queue' "
         f"(the rigged replica's preloaded slot): {hop_means}")
    coverage = sd["coverage"]
    assert coverage is not None and coverage >= 0.9, \
        f"hop attribution covers {coverage!r} of e2e, want >= 0.9"
    attributed = sum(hop_means.values())
    assert abs(attributed - sd["e2e_mean_s"]) <= 0.1 * sd["e2e_mean_s"], \
        (f"per-hop attribution {attributed:.4f}s deviates more than "
         f"10% from measured e2e {sd['e2e_mean_s']:.4f}s")
    print(f"SMOKE reqtrace: {rt['traces']} kept traces, slow decile "
          f"e2e {sd['e2e_mean_s'] * 1000:.1f}ms -> worst hop "
          f"'{worst_hop}' ({hop_means[worst_hop] * 1000:.1f}ms), "
          f"coverage {coverage * 100:.1f}%")
    print(f"SMOKE reqtrace: {parity} routed requests token-identical "
          f"to direct generate() with tracing on")
    section = [ln for ln in render_text(rep).splitlines()
               if "request traces" in ln]
    assert section, "render_text lost the request-traces section"

    total_wall = time.monotonic() - t0
    bank = {
        "seed": args.seed,
        "total_wall_s": round(total_wall, 2),
        "requests": parity,
        "slow_replica": bound,
        "parity_ok": True,
        "sampler": sampler,
        "report": rt,
    }
    with open(os.path.join(REPO, "REQTRACE_SMOKE.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True, default=str)
    print(f"REQTRACE SMOKE PASS in {total_wall:.1f}s "
          "(banked REQTRACE_SMOKE.json)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
