"""Perf probe — ResNet-50 train-step variants on the real chip.

Explores the two bottlenecks BASELINE.md's analysis identified
(1x1-conv MXU mapping, BatchNorm bandwidth tax) plus data layout:

  layout    : NCHW (BigDL convention) vs NHWC (channels-minor = TPU lanes)
  bn        : f32 elementwise normalize (current) vs bf16 normalize with
              f32-accumulated statistics
  dot11     : lower 1x1 convs to reshape+dot_general instead of
              lax.conv_general_dilated

Usage:  python scripts/perf_probe.py [batch] [iters]
Prints one JSON line per variant: {"variant": ..., "step_ms": ..., "mfu": ...}
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from bench import (  # noqa: E402
    _resnet50_cfg,
    train_step_flops_per_image,
    _peak_flops,
)

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 128
ITERS = int(sys.argv[2]) if len(sys.argv) > 2 else 10
IMG = 224
N_CLASSES = 1000


def init_params(rng, layout):
    import jax
    import jax.numpy as jnp

    params = {}

    def conv_p(key, cin, cout, k):
        fan = cin * k * k
        shape = (cout, cin, k, k) if layout == "NCHW" else (k, k, cin, cout)
        params[key] = {
            "w": jax.random.normal(
                jax.random.fold_in(rng, hash(key) % (2**31)), shape,
                dtype=np.float32) * np.sqrt(2.0 / fan)
        }

    def bn_p(key, c):
        params[key] = {
            "scale": jnp.ones(c), "bias": jnp.zeros(c),
        }

    conv_p("stem", 3, 64, 7)
    bn_p("stem_bn", 64)
    cin = 64
    for s, (w, n, stride) in enumerate(_resnet50_cfg()):
        for i in range(n):
            pfx = f"s{s}b{i}"
            conv_p(pfx + "c1", cin, w, 1)
            bn_p(pfx + "bn1", w)
            conv_p(pfx + "c2", w, w, 3)
            bn_p(pfx + "bn2", w)
            conv_p(pfx + "c3", w, w * 4, 1)
            bn_p(pfx + "bn3", w * 4)
            if i == 0:
                conv_p(pfx + "sc", cin, w * 4, 1)
                bn_p(pfx + "scbn", w * 4)
            cin = w * 4
    params["fc"] = {
        "w": jax.random.normal(jax.random.fold_in(rng, 77), (cin, N_CLASSES))
        * 0.01,
        "b": jnp.zeros(N_CLASSES),
    }
    return params


def make_forward(layout, bn_mode, dot11):
    import jax
    import jax.numpy as jnp
    from jax import lax

    dn = (layout, "OIHW" if layout == "NCHW" else "HWIO", layout)
    caxis = 1 if layout == "NCHW" else 3

    def conv(p, x, stride=1):
        w = p["w"]
        k = w.shape[2] if layout == "NCHW" else w.shape[0]
        if dot11 and k == 1:
            if stride != 1:
                if layout == "NCHW":
                    x = x[:, :, ::stride, ::stride]
                else:
                    x = x[:, ::stride, ::stride, :]
            if layout == "NCHW":
                n, c, h, wd = x.shape
                cout = w.shape[0]
                y = jnp.einsum("nchw,oc->nohw", x, w[:, :, 0, 0])
                return y
            else:
                n, h, wd, c = x.shape
                cout = w.shape[3]
                y = x.reshape(n * h * wd, c) @ w[0, 0]
                return y.reshape(n, h, wd, cout)
        return lax.conv_general_dilated(
            x, w, (stride, stride), "SAME", dimension_numbers=dn)

    def bn(p, x):
        axes = (0, 2, 3) if layout == "NCHW" else (0, 1, 2)
        bshape = (1, -1, 1, 1) if layout == "NCHW" else (1, 1, 1, -1)
        if bn_mode == "f32":
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            inv = lax.rsqrt(var + 1e-5) * p["scale"]
            y = xf * inv.reshape(bshape) + (
                p["bias"] - mean * inv).reshape(bshape)
            return y.astype(x.dtype)
        elif bn_mode == "bf16_2pass":
            # two-pass f32 stats (mean then E[(x-mean)^2]) like the
            # framework today, but normalize in the compute dtype
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            inv = lax.rsqrt(var + 1e-5) * p["scale"]
            shift = p["bias"] - mean * inv
            return x * inv.astype(x.dtype).reshape(bshape) + \
                shift.astype(x.dtype).reshape(bshape)
        else:  # bf16 normalize, f32-accumulated single-pass stats
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            mean2 = jnp.mean(
                lax.square(x.astype(jnp.float32)), axis=axes)
            var = jnp.maximum(mean2 - mean * mean, 0.0)
            inv = lax.rsqrt(var + 1e-5) * p["scale"]
            shift = p["bias"] - mean * inv
            return x * inv.astype(x.dtype).reshape(bshape) + \
                shift.astype(x.dtype).reshape(bshape)

    def forward(params, x):
        x = conv(params["stem"], x, 2)
        x = jax.nn.relu(bn(params["stem_bn"], x))
        window = (1, 1, 3, 3) if layout == "NCHW" else (1, 3, 3, 1)
        strides = (1, 1, 2, 2) if layout == "NCHW" else (1, 2, 2, 1)
        pads = [(0, 0), (0, 0), (1, 1), (1, 1)] if layout == "NCHW" else \
            [(0, 0), (1, 1), (1, 1), (0, 0)]
        x = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        for s, (w, n, stride) in enumerate(_resnet50_cfg()):
            for i in range(n):
                pfx = f"s{s}b{i}"
                st = stride if i == 0 else 1
                y = jax.nn.relu(
                    bn(params[pfx + "bn1"], conv(params[pfx + "c1"], x)))
                y = jax.nn.relu(
                    bn(params[pfx + "bn2"], conv(params[pfx + "c2"], y, st)))
                y = bn(params[pfx + "bn3"], conv(params[pfx + "c3"], y))
                if i == 0:
                    sc = bn(params[pfx + "scbn"],
                            conv(params[pfx + "sc"], x, st))
                else:
                    sc = x
                x = jax.nn.relu(y + sc)
        x = jnp.mean(x, axis=(2, 3) if layout == "NCHW" else (1, 2))
        return x @ params["fc"]["w"] + params["fc"]["b"]

    return forward


def bench_variant(layout, bn_mode, dot11, x, y):
    import jax
    import jax.numpy as jnp
    from jax import lax

    fwd = make_forward(layout, bn_mode, dot11)
    params = init_params(jax.random.key(0), layout)

    def loss_fn(p, x, y):
        ct = jnp.bfloat16
        p = jax.tree.map(
            lambda a: a.astype(ct)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
        logits = fwd(p, x.astype(ct)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        idx = y.astype(jnp.int32) - 1
        return -jnp.mean(jnp.take_along_axis(logp, idx[:, None], 1))

    def step(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        p = jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g)
        return p, loss

    @jax.jit
    def run(carry, x, y):
        def body(c, _):
            c, loss = step(c, x, y)
            return c, loss
        _, losses = lax.scan(body, carry, None, length=ITERS)
        return losses[-1]

    xd = jnp.asarray(x if layout == "NCHW" else x.transpose(0, 2, 3, 1))
    yd = jnp.asarray(y)
    float(run(params, xd, yd))
    t0 = time.perf_counter()
    float(run(params, xd, yd))
    dt = time.perf_counter() - t0
    return dt / ITERS


def main():
    import jax

    dev = jax.devices()[0]
    peak = _peak_flops(dev.device_kind)
    print(json.dumps({"device": dev.device_kind, "batch": BATCH}), flush=True)
    x = np.random.RandomState(0).randn(BATCH, 3, IMG, IMG).astype(np.float32)
    y = (np.random.RandomState(1).randint(0, N_CLASSES, BATCH) + 1).astype(
        np.float32)
    flops = train_step_flops_per_image(IMG) * BATCH
    variants = itertools.product(
        ("NCHW", "NHWC"), ("f32", "bf16"), (False, True))
    if len(sys.argv) > 3:  # explicit variant list: LAYOUT/bn/dot11 triples
        variants = [tuple(v.split("/")) for v in sys.argv[3].split(",")]
        variants = [(l, b, d == "1") for l, b, d in variants]
    for layout, bn_mode, dot11 in variants:
        try:
            s = bench_variant(layout, bn_mode, dot11, x, y)
            mfu = flops / s / peak if peak else None
            print(json.dumps({
                "variant": f"{layout}/bn-{bn_mode}/dot11-{int(dot11)}",
                "step_ms": round(s * 1e3, 2),
                "images_per_sec": round(BATCH / s, 1),
                "mfu": round(mfu, 4) if mfu else None,
            }), flush=True)
        except Exception as e:
            print(json.dumps({
                "variant": f"{layout}/bn-{bn_mode}/dot11-{int(dot11)}",
                "error": f"{type(e).__name__}: {str(e)[:200]}",
            }), flush=True)


if __name__ == "__main__":
    main()
