"""Per-lever microbench for the conv+BN epilogue-stats kernels.

Times, for every distinct ResNet-50 (batch 128) conv+BN shape, the
Pallas `conv_bn_stats` path against the unfused XLA pair (conv, then a
separate stats reduction) — the per-lever evidence BASELINE.md's r04
table predicts.  One JSON line per shape.

    python scripts/fused_probe.py [batch]

Runs on whatever the default backend is; on CPU the kernel drops to
interpret mode, so real numbers need the chip.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def resnet50_conv_bn_shapes(img=224):
    """(cin, cout, k, stride, h_in) for every conv feeding a BN."""
    shapes = []
    h = img // 4  # post stem+pool: 56
    cin = 64
    for w, n, stride in [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]:
        for i in range(n):
            st = stride if i == 0 else 1
            shapes.append((cin, w, 1, 1, h))
            h2 = (h + 2 * 0 - 1) // st + 1 if st > 1 else h
            shapes.append((w, w, 3, st, h))
            shapes.append((w, w * 4, 1, 1, h2))
            if i == 0:
                shapes.append((cin, w * 4, 1, st, h))
            h = h2
            cin = w * 4
    # dedupe preserving order
    seen, out = set(), []
    for s in shapes:
        if s not in seen:
            seen.add(s)
            out.append(s)
    return out


def main():
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.conv_bn import _reference, conv_bn_stats

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    dev = jax.devices()[0]
    print(json.dumps({"device": dev.device_kind, "batch": batch}),
          flush=True)

    def timeit(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(5):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 5

    for cin, cout, k, stride, h in resnet50_conv_bn_shapes():
        pad = (k - 1) // 2
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(batch, cin, h, h).astype(np.float32)
                        ).astype(jnp.bfloat16)
        w = jnp.asarray((rs.randn(cout, cin, k, k) * 0.1).astype(np.float32)
                        ).astype(jnp.bfloat16)
        shift = jnp.asarray(rs.randn(cout).astype(np.float32) * 0.01)
        try:
            # per-shape fresh jit is the probe protocol (each shape is
            # measured with its own compile)
            fused = jax.jit(lambda a, b, s: conv_bn_stats(  # graftlint: disable=JX003
                a, b, s, stride=stride, pad=pad))
            unfused = jax.jit(lambda a, b, s: _reference(  # graftlint: disable=JX003
                a, b, s, stride, pad))
            tf_ = timeit(fused, x, w, shift)
            tu = timeit(unfused, x, w, shift)
            print(json.dumps({
                "shape": f"{cin}->{cout} k{k}/s{stride} @{h}",
                "fused_ms": round(tf_ * 1e3, 3),
                "unfused_ms": round(tu * 1e3, 3),
                "speedup": round(tu / tf_, 3),
            }), flush=True)
        except Exception as e:
            print(json.dumps({
                "shape": f"{cin}->{cout} k{k}/s{stride} @{h}",
                "error": f"{type(e).__name__}: {str(e)[:200]}",
            }), flush=True)


if __name__ == "__main__":
    main()
