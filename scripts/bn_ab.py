"""A/B the framework ResNet-50 train step's BatchNormalization on chip.

Round-5 regression hunt: the unchanged-since-r03b framework step dropped
from 1867 img/s (b32) to 355 under the relay's new AOT compile path,
while bench.py's raw-JAX baseline (naive two-pass BN) kept its speed.
This script measures the framework step with the BN training-mode
formulation swapped, one subprocess per variant so a hung remote compile
costs only that variant:

  cur     — shipping code (whatever layers.py currently does)
  nocond  — rm-shifted single-pass stats, straight-line (the winner;
            what shipping code adopted after this hunt)
  cond    — rm-shifted single-pass + the r03b/r04 lax.cond stale-shift
            rescue (the pre-hunt shipping formulation)
  where   — rm-shifted single-pass + branch-free jnp.where rescue onto
            an exact-centered 1/16-subsample variance
  s0      — single-pass shifted by sample 0's per-channel mean
            (data-derived shift, stop_gradient)
  pix     — single-pass shifted by one pixel per channel (x[0,:,0,0])
  twopass — naive two-pass f32 stats (the baseline's formulation)
  fused   — fused conv+BN Pallas kernels (nn/fused.py), static dispatch
  tuned   — fused conv+BN with the kernel auto-tuner on
            (ops/autotune.py, BIGDL_TUNER=1): per-site impl/block-o
            from the cached cost-model search; the fused-vs-tuned pair
            is the tuner's A/B, and the never-lose gate means tuned
            can only match or beat fused per shape

Measured 2026-07-31 on the relay's TPU v5 lite, b128 ms/step: nocond
50.1-53.5, pix 53.4, twopass 57.8, s0 64.2-64.5, where 85.5, cond OOM
at b64+ and 89.8 ms at b32 (vs 18.1 nocond) — hot-path control flow and
stats-shift data dependencies both defeat the 2026-07 XLA's fusion.

Usage: python scripts/bn_ab.py [batch] [iters] [variant...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 32
ITERS = int(sys.argv[2]) if len(sys.argv) > 2 else 10
VARIANTS = sys.argv[3:] or ["cur", "nocond", "twopass"]


def _patch_bn(variant: str):
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.nn.layers import BatchNormalization

    if variant == "cur":
        return

    def apply(self, params, state, input, *, training=False, rng=None):
        axes, bshape = self._axes_and_shape(input)
        if not training:
            rm = state["running_mean"]
            scale, offset = self._fold(params, rm, state["running_var"], rm)
            dt = input.dtype
            y = (input - rm.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
            return y, state

        xf = input.astype(jnp.float32)
        if variant in ("cond", "where"):
            # rm-shifted single-pass with the two historical rescue
            # styles for the stale-shift cancellation
            rm = state["running_mean"]
            xc = xf - rm.reshape(bshape)
            d = jnp.mean(xc, axis=axes)
            m2 = jnp.mean(lax.square(xc), axis=axes)
            mean = rm + d
            var_sp = jnp.maximum(m2 - lax.square(d), 0.0)
            dt = input.dtype
            if variant == "cond":
                # r03b/r04 shipping formulation: lax.cond recomputes
                # two-pass and renormalizes when the shift went stale
                def _pathological():
                    var = jnp.maximum(
                        jnp.mean(lax.square(xf - mean.reshape(bshape)),
                                 axis=axes), 0.0)
                    sc, of = self._fold(params, mean, var, mean)
                    out = (xf - mean.reshape(bshape)) \
                        * sc.reshape(bshape) + of.reshape(bshape)
                    return out.astype(dt), var

                def _fast():
                    sc, of = self._fold(params, mean, var_sp, rm)
                    out = (input - rm.astype(dt).reshape(bshape)) \
                        * sc.astype(dt).reshape(bshape) \
                        + of.astype(dt).reshape(bshape)
                    return out, var_sp

                y, var = lax.cond(
                    jnp.any(lax.square(d) > 4096.0 * var_sp),
                    _pathological, _fast)
            else:
                # branch-free: always compute an exact-centered
                # subsample variance, per-channel select
                sub = xf if input.ndim == 2 else xf[:, :, ::4, ::4]
                var_sub = jnp.mean(
                    lax.square(sub - mean.reshape(bshape)), axis=axes)
                badc = lax.square(d) > 4096.0 * var_sp
                var = jnp.where(badc, var_sub, var_sp)
                center = jnp.where(badc, mean, rm)
                sc, of = self._fold(params, mean, var, center)
                y = (input - center.astype(dt).reshape(bshape)) \
                    * sc.astype(dt).reshape(bshape) \
                    + of.astype(dt).reshape(bshape)
        elif variant == "s0":
            # data-derived shift: sample 0's per-channel mean
            s = lax.stop_gradient(jnp.mean(xf[:1], axis=axes))
            xc = xf - s.reshape(bshape)
            d = jnp.mean(xc, axis=axes)
            m2 = jnp.mean(lax.square(xc), axis=axes)
            mean = s + d
            var = jnp.maximum(m2 - lax.square(d), 0.0)
            scale, offset = self._fold(params, mean, var, s)
            dt = input.dtype
            y = (input - s.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
        elif variant == "pix":
            # single-element-per-channel data-derived shift: one gather,
            # no reduction dependency before the fused stats pass
            s = lax.stop_gradient(
                xf[0, :, 0, 0] if input.ndim == 4 else xf[0])
            xc = xf - s.reshape(bshape)
            d = jnp.mean(xc, axis=axes)
            m2 = jnp.mean(lax.square(xc), axis=axes)
            mean = s + d
            var = jnp.maximum(m2 - lax.square(d), 0.0)
            scale, offset = self._fold(params, mean, var, s)
            dt = input.dtype
            y = (input - s.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
        elif variant == "nocond":
            shift = state["running_mean"].reshape(bshape)
            xc = xf - shift
            d = jnp.mean(xc, axis=axes)
            m2 = jnp.mean(lax.square(xc), axis=axes)
            mean = state["running_mean"] + d
            var = jnp.maximum(m2 - lax.square(d), 0.0)
            scale, offset = self._fold(params, mean, var,
                                       state["running_mean"])
            dt = input.dtype
            y = (input - state["running_mean"].astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
        elif variant == "twopass":
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(
                lax.square(xf - mean.reshape(bshape)), axis=axes)
            scale, offset = self._fold(params, mean, var, mean)
            dt = input.dtype
            y = (input - mean.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
        else:
            raise SystemExit(f"unknown variant {variant}")
        n = 1
        for a in axes:
            n *= input.shape[a]
        unbiased = var * (n / max(1, n - 1))
        new_state = {
            "running_mean": (1 - self.momentum) * state["running_mean"]
            + self.momentum * mean,
            "running_var": (1 - self.momentum) * state["running_var"]
            + self.momentum * unbiased,
        }
        return y, new_state

    BatchNormalization.apply = apply


def _run_one(variant: str):
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "axon")
    fuse = variant in ("fused", "tuned")
    tuner_info = None
    if variant == "tuned":
        os.environ.setdefault("BIGDL_TUNER", "1")
        os.environ.setdefault(
            "BIGDL_TUNER_CACHE",
            os.environ.get("BN_AB_TUNER_CACHE",
                           "/tmp/bigdl_bn_ab_tuner.json"))
    if not fuse:
        _patch_bn(variant)
    import bench as B

    rs = np.random.RandomState(0)
    x = rs.randn(BATCH, 3, 224, 224).astype(np.float32)
    y = (rs.randint(0, 1000, BATCH) + 1).astype(np.float32)
    t0 = time.time()
    ips, step_s = B._bench_framework(x, y, BATCH, ITERS,
                                     compute_dtype="bfloat16",
                                     fuse=fuse)
    if variant == "tuned":
        from bigdl_tpu.ops import autotune

        tuner_info = [f"{d['site']}:{d['label']}<-{d['source']}"
                      for d in autotune.summary()["decisions"]]
    rec = {
        "variant": variant, "batch": BATCH,
        "images_per_sec": round(ips, 1),
        "step_ms": round(step_s * 1e3, 2),
        "wall_s": round(time.time() - t0, 1),
    }
    if tuner_info is not None:
        rec["tuner"] = tuner_info
    print(json.dumps(rec), flush=True)


def main():
    if os.environ.get("BN_AB_CHILD"):
        _run_one(os.environ["BN_AB_CHILD"])
        return
    for v in VARIANTS:
        t0 = time.time()
        env = dict(os.environ, BN_AB_CHILD=v)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 str(BATCH), str(ITERS)],
                capture_output=True, text=True, timeout=420, env=env,
            )
            out = (proc.stdout or "").strip().splitlines()
            line = out[-1] if out else (proc.stderr or "")[-240:]
        except subprocess.TimeoutExpired:
            line = f'{{"variant": "{v}", "error": "TIMEOUT 420s"}}'
        print(f"{line}   [{time.time()-t0:.0f}s]", flush=True)


if __name__ == "__main__":
    main()
