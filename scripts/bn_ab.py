"""A/B the framework ResNet-50 train step's BatchNormalization on chip.

Round-5 regression hunt: the unchanged-since-r03b framework step dropped
from 1867 img/s (b32) to 355 under the relay's new AOT compile path,
while bench.py's raw-JAX baseline (naive two-pass BN) kept its speed.
This script measures the framework step with the BN training-mode
formulation swapped, one subprocess per variant so a hung remote compile
costs only that variant:

  cur     — shipping code (single-pass shifted stats + lax.cond rescue)
  nocond  — single-pass shifted stats, rescue branch removed
  twopass — naive two-pass f32 stats (the baseline's formulation)

Usage: python scripts/bn_ab.py [batch] [iters] [variant...]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 32
ITERS = int(sys.argv[2]) if len(sys.argv) > 2 else 10
VARIANTS = sys.argv[3:] or ["cur", "nocond", "twopass"]


def _patch_bn(variant: str):
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.nn.layers import BatchNormalization

    if variant == "cur":
        return

    def apply(self, params, state, input, *, training=False, rng=None):
        axes, bshape = self._axes_and_shape(input)
        if not training:
            rm = state["running_mean"]
            scale, offset = self._fold(params, rm, state["running_var"], rm)
            dt = input.dtype
            y = (input - rm.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
            return y, state

        xf = input.astype(jnp.float32)
        if variant == "nocond":
            shift = state["running_mean"].reshape(bshape)
            xc = xf - shift
            d = jnp.mean(xc, axis=axes)
            m2 = jnp.mean(lax.square(xc), axis=axes)
            mean = state["running_mean"] + d
            var = jnp.maximum(m2 - lax.square(d), 0.0)
            scale, offset = self._fold(params, mean, var,
                                       state["running_mean"])
            dt = input.dtype
            y = (input - state["running_mean"].astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
        elif variant == "twopass":
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(
                lax.square(xf - mean.reshape(bshape)), axis=axes)
            scale, offset = self._fold(params, mean, var, mean)
            dt = input.dtype
            y = (input - mean.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)
        else:
            raise SystemExit(f"unknown variant {variant}")
        n = 1
        for a in axes:
            n *= input.shape[a]
        unbiased = var * (n / max(1, n - 1))
        new_state = {
            "running_mean": (1 - self.momentum) * state["running_mean"]
            + self.momentum * mean,
            "running_var": (1 - self.momentum) * state["running_var"]
            + self.momentum * unbiased,
        }
        return y, new_state

    BatchNormalization.apply = apply


def _run_one(variant: str):
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "axon")
    _patch_bn(variant)
    import bench as B

    rs = np.random.RandomState(0)
    x = rs.randn(BATCH, 3, 224, 224).astype(np.float32)
    y = (rs.randint(0, 1000, BATCH) + 1).astype(np.float32)
    t0 = time.time()
    ips, step_s = B._bench_framework(x, y, BATCH, ITERS,
                                     compute_dtype="bfloat16")
    print(json.dumps({
        "variant": variant, "batch": BATCH,
        "images_per_sec": round(ips, 1),
        "step_ms": round(step_s * 1e3, 2),
        "wall_s": round(time.time() - t0, 1),
    }), flush=True)


def main():
    if os.environ.get("BN_AB_CHILD"):
        _run_one(os.environ["BN_AB_CHILD"])
        return
    for v in VARIANTS:
        t0 = time.time()
        env = dict(os.environ, BN_AB_CHILD=v)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 str(BATCH), str(ITERS)],
                capture_output=True, text=True, timeout=420, env=env,
            )
            out = (proc.stdout or "").strip().splitlines()
            line = out[-1] if out else (proc.stderr or "")[-240:]
        except subprocess.TimeoutExpired:
            line = f'{{"variant": "{v}", "error": "TIMEOUT 420s"}}'
        print(f"{line}   [{time.time()-t0:.0f}s]", flush=True)


if __name__ == "__main__":
    main()
