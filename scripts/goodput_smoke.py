#!/usr/bin/env python
"""--goodput smoke: the goodput ledger + bottleneck attribution loop,
end to end.

Driven by ``scripts/run-tests.sh --goodput``.  Four stages, each a hard
assert:

1. two simulated hosts (separate OS processes, ``BIGDL_PROCESS_ID``
   0/1, CPU backend) each run a 10-step traced DistriOptimizer job into
   ONE shared trace/metrics volume — with the input pipeline
   **synthetically starved** (every batch sleeps before delivery), so
   the run is input-bound by construction, and a 4-step
   ``BIGDL_GOODPUT_WINDOW`` so the windowed classifier ticks;
2. ``python -m bigdl_tpu.obs.aggregate`` merges the shards (the merge
   now carries straggler detection — two healthy hosts must flag
   nothing);
3. ``python -m bigdl_tpu.obs.report`` renders the goodput section in
   text — ratio, badput causes, the bottleneck verdict;
4. ``--json`` carries the same numbers machine-readably and the
   bottleneck label must be ``input_bound`` — the classifier agreeing
   with how the run was sabotaged is the acceptance.

Exit 0 only when all four hold.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys, time
sys.path.insert(0, os.environ["BIGDL_REPO"])
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") \\
    + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import bigdl_tpu.native as native
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import (ClassNLLCriterion, Linear, LogSoftMax, ReLU,
                          Sequential)
from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

# synthetic input starvation: every batch arrives late, so the driver's
# data_wait dwarfs the (tiny CPU) step time -> input_bound by design
_P = native.PrefetchIterator

class Starved:
    def __init__(self, iterable, depth=2):
        self._it = iter(_P(iterable, depth))

    def __iter__(self):
        return self

    def __next__(self):
        time.sleep(float(os.environ.get("SMOKE_BATCH_DELAY", "0.03")))
        return next(self._it)

native.PrefetchIterator = Starved

Engine.init()
rng = np.random.RandomState(0)
w = rng.randn(16, 4)
x = rng.randn(320, 16).astype(np.float32)
y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
model = Sequential().add(Linear(16, 32)).add(ReLU()) \\
    .add(Linear(32, 4)).add(LogSoftMax())
opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=32)
opt.set_optim_method(SGD(learningrate=0.1))
opt.set_end_when(Trigger.max_iteration(10))
opt.optimize()
assert opt.state["neval"] == 11, opt.state["neval"]
"""


def run(cmd, **env):
    e = dict(os.environ)
    e.update({k: str(v) for k, v in env.items()})
    e["BIGDL_REPO"] = REPO
    return subprocess.run(cmd, env=e, cwd=REPO, capture_output=True,
                          text=True, timeout=300)


def _attempt(delay: str, frac: float) -> int:
    tmp = tempfile.mkdtemp(prefix="bigdl_goodput_smoke_")
    trace_dir = os.path.join(tmp, "trace")
    metrics_dir = os.path.join(tmp, "metrics")

    # -- 1: two input-starved hosts, one shared volume ----------------
    for host in (0, 1):
        p = run([sys.executable, "-c", _WORKER],
                BIGDL_PROCESS_ID=host, BIGDL_TRACE_DIR=trace_dir,
                BIGDL_METRICS_DIR=metrics_dir, BIGDL_GOODPUT_WINDOW=4,
                SMOKE_BATCH_DELAY=delay)
        assert p.returncode == 0, \
            f"host {host} worker failed:\n{p.stdout}\n{p.stderr}"
        print(f"[goodput-smoke] host {host}: starved 10-step run ok")

    # -- 2: merge (straggler detection rides along) -------------------
    merged = os.path.join(tmp, "merged.trace.json")
    p = run([sys.executable, "-m", "bigdl_tpu.obs.aggregate", trace_dir,
             "-o", merged])
    assert p.returncode == 0, p.stdout + p.stderr
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["hosts"] == [0, 1], summary
    assert summary["stragglers"] == [], \
        f"two equally-starved hosts flagged: {summary}"
    doc = json.load(open(merged))
    assert "stragglers" in doc["otherData"], doc["otherData"].keys()
    print(f"[goodput-smoke] merged {summary['shards']} shards, "
          f"stragglers={summary['stragglers']}")

    # -- 3: the goodput section renders in text -----------------------
    p = run([sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
             "--metrics-dir", metrics_dir])
    assert p.returncode == 0, p.stdout + p.stderr
    for needle in ("-- goodput --", "goodput ratio", "badput:",
                   "data_wait", "bottleneck: input_bound"):
        assert needle in p.stdout, \
            f"report missing {needle!r}:\n{p.stdout}"
    print("[goodput-smoke] text report renders the goodput section "
          "with the input_bound verdict")

    # -- 4: --json carries the same, machine-readably -----------------
    p = run([sys.executable, "-m", "bigdl_tpu.obs.report", trace_dir,
             "--metrics-dir", metrics_dir, "--json"])
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    gp = rep["goodput"]
    assert gp, "no goodput section in --json report"
    ratio = gp["goodput_ratio"]
    assert ratio is not None and 0 < ratio < 1, gp
    assert gp["badput_s"].get("data_wait", 0) > 0, gp["badput_s"]
    assert gp["bottleneck"]["label"] == "input_bound", gp["bottleneck"]
    assert gp["hosts"] == [0, 1], gp
    # the starved run's input share must clear the classifier threshold
    assert gp["bottleneck"]["input_fraction"] >= frac, \
        f"input_fraction {gp['bottleneck']['input_fraction']:.3f} < " \
        f"{frac:g} ({gp['bottleneck']})"
    print(f"[goodput-smoke] --json: ratio {ratio:.3f}, data_wait "
          f"{gp['badput_s']['data_wait']:.2f}s vs productive "
          f"{gp['productive_s']:.2f}s, bottleneck "
          f"{gp['bottleneck']['label']} (via {gp['bottleneck']['source']})")
    print("[goodput-smoke] PASS")
    return 0


def main() -> int:
    # the input-share threshold is a *relative* signal: on a CPU-
    # contended machine the (tiny) compute side slows down too, eroding
    # the starved run's input fraction.  SMOKE_INPUT_FRACTION lowers
    # the bar explicitly; otherwise one retry with a 2x slower input
    # pipeline restores the designed contrast.
    frac = float(os.environ.get("SMOKE_INPUT_FRACTION", "0.3"))
    delay = os.environ.get("SMOKE_BATCH_DELAY", "0.03")
    try:
        return _attempt(delay, frac)
    except AssertionError as e:
        if "input_fraction" not in str(e):
            raise
        print(f"[goodput-smoke] {e}")
        print("[goodput-smoke] input share below threshold (busy "
              "machine?) — retrying once with a 2x slower input "
              "pipeline")
        return _attempt(str(2 * float(delay)), frac)


if __name__ == "__main__":
    raise SystemExit(main())
