#!/usr/bin/env python
"""Live weight rollout smoke — watcher, canary, chaos scenario.

Driven by ``scripts/run-tests.sh --rollout``.  Three segments:

1. **Checkpoint watcher against a live engine**: a new version is
   published into a watch directory (model npz + manifest) while a
   long decode is in flight.  The watcher must verify-then-hot-swap
   between decode steps: the in-flight request completes, page tables
   and slots survive, post-swap requests are temperature-0 BIT-EQUAL
   to ``generate()`` on the new weights, and ``stats()``/``/healthz``
   carry the new version + manifest digest.  Then the gate: a publish
   torn mid-write (no manifest yet) is skipped, and a publish
   corrupted post-manifest (``publish:K:corrupt`` fault plan) is
   rejected — counted, never loaded, the engine keeps serving the
   incumbent bit-exactly.

2. **Canary promote/rollback over live engines**: a
   :class:`CanaryController` over four engine replicas.  A good
   version canaries on one replica, holds clean (zero pinned-prompt
   divergence) and promotes fleet-wide; a bad version (different
   weights — wildly divergent tokens) breaches the divergence
   threshold ``for_count`` evaluations in a row and rolls back
   exactly once, draining the canary first so nothing is dropped; the
   cooldown then refuses an immediate re-offer.

3. **Chaos scenario** (``bigdl_tpu/sim/serve.py``): the
   ``weight_rollout`` scenario on the virtual clock — good promote,
   exactly-one-rollback on the bad version, corrupt publish rejected,
   and the rollout invariants (``rollback_exactly_once``,
   ``no_version_skew_after_settle``, ``corrupt_never_loaded``,
   ``zero_dropped_requests``) all green.

Banks ``ROLLOUT_SMOKE.json`` at the repo root; bench.py folds it into
BENCH ``extras.rollout``.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _build(seed: int):
    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm

    RandomGenerator.RNG.set_seed(seed)
    model = build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                 max_len=64, attn_impl="xla")
    return model, model.params()


def _ref(model, params, prompt, n):
    import numpy as np

    return [int(t) for t in np.asarray(model.generate(
        params, np.asarray(prompt)[None, :], n))[0]]


def _gen(engine, prompt, n, timeout=120.0):
    req = engine.submit(prompt, n, timeout=timeout)
    req.wait(timeout)
    assert not req.error, f"engine request failed: {req.error}"
    return [int(t) for t in prompt] + [int(t) for t in req.tokens]


def run_watcher(args, watch_dir) -> dict:
    """Segment 1: publish -> verify -> hot-swap against a live engine,
    then the torn/corrupt rejection paths."""
    import numpy as np

    from bigdl_tpu.resilience.faults import reset_injector
    from bigdl_tpu.serving import LMEngine, publish_checkpoint
    from bigdl_tpu.serving.rollout import CheckpointWatcher
    from bigdl_tpu.utils.serializer import save_module

    model_a, params_a = _build(13)     # the incumbent ("v0")
    model_b, params_b = _build(17)     # genuinely different weights
    engine = LMEngine(model_a, max_batch=4, page_size=8).start()
    watcher = CheckpointWatcher(engine, watch_dir, poll_s=0.05)
    watcher.start()

    rs = np.random.RandomState(args.seed)
    prompt = rs.randint(0, 48, (6,)).tolist()
    assert _gen(engine, prompt, 8) == _ref(model_a, params_a, prompt, 8)

    # a long decode is in flight while the new version publishes: the
    # swap must not disturb its slot or page table — it completes with
    # every owed token
    inflight = engine.submit(rs.randint(0, 48, (5,)).tolist(), 48,
                             timeout=120.0)
    pages_before = engine.stats()["kv_pages_total"]
    publish_checkpoint(model_b, watch_dir, "v1")
    deadline = time.monotonic() + 30.0
    while engine.weight_version != "v1" and time.monotonic() < deadline:
        time.sleep(0.02)
    assert engine.weight_version == "v1", \
        f"watcher never swapped (still {engine.weight_version})"
    inflight.wait(120.0)
    assert not inflight.error and len(inflight.tokens) == 48, \
        f"in-flight decode did not survive the swap: " \
        f"error={inflight.error} tokens={len(inflight.tokens)}"

    st = engine.stats()
    assert st["weight_version"] == "v1" and st["manifest_sha"], st
    assert st["weight_swaps"] == 1 and engine.swaps == 1
    assert st["kv_pages_total"] == pages_before, \
        "page pool changed across a weight swap"
    assert engine.cache.pages_in_use() == 0, \
        "pages leaked across the swap"
    # post-swap requests are bit-equal to generate() on the NEW weights
    for n in (5, 9, 4):
        p = rs.randint(0, 48, (n,)).tolist()
        assert _gen(engine, p, 8) == _ref(model_b, params_b, p, 8), \
            "post-swap output diverged from generate() on new weights"
    print(f"SMOKE watcher: published v1 hot-swapped mid-decode "
          f"(in-flight finished 48/48 tokens, pages stable, 3 post-swap "
          f"requests bit-equal, sha {st['manifest_sha']})")

    # torn publish: model npz lands, the manifest never does — the
    # watcher must SKIP it (still publishing), not load, not reject
    save_module(model_a, os.path.join(watch_dir, "v2-torn.model"))
    time.sleep(0.3)
    assert engine.weight_version == "v1" and not watcher.rejected, \
        f"manifest-less publish was consumed: {watcher.stats()}"

    # corrupt post-manifest publish: the fault plan flips bytes in the
    # model npz AFTER the manifest records its sha — verify must catch
    # it, count it, and never touch serving state
    os.environ["BIGDL_FAULT_PLAN"] = "publish:1:corrupt"
    reset_injector()
    try:
        publish_checkpoint(model_a, watch_dir, "v3")
        deadline = time.monotonic() + 30.0
        while not watcher.rejected and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        os.environ.pop("BIGDL_FAULT_PLAN", None)
        reset_injector()
    rejects = {os.path.basename(k): v for k, v in
               watcher.rejected.items()}
    assert "v3" in rejects and "checksum" in rejects["v3"], rejects
    assert engine.weight_version == "v1" and engine.swaps == 1, \
        "corrupt publish reached the engine"
    p = rs.randint(0, 48, (7,)).tolist()
    assert _gen(engine, p, 8) == _ref(model_b, params_b, p, 8), \
        "engine output drifted after a rejected publish"
    print(f"SMOKE verify gate: torn publish skipped, corrupt publish "
          f"rejected ({rejects['v3']}) — engine still serving v1 "
          f"bit-exactly")
    watcher.stop()
    out = {"swapped": list(watcher.swapped),
           "rejected": rejects,
           "manifest_sha": st["manifest_sha"],
           "inflight_tokens": len(inflight.tokens)}
    engine.close()
    return out


def run_canary(args) -> dict:
    """Segment 2: the CanaryController over four live engine replicas
    — clean promote, divergence rollback, cooldown."""
    import numpy as np

    from bigdl_tpu.serving import LMEngine
    from bigdl_tpu.serving.rollout import CanaryController

    model_a, params_a = _build(13)
    model_b, params_b = _build(17)
    # "good" = the incumbent weights republished under a new version
    # (pinned-prompt replay is bit-equal); "bad" = different weights
    # (wildly divergent tokens)
    weights = {"v1": params_a, "v2": params_a, "v3": params_b}
    engines = {f"r{i}": LMEngine(model_a, max_batch=2,
                                 page_size=8).start()
               for i in range(4)}
    for eng in engines.values():
        eng.swap_weights(params_a, version="v1")

    def set_version(name, version):
        engines[name].swap_weights(weights[version], version=version)

    def drain_cb(name):
        engines[name].drain(deadline_s=5.0)

    def undrain_cb(name):
        engines[name].draining = False

    rs = np.random.RandomState(args.seed)
    pinned = [rs.randint(0, 48, (n,)).tolist() for n in (5, 7, 4, 6)]

    def measure():
        from bigdl_tpu.serving.rollout import token_divergence

        canary = ctl.canaries[0]
        incumbents = [n for n in engines if n not in ctl.canaries]
        worst = 0.0
        for p in pinned:
            ref = _gen(engines[incumbents[0]], p, 8)
            got = _gen(engines[canary], p, 8)
            worst = max(worst, token_divergence(ref, got))
        return worst

    now = [0.0]
    ctl = CanaryController(
        sorted(engines), set_version=set_version, incumbent="v1",
        measure_divergence=measure, alerts=lambda: [],
        drain=drain_cb, undrain=undrain_cb,
        fraction=0.25, divergence_threshold=0.05, for_count=2,
        hold_evals=3, cooldown_s=30.0, clock=lambda: now[0])

    assert ctl.offer("v2", now=now[0])
    for _ in range(3):
        now[0] += 5.0
        ctl.evaluate(now=now[0])
    assert ctl.state == "idle" and ctl.incumbent == "v2", ctl.stats()
    versions = {n: e.weight_version for n, e in engines.items()}
    assert set(versions.values()) == {"v2"}, versions
    print(f"SMOKE canary promote: v2 held clean 3 rounds, promoted "
          f"fleet-wide ({versions})")

    assert ctl.offer("v3", now=now[0])
    evals = []
    for _ in range(2):
        now[0] += 5.0
        evals.append(ctl.evaluate(now=now[0]))
    assert len(ctl.rollbacks) == 1 \
        and ctl.rollbacks[0]["reason"] == "divergence", ctl.stats()
    versions = {n: e.weight_version for n, e in engines.items()}
    assert set(versions.values()) == {"v2"}, \
        f"rollback left version skew: {versions}"
    assert all(not e.draining for e in engines.values()), \
        "a canary was left draining after rollback"
    # inside the cooldown the same (or any) version is refused
    assert not ctl.offer("v3", now=now[0] + 1.0)
    assert ctl.offer("v2", now=now[0] + 60.0), \
        "offer still refused after the cooldown elapsed"
    worst_div = max(e["divergence"] for e in evals)
    print(f"SMOKE canary rollback: v3 diverged {worst_div:.2f} > 0.05 "
          f"for 2 rounds -> exactly one rollback, fleet back on v2, "
          f"re-offer refused in cooldown")
    for eng in engines.values():
        eng.close()
    return {"promotions": list(ctl.promotions),
            "rollbacks": [dict(r) for r in ctl.rollbacks],
            "worst_divergence": round(worst_div, 4),
            "refused_offers": ctl.refused_offers,
            "versions": versions}


def run_scenario(args) -> dict:
    """Segment 3: the weight_rollout chaos scenario on the virtual
    clock."""
    from bigdl_tpu.sim.serve import run_serve_scenario

    res = run_serve_scenario("weight_rollout", seed=args.seed)
    print("SMOKE " + res.summary())
    for inv in res.invariants:
        print("   ", inv)
    assert res.ok, "weight_rollout scenario invariants FAILED"
    assert res.rollout and res.rollout["rollbacks"] == 1, res.rollout
    assert res.rollout["corrupt_loaded"] == 0
    assert res.lost == 0 and res.duplicates == 0 and res.shed == 0
    return res.to_dict()


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/rollout_smoke.py",
        description="Live weight rollout smoke: checkpoint watcher "
                    "hot-swap + verify gate, canary promote/rollback, "
                    "and the weight_rollout chaos scenario "
                    "(BIGDL_ROLLOUT_* knobs are the env spelling of "
                    "the rollout config).")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--skip-engines", action="store_true",
                    help="chaos scenario only (no jax model build)")
    args = ap.parse_args()

    import tempfile

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_rollout_smoke_")
    obs_dir = os.path.join(smoke_dir, "obs")
    os.environ["BIGDL_TRACE_DIR"] = obs_dir
    os.environ["BIGDL_METRICS_DIR"] = obs_dir

    t0 = time.monotonic()
    watcher = None
    canary = None
    if not args.skip_engines:
        watcher = run_watcher(args, os.path.join(smoke_dir, "watch"))
        canary = run_canary(args)
    scenario = run_scenario(args)
    total_wall = time.monotonic() - t0
    print(f"SMOKE rollout: all segments PASS in {total_wall:.1f}s")

    bank = {
        "seed": args.seed,
        "total_wall_s": round(total_wall, 2),
        "watcher": watcher,
        "canary": canary,
        "scenario": scenario,
    }
    with open(os.path.join(REPO, "ROLLOUT_SMOKE.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True, default=str)
    print("ROLLOUT SMOKE PASS (banked ROLLOUT_SMOKE.json)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
