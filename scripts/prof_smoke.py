#!/usr/bin/env python
"""Continuous-profiling + debug-bundle smoke — the rigged hot span.

Driven by ``scripts/run-tests.sh --prof``.  The scenario: the sampling
profiler (``obs/prof.py``) on at a real rate while one synthetically
hot tracer span burns CPU and a cold span sleeps, with the black-box
bundle plane (``obs/bundle.py``) armed and a live telemetry endpoint
up.  The assertions are the tentpole's acceptance criteria:

* **attribution** — the hot span owns >= 50% of the span-attributed
  self-time samples (the per-thread phase stack really labels stacks);
* **overhead** — the profiler's measured self-overhead ratio stays
  under 1% of wall (the ``BIGDL_PROF_BUDGET`` cap is real headroom,
  not the thing keeping the number down);
* **exactly one bundle per alert episode** — a threshold alert fires
  once and the alert->bundle path cuts exactly ONE manifest-valid
  bundle carrying the folded profile, the kept request traces, the
  metrics snapshot and the flight-recorder ring; a second evaluation
  of the same (still-firing) episode must NOT cut another;
* **live endpoints** — ``/profilez`` (JSON + ``?format=collapsed``)
  and ``/debugz`` (builds an on-demand bundle) answer over real HTTP;
* **report** — the profiles section renders the hot span and the
  bundle inventory in text and survives ``--json``.

Banks ``PROF_SMOKE.json`` at the repo root; bench.py folds it into
BENCH ``extras.prof``.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/prof_smoke.py",
        description="Continuous-profiling smoke: rigged hot span "
                    "attribution, <1% overhead, one alert -> exactly "
                    "one debug bundle, /profilez + /debugz live.")
    ap.add_argument("--hz", type=float, default=50.0,
                    help="sampling rate for the smoke (default 50)")
    ap.add_argument("--hot-s", type=float, default=3.0,
                    help="seconds the hot span burns CPU (default 3)")
    ap.add_argument("--cold-s", type=float, default=0.6,
                    help="seconds the cold span sleeps (default 0.6)")
    args = ap.parse_args()

    import tempfile
    import urllib.request

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_prof_smoke_")
    obs_dir = os.path.join(smoke_dir, "obs")
    bundle_dir = os.path.join(obs_dir, "bundles")
    os.environ["BIGDL_TRACE_DIR"] = obs_dir
    os.environ["BIGDL_METRICS_DIR"] = obs_dir
    os.environ["BIGDL_BUNDLE_DIR"] = bundle_dir
    os.environ["BIGDL_BUNDLE_RATE_LIMIT"] = "0"
    os.environ["BIGDL_PROF_HZ"] = f"{args.hz:g}"
    os.environ["BIGDL_PROF_BUDGET"] = "0.01"
    os.environ["BIGDL_OBS_PORT"] = "0"  # ephemeral

    from bigdl_tpu import obs
    from bigdl_tpu.obs import alerts, bundle, names, prof, server
    from bigdl_tpu.obs.report import build_report, render_text

    t0 = time.monotonic()
    profiler = prof.get_profiler()
    assert profiler.enabled, "BIGDL_PROF_HZ set but profiler is off"
    srv = server.ensure_server()
    assert srv is not None, "BIGDL_OBS_PORT set but no server bound"
    tracer = obs.get_tracer()

    # --- the rigged workload: one hot span burning CPU, one cold span
    # sleeping — attribution must split them, not blur them ----------
    def _burn(until: float) -> int:
        acc = 0
        while time.monotonic() < until:
            acc += sum(i * i for i in range(200))
        return acc

    wall0 = time.monotonic()
    with tracer.span("smoke.hot"):
        _burn(time.monotonic() + args.hot_s)
    with tracer.span("smoke.cold"):
        time.sleep(args.cold_s)
    step_wall = time.monotonic() - wall0

    snap = profiler.snapshot()
    assert snap["samples"] >= 10, \
        f"only {snap['samples']} samples in {step_wall:.1f}s " \
        f"at {args.hz:g} Hz"
    spanned = {ph: p["samples"] for ph, p in snap["phases"].items()
               if ph != prof.NO_SPAN}
    assert "smoke.cold" in spanned, \
        f"cold span never sampled: {sorted(spanned)}"
    hot = spanned.get("smoke.hot", 0)
    share = hot / max(1, sum(spanned.values()))
    assert share >= 0.5, \
        (f"hot span got {share * 100:.1f}% of span-attributed "
         f"self-time, expected >= 50%: {spanned}")
    overhead = snap["overhead_ratio"]
    assert overhead < 0.01, \
        f"profiler overhead {overhead * 100:.2f}% >= the 1% gate"
    print(f"SMOKE prof: {snap['samples']} samples at {args.hz:g} Hz "
          f"over {step_wall:.1f}s; hot span {share * 100:.1f}% of "
          f"span-attributed self-time, overhead "
          f"{overhead * 100:.3f}% (< 1%)")

    # --- /profilez over live HTTP ------------------------------------
    with urllib.request.urlopen(srv.url("/profilez"), timeout=10) as r:
        pz = json.loads(r.read())
    assert pz["enabled"] and pz["samples"] > 0, pz
    with urllib.request.urlopen(srv.url("/profilez?format=collapsed"),
                                timeout=10) as r:
        collapsed = r.read().decode("utf-8")
    assert "smoke.hot;" in collapsed, \
        "collapsed-stack render lost the hot phase root"
    print("SMOKE prof: /profilez serves JSON + collapsed stacks "
          f"({len(collapsed.splitlines())} folded stack(s))")

    # --- one alert episode -> exactly one manifest-valid bundle ------
    rule = {"name": "prof_smoke_hot", "type": "threshold",
            "metric": names.PROF_SAMPLES_TOTAL, "op": ">",
            "value": 5, "for": 1, "severity": "warning"}
    engine = alerts.AlertEngine([rule])
    fired = engine.evaluate()
    assert [t["state"] for t in fired] == ["firing"], fired
    inv = bundle.inventory(bundle_dir)
    assert len(inv) == 1 and inv[0]["ok"], inv
    assert inv[0]["trigger"] == "alert", inv[0]
    # the same still-firing episode must NOT cut a second bundle
    engine.evaluate()
    assert len(bundle.inventory(bundle_dir)) == 1, \
        "a still-firing episode cut a second bundle"
    bpath = inv[0]["path"]
    with open(os.path.join(bpath, bundle.MANIFEST),
              encoding="utf-8") as fh:
        manifest = json.load(fh)
    for need in ("profile.json", "reqtraces.json", "metrics.json",
                 "ring.json", "alerts.json", "runtime.json"):
        assert need in manifest["files"], \
            f"bundle manifest missing {need}: {sorted(manifest['files'])}"
    with open(os.path.join(bpath, "profile.json"),
              encoding="utf-8") as fh:
        bundled_prof = json.load(fh)
    assert "smoke.hot" in (bundled_prof.get("phases") or {}), \
        "bundled profile lost the hot phase"
    ok, why = bundle.verify_bundle(bpath)
    assert ok, why
    print(f"SMOKE bundle: one alert episode -> exactly one "
          f"manifest-valid bundle ({why}; "
          f"{len(manifest['files'])} files)")

    # --- /debugz cuts an on-demand bundle over live HTTP -------------
    with urllib.request.urlopen(srv.url("/debugz"), timeout=30) as r:
        dz = json.loads(r.read())
    assert dz.get("bundle") and not dz.get("error"), dz
    inv2 = bundle.inventory(bundle_dir)
    assert len(inv2) == 2 and all(b["ok"] for b in inv2), inv2
    assert sum(1 for b in inv2 if b["trigger"] == "alert") == 1, inv2
    print(f"SMOKE bundle: /debugz cut an on-demand bundle "
          f"({os.path.basename(dz['bundle'])})")

    # --- the report's profiles section, text + --json ----------------
    obs.flush()
    rep = build_report(obs_dir, obs_dir)
    pr = rep.get("profiles")
    assert pr and pr["samples"] > 0, pr
    assert "smoke.hot" in pr["phases"], sorted(pr["phases"])
    assert pr["bundles_valid"] == 2, pr
    text = render_text(rep)
    assert "-- profiles --" in text and "smoke.hot" in text, text
    assert "bundles: 2/2 valid" in text, text
    json.dumps(rep, default=str)  # --json path must survive
    print("SMOKE report: profiles section renders the hot span + "
          "bundle inventory (text + --json)")

    total_wall = time.monotonic() - t0
    bank = {
        "hz": args.hz,
        "total_wall_s": round(total_wall, 2),
        "step_wall_s": round(step_wall, 2),
        "samples": snap["samples"],
        "skipped": snap["skipped"],
        "hot_share": round(share, 4),
        "overhead_ratio": round(overhead, 6),
        "bundles": {"alert": 1, "http": 1, "valid": 2},
        "profiles": {k: pr[k] for k in
                     ("samples", "skipped", "overhead_ratio",
                      "bundles_valid")},
    }
    with open(os.path.join(REPO, "PROF_SMOKE.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True, default=str)
    print(f"PROF SMOKE PASS in {total_wall:.1f}s "
          "(banked PROF_SMOKE.json)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
