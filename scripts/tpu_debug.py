"""Escalating TPU compile/run probe — attributes relay failures.

The round-5 outage mode: the probe reaches the chip, but the relay's
remote_compile service 500s (or hangs) on large programs.  This script
runs an escalating ladder of programs, each in its OWN subprocess with
a hard timeout, and prints one status line per rung — so a single run
says exactly where the tunnel/compiler breaks.

    python scripts/tpu_debug.py            # full ladder
    python scripts/tpu_debug.py --rung 4   # one rung, in-process

This probes the COMPILE path.  For a run that completed (or died) with
``BIGDL_TRACE_DIR`` set, the post-run analysis lives in the obs CLIs:
``python -m bigdl_tpu.obs.report <trace_dir>`` (step-time percentiles,
collective bytes, slowest spans per host, and — when the run exported
health telemetry via ``BIGDL_HEALTH_EVERY`` — the "training health"
section: per-layer grad/param norms, update ratios, non-finite layer
attributions, numerics anomalies; ``--json`` for machines) and
``python -m bigdl_tpu.obs.aggregate <trace_dir>`` (one Perfetto
timeline from all host shards, with cross-host straggler flags).  A
NaN'd run names its first offending layer in the report's health
section — start there before blaming the compiler.  A run that is
merely SLOW (or restarts a lot) starts at the report's "goodput"
section instead: the wall-clock ledger says how much time went to
compiles, checkpoints, input waits, supervisor backoff, and
restart rework vs. productive steps, and the bottleneck line says
whether the run was input/compute/comm/host bound — see MIGRATION.md
"Goodput & bottleneck attribution" for the knobs and
``scripts/run-tests.sh --goodput`` for the end-to-end smoke.

A run that compiles and is healthy but SLOWER than expected on its hot
kernels (attention, fused conv+BN) is a dispatch question before a
compiler one: enable the auto-tuner (`BIGDL_TUNER=1
BIGDL_TUNER_CACHE=/path/tuner.json`, add `BIGDL_TUNER_MEASURE=1` on a
real chip) and read the report's "kernel auto-tuner" section — which
impl/blocks each site chose, from cache or measurement, and how far
the static policy was off — see MIGRATION.md "Kernel auto-tuning" and
``scripts/run-tests.sh --tune`` for the end-to-end smoke.

A healthy run whose goodput verdict says COMM-bound pays the wire
first: turn on the compressed collective wire (`BIGDL_WIRE_DTYPE=int8
BIGDL_WIRE_EF=1`, or `fp8_e4m3`) and read the report's collective
bytes — `bigdl_collective_wire_savings_ratio{path=...}` says what the
gradient/TP/MoE/ring exchanges ship vs f32 (>= 3.2x on the gradient
path), with error feedback keeping the loss trajectory within the f32
run's — see MIGRATION.md "Quantized collectives v2" and
``scripts/run-tests.sh --wire`` for the measured A/B.  Still
comm-bound (or input-bound, or stalling on checkpoints) after the
wire is compressed?  HIDE the cost instead of shrinking it: the
overlapped step (`BIGDL_OVERLAP_BUCKET_MB` bucketed last-layer-first
gradient exchange, `BIGDL_CHECKPOINT_ASYNC=1` snapshot-then-
background-write checkpoints, `BIGDL_INPUT_DOUBLE_BUFFER=1`
prefetched device transfer) rides comm/IO under backward — the
report's "overlap" block shows buckets, the exposed-comm share and
snapshot-vs-write times, and the `exposed_comm_high` alert pages when
the buckets are too coarse to hide the wire — see MIGRATION.md
"Overlapped step" and ``scripts/run-tests.sh --overlap`` for the
measured on-vs-off A/B.

A run that keeps DYING (preemption, host loss) rather than failing to
compile belongs under the restart supervisor instead: ``python -m
bigdl_tpu.resilience.supervisor -- <train cmd>`` resumes preempted
children from their emergency checkpoint (exit code 170) for free and
transient crashes under the retry budget — see MIGRATION.md "Elastic
training" for the exit-code/heartbeat/resize knobs, and
``scripts/run-tests.sh --elastic`` for the end-to-end smoke.

A run that is the WRONG SIZE for its load — step time over target,
the streaming input buffer backing up, or chips idling on a drained
queue — doesn't need an operator either: add ``--autoscale`` (or
``BIGDL_AUTOSCALE=1``) and the supervisor's policy loop scrapes the
live `/healthz`/`/metrics` signals and executes checkpoint-stop-
restart resizes inside ``BIGDL_AUTOSCALE_MIN_WORLD..MAX_WORLD`` —
with hysteresis + cooldown so flapping signals can't thrash, dry-run
mode to watch it decide, and exactly-once streaming resume
(`dataset/stream.py` offsets ride the checkpoint).  The report's
"autoscaling & stream" section shows every decision; see MIGRATION.md
"Autoscaling & streaming training" and ``scripts/run-tests.sh
--autoscale`` for the end-to-end 1→2→1 smoke.

A SERVING deployment (bigdl_tpu/serving) that is slow or backing up
reads the report's "serving" section first: per-kind request-latency
percentiles (ttft / per_token / e2e), tokens/sec, batcher occupancy
and queue depth.  Low occupancy with a deep queue means admission is
starved (pages exhausted? check bigdl_serve_kv_pages_in_use and
preemptions); high occupancy with a rising p99 means the world is
undersized — the autoscaler's queue band (BIGDL_AUTOSCALE_QUEUE_*) and
latency band (BIGDL_AUTOSCALE_P99_*) scale on exactly these signals.
SLOW DECODE specifically starts at the serving section's "decode:
X ms/step, Y MB/token" line (gauges bigdl_serve_decode_attn_ms /
bigdl_serve_decode_hbm_bytes_per_token): a high MB/token with
BIGDL_SERVE_DECODE_BUCKET off or decode_attn pinned to "dense" means
you are paying the full-pool gather tax — enable BIGDL_TUNER=1 so the
cached decode_attn site dispatches the fused/Pallas flash-decode path
(pre-warm with autotune.prewarm_decode_attn; MIGRATION.md "Decode
kernels").  A P99 REGRESSION you cannot place from aggregates alone
reads the report's "request traces" section next (run with
BIGDL_REQTRACE_SAMPLE > 0): the slowest decile's per-hop breakdown
(queue / prefill / preempt / decode / placement / retry / handoff)
names the guilty hop, latency-histogram exemplars link a bucket spike
to a kept trace_id, and ``GET /trace?request=<id>`` on the obs server
returns that request's full span list (anomalous requests — errored,
retried, preempted, handed off, SLO-violating — are always kept; see
MIGRATION.md "Request tracing").  See MIGRATION.md "Inference
serving" and ``scripts/run-tests.sh --serve`` for the end-to-end
smoke.

A run you need to watch RIGHT NOW (not post-mortem) has the live
telemetry plane: export ``BIGDL_OBS_PORT`` and curl the host's
``/healthz`` (status / last-step age / live goodput / firing alerts)
and ``/metrics`` (Prometheus, scrapeable), or point ``python -m
bigdl_tpu.obs.report <dir> --watch`` at the fleet
(``BIGDL_OBS_PEERS=h0:P,h1:P`` for live scraping, shard tailing
otherwise).  A run that silently WEDGES — alive, no step progress —
is exactly what ``BIGDL_HANG_TIMEOUT`` + the supervisor's /healthz
hang watchdog restarts; the declarative alert pack
(``BIGDL_ALERT_RULES``/``BIGDL_ALERT_SINK``) pages on goodput SLO
burn, non-finite spikes, stragglers, checkpoint failures and stale
heartbeats — see MIGRATION.md "Live telemetry & alerting" and
``scripts/run-tests.sh --live`` for the end-to-end smoke.

An incident that is GONE by the time anyone attaches tools (the 3am
p99 spike, the once-a-week hang) is what the continuous profiling
plane is for: with ``BIGDL_PROF_HZ`` set a sampling profiler is
*always* on (span-attributed folded stacks, self-overhead capped hard
at ``BIGDL_PROF_BUDGET`` — published as ``bigdl_prof_overhead_ratio``
so a misconfigured rate is itself an alertable signal), served live at
``GET /profilez`` (``?format=collapsed`` feeds any flamegraph tool)
and folded into the report's "profiles" section.  With
``BIGDL_BUNDLE_DIR`` set, every alert *firing* transition (exactly
once per episode, per-rule rate-limited by
``BIGDL_BUNDLE_RATE_LIMIT``), every supervisor crash/hang restart,
and ``GET /debugz`` on demand cuts a black-box debug bundle — the
profile, kept request traces, metrics snapshot, flight ring, runtime
and alert state, sha256-manifested so a torn write is *detected*, not
trusted; ``report`` inventories them and a SIGTERM'd process still
lands its traces + profile through the atexit flush — see MIGRATION.md
"Continuous profiling & debug bundles" and ``scripts/run-tests.sh
--prof`` for the end-to-end smoke.

A FLEET POLICY CHANGE (autoscale bands, alert rules, scrape or
watchdog behavior) is validated BEFORE it meets real traffic by the
control-plane simulator: ``scripts/run-tests.sh --fleet`` runs the
chaos scenario matrix (diurnal wave, correlated stragglers, network
partition, cascading preemptions, flapping hosts + poisoned alert
sink, latency wave) at 200 synthetic hosts against the REAL
controller/alert engine/aggregator on a virtual clock, and the
invariants (no-flap convergence, exactly-once alert episodes,
O(hosts) aggregation, conservative degradation, free preemption
restarts) tell you precisely which property the change broke — read
the report's "fleet simulation" section and FLEET_SIM.json.  Author a
targeted scenario (BIGDL_FLEET_SCENARIO=<file.json>) reproducing the
incident you are chasing; see MIGRATION.md "Fleet simulation & chaos
scenarios".

A FLEET P99 (or any fleet-merged number) that LOOKS WRONG is a
pipeline question before a workload one — check the metrics plane's
own meta-metrics first: ``bigdl_fleet_stale_hosts`` and the report's
``STALE`` lines say which hosts were *excluded* from the merge (clock
skew past BIGDL_STALE_AFTER_S, or failed scrapes — their reasons are
in ``bigdl_fleet_scrape_errors_total{reason}`` and the per-host
``bigdl_fleet_host_staleness_seconds``/``_scrape_latency_seconds``
gauges), and ``bigdl_rollup_series_dropped_total{family}`` says which
families hit the BIGDL_ROLLUP_TOP_K cardinality bound and folded their
tail into the ``other`` bucket (a fleet percentile is exact over what
was merged — the drop counter tells you what wasn't).  A merged value
that still disagrees with a flat scrape is the exactness invariant's
territory: ``scripts/run-tests.sh --fleetobs`` re-proves
hierarchical == flat at 1000 simulated hosts (FLEETOBS_SMOKE.json);
see MIGRATION.md "Fleet-scale metrics".

A STUCK ROLLOUT (new weights published, fleet still on the old
version) or VERSION SKEW (replicas disagree on ``weight_version`` in
``/healthz`` / ``stats()``) is triaged from the rollout plane's own
counters before anyone re-publishes: ``bigdl_rollout_rejected_total
{reason}`` says the watcher *refused* the checkpoint (``torn`` /
``checksum`` / ``size`` / ``missing`` — re-publish via
``publish_checkpoint``, which writes the manifest LAST, rather than
hand-copying files); a publish that verified but never promoted shows
in the CanaryController's stats — ``refused_offers`` (offered inside
the post-rollback cooldown), ``bigdl_rollout_rollbacks_total
{reason}`` (``slo_burn`` vs ``divergence`` says *which* signal keeps
firing) and the ``bigdl_rollout_canary_divergence`` gauge (a high
value is the pinned-prompt replay disagreeing with the incumbent —
usually a genuinely different model, not an infra fault).  Lingering
skew after a settle also shows up as drain replays refusing absorbers
(``bigdl_rollout_version_mismatch_total`` climbing) — find the
replica whose ``/healthz`` ``weight_version`` disagrees and offer it
the incumbent.  ``scripts/run-tests.sh --rollout`` re-proves the
whole plane end-to-end (ROLLOUT_SMOKE.json), and the fleet
simulator's ``weight_rollout`` scenario replays promote / rollback /
corrupt-publish against the real controller — see MIGRATION.md "Live
weight rollout".

A LINT FAILURE (``scripts/run-tests.sh --lint`` /
``tests/test_lint.py::test_repo_is_clean``) is triaged from the
finding line itself — ``path:line: RULE message``.  JX* findings are
tracing hazards (host sync, tracer leak, jit-in-loop, unhashable
static, tracer branch): fix the traced scope, don't suppress — these
are exactly the recompile/host-sync bugs this ladder exists to chase
after the fact.  CC* findings are lock-discipline (acquisition-order
cycle, unlocked shared write, bare acquire): pick one global lock
order / take the class lock.  RD* findings are registry drift: declare
the env var in ``bigdl_tpu/config.py`` (or metric in
``bigdl_tpu/obs/names.py``) instead of minting spellings inline.  A
deliberate exception gets an inline ``# graftlint: disable=RULE`` with
a rationale comment; a legacy finding you must ship around goes in
the baseline via ``--write-baseline`` — see MIGRATION.md "Static
analysis" for rule ids, the baseline lifecycle and suppression syntax.
"""

import argparse
import json
import os
import subprocess
import sys
import time

RUNGS = [
    ("matmul_1k", "1k x 1k bf16 matmul"),
    ("conv_small", "3x3 conv 16ch @64px"),
    ("bottleneck_fwd", "one ResNet bottleneck fwd, batch 32"),
    ("resnet_fwd", "full ResNet-50 fwd, batch 32"),
    ("resnet_step", "ResNet-50 train step (no scan), batch 32"),
    ("resnet_scan", "ResNet-50 train step in a 10-step scan, batch 32"),
    ("resnet_scan_b128", "scan step at the bench operating point b128"),
    ("fused_scan_b128", "fused conv+BN scan step, b128"),
]


def _run_rung(name: str):
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "axon")
    import numpy as np

    dev = jax.devices()[0]
    t0 = time.time()

    if name == "matmul_1k":
        x = jnp.ones((1024, 1024), jnp.bfloat16)
        jax.jit(lambda a: a @ a)(x).block_until_ready()
    elif name == "conv_small":
        from jax import lax

        img = jnp.ones((8, 16, 64, 64), jnp.bfloat16)
        k = jnp.ones((16, 16, 3, 3), jnp.bfloat16)
        jax.jit(lambda i, w: lax.conv_general_dilated(
            i, w, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        ))(img, k).block_until_ready()
    elif name in ("bottleneck_fwd", "resnet_fwd", "resnet_step",
                  "resnet_scan", "resnet_scan_b128", "fused_scan_b128"):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench as B

        batch = 128 if name.endswith("b128") else 32
        rs = np.random.RandomState(0)
        if name == "bottleneck_fwd":
            from bigdl_tpu.nn import (
                ReLU,
                Sequential,
                SpatialBatchNormalization,
                SpatialConvolution,
            )

            m = Sequential()
            m.add(SpatialConvolution(256, 64, 1, 1, with_bias=False))
            m.add(SpatialBatchNormalization(64)).add(ReLU())
            m.add(SpatialConvolution(64, 64, 3, 3, 1, 1, -1, -1,
                                     with_bias=False))
            m.add(SpatialBatchNormalization(64)).add(ReLU())
            m.add(SpatialConvolution(64, 256, 1, 1, with_bias=False))
            m.add(SpatialBatchNormalization(256))
            params, state = m.params(), m.state()
            x = jnp.asarray(rs.randn(32, 256, 56, 56).astype(np.float32))

            @jax.jit
            def f(p, x):
                out, _ = m.apply(p, state, x, training=True,
                                 rng=jax.random.key(0))
                return out.sum()

            f(params, x).block_until_ready()
        else:
            x = rs.randn(batch, 3, 224, 224).astype(np.float32)
            y = (rs.randint(0, 1000, batch) + 1).astype(np.float32)
            if name == "resnet_fwd":
                from bigdl_tpu.models import build_resnet_imagenet

                model = build_resnet_imagenet(depth=50, class_num=1000)
                params, state = model.params(), model.state()

                @jax.jit
                def f(p, xx):
                    out, _ = model.apply(p, state, xx, training=False,
                                         rng=None)
                    return out.sum()

                f(params, jnp.asarray(x)).block_until_ready()
            elif name == "resnet_step":
                # the bench's framework step, ONE call, no scan
                ips, _ = B._bench_framework(x, y, batch, 1,
                                            compute_dtype="bfloat16")
            else:
                fuse = name.startswith("fused")
                ips, _ = B._bench_framework(x, y, batch, 10,
                                            compute_dtype="bfloat16",
                                            fuse=fuse)
                print(json.dumps({"rung": name,
                                  "images_per_sec": round(ips, 2)}))
    else:
        raise SystemExit(f"unknown rung {name}")
    print(json.dumps({"rung": name, "ok": True,
                      "device": dev.device_kind,
                      "seconds": round(time.time() - t0, 1)}))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rung", type=int, default=None)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--stop-on-fail", action="store_true")
    args = p.parse_args()

    if args.rung is not None:
        _run_rung(RUNGS[args.rung][0])
        return

    for i, (name, desc) in enumerate(RUNGS):
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--rung", str(i)],
                capture_output=True, text=True, timeout=args.timeout,
            )
            ok = proc.returncode == 0
            tail = (proc.stdout or proc.stderr or "").strip().splitlines()
            detail = tail[-1][:240] if tail else ""
        except subprocess.TimeoutExpired:
            ok, detail = False, f"TIMEOUT after {args.timeout:.0f}s"
        print(f"[{i}] {name:18s} {desc:45s} "
              f"{'OK' if ok else 'FAIL'} {time.time()-t0:6.1f}s  {detail}",
              flush=True)
        if not ok and args.stop_on_fail:
            break


if __name__ == "__main__":
    main()
