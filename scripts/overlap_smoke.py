#!/usr/bin/env python
"""--overlap smoke: the overlapped training step, A/B'd end to end.

Driven by ``scripts/run-tests.sh --overlap``.  One process, a 2-"host"
(2 forced CPU devices) data mesh — the same simulated-host convention
as the wire smoke — running the SAME 160-step job twice with a
synthetically slow input producer (every batch arrives ~8ms late):

* **overlap OFF** — monolithic f32 gradient exchange, foreground
  input, synchronous checkpoints (the pre-ISSUE-11 step);
* **overlap ON** — bucketed exchange (``overlap_bucket_mb`` small
  enough for several buckets), double-buffered input
  (``BIGDL_INPUT_DOUBLE_BUFFER=1``) and fully-async checkpoints
  (``BIGDL_CHECKPOINT_ASYNC=1``).

Asserted, not eyeballed:

* per-step trajectory equivalence (worst relative loss error < 1e-5 —
  bucketing changes WHEN bytes move, never the math);
* golden byte parity: both runs ship EXACTLY the same total exchange
  bytes (``bigdl_collective_bytes_total``);
* the ``comm_bound`` signal falls: the mean per-window comm fraction
  (goodput.bottleneck events, estimated over ``BIGDL_WIRE_GBPS``) is
  strictly lower with the bucketed exchange;
* the ``input_bound`` signal falls: ``data_wait`` badput seconds (and
  their share of wall clock) drop with double-buffering;
* checkpoint badput falls: ``checkpoint_save`` seconds shrink to the
  snapshot span, while the async write is durable (the newest
  checkpoint verifies and its manifest carries the bucket plan + the
  per-bucket EF-capable topology);
* ``bigdl_goodput_ratio`` strictly improves overlap-on;
* the report renders the new "overlap" block.

Results are banked to ``OVERLAP_SMOKE.json`` at the repo root, which
``bench.py`` folds into its BENCH JSON as ``extras.overlap``.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

EPOCHS = 20           # x 8 batches = 160 steps
BATCH_DELAY = 0.008   # synthetic producer latency per batch
TOL = 1e-5
OUT = os.path.join(REPO, "OVERLAP_SMOKE.json")


def main() -> int:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import numpy as np

    from bigdl_tpu import obs
    import bigdl_tpu.native as native
    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.nn import (
        ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
    )
    from bigdl_tpu.obs import goodput as G
    from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

    # synthetic input starvation: the producer delivers every batch
    # late, so the un-overlapped loop eats a data_wait per step while
    # the double-buffered loop hides the same latency under the step
    _P = native.PrefetchIterator

    class Slow:
        def __init__(self, iterable, depth=2):
            self._it = iter(_P(iterable, depth))

        def __iter__(self):
            return self

        def __next__(self):
            time.sleep(BATCH_DELAY)
            return next(self._it)

    native.PrefetchIterator = Slow

    Engine.init()
    import jax

    n = 2
    assert len(jax.devices()) == n, jax.devices()

    rng = np.random.RandomState(0)
    # a model big enough that the exchange dominates the byte budget
    d, h, k = 32, 128, 4
    w = rng.randn(d, k)
    x = rng.randn(256, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)

    class Tape:
        def __init__(self):
            self.loss = {}

        def add_scalar(self, tag, value, step):
            if tag == "Loss":
                self.loss[step] = float(value)

        def add_histogram(self, *a, **kw):
            pass

        def get_summary_trigger(self, name):
            return None

        def add_resilience(self, step, **c):
            pass

    def exchange_bytes():
        fam = obs.get_registry().counter(
            "bigdl_collective_bytes_total", labels=("op", "dtype"))
        return fam.labels(op="psum_scatter", dtype="float32").value

    def run(tag, overlap):
        tmp = tempfile.mkdtemp(prefix=f"bigdl_overlap_{tag}_")
        os.environ["BIGDL_METRICS_DIR"] = os.path.join(tmp, "metrics")
        os.environ["BIGDL_TRACE_DIR"] = os.path.join(tmp, "trace")
        os.environ["BIGDL_GOODPUT_WINDOW"] = "8"
        # assumed wire bandwidth for the comm-seconds estimate: slow
        # enough that the monolithic exchange reads as a real cost,
        # fast enough that the fraction stays under the min(1, ...) cap
        # so the A/B difference is visible
        os.environ["BIGDL_WIRE_GBPS"] = "0.03"
        os.environ["BIGDL_INPUT_DOUBLE_BUFFER"] = "1" if overlap else "0"
        os.environ["BIGDL_CHECKPOINT_ASYNC"] = "1" if overlap else "0"
        from bigdl_tpu.config import reload_from_env

        reload_from_env()
        obs.reset()
        RandomGenerator.RNG.set_seed(7)
        model = Sequential().add(Linear(d, h)).add(ReLU()) \
            .add(Linear(h, k)).add(LogSoftMax())
        opt = DistriOptimizer(
            model, (x, y), ClassNLLCriterion(), batch_size=32,
            wire_dtype="float32",
            overlap_bucket_mb=0.004 if overlap else 0)
        opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
        opt.set_end_when(Trigger.max_epoch(EPOCHS))
        opt.set_checkpoint(os.path.join(tmp, "ck"),
                           Trigger.several_iteration(40))
        tape = Tape()
        opt.set_train_summary(tape)
        t0 = time.perf_counter()
        opt.optimize()
        wall = time.perf_counter() - t0
        # per-window comm fractions from the bottleneck events, before
        # the reset drops the flight ring
        comm_fracs = [float(r["attrs"]["comm_fraction"])
                      for r in obs.get_tracer().recent()
                      if r.get("name") == "goodput.bottleneck"]
        bytes_total = exchange_bytes()
        obs.flush()
        gp = G.aggregate_goodput(os.environ["BIGDL_METRICS_DIR"])
        return {"tape": tape, "opt": opt, "tmp": tmp, "wall": wall,
                "gp": gp, "comm_fracs": comm_fracs,
                "exchange_bytes": bytes_total,
                "buckets": len(opt._buckets)}

    print(f"== overlap smoke: {EPOCHS * 8}-step A/B on a {n}-host mesh, "
          f"{BATCH_DELAY * 1000:.0f}ms/batch producer ==")
    off = run("off", overlap=False)
    on = run("on", overlap=True)
    steps = EPOCHS * 8
    assert len(off["tape"].loss) == steps, len(off["tape"].loss)
    assert off["buckets"] == 1 and on["buckets"] > 1, (
        off["buckets"], on["buckets"])

    # -- 1: trajectory equivalence ------------------------------------
    worst = max(abs(on["tape"].loss[s] - off["tape"].loss[s])
                / (abs(off["tape"].loss[s]) + 1e-9)
                for s in off["tape"].loss)
    assert worst < TOL, worst
    print(f"   trajectory: worst per-step rel err {worst:.2e} "
          f"(< {TOL:g}) over {steps} steps")

    # -- 2: golden byte parity ----------------------------------------
    assert on["exchange_bytes"] == off["exchange_bytes"] > 0, (
        on["exchange_bytes"], off["exchange_bytes"])
    print(f"   wire: {on['exchange_bytes']:.0f} exchange bytes, "
          f"identical across {on['buckets']} buckets vs monolithic")

    # -- 3: the comm signal falls -------------------------------------
    assert off["comm_fracs"] and on["comm_fracs"]
    comm_off = sum(off["comm_fracs"]) / len(off["comm_fracs"])
    comm_on = sum(on["comm_fracs"]) / len(on["comm_fracs"])
    assert comm_on < comm_off, (comm_on, comm_off)
    print(f"   comm fraction: {comm_off:.3f} -> {comm_on:.3f} "
          f"({on['buckets']} buckets hide the exchange under backward)")

    # -- 4: the input signal falls ------------------------------------
    wait_off = off["gp"]["badput_s"].get("data_wait", 0.0)
    wait_on = on["gp"]["badput_s"].get("data_wait", 0.0)
    input_off = wait_off / off["gp"]["total_s"]
    input_on = wait_on / on["gp"]["total_s"]
    assert wait_on < wait_off and input_on < input_off, (
        wait_on, wait_off)
    print(f"   input badput: {wait_off:.2f}s ({input_off * 100:.0f}% of "
          f"wall) -> {wait_on:.2f}s ({input_on * 100:.0f}%) "
          "double-buffered")

    # -- 5: checkpoint badput shrinks to the snapshot span ------------
    ck_off = off["gp"]["badput_s"].get("checkpoint_save", 0.0)
    ck_on = on["gp"]["badput_s"].get("checkpoint_save", 0.0)
    assert ck_off > 0, off["gp"]["badput_s"]
    assert ck_on < ck_off, (ck_on, ck_off)
    from bigdl_tpu.utils.serializer import (
        checkpoint_prefixes, read_checkpoint_topology, verify_checkpoint,
    )

    ck_dir = os.path.join(on["tmp"], "ck")
    newest = os.path.join(ck_dir, checkpoint_prefixes(ck_dir)[-1])
    ok, reason = verify_checkpoint(newest)
    assert ok, reason
    topo = read_checkpoint_topology(newest)
    assert len(topo.get("buckets") or []) > 1, topo
    print(f"   checkpoint_save badput: {ck_off * 1000:.1f}ms sync -> "
          f"{ck_on * 1000:.1f}ms async (snapshot only; newest intact, "
          "manifest carries the bucket plan)")

    # -- 6: goodput strictly improves ---------------------------------
    ratio_off = off["gp"]["goodput_ratio"]
    ratio_on = on["gp"]["goodput_ratio"]
    assert ratio_on > ratio_off, (ratio_on, ratio_off)
    print(f"   goodput ratio: {ratio_off:.3f} -> {ratio_on:.3f}")

    # -- 7: the report renders the overlap block ----------------------
    from bigdl_tpu.obs.report import build_report, render_text

    rep = build_report(os.path.join(on["tmp"], "trace"),
                       os.path.join(on["tmp"], "metrics"))
    ov = rep["overlap"]
    assert (ov["buckets"] or 0) > 1 and ov["async_checkpoint_writes"], ov
    text = render_text(rep)
    assert "-- overlap --" in text and "buckets" in text, text
    print(f"   report: overlap block renders ({int(ov['buckets'])} "
          f"buckets, {ov['async_checkpoint_writes']} async write(s), "
          f"exposed comm {ov['exposed_comm_fraction']:.2f})")

    results = {
        "steps": steps, "hosts": n, "batch_delay_s": BATCH_DELAY,
        "buckets": on["buckets"],
        "worst_step_rel": worst,
        "exchange_bytes_total": on["exchange_bytes"],
        "comm_fraction": {"off": comm_off, "on": comm_on},
        "data_wait_s": {"off": wait_off, "on": wait_on},
        "checkpoint_save_s": {"off": ck_off, "on": ck_on},
        "goodput_ratio": {"off": ratio_off, "on": ratio_on},
        "exposed_comm_fraction": ov["exposed_comm_fraction"],
        "wall_s": {"off": off["wall"], "on": on["wall"]},
    }
    with open(OUT, "w", encoding="utf-8") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
    print(f"   banked {OUT}")
    print("== overlap smoke PASS ==")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
