#!/usr/bin/env python
"""Quantized-collectives smoke — the ISSUE 9 acceptance, end to end.

Driven by ``scripts/run-tests.sh --wire``.  One process, a 2-"host"
(2 forced CPU devices) data mesh — the same simulated-host convention
as the other smokes — A/B-ing DistriOptimizer's gradient wire over a
else-identical 200-step run:

1. **f32 baseline** — uncompressed psum_scatter exchange;
2. **int8 + error feedback** — the staged in-reduce ring
   (parallel/wire.py): per-hop re-quantization, f32 accumulation, the
   per-device residual carried across steps;
3. **fp8_e4m3 + error feedback** — same ring at the fp8 design point.

Asserted, not eyeballed:

* golden byte counts: each run's ``bigdl_collective_bytes_total``
  matches the static cost model (``staged_ring_exchange_bytes``) times
  the step count, exactly;
* ``bigdl_collective_wire_savings_ratio{path="grad"}`` >= 3.2 for both
  compressed wires (the EQuARX headline the int8 wire measured in PR 3,
  now also true of fp8);
* loss-trajectory agreement: with EF on, every step of the int8 and
  fp8 trajectories stays within ``TOL`` of the f32 baseline (the
  error-feedback claim — without EF the same run drifts ~10x further,
  also measured and reported);
* the EF residual really lives in the optimizer state (shape, liveness).

Results are banked to ``WIRE_SMOKE.json`` at the repo root, which
``bench.py`` folds into its BENCH JSON as ``extras.wire``.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

STEPS = 200
TOL = 0.05  # per-step relative loss agreement gate (EF wires vs f32)
BLOCK = 64
OUT = os.path.join(REPO, "WIRE_SMOKE.json")


def main():
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from bigdl_tpu import obs
    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.nn import (
        ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
    )
    from bigdl_tpu.obs import collectives as C
    from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

    Engine.init()
    import jax

    n = 2
    assert len(jax.devices()) == n, jax.devices()

    rng = np.random.RandomState(0)
    w = rng.randn(16, 4)
    x = rng.randn(256, 16).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    epochs = STEPS // (256 // 32)

    class Tape:
        def __init__(self):
            self.loss = {}

        def add_scalar(self, tag, value, step):
            if tag == "Loss":
                self.loss[step] = float(value)

        def add_histogram(self, *a, **k):
            pass

        def get_summary_trigger(self, name):
            return None

        def add_resilience(self, step, **c):
            pass

    def counter(op, dtype):
        fam = obs.get_registry().counter(
            "bigdl_collective_bytes_total", labels=("op", "dtype"))
        return fam.labels(op=op, dtype=dtype).value

    def savings():
        fam = obs.get_registry().gauge(
            "bigdl_collective_wire_savings_ratio", labels=("path",))
        return fam.labels(path="grad").value

    def run(**kw):
        obs.reset()
        RandomGenerator.RNG.set_seed(7)
        model = Sequential().add(Linear(16, 32)).add(ReLU()) \
            .add(Linear(32, 4)).add(LogSoftMax())
        opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                              batch_size=32, **kw)
        opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
        opt.set_end_when(Trigger.max_epoch(epochs))
        tape = Tape()
        opt.set_train_summary(tape)
        opt.optimize()
        return tape, opt

    print(f"== wire smoke: {STEPS}-step A/B on a {n}-host mesh ==")
    base, base_opt = run(wire_dtype="float32")
    assert len(base.loss) == STEPS, len(base.loss)
    padded = base_opt._flat_elems + base_opt._pad
    f32_per_step = C.reduce_scatter_bytes(padded, "float32", n)
    got = counter("psum_scatter", "float32")
    assert got == f32_per_step * STEPS, (got, f32_per_step * STEPS)
    print(f"   f32 baseline: final loss {base.loss[STEPS]:.6f}, "
          f"{f32_per_step:.0f} exchange B/step")

    def compare(tape):
        rels = [abs(tape.loss[s] - base.loss[s])
                / (abs(base.loss[s]) + 1e-9) for s in sorted(base.loss)]
        return max(rels), max(rels[-20:])

    results = {"steps": STEPS, "block": BLOCK, "hosts": n,
               "f32_final_loss": base.loss[STEPS], "wires": {}}

    for dtype in ("int8", "fp8_e4m3"):
        tape, opt = run(wire_dtype=dtype, wire_block=BLOCK, wire_ef=True)
        padded = opt._flat_elems + opt._pad
        spec = opt.wire
        ex = C.staged_ring_exchange_bytes(padded, n, BLOCK,
                                          spec.wire_name)
        for name, per_step in ex.items():
            got = counter("ring_rs", name)
            assert got == per_step * STEPS, (dtype, name, got,
                                             per_step * STEPS)
        ratio = savings()
        wire_per_step = sum(ex.values())
        assert ratio >= 3.2, (dtype, ratio)
        worst, tail = compare(tape)
        assert worst < TOL, (dtype, worst)
        ef = np.asarray(opt.optim_method.state["wire_ef"])
        assert ef.shape == (n, padded) and np.abs(ef).sum() > 0

        # the same wire WITHOUT error feedback, for the EF headline
        tape_noef, _ = run(wire_dtype=dtype, wire_block=BLOCK)
        worst_noef, _ = compare(tape_noef)
        print(f"   {dtype + '-EF':12s} savings {ratio:.2f}x "
              f"({wire_per_step:.0f} B/step), worst step rel "
              f"{worst:.4f} (no-EF drifts to {worst_noef:.4f}), "
              f"final loss {tape.loss[STEPS]:.6f}")
        results["wires"][dtype] = {
            "savings_ratio": ratio,
            "wire_bytes_per_step": wire_per_step,
            "f32_bytes_per_step": f32_per_step,
            "worst_step_rel_vs_f32": worst,
            "tail_rel_vs_f32": tail,
            "worst_step_rel_no_ef": worst_noef,
            "final_loss": tape.loss[STEPS],
        }
        assert worst < worst_noef, (
            "error feedback did not improve trajectory agreement")

    with open(OUT, "w", encoding="utf-8") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
    print(f"   banked {OUT}")
    print("== wire smoke PASS ==")


if __name__ == "__main__":
    main()
