#!/usr/bin/env bash
# Test runner (VERDICT r2 #9; reference: dl/src/test run-tests*.sh).
# Forces the 8-virtual-device CPU backend the suite expects (the
# reference's local[4]-Spark-master trick, SURVEY.md §4.5) and runs
# pytest.  Usage: scripts/run-tests.sh [pytest args]
#   scripts/run-tests.sh --chaos [pytest args]   # only the fault-injection
#                                                # / recovery specs (-m chaos)
#   scripts/run-tests.sh --trace [pytest args]   # observability smoke: tiny
#                                                # traced train loops that
#                                                # assert a well-formed Chrome
#                                                # trace + Prometheus snapshot
#                                                # (-m obs)
#   scripts/run-tests.sh --obs-report            # distributed-obs smoke: a
#                                                # 2-host traced 10-step
#                                                # DistriOptimizer run, shard
#                                                # merge, report render, and
#                                                # the perf-regression gate
#                                                # against a synthetic
#                                                # trajectory (no pytest)
#   scripts/run-tests.sh --elastic               # supervisor chaos smoke: a
#                                                # 2-host run fault-killed at
#                                                # step 7, restarted by the
#                                                # real supervisor at world
#                                                # size 1; asserts the resumed
#                                                # loss trajectory, the
#                                                # bigdl_resumes_total{
#                                                # resize="2to1"} counter, and
#                                                # a cross-attempt goodput
#                                                # ratio with nonzero rework
#                                                # badput (no pytest)
#   scripts/run-tests.sh --tune                  # auto-tuner smoke: tunes one
#                                                # attention, one conv+BN, one
#                                                # serving decode_attn and one
#                                                # int8_mm shape on CPU
#                                                # (interpret mode, measured
#                                                # candidates), asserts a
#                                                # persisted JSON cache,
#                                                # re-runs with zero
#                                                # re-measurements, and checks
#                                                # the report's kernel
#                                                # auto-tuner section
#                                                # (no pytest)
#   scripts/run-tests.sh --goodput               # goodput smoke: a 2-host
#                                                # traced run with a
#                                                # synthetically starved input
#                                                # pipeline -> aggregate ->
#                                                # report; asserts the goodput
#                                                # section renders (text +
#                                                # --json) and the bottleneck
#                                                # classifier says input_bound
#                                                # (no pytest)
#   scripts/run-tests.sh --wire                  # quantized-collectives
#                                                # smoke: a 2-host 200-step
#                                                # A/B of the f32 vs int8-EF
#                                                # vs fp8-EF gradient wires,
#                                                # asserting golden byte
#                                                # counts, savings ratio >=
#                                                # 3.2x, and loss-trajectory
#                                                # agreement with error
#                                                # feedback on; banks
#                                                # WIRE_SMOKE.json for BENCH
#                                                # extras.wire (no pytest)
#   scripts/run-tests.sh --autoscale             # autoscaling + streaming
#                                                # smoke: the REAL supervisor
#                                                # + policy loop resize a
#                                                # streaming training child
#                                                # 1->2->1 from live queue
#                                                # signals; asserts resumed
#                                                # trajectory equivalence, an
#                                                # exactly-once stream audit
#                                                # (every record id trained
#                                                # once across both resizes),
#                                                # and the resize/decision
#                                                # counters; banks
#                                                # AUTOSCALE_SMOKE.json for
#                                                # BENCH extras.autoscale
#                                                # (no pytest)
#   scripts/run-tests.sh --overlap               # overlapped-step smoke: a
#                                                # 2-host 160-step A/B of
#                                                # overlap on (bucketed
#                                                # exchange + async ckpt +
#                                                # double-buffered input) vs
#                                                # off, asserting per-step
#                                                # trajectory equivalence,
#                                                # unchanged golden exchange
#                                                # bytes, lower comm/input
#                                                # badput fractions, smaller
#                                                # checkpoint_save badput and
#                                                # a strictly higher goodput
#                                                # ratio; banks
#                                                # OVERLAP_SMOKE.json for
#                                                # BENCH extras.overlap
#                                                # (no pytest)
#   scripts/run-tests.sh --serve                 # serving-tier smoke: the
#                                                # continuous-batching LM
#                                                # engine A/B'd against
#                                                # static batching on one
#                                                # bursty request trace
#                                                # (must win tokens/sec at
#                                                # equal-or-better p99), the
#                                                # flash-decode kernel A/B
#                                                # (tuner-dispatched fused
#                                                # path must beat the dense
#                                                # full-width gather >=1.15x
#                                                # at equal p99, token-
#                                                # identical),
#                                                # concurrent HTTP clients
#                                                # against an int8 ResNet +
#                                                # the LM decoder, a queue-
#                                                # driven autoscale decision
#                                                # scraped off the live
#                                                # /metrics endpoint, and
#                                                # the report's serving
#                                                # section; banks
#                                                # SERVE_SMOKE.json for
#                                                # BENCH extras.serve
#                                                # (no pytest)
#   scripts/run-tests.sh --router                # serving router smoke: the
#                                                # three data-plane chaos
#                                                # scenarios (preemption
#                                                # storm, brownout, drain
#                                                # wave) at 8 replicas on the
#                                                # virtual clock with the
#                                                # REAL placement / retry-
#                                                # budget / handoff-ledger
#                                                # policies in the loop (zero
#                                                # lost, zero duplicated,
#                                                # amplification <= the
#                                                # budget factor, SLO-burn
#                                                # never flaps), then the
#                                                # real-engine segment:
#                                                # temperature-0 routed
#                                                # output bit-equal to direct
#                                                # generate(), a mid-decode
#                                                # drain replayed exactly
#                                                # once on the survivor, the
#                                                # full RouterServer ->
#                                                # ServingServer HTTP
#                                                # topology, and queue-full
#                                                # 503 + Retry-After; banks
#                                                # ROUTER_SMOKE.json for
#                                                # BENCH extras.router
#                                                # (no pytest)
#   scripts/run-tests.sh --rollout               # live-weight-rollout smoke:
#                                                # a checkpoint watcher hot-
#                                                # swaps a published version
#                                                # into a live engine mid-
#                                                # decode (in-flight request
#                                                # finishes, pages stable,
#                                                # post-swap output bit-equal
#                                                # to generate() on the new
#                                                # weights), torn and corrupt
#                                                # publishes are rejected by
#                                                # the verify gate without
#                                                # touching serving state, a
#                                                # canary controller promotes
#                                                # a clean version and rolls
#                                                # back a divergent one
#                                                # exactly once (cooldown
#                                                # refuses the re-offer), and
#                                                # the weight_rollout chaos
#                                                # scenario passes all
#                                                # rollout invariants; banks
#                                                # ROLLOUT_SMOKE.json for
#                                                # BENCH extras.rollout
#                                                # (no pytest)
#   scripts/run-tests.sh --reqtrace              # request-tracing smoke: a
#                                                # router over two live
#                                                # engines with one rigged
#                                                # slow replica, every trace
#                                                # kept; routed tokens must
#                                                # bit-match generate() and
#                                                # the report's request-
#                                                # traces section must blame
#                                                # the slow decile on the
#                                                # queue hop with >= 90%
#                                                # attribution coverage;
#                                                # banks REQTRACE_SMOKE.json
#                                                # for BENCH extras.reqtrace
#                                                # (no pytest)
#   scripts/run-tests.sh --lint                  # graftlint static analysis:
#                                                # JAX hazards (JX*), lock
#                                                # discipline (CC*), config/
#                                                # metric registry drift (RD*)
#                                                # over bigdl_tpu + scripts,
#                                                # gated on the checked-in
#                                                # .graftlint-baseline.json
#                                                # (also runs in tier-1 via
#                                                # tests/test_lint.py::
#                                                # test_repo_is_clean)
#   scripts/run-tests.sh --fleet                 # fleet-scale control-plane
#                                                # simulator: the chaos
#                                                # scenario matrix (diurnal
#                                                # wave, stragglers,
#                                                # partition, cascading
#                                                # preemptions, flapping +
#                                                # poisoned sink, latency
#                                                # wave) at 200 synthetic
#                                                # hosts against the REAL
#                                                # autoscaler / alert engine
#                                                # / fleet aggregator on a
#                                                # virtual clock; all
#                                                # invariants must pass
#                                                # (no-flap convergence,
#                                                # exactly-once alert
#                                                # episodes, O(hosts)
#                                                # aggregation, conservative
#                                                # scrape degradation, free
#                                                # preemption restarts);
#                                                # banks FLEET_SIM.json for
#                                                # BENCH extras.fleet
#                                                # (no pytest)
#   scripts/run-tests.sh --fleetobs              # fleet-scale metrics
#                                                # pipeline smoke: the three
#                                                # pinned invariants at 1000
#                                                # simulated hosts on a
#                                                # virtual clock with real
#                                                # registries — hierarchical
#                                                # rollup bit-equal to the
#                                                # flat merge (fleet p99
#                                                # identical), top-K
#                                                # cardinality + memory +
#                                                # scrape-wall bounds, and
#                                                # skewed/partitioned hosts
#                                                # excluded-and-accounted —
#                                                # plus the 1000-address
#                                                # bounded scrape pool and a
#                                                # retention-store
#                                                # downsample/replay pass;
#                                                # banks FLEETOBS_SMOKE.json
#                                                # for BENCH extras.fleetobs
#                                                # (no pytest)
#   scripts/run-tests.sh --prof                  # continuous-profiling +
#                                                # debug-bundle smoke: a
#                                                # rigged run with one
#                                                # synthetically hot span
#                                                # (must take >= 50% of the
#                                                # profiler's self-time at
#                                                # < 1% measured overhead),
#                                                # one fired alert that must
#                                                # cut exactly ONE manifest-
#                                                # valid black-box bundle
#                                                # (profile + traces +
#                                                # metrics + ring inside),
#                                                # /profilez + /debugz over
#                                                # live HTTP, and the
#                                                # report's profiles section
#                                                # (text + --json); banks
#                                                # PROF_SMOKE.json for BENCH
#                                                # extras.prof (no pytest)
#   scripts/run-tests.sh --live                  # live-telemetry smoke: a
#                                                # 2-host run with /metrics +
#                                                # /healthz servers on
#                                                # ephemeral ports, scraped
#                                                # mid-run; fleet snapshot
#                                                # merged from both; a goodput
#                                                # SLO alert fires during a
#                                                # starved window and resolves
#                                                # after; report --watch
#                                                # --once renders the alerts
#                                                # section; the supervisor
#                                                # hang watchdog restarts a
#                                                # deliberately wedged child
#                                                # (no pytest)
# The chaos and obs specs are deterministic and part of the default
# selection; the flags are the focused loops for hacking on those layers.
set -euo pipefail
cd "$(dirname "$0")/.."

export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export JAX_PLATFORMS=cpu

MARKER=()
if [[ "${1:-}" == "--chaos" ]]; then
  shift
  MARKER=(-m chaos)
elif [[ "${1:-}" == "--trace" ]]; then
  shift
  MARKER=(-m obs)
elif [[ "${1:-}" == "--obs-report" ]]; then
  shift
  exec python scripts/obs_smoke.py "$@"
elif [[ "${1:-}" == "--elastic" ]]; then
  shift
  exec python scripts/elastic_smoke.py "$@"
elif [[ "${1:-}" == "--goodput" ]]; then
  shift
  exec python scripts/goodput_smoke.py "$@"
elif [[ "${1:-}" == "--tune" ]]; then
  shift
  exec python scripts/tune_smoke.py "$@"
elif [[ "${1:-}" == "--lint" ]]; then
  shift
  exec python -m bigdl_tpu.analysis.lint "$@"
elif [[ "${1:-}" == "--prof" ]]; then
  shift
  exec python scripts/prof_smoke.py "$@"
elif [[ "${1:-}" == "--live" ]]; then
  shift
  exec python scripts/live_smoke.py "$@"
elif [[ "${1:-}" == "--fleet" ]]; then
  shift
  exec python scripts/fleet_sim.py "$@"
elif [[ "${1:-}" == "--fleetobs" ]]; then
  shift
  exec python scripts/fleetobs_smoke.py "$@"
elif [[ "${1:-}" == "--autoscale" ]]; then
  shift
  exec python scripts/autoscale_smoke.py "$@"
elif [[ "${1:-}" == "--wire" ]]; then
  shift
  exec python scripts/wire_smoke.py "$@"
elif [[ "${1:-}" == "--overlap" ]]; then
  shift
  exec python scripts/overlap_smoke.py "$@"
elif [[ "${1:-}" == "--serve" ]]; then
  shift
  exec python scripts/serve_smoke.py "$@"
elif [[ "${1:-}" == "--router" ]]; then
  shift
  exec python scripts/router_smoke.py "$@"
elif [[ "${1:-}" == "--reqtrace" ]]; then
  shift
  exec python scripts/reqtrace_smoke.py "$@"
elif [[ "${1:-}" == "--rollout" ]]; then
  shift
  exec python scripts/rollout_smoke.py "$@"
fi

# tier-1 wall clock is budgeted (ROADMAP: 870s) — print where the suite
# sits so creeping cost is visible on every run, not just when it blows
START=$(date +%s)
set +e
python -m pytest tests/ -q "${MARKER[@]}" "$@"
rc=$?
set -e
ELAPSED=$(( $(date +%s) - START ))
BUDGET=870
echo "[run-tests] wall clock: ${ELAPSED}s of the ${BUDGET}s tier-1 budget ($(( ELAPSED * 100 / BUDGET ))%)"
exit $rc
