#!/usr/bin/env python
"""Serving data-plane router smoke — chaos matrix + the real engines.

Driven by ``scripts/run-tests.sh --router``.  Two halves:

1. **Chaos matrix** (``bigdl_tpu/sim/serve.py``): the three builtin
   serving scenarios at >= 8 replicas on the virtual clock, with the
   REAL router policies in the loop — placement, the shared
   retry-budget token bucket, the exactly-once handoff ledger:

   * ``preemption_storm`` — half the fleet preempted at once; the
     survivors absorb the dumped queues (claim-gated replays), the
     overflow is shed with explicit 503s, the SLO-burn alert fires
     once and resolves, and not one request is lost or duplicated;
   * ``brownout`` — a 40x-slow replica; retries stay inside the
     budget's amplification ceiling while zombie completions are
     discarded, never double-answered;
   * ``drain_wave`` — replicas drain under a diurnal wave with zero
     dropped, zero duplicated, zero shed requests.

2. **Real engines**: a :class:`Router` over two live
   :class:`LMEngine` replicas — temperature-0 outputs routed (with
   session affinity) must BIT-MATCH the direct ``generate()``
   reference; then one replica drains mid-decode and the checkpointed
   request must replay on the survivor exactly once and still
   bit-match; finally the full HTTP topology (RouterServer ->
   HTTPReplica -> ServingServer) serves a routed request end to end
   and a queue-full admission answers 503 + ``Retry-After``.

Banks ``ROUTER_SMOKE.json`` at the repo root; bench.py folds it into
BENCH ``extras.router`` — the artifact future routing-policy PRs
regress against.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run_matrix(args) -> list:
    from bigdl_tpu.sim import SERVE_SCENARIOS, run_serve_scenario

    names = ([s.strip() for s in args.scenarios.split(",") if s.strip()]
             if args.scenarios else list(SERVE_SCENARIOS))
    results = []
    failed = []
    for name in names:
        res = run_serve_scenario(name, seed=args.seed)
        results.append(res)
        print("SMOKE " + res.summary())
        for inv in res.invariants:
            print("   ", inv)
        assert res.replicas >= 8, \
            f"{res.name}: chaos scenarios must run at >= 8 replicas"
        assert res.wall_s <= args.budget_s, \
            (f"scenario {res.name} took {res.wall_s:.1f}s — over the "
             f"{args.budget_s:.0f}s budget")
        if not res.ok:
            failed.append(res.name)
    assert not failed, f"serve scenario invariants FAILED: {failed}"
    # the matrix must exercise every recovery surface at least once
    assert sum(r.handoff_replays for r in results) > 0, \
        "no scenario replayed a handoff"
    assert sum(r.retries for r in results) > 0, \
        "no scenario spent retry budget"
    assert sum(r.shed for r in results) > 0, \
        "no scenario shed load — the budget ceiling went untested"
    assert all(r.lost == 0 and r.duplicates == 0 for r in results)
    return results


def run_real_engines(args) -> dict:
    """Router over two live engines: bit-equality, drain/handoff,
    and the full HTTP topology."""
    import threading

    import numpy as np

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm
    from bigdl_tpu.serving import LMEngine, ServingServer
    from bigdl_tpu.serving.router import (EngineReplica, HTTPReplica,
                                          Router, RouterServer)

    RandomGenerator.RNG.set_seed(13)
    model = build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                 max_len=64, attn_impl="xla")
    params = model.params()

    def ref(prompt, n):
        return list(np.asarray(model.generate(
            params, np.asarray(prompt)[None, :], n))[0])

    e1 = LMEngine(model, max_batch=2, page_size=8).start()
    e2 = LMEngine(model, max_batch=2, page_size=8).start()
    router = Router([EngineReplica("r1", e1), EngineReplica("r2", e2)],
                    request_timeout_s=120.0)
    rs = np.random.RandomState(args.seed)
    prompts = [rs.randint(0, 48, (n,)).tolist() for n in (5, 9, 4, 7)]
    for p in prompts:
        out = router.route(p, 8, session="smoke-session")
        assert [int(t) for t in list(p) + out["tokens"]] == ref(p, 8), \
            f"routed output diverged from direct generate() for {p}"
    aff = router.placement.stats()
    assert aff["affinity_hits"] >= len(prompts) - 1, aff
    print(f"SMOKE router bit-equality: {len(prompts)} routed requests "
          f"token-identical to direct generate() "
          f"({aff['affinity_hits']} affinity hits)")

    # drain the session's bound replica mid-decode; the checkpointed
    # request must finish on the survivor, bit-equal, exactly once
    bound = router.placement.lookup("smoke-session")
    long_p = rs.randint(0, 48, (6,)).tolist()
    res = {}
    t = threading.Thread(target=lambda: res.update(
        router.route(long_p, 24, session="smoke-session")))
    t.start()
    time.sleep(0.3)
    drain = router.begin_drain(bound, deadline_s=0.05)
    t.join(60)
    assert res, "drained request never completed"
    assert [int(x) for x in list(long_p) + res["tokens"]] \
        == ref(long_p, 24), "handoff replay diverged"
    assert res["handoffs"] >= 1 and res["replica"] != bound, res
    ledger = router.ledger.stats()
    assert ledger["duplicates"] == 0, ledger
    print(f"SMOKE drain/handoff: {bound} drained mid-decode, request "
          f"replayed on {res['replica']} bit-equal "
          f"({drain['handoffs']} checkpoint(s), 0 duplicates)")
    e1.close()
    e2.close()

    # full HTTP topology: RouterServer -> HTTPReplica -> ServingServer
    e3 = LMEngine(model, max_batch=2, page_size=8).start()
    e4 = LMEngine(model, max_batch=2, page_size=8).start()
    s3, s4 = ServingServer(lm=e3), ServingServer(lm=e4)
    http_router = Router(
        [HTTPReplica("h1", f"127.0.0.1:{s3.port}"),
         HTTPReplica("h2", f"127.0.0.1:{s4.port}")],
        request_timeout_s=120.0)
    front = RouterServer(http_router)
    import urllib.request

    p = prompts[0]
    body = json.dumps({"prompt": p, "max_new_tokens": 8,
                       "session": "http-session"}).encode()
    with urllib.request.urlopen(urllib.request.Request(
            front.url("/v1/generate"), data=body,
            headers={"Content-Type": "application/json"}),
            timeout=120) as r:
        out = json.loads(r.read())
    assert [int(x) for x in list(p) + out["tokens"]] == ref(p, 8), \
        "HTTP-routed output diverged from direct generate()"
    with urllib.request.urlopen(front.url("/healthz"), timeout=10) as r:
        health = json.loads(r.read())
    assert set(health["replicas"].values()) == {"up"}, health
    print(f"SMOKE http topology: RouterServer:{front.port} -> 2x "
          f"ServingServer routed bit-equal, /healthz reports "
          f"{health['replicas']}")

    # queue-full admission at the replica answers 503 + Retry-After
    import urllib.error

    e_small = LMEngine(model, max_batch=1, page_size=8,
                       queue_capacity=1)
    s_small = ServingServer(lm=e_small, request_timeout_s=0.05)
    e_small.submit([1, 2, 3], 4)           # occupies the queue
    code, retry_after = None, None
    try:
        urllib.request.urlopen(urllib.request.Request(
            s_small.url("/v1/generate"),
            data=json.dumps({"prompt": [1], "max_new_tokens": 2}
                            ).encode(),
            headers={"Content-Type": "application/json"}), timeout=10)
    except urllib.error.HTTPError as e:
        code, retry_after = e.code, e.headers.get("Retry-After")
    assert code == 503 and retry_after is not None, \
        f"queue-full admission answered {code} " \
        f"(Retry-After={retry_after!r}), want 503 + Retry-After"
    print(f"SMOKE backpressure: queue-full admission answered 503 "
          f"Retry-After={retry_after}")
    for closer in (front.close, s3.close, s4.close, s_small.close,
                   e3.close, e4.close, e_small.close):
        closer()
    return {
        "bit_equal_requests": len(prompts),
        "affinity_hits": aff["affinity_hits"],
        "drain": {"replica": bound, "handoffs": drain["handoffs"],
                  "replayed_on": res["replica"],
                  "duplicates": ledger["duplicates"]},
        "http_ok": True,
        "queue_full_status": code,
        "retry_after": retry_after,
    }


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/router_smoke.py",
        description="Serving router chaos matrix + real-engine "
                    "bit-equality smoke (BIGDL_ROUTER_* knobs are the "
                    "env spelling of the router's config).")
    ap.add_argument("--scenarios", default=None,
                    help="comma-separated builtin serve scenarios "
                         "(default: all three)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--budget-s", type=float, default=60.0,
                    help="per-scenario wall-clock budget (default 60)")
    ap.add_argument("--skip-engines", action="store_true",
                    help="chaos matrix only (no jax model build)")
    args = ap.parse_args()

    import tempfile

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_router_smoke_")
    obs_dir = os.path.join(smoke_dir, "obs")
    os.environ["BIGDL_TRACE_DIR"] = obs_dir
    os.environ["BIGDL_METRICS_DIR"] = obs_dir

    t0 = time.monotonic()
    results = run_matrix(args)
    engines = None if args.skip_engines else run_real_engines(args)
    total_wall = time.monotonic() - t0
    print(f"SMOKE router: {len(results)} scenario(s) PASS in "
          f"{total_wall:.1f}s")

    bank = {
        "seed": args.seed,
        "total_wall_s": round(total_wall, 2),
        "scenarios": [r.to_dict() for r in results],
        "engines": engines,
    }
    with open(os.path.join(REPO, "ROUTER_SMOKE.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True, default=str)
    print("ROUTER SMOKE PASS (banked ROUTER_SMOKE.json)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
