#!/usr/bin/env python
"""--tune smoke: the kernel auto-tuner loop, end to end on CPU.

Driven by ``scripts/run-tests.sh --tune``.  Four stages, each a hard
assert:

1. a FRESH process (``BIGDL_TUNER=1``, ``BIGDL_TUNER_MEASURE=1``, CPU
   interpret mode) tunes one attention shape, one conv+BN shape, one
   serving ``decode_attn`` shape (flash-decode over the paged KV
   cache) and one ``int8_mm`` shape through the real ``impl="auto"``
   dispatchers, measures candidates (wall clock), and must persist a
   well-formed JSON cache under ``BIGDL_TUNER_CACHE`` with one
   decision per site;
2. a SECOND fresh process re-runs the same shapes against the same
   cache and must serve every decision from it: zero cache misses,
   zero wall-clock re-measurements (the chip-unavailable-round
   contract — decisions survive restarts);
3. numerics under the tuner must match the untuned reference exactly
   (whatever impl won, the answer is the same);
4. ``python -m bigdl_tpu.obs.report`` over the run's trace/metrics
   dirs renders the "kernel auto-tuner" section — decision counts by
   site/impl, cache traffic, and the ``tuner.decision`` events — in
   text AND ``--json``.

Exit 0 only when all four hold.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys
sys.path.insert(0, os.environ["BIGDL_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from bigdl_tpu import obs
from bigdl_tpu.ops import autotune
from bigdl_tpu.ops.attention import _reference_attention
from bigdl_tpu.ops.conv_bn import _reference

# one attention site (concrete arrays -> measurable) ...
out = autotune.prewarm_attention(1, 2, 128, 256, 16, causal=True)
rs = np.random.RandomState(0)
q = jnp.asarray(rs.randn(1, 2, 128, 16).astype(np.float32))
k = jnp.asarray(rs.randn(1, 2, 256, 16).astype(np.float32))
v = jnp.asarray(rs.randn(1, 2, 256, 16).astype(np.float32))
ref = _reference_attention(q, k, v, causal=True, scale=16 ** -0.5)
from bigdl_tpu.ops.attention import dot_product_attention
got = dot_product_attention(q, k, v, causal=True)
np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=3e-5)

# ... and one conv+BN site (the restored kxk stride-2 regime)
y, s1, s2 = autotune.prewarm_conv_bn(2, 8, 8, 8, 16, 3, stride=2, pad=1)
x = jnp.asarray(rs.randn(2, 8, 8, 8).astype(np.float32))
w = jnp.asarray((rs.randn(16, 8, 3, 3) * 0.1).astype(np.float32))
sh = jnp.asarray(rs.randn(16).astype(np.float32))
from bigdl_tpu.ops.conv_bn import conv_bn_stats
yt, s1t, s2t = conv_bn_stats(x, w, sh, stride=2, pad=1)
yr, s1r, s2r = _reference(x, w, sh, 2, 1)
np.testing.assert_allclose(np.asarray(yt), np.asarray(yr), atol=1e-4,
                           rtol=1e-4)

# ... the serving decode_attn site (flash-decode over the paged cache):
# the measured prewarm must agree with the static dense path
from bigdl_tpu.ops.decode_attention import paged_decode_attention
got = autotune.prewarm_decode_attn(2, 2, 16, page_size=8, maxp=2, seed=3)
rs2 = np.random.RandomState(3)
pool = 2 * 2 + 1
qd = jnp.asarray(rs2.randn(2, 2, 16).astype(np.float32))
kpd = jnp.asarray(rs2.randn(pool, 2, 8, 16).astype(np.float32))
vpd = jnp.asarray(rs2.randn(pool, 2, 8, 16).astype(np.float32))
lens = jnp.asarray(rs2.randint(1, 16, (2,)).astype(np.int32))
tbls = jnp.asarray(rs2.randint(1, pool, (2, 2)).astype(np.int32))
refd = paged_decode_attention(qd, kpd, vpd, tbls, lens, page_size=8,
                              impl="dense")
np.testing.assert_allclose(np.asarray(got), np.asarray(refd), atol=1e-5)

# ... and the int8_mm site the int8 decode matmuls ride
autotune.prewarm_int8_mm(4, 32, 64)

summ = autotune.summary()
obs.flush()
print("TUNER_SUMMARY " + __import__("json").dumps(summ), flush=True)
"""


def run(script, **env):
    e = dict(os.environ)
    e.update({k: str(v) for k, v in env.items()})
    e["BIGDL_REPO"] = REPO
    e["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable, "-c", script], env=e,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=600)


def _summary(proc):
    for line in (proc.stdout or "").splitlines():
        if line.startswith("TUNER_SUMMARY "):
            return json.loads(line[len("TUNER_SUMMARY "):])
    raise AssertionError(
        f"worker printed no summary\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        cache = os.path.join(d, "tuner_cache.json")
        trace = os.path.join(d, "trace")
        metrics = os.path.join(d, "metrics")
        env = dict(BIGDL_TUNER=1, BIGDL_TUNER_CACHE=cache,
                   BIGDL_TUNER_MEASURE=1, BIGDL_TRACE_DIR=trace,
                   BIGDL_METRICS_DIR=metrics)

        # ---- stage 1: cold tune must persist the cache --------------
        p1 = run(_WORKER, **env)
        assert p1.returncode == 0, (p1.stdout[-2000:], p1.stderr[-2000:])
        s1 = _summary(p1)
        assert os.path.exists(cache), "no cache file persisted"
        doc = json.load(open(cache, encoding="utf-8"))
        assert doc["version"] == 1
        sites = {r["site"] for r in doc["decisions"].values()}
        assert sites == {"attn", "conv_bn_kxk", "decode_attn",
                         "int8_mm"}, sites
        assert s1["cache"]["misses"] >= 4
        for rec in doc["decisions"].values():
            assert rec["source"] == "measured", rec
            assert rec["measured_s"], rec
        da = [r for r in doc["decisions"].values()
              if r["site"] == "decode_attn"]
        assert da and "dense" in da[0]["measured_s"], da
        assert any(lbl.startswith("fused") for lbl in
                   da[0]["measured_s"]), da
        print(f"[tune_smoke] cold run: {len(doc['decisions'])} "
              f"measured decision(s) persisted -> {cache}")

        # ---- stage 2: warm re-run serves everything from cache ------
        p2 = run(_WORKER, **env)
        assert p2.returncode == 0, (p2.stdout[-2000:], p2.stderr[-2000:])
        s2 = _summary(p2)
        assert s2["cache"]["misses"] == 0, s2["cache"]
        assert s2["cache"]["hits"] >= 4, s2["cache"]
        doc2 = json.load(open(cache, encoding="utf-8"))
        assert doc2["decisions"] == doc["decisions"], \
            "warm run mutated the cache"
        print(f"[tune_smoke] warm run: {s2['cache']['hits']} hit(s), "
              "0 misses, 0 re-measurements")

        # ---- stage 3: report renders the tuner section --------------
        e = dict(os.environ, BIGDL_REPO=REPO, JAX_PLATFORMS="cpu")
        rep = subprocess.run(
            [sys.executable, "-m", "bigdl_tpu.obs.report", trace,
             "--metrics-dir", metrics],
            env=e, cwd=REPO, capture_output=True, text=True, timeout=300)
        assert rep.returncode == 0, rep.stderr[-2000:]
        assert "-- kernel auto-tuner --" in rep.stdout, rep.stdout
        assert "attn:" in rep.stdout and "conv_bn_kxk:" in rep.stdout, \
            rep.stdout
        assert "decode_attn:" in rep.stdout and "int8_mm:" in \
            rep.stdout, rep.stdout
        assert "wall-clock probe(s)" in rep.stdout
        rep_j = subprocess.run(
            [sys.executable, "-m", "bigdl_tpu.obs.report", trace,
             "--metrics-dir", metrics, "--json"],
            env=e, cwd=REPO, capture_output=True, text=True, timeout=300)
        assert rep_j.returncode == 0, rep_j.stderr[-2000:]
        tn = json.loads(rep_j.stdout)["tuner"]
        assert tn["decisions_total"], tn
        assert tn["measurements"] >= 2, tn
        assert any(ev.get("site") == "attn" for ev in tn["events"]), tn
        print("[tune_smoke] report renders the kernel auto-tuner "
              "section (text + --json)")
    print("[tune_smoke] OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
