#!/usr/bin/env bash
# Launcher — the scripts/bigdl.sh + dist/conf/spark-bigdl.conf analogue.
#
# The reference launches one JVM executor per node via spark-submit with
# required conf (locality off, min-resources 1.0, speculation off) and
# env (KMP_AFFINITY, OMP_NUM_THREADS).  The TPU rebuild launches one JAX
# process per host; multi-host bring-up rides the same env-var contract
# Engine.init reads (SURVEY.md §2.5 "spark-submit remains only as a
# launcher").
#
# Single host:
#   scripts/bigdl_tpu.sh python -m bigdl_tpu.models.lenet -e 2
#
# Multi-host (run on every host, same coordinator):
#   BIGDL_COORDINATOR_ADDRESS=host0:8476 \
#   BIGDL_NUM_PROCESSES=4 BIGDL_PROCESS_ID=<i> \
#   scripts/bigdl_tpu.sh python -m bigdl_tpu.models.resnet --distributed
#
# Under Spark, set these from the executor context:
#   BIGDL_COORDINATOR_ADDRESS=$(spark-conf spark.driver.host):8476
#   BIGDL_NUM_PROCESSES=$SPARK_EXECUTOR_INSTANCES
#   BIGDL_PROCESS_ID=$SPARK_EXECUTOR_ID

set -euo pipefail

# --- reference env parity -------------------------------------------------
# the reference pins MKL threading (OMP_NUM_THREADS=1, KMP_AFFINITY) so
# Spark task threads don't oversubscribe; on TPU the host-side analogue
# keeps BLAS single-threaded for the feeding path and leaves the chip to
# XLA.
export OMP_NUM_THREADS="${OMP_NUM_THREADS:-1}"
export KMP_AFFINITY="${KMP_AFFINITY:-granularity=fine,compact,1,0}"

# TPU runtime knobs (safe defaults; override freely)
export JAX_PLATFORMS="${JAX_PLATFORMS:-}"
export XLA_FLAGS="${XLA_FLAGS:-}"

# pass through the multi-host contract if set
: "${BIGDL_COORDINATOR_ADDRESS:=}"
: "${BIGDL_NUM_PROCESSES:=}"
: "${BIGDL_PROCESS_ID:=}"

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}"

if [[ $# -eq 0 ]]; then
    echo "usage: $0 <command> [args...]" >&2
    echo "  e.g. $0 python -m bigdl_tpu.models.lenet -e 2" >&2
    exit 2
fi

exec "$@"
