#!/usr/bin/env python
"""Fleet-scale control-plane simulator smoke — the chaos matrix at 200
hosts, for real.

Driven by ``scripts/run-tests.sh --fleet``.  Stands up hundreds of
synthetic ``/metrics`` + ``/healthz`` hosts in THIS process
(``bigdl_tpu/sim``) and runs the REAL control plane against them — the
real :class:`AutoscaleController` fed by the real
:class:`EndpointScraper`/:class:`FleetAggregator` bounded-pool scrape,
a real per-host :class:`AlertEngine` — through the builtin chaos
scenario matrix on a virtual clock:

* ``diurnal`` — a traffic wave the autoscaler must ride up and back
  down without one flap inside a cooldown window;
* ``stragglers`` — correlated 6x stragglers; the slowest host gates
  the fleet step-time signal, one alert episode per slow host;
* ``partition`` — 30% of peers time out (with real wall-clock stalls):
  absent signals never breach a rule, and the concurrent scrape keeps
  the cycle wall bounded where a serial scrape would pay N × timeout;
* ``preemptions`` — a cascading preemption of a quarter of the fleet;
  survivors inherit the load, the controller buys exactly one
  doubling, each survivor alerts exactly once;
* ``flapping`` — flapping hosts + a poisoned alert sink; the world
  never thrashes, sink failures are counted (never wedging), and the
  real Supervisor rides the flapping child without spending one unit
  of retry budget;
* ``alert_storm`` — three fleet-wide goodput dips with the debug-bundle
  plane armed (``BIGDL_BUNDLE_DIR`` + rate limit off): every firing
  transition must cut exactly ONE manifest-valid black-box bundle —
  none dropped, none duplicated, none torn;
* ``latency_wave`` — a fleet-wide p99 wave through the serving
  latency-histogram signal path.

Every scenario's invariants must PASS; on top the smoke asserts the
O(hosts) aggregation budget at 200 hosts, renders the report's fleet
section (text + ``--json``), and banks ``FLEET_SIM.json`` (bench.py
folds it into BENCH ``extras.fleet``) — the artifact every future
policy PR regresses against.
"""

import argparse
import json
import logging
import os
import sys
import time

# the atexit obs flush imports jax (device memory stats) — pin CPU or
# this container's TPU plugin probes the GCP metadata service forever
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_SCENARIOS = ("diurnal", "stragglers", "partition",
                     "preemptions", "flapping", "alert_storm",
                     "latency_wave")


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/fleet_sim.py",
        description="Run the chaos scenario matrix against the real "
                    "control plane at fleet scale (BIGDL_FLEET_* knobs "
                    "are the env spelling of these flags).")
    ap.add_argument("--hosts", type=int, default=None,
                    help="synthetic host count (default "
                         "BIGDL_FLEET_HOSTS = 200)")
    ap.add_argument("--scenarios", default=None,
                    help="comma-separated builtin names / JSON / paths "
                         "(default BIGDL_FLEET_SCENARIO or the full "
                         "matrix)")
    ap.add_argument("--seed", type=int, default=None,
                    help="default BIGDL_FLEET_SEED")
    ap.add_argument("--compression", type=float, default=None,
                    help="time-compression factor (default "
                         "BIGDL_FLEET_TIME_COMPRESSION)")
    ap.add_argument("--budget-s", type=float, default=90.0,
                    help="per-scenario wall-clock budget (default 90)")
    ap.add_argument("--agg-budget-s", type=float, default=1.5,
                    help="200-host aggregation snapshot budget "
                         "(default 1.5)")
    ap.add_argument("--partition-stall-s", type=float, default=0.02,
                    help="real wall stall a partitioned fetch costs "
                         "(default 0.02)")
    args = ap.parse_args()

    import tempfile

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_fleet_sim_")
    obs_dir = os.path.join(smoke_dir, "obs")
    os.environ["BIGDL_TRACE_DIR"] = obs_dir
    os.environ["BIGDL_METRICS_DIR"] = obs_dir

    from bigdl_tpu import obs
    from bigdl_tpu.config import refresh_from_env
    from bigdl_tpu.sim import run_scenario
    from bigdl_tpu.sim.invariants import check_aggregation_scaling

    # the poisoned-sink scenario logs one warning per failed delivery
    # (hundreds at 200 hosts); the invariant counts them — keep the
    # smoke output readable
    logging.getLogger("bigdl_tpu.obs").setLevel(logging.ERROR)

    fcfg = refresh_from_env().fleet
    hosts = args.hosts if args.hosts is not None else fcfg.hosts
    seed = args.seed if args.seed is not None else fcfg.seed
    compression = (args.compression if args.compression is not None
                   else fcfg.time_compression)
    spec = args.scenarios if args.scenarios is not None else \
        fcfg.scenario
    scenarios = ([s.strip() for s in spec.split(",") if s.strip()]
                 if spec and not spec.lstrip().startswith(("{", "["))
                 else ([spec] if spec else list(DEFAULT_SCENARIOS)))
    assert len(scenarios) >= 3 or spec, \
        "the smoke needs >= 3 scenarios to mean anything"
    assert hosts >= 200 or args.hosts is not None, \
        f"fleet smoke runs at >= 200 hosts, got {hosts}"

    print(f"FLEET SIM: {len(scenarios)} scenario(s) at {hosts} hosts "
          f"(seed {seed}, compression {compression:g}x, per-scenario "
          f"budget {args.budget_s:.0f}s)")
    results = []
    failed = []
    # the bundle plane is armed ONLY for alert_storm: with it global,
    # every firing transition in every scenario would cut a bundle and
    # the other scenarios' wall budgets would be paying for it
    bundles_dir = os.path.join(obs_dir, "bundles")
    t_total0 = time.monotonic()
    for name in scenarios:
        if name == "alert_storm":
            os.environ["BIGDL_BUNDLE_DIR"] = bundles_dir
            os.environ["BIGDL_BUNDLE_RATE_LIMIT"] = "0"
        else:
            os.environ.pop("BIGDL_BUNDLE_DIR", None)
        res = run_scenario(name, hosts=hosts, seed=seed,
                           time_compression=compression,
                           partition_stall_s=args.partition_stall_s)
        results.append(res)
        print("SMOKE " + res.summary())
        for inv in res.invariants:
            print("   ", inv)
        if not res.ok:
            failed.append(res.name)
        assert res.wall_s <= args.budget_s, \
            (f"scenario {res.name} took {res.wall_s:.1f}s — over the "
             f"{args.budget_s:.0f}s budget")
    total_wall = time.monotonic() - t_total0
    os.environ.pop("BIGDL_BUNDLE_DIR", None)
    assert not failed, f"scenario invariants FAILED: {failed}"
    decided = sum(len(r.decisions) for r in results)
    episodes = sum(r.episodes for r in results)
    bundled = sum(r.bundles for r in results)
    if "alert_storm" in scenarios:
        assert bundled > 0, \
            "alert_storm ran but the bundle plane cut no bundles"
    if spec is None:
        # the default matrix must exercise both policy surfaces; a
        # user-supplied scenario is allowed to target just one (its
        # own expect block carries the real assertions)
        assert decided > 0, "no scenario produced an autoscale decision"
        assert episodes > 0, "no scenario produced an alert episode"
    print(f"SMOKE scenarios: {len(results)} PASS in {total_wall:.1f}s "
          f"({decided} decisions, {episodes} alert episodes, "
          f"{bundled} debug bundles)")

    # --- O(hosts) aggregation budget at fleet scale -------------------
    agg = check_aggregation_scaling(hosts, args.agg_budget_s, seed=seed)
    print("SMOKE", agg)
    assert agg.ok, agg.detail

    # --- the report's fleet section, text + --json --------------------
    obs.flush()
    from bigdl_tpu.obs.report import build_report, render_text

    rep = build_report(obs_dir, obs_dir)
    assert rep.get("fleet"), "report grew no fleet section"
    scen_names = {e.get("scenario") for e in rep["fleet"]["scenarios"]}
    assert scen_names >= set(r.name for r in results), scen_names
    text = render_text(rep)
    assert "-- fleet simulation --" in text
    for r in results:
        assert f"{r.name:14s} PASS" in text, \
            f"{r.name} verdict missing from report text:\n{text}"
    assert "scrape cycle:" in text, text
    print("SMOKE report: fleet section renders all "
          f"{len(results)} scenario verdicts + scrape latency")
    if bundled:
        # the bundles landed under <metrics_dir>/bundles, so the
        # report's profiles section must inventory them unprompted
        pr = rep.get("profiles") or {}
        assert pr.get("bundles_valid"), \
            f"report found no valid bundles: {pr}"
        assert "-- profiles --" in text and "bundles:" in text, text
        print(f"SMOKE report: profiles section inventories "
              f"{pr['bundles_valid']} manifest-valid bundle(s)")

    # --- bank ---------------------------------------------------------
    bank = {
        "hosts": hosts,
        "seed": seed,
        "time_compression": compression,
        "partition_stall_s": args.partition_stall_s,
        "total_wall_s": round(total_wall, 2),
        "scenarios": [r.to_dict() for r in results],
        "aggregation": {"ok": agg.ok, "detail": agg.detail},
        "decisions": decided,
        "episodes": episodes,
        "bundles": bundled,
    }
    with open(os.path.join(REPO, "FLEET_SIM.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True, default=str)
    print("FLEET SIM PASS (banked FLEET_SIM.json)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
