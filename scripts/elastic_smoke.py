#!/usr/bin/env python
"""Supervisor chaos smoke — the elastic story end-to-end, for real.

Driven by ``scripts/run-tests.sh --elastic``.  The parent runs the REAL
restart supervisor (``bigdl_tpu.resilience.supervisor``) over a real
training child:

1. launch 0: a 2-"host" (2 forced CPU devices) DistriOptimizer run,
   checkpointing every epoch, with a fault plan killing it at step 7
   (``step:7:raise`` + ``max_retry=0`` — the in-process retry budget is
   deliberately empty, so the process dies with the TRANSIENT exit
   code after the epoch-1 checkpoint is on disk);
2. the supervisor classifies the exit, burns one retry-budget slot,
   and relaunches with ``BIGDL_ELASTIC_ATTEMPT=1``;
3. launch 1: the child comes back at world size **1**, resumes via
   ``elastic.restore_latest`` (the 2-shard checkpoint re-partitions for
   the 1-shard mesh), and trains to completion;
4. the parent then runs an uninterrupted 1-host baseline from the same
   seeds and asserts the resumed loss trajectory matches step-for-step,
   and that the resumed child's metrics shard recorded
   ``bigdl_resumes_total{resize="2to1"} 1``;
5. the goodput ledger (obs/goodput.py) aggregated ACROSS the two
   attempts via ``python -m bigdl_tpu.obs.report --json`` shows a
   cross-attempt goodput ratio in (0, 1) with nonzero ``rework``
   (the replayed steps between the restored step and the crashed
   attempt's high-water mark) and nonzero ``checkpoint_restore``
   badput.

Everything is subprocesses — the parent never imports jax — so the
smoke also exercises the exit-code contract exactly as a launcher
would.
"""

import glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
KILL_STEP = 7
EPOCHS = 4  # 128 samples / batch 32 = 4 steps per epoch -> 16 steps


def child():
    attempt = int(os.environ.get("BIGDL_ELASTIC_ATTEMPT", "0"))
    world = 2 if attempt == 0 else 1
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count"
                                 f"={world}")
    if attempt == 0:
        os.environ["BIGDL_FAULT_PLAN"] = f"step:{KILL_STEP}:raise"
    else:
        os.environ.pop("BIGDL_FAULT_PLAN", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.nn import (
        ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
    )
    from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger
    from bigdl_tpu.resilience import elastic

    smoke_dir = os.environ["BIGDL_SMOKE_DIR"]
    Engine.init()
    assert len(jax.devices()) == world, jax.devices()
    RandomGenerator.RNG.set_seed(7)
    model = Sequential().add(Linear(16, 32)).add(ReLU()) \
        .add(Linear(32, 4)).add(LogSoftMax())
    rng = np.random.RandomState(0)
    w = rng.randn(16, 4)
    x = rng.randn(128, 16).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    opt = DistriOptimizer(model, ArrayDataSet(x, y, 32, shuffle=False),
                          ClassNLLCriterion(), batch_size=32,
                          wire_dtype="none")
    opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
    opt.set_end_when(Trigger.max_epoch(EPOCHS))
    opt.set_checkpoint(os.path.join(smoke_dir, "ckpt"),
                       Trigger.every_epoch())
    opt.max_retry = 0  # first transient failure kills the process

    losses = {}

    class Tape:
        def add_scalar(self, tag, value, step):
            if tag == "Loss":
                losses[step] = float(value)

        def add_histogram(self, *a, **k):
            pass

        def get_summary_trigger(self, name):
            return None

        def add_resilience(self, *a, **k):
            pass

    opt.set_train_summary(Tape())
    extra = elastic.restore_latest(opt)
    print(f"SMOKE_CHILD attempt={attempt} world={world} "
          f"resumed={extra is not None} "
          f"from_world={(extra or {}).get('topology', {}).get('world_size')}",
          flush=True)

    def train():
        try:
            opt.optimize()
        finally:
            out = os.path.join(smoke_dir, f"losses.attempt{attempt}.json")
            with open(out, "w", encoding="utf-8") as fh:
                json.dump(losses, fh)

    sys.exit(elastic.run_main(train))


def baseline(smoke_dir, env):
    """Uninterrupted 1-host run from the same seeds (a fresh child with
    attempt forced to 1 and an empty checkpoint dir)."""
    bdir = os.path.join(smoke_dir, "baseline")
    os.makedirs(bdir, exist_ok=True)
    benv = dict(env)
    benv["BIGDL_SMOKE_DIR"] = bdir
    benv["BIGDL_ELASTIC_ATTEMPT"] = "1"
    # the baseline's obs shards must not pollute the supervised run's
    # cross-attempt goodput aggregation
    benv["BIGDL_METRICS_DIR"] = bdir
    benv["BIGDL_TRACE_DIR"] = bdir
    subprocess.run([sys.executable, os.path.abspath(__file__),
                    "--child"], env=benv, check=True)
    with open(os.path.join(bdir, "losses.attempt1.json"),
              encoding="utf-8") as fh:
        return {int(k): v for k, v in json.load(fh).items()}


def main():
    import tempfile

    from bigdl_tpu.resilience.elastic import EXIT_TRANSIENT
    from bigdl_tpu.resilience.supervisor import Supervisor

    smoke_dir = tempfile.mkdtemp(prefix="bigdl_elastic_smoke_")
    obs_dir = os.path.join(smoke_dir, "obs")
    # instant restarts for the supervisor's own RetryPolicy too (it
    # reads the live config of THIS process)
    os.environ["BIGDL_RETRY_BACKOFF_BASE"] = "0"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env.update(BIGDL_SMOKE_DIR=smoke_dir, BIGDL_METRICS_DIR=obs_dir,
               BIGDL_TRACE_DIR=obs_dir,
               BIGDL_RETRY_BACKOFF_BASE="0", PYTHONPATH=REPO)

    rcs = []

    def runner(cmd, child_env):
        rc = subprocess.call(cmd, env={**env, **{
            k: child_env[k] for k in ("BIGDL_ELASTIC_ATTEMPT",
                                      "BIGDL_ELASTIC_PREEMPTIONS")}})
        rcs.append(rc)
        return rc

    sup = Supervisor(
        [sys.executable, os.path.abspath(__file__), "--child"],
        max_retries=3, runner=runner, sleep=lambda s: None)
    rc = sup.run()
    assert rc == 0, f"supervisor gave up with rc {rc} (children: {rcs})"
    assert rcs == [EXIT_TRANSIENT, 0], \
        f"expected one transient kill then success, got {rcs}"
    print(f"SMOKE supervisor: launches={sup.attempt} child_rcs={rcs}")

    # --- resumed trajectory must match an uninterrupted 1-host run ----
    with open(os.path.join(smoke_dir, "losses.attempt1.json"),
              encoding="utf-8") as fh:
        resumed = {int(k): v for k, v in json.load(fh).items()}
    base = baseline(smoke_dir, env)
    assert resumed, "resumed child recorded no losses"
    worst = 0.0
    for step, val in sorted(resumed.items()):
        assert step in base, f"resumed step {step} not in baseline"
        rel = abs(val - base[step]) / max(1.0, abs(base[step]))
        worst = max(worst, rel)
        assert rel < 1e-3, \
            f"loss diverged at step {step}: {val} vs {base[step]}"
    print(f"SMOKE trajectory: {len(resumed)} resumed steps match the "
          f"uninterrupted baseline (worst rel err {worst:.2e})")

    # --- the resize was counted in the resumed child's metrics shard --
    proms = glob.glob(os.path.join(obs_dir, "metrics.*.prom"))
    assert proms, f"no metrics shards under {obs_dir}"
    blob = "".join(open(p, encoding="utf-8").read() for p in proms)
    needle = 'bigdl_resumes_total{resize="2to1"} 1'
    assert needle in blob, \
        f"{needle!r} not found in metrics shards:\n{blob[-2000:]}"
    print(f"SMOKE metrics: found {needle!r}")

    # --- cross-attempt goodput: the ledger shards of BOTH attempts
    # aggregate into one ratio, with the restart's cost visible -------
    # (the report CLI imports the bigdl_tpu package, which imports jax:
    # pin the CPU platform so it never probes for a TPU — the training
    # children pin it themselves, which is why env dropped it above)
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.report", obs_dir,
         "--json"], env={**env, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    gp = rep["goodput"]
    assert gp, f"report has no goodput section: {rep.keys()}"
    assert gp["attempts"] >= 2, gp
    ratio = gp["goodput_ratio"]
    assert ratio is not None and 0 < ratio < 1, gp
    assert gp["badput_s"].get("checkpoint_restore", 0) > 0, \
        f"no checkpoint_restore badput: {gp['badput_s']}"
    assert gp["badput_s"].get("rework", 0) > 0, \
        f"no rework badput (replayed steps not re-tagged): {gp}"
    assert gp["rework_steps"] > 0, gp
    print(f"SMOKE goodput: ratio {ratio:.3f} across {gp['attempts']} "
          f"attempts, rework {gp['badput_s']['rework'] * 1000:.1f}ms "
          f"({gp['rework_steps']} steps), restore "
          f"{gp['badput_s']['checkpoint_restore'] * 1000:.1f}ms")
    print("ELASTIC SMOKE PASS")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child()
    else:
        main()
