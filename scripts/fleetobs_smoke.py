#!/usr/bin/env python
"""Fleet-scale metrics pipeline smoke — the pinned invariants at 1000
simulated hosts, for real.

Driven by ``scripts/run-tests.sh --fleetobs``.  Stands up 1000
synthetic hosts in THIS process (``bigdl_tpu/sim`` — each a genuine
``MetricsRegistry`` exposition and ``health_payload`` surface) on a
virtual clock and runs the REAL metrics pipeline over them:

* **exactness** — a two-tier leaf→root rollup
  (``obs/rollup.py::build_tiers``, ~√N fan-in) must reproduce the flat
  single-tier merge **bit-equally** (counters, gauges, cumulative
  ``_bucket``/``_count`` samples; the float ``_sum`` alone gets its
  last ulp) and derive the identical fleet p99 from merged buckets;
* **bounds** — with the top-K cardinality cap active no family tracks
  more than K+1 logical series (the +1 is the ``other`` fold), every
  drop is counted, the node's self-scraped memory stays proportional
  to the bound (not to N hosts), and the scrape wall stays inside its
  budget;
* **staleness** — a skewed-clock host and a partitioned host are
  excluded from every merge and accounted in the stale map +
  ``bigdl_fleet_stale_hosts``, while the fleet p99 still derives from
  the live remainder;
* the **scrape pool** — one bounded-pool round over all 1000
  addresses with a rigged dead minority must land inside
  ``ceil(N / workers) × timeout`` and surface per-host errors without
  failing the round;
* the **retention store** — the fleet trend signals ingested per
  cycle downsample into the 10s/1m rings and replay losslessly from
  the torn-write-safe JSONL.

Banks ``FLEETOBS_SMOKE.json`` (bench.py folds it into BENCH
``extras.fleetobs``) — the artifact every future metrics-plane PR
regresses against.
"""

import argparse
import dataclasses
import json
import math
import os
import sys
import tempfile
import time

# the atexit obs flush imports jax (device memory stats) — pin CPU or
# this container's TPU plugin probes the GCP metadata service forever
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="scripts/fleetobs_smoke.py",
        description="Prove the fleet metrics pipeline (hierarchical "
                    "rollup, cardinality bounds, staleness exclusion, "
                    "bounded scrape pool, retention store) at scale.")
    ap.add_argument("--hosts", type=int,
                    default=int(os.environ.get("BIGDL_FLEET_HOSTS",
                                               "1000")),
                    help="simulated fleet size (default 1000)")
    ap.add_argument("--top-k", type=int, default=8,
                    help="rollup cardinality bound for the bounds probe")
    ap.add_argument("--budget-s", type=float, default=60.0,
                    help="scrape wall budget for the bounds probe")
    args = ap.parse_args()
    n = int(args.hosts)
    # √N-balanced shards: 1000 hosts -> ~32 leaves of ~32
    shard = max(2, int(round(math.sqrt(n))))

    from bigdl_tpu import obs
    from bigdl_tpu.obs.aggregate import FleetAggregator
    from bigdl_tpu.obs.retain import RetentionStore
    from bigdl_tpu.sim import invariants as inv

    t0 = time.perf_counter()
    results = []

    obs.reset()
    res = inv.check_rollup_exactness(n_hosts=n, shard_size=shard)
    print(f"SMOKE {res}")
    assert res.ok, res.detail
    results.append(res)

    obs.reset()
    res = inv.check_rollup_bounds(n_hosts=n, shard_size=shard,
                                  top_k=int(args.top_k),
                                  budget_s=float(args.budget_s))
    print(f"SMOKE {res}")
    assert res.ok, res.detail
    results.append(res)

    obs.reset()
    res = inv.check_staleness_exclusion(
        n_hosts=n, skew_id=n // 3, partition_id=(2 * n) // 3)
    print(f"SMOKE {res}")
    assert res.ok, res.detail
    results.append(res)

    # --- the bounded scrape pool over every address, one round --------
    obs.reset()
    from bigdl_tpu.sim import SimFleet, VirtualClock

    clock = VirtualClock()
    fleet = SimFleet(n, clock, seed=0)
    fleet.tick(1.0)
    dead = list(range(0, n, max(1, n // 10)))[:10]
    for h in dead:
        fleet.hosts[h].up = False
    workers, timeout_s = 64, 2.0
    agg = FleetAggregator(peers=fleet.addrs, fetch=fleet.fetch,
                          timeout_s=timeout_s, max_workers=workers,
                          clock=clock.now)
    scraped = agg.scrape_peers(agg.peers)
    bound = math.ceil(n / workers) * timeout_s
    assert agg.last_scrape_s <= bound, \
        f"scrape wall {agg.last_scrape_s:.2f}s > bound {bound:.2f}s"
    errors = {p["addr"]: p["error"] for p in scraped if not p["ok"]}
    assert len(scraped) == n and len(errors) == len(dead), \
        f"round lost peers: {len(scraped)}/{n}, {len(errors)} errors"
    print(f"SMOKE scrape pool: {n} addresses in "
          f"{agg.last_scrape_s * 1000:.0f}ms (bound {bound:.0f}s), "
          f"{len(errors)} dead peer(s) surfaced, round intact")

    # --- retention: ingest a few cycles, downsample, replay -----------
    with tempfile.TemporaryDirectory(prefix="bigdl-fleetobs-") as d:
        store = RetentionStore(directory=d)
        cycles = 30
        for i in range(cycles):
            fleet.tick(5.0)
            snap = agg.snapshot()
            store.ingest_snapshot(clock.now(), snap)
        summary = store.summary()
        assert summary, "retention store retained nothing"
        downsampled = any(v["n_10s"] < v["n"] for v in summary.values())
        assert downsampled, f"10s ring never downsampled: {summary}"
        replay = RetentionStore(directory=d)
        n_replayed = store_points = replay.load()
        assert replay.summary() == summary, "replay diverged from live"
    print(f"SMOKE retention: {cycles} cycles -> "
          f"{len(summary)} series, {n_replayed} point(s) replayed "
          "bit-equal from JSONL")

    total_wall = time.perf_counter() - t0
    bank = {
        "hosts": n,
        "shard_size": shard,
        "top_k": int(args.top_k),
        "total_wall_s": round(total_wall, 2),
        "invariants": [dataclasses.asdict(r) for r in results],
        "scrape_pool": {
            "addresses": n,
            "workers": workers,
            "wall_s": round(agg.last_scrape_s, 4),
            "bound_s": bound,
            "dead_surfaced": len(errors),
        },
        "retention": {
            "cycles": cycles,
            "series": len(summary),
            "replayed_points": store_points,
            "summary": summary,
        },
    }
    with open(os.path.join(REPO, "FLEETOBS_SMOKE.json"), "w",
              encoding="utf-8") as fh:
        json.dump(bank, fh, indent=2, sort_keys=True, default=str)
    print(f"FLEETOBS PASS in {total_wall:.1f}s "
          "(banked FLEETOBS_SMOKE.json)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
