"""Which Pallas/Mosaic programs does the relay's remote-compile accept?

Round-5 context: bench.py's fused segment died with MosaicError (HTTP
500 from the relay's tpu_compile_helper) while the transformer secondary
— whose attention layer auto-routes to the Pallas flash kernel on TPU —
completed.  This probe runs each Mosaic kernel in its own subprocess
with a hard timeout and prints one status line per rung, so one run says
whether Mosaic is rejected wholesale or per-kernel.

    python scripts/mosaic_probe.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RUNGS = [
    ("flash_attn", "flash attention fwd (2,4,256,64)"),
    ("flash_attn_bwd", "flash attention + lax-recompute bwd"),
    ("conv_bn_1x1", "fused 1x1 conv+BN stats (8,64,16,16)"),
    ("conv_bn_3x3", "fused 3x3 conv+BN stats (8,64,16,16)"),
]


def _run_rung(name: str):
    import numpy as np

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "axon")
    dev = jax.devices()[0]
    t0 = time.time()
    rs = np.random.RandomState(0)

    if name.startswith("flash_attn"):
        from bigdl_tpu.ops.attention import flash_attention

        q = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        if name == "flash_attn":
            flash_attention(q, q, q, causal=True).block_until_ready()
        else:
            jax.grad(
                lambda a: flash_attention(a, a, a, causal=True).sum()
            )(q).block_until_ready()
    else:
        from bigdl_tpu.ops.conv_bn import conv_bn_stats

        x = jnp.asarray(rs.randn(8, 64, 16, 16),
                        dtype=jnp.bfloat16)
        k = 1 if name.endswith("1x1") else 3
        w = jnp.asarray(rs.randn(64, 64, k, k) * 0.05,
                        dtype=jnp.bfloat16)
        shift = jnp.zeros(64, jnp.float32)

        @jax.jit
        def f(x, w, shift):
            y, s1, s2 = conv_bn_stats(x, w, shift,
                                      pad=(k - 1) // 2)
            return y.sum() + s1.sum() + s2.sum()

        f(x, w, shift).block_until_ready()
    print(json.dumps({"rung": name, "ok": True,
                      "device": dev.device_kind,
                      "seconds": round(time.time() - t0, 1)}))


def main():
    if os.environ.get("MOSAIC_PROBE_CHILD"):
        _run_rung(os.environ["MOSAIC_PROBE_CHILD"])
        return
    for name, desc in RUNGS:
        t0 = time.time()
        env = dict(os.environ, MOSAIC_PROBE_CHILD=name)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=240, env=env,
            )
            ok = proc.returncode == 0
            tail = (proc.stdout or proc.stderr or "").strip().splitlines()
            detail = tail[-1][:200] if tail else ""
        except subprocess.TimeoutExpired:
            ok, detail = False, "TIMEOUT 240s"
        print(f"{name:16s} {desc:42s} {'OK' if ok else 'FAIL'} "
              f"{time.time()-t0:6.1f}s  {detail}", flush=True)


if __name__ == "__main__":
    main()
