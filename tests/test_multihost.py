"""Multi-host contract test (VERDICT r1 weak 4 / item 6).

The spark-submit parity seam: the launcher exports
``BIGDL_COORDINATOR_ADDRESS`` / ``BIGDL_NUM_PROCESSES`` /
``BIGDL_PROCESS_ID`` and ``Engine.init`` joins the world via
``jax.distributed.initialize`` (SURVEY.md §2.5 — "spark-submit remains
only as a launcher that starts one JAX process per host").

Here: two REAL OS processes, each with 2 forced host devices, run the
REAL DistriOptimizer (shard_map + psum_scatter/all_gather over the
4-device global mesh) and must agree bit-for-bit on the final loss —
the CPU analogue of the reference's local[4]-master DistriOptimizerSpec.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, os.environ["BIGDL_REPO"])
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") \\
        + " --xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from bigdl_tpu.engine import Engine

    Engine.init()
    assert len(jax.devices()) == 4, jax.devices()
    assert len(jax.local_devices()) == 2

    from bigdl_tpu.nn import (
        ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
    )
    from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger
    from bigdl_tpu.common import RandomGenerator
    RandomGenerator.RNG.set_seed(42)

    rng = np.random.RandomState(0)
    w = rng.randn(16, 4)
    x = rng.randn(128, 16).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    model = Sequential().add(Linear(16, 32)).add(ReLU()) \\
        .add(Linear(32, 4)).add(LogSoftMax())
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(3))
    opt.optimize()
    print("FINAL_LOSS %.9f" % opt.state["loss"], flush=True)

    # distributed evaluation: each process folds only its shard of the
    # per-process dataset; the monoids must allreduce so every host
    # reports the GLOBAL accuracy (VERDICT r3 review finding)
    from bigdl_tpu.dataset import DistributedDataSet
    from bigdl_tpu.optim import Top1Accuracy
    from bigdl_tpu.optim.evaluator import evaluate_dataset

    val = DistributedDataSet(x, y, 32, shuffle=False)
    (acc,) = evaluate_dataset(model, val, [Top1Accuracy()])
    value, count = acc.result()
    assert count == 128, count  # global sample count, not the local 64
    print("VAL_ACC %.9f" % value, flush=True)

    # ragged dataset (134 = 4*32 + 6): the per-process iterator must
    # repeat-pad the tail to the process multiple and the trainer's
    # masked step must pad the local slice to the device multiple —
    # both processes end bit-identical (VERDICT r3 items 5/7 seam)
    RandomGenerator.RNG.set_seed(43)
    x2 = rng.randn(134, 16).astype(np.float32)
    y2 = (np.argmax(x2 @ w, axis=1) + 1).astype(np.float32)
    m2 = Sequential().add(Linear(16, 32)).add(ReLU()) \\
        .add(Linear(32, 4)).add(LogSoftMax())
    ds2 = DistributedDataSet(x2, y2, 32, shuffle=False)
    opt2 = DistriOptimizer(m2, ds2, ClassNLLCriterion(), batch_size=32)
    opt2.set_optim_method(SGD(learningrate=0.5))
    opt2.set_end_when(Trigger.max_epoch(2))
    opt2.optimize()
    print("RAGGED_LOSS %.9f" % opt2.state["loss"], flush=True)

    # int8 blockwise wire: the quantized all_to_all exchange must work
    # across REAL process boundaries too (payload + scales cross the
    # distributed backend), and both hosts must agree bit-for-bit
    RandomGenerator.RNG.set_seed(44)
    m3 = Sequential().add(Linear(16, 32)).add(ReLU()) \\
        .add(Linear(32, 4)).add(LogSoftMax())
    opt3 = DistriOptimizer(m3, (x, y), ClassNLLCriterion(), batch_size=32,
                           wire_dtype="int8", int8_block=64)
    opt3.set_optim_method(SGD(learningrate=0.5))
    opt3.set_end_when(Trigger.max_epoch(2))
    opt3.optimize()
    print("INT8_LOSS %.9f" % opt3.state["loss"], flush=True)
    """
)


_PROBE = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") \\
        + " --xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    jax.distributed.initialize(
        coordinator_address=os.environ["PROBE_COORD"],
        num_processes=2, process_id=int(os.environ["PROBE_PID"]))
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))
    # the two transfer paths DistriOptimizer uses on a multi-host CPU
    # world — exactly what this container's jax build is known to reject
    a = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), np.ones((2, 1), np.float32))
    b = jax.device_put(np.ones((4,), np.float32),
                       NamedSharding(mesh, P()))
    print("PROBE_OK", float(jax.jit(lambda x: x.sum())(b)), flush=True)
    """
)

_probe_cache = None


def _multiprocess_cpu_support():
    """Probe (once per pytest process) whether this jax build supports
    multiprocess-CPU device transfer at all.  CHANGES.md PR 4 notes the
    container's build rejects multiprocess CPU ``device_put`` — on such
    a build the full test must SKIP with the probe's reason instead of
    hard-failing on an environment limitation."""
    global _probe_cache
    if _probe_cache is not None:
        return _probe_cache
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        worker = os.path.join(td, "probe.py")
        with open(worker, "w", encoding="utf-8") as fh:
            fh.write(_PROBE)
        port = _free_port()
        procs = []
        for pid in range(2):
            env = dict(os.environ)
            env.pop("JAX_PLATFORMS", None)
            env.update(PROBE_COORD=f"localhost:{port}",
                       PROBE_PID=str(pid))
            procs.append(subprocess.Popen(
                [sys.executable, worker], stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, env=env, text=True))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                _probe_cache = (False, "probe timed out")
                return _probe_cache
            outs.append(out)
        if all(p.returncode == 0 and "PROBE_OK" in o
               for p, o in zip(procs, outs)):
            _probe_cache = (True, "ok")
        else:
            bad = next(o for p, o in zip(procs, outs)
                       if p.returncode != 0 or "PROBE_OK" not in o)
            tail = bad.strip().splitlines()[-1][:300] if bad.strip() \
                else f"rc={procs[0].returncode}"
            _probe_cache = (False, tail)
    return _probe_cache


def _free_port():
    """Coordinator port for this run's 2-process jax.distributed world.

    Plain bind-ephemeral-then-release is racy under CONCURRENT pytest
    runs: both runs can be handed the same just-released port in the
    window before their workers bind it, and the second world's
    coordinator then fails to start (the spurious failure CHANGES.md r3
    flagged).  Deriving the search base from the PID gives concurrent
    runs disjoint probe ranges; each candidate is still bind-checked so
    a genuinely busy port is skipped, and the chosen port is released
    immediately before the workers (which inherit it via
    BIGDL_COORDINATOR_ADDRESS) bind it."""
    base = 20000 + (os.getpid() * 41) % 20000
    for offset in range(256):
        port = 20000 + (base - 20000 + offset) % 20000
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("localhost", port))
        except OSError:
            continue
        finally:
            s.close()
        return port
    raise RuntimeError("no free coordinator port in the PID-derived range")


@pytest.mark.slow
def test_two_process_distri_fit_agrees(tmp_path):
    supported, reason = _multiprocess_cpu_support()
    if not supported:
        pytest.skip("this jax build does not support multiprocess-CPU "
                    f"device transfer (pre-existing container "
                    f"limitation, CHANGES.md PR 4): {reason}")
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(
            BIGDL_REPO=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            BIGDL_COORDINATOR_ADDRESS=f"localhost:{port}",
            BIGDL_NUM_PROCESSES="2",
            BIGDL_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                env=env,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        outs.append(out)
    losses = []
    accs = []
    ragged = []
    for i, out in enumerate(outs):
        assert procs[i].returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        line = [l for l in out.splitlines() if l.startswith("FINAL_LOSS")]
        assert line, f"worker {i} printed no FINAL_LOSS:\n{out[-2000:]}"
        losses.append(line[-1].split()[1])
        aline = [l for l in out.splitlines() if l.startswith("VAL_ACC")]
        assert aline, f"worker {i} printed no VAL_ACC:\n{out[-2000:]}"
        accs.append(aline[-1].split()[1])
        rline = [l for l in out.splitlines() if l.startswith("RAGGED_LOSS")]
        assert rline, f"worker {i} printed no RAGGED_LOSS:\n{out[-2000:]}"
        ragged.append(rline[-1].split()[1])
    int8 = []
    for i, out in enumerate(outs):
        iline = [l for l in out.splitlines() if l.startswith("INT8_LOSS")]
        assert iline, f"worker {i} printed no INT8_LOSS:\n{out[-2000:]}"
        int8.append(iline[-1].split()[1])
    # both processes drive the same global computation: exact agreement
    assert losses[0] == losses[1], losses
    # every host reports the same GLOBAL validation accuracy
    assert accs[0] == accs[1], accs
    # ragged tail (repeat-padded + masked) also agrees bit-for-bit
    assert ragged[0] == ragged[1], ragged
    # quantized all_to_all across process boundaries agrees too
    assert int8[0] == int8[1], int8
