"""Unit tests for the breadth families added in round 2: volumetric
(3-D) layers, locally-connected / separable convs, shrink activations,
noise layers, spatial dropouts, crops/resizes, spatial normalizations,
shape utilities, new table ops, new criterions, and the stacked /
convolutional recurrent cells.

Mirrors the reference's per-layer spec pattern (SURVEY.md §4.1: fixed
seed, small hand-sized tensors, outputs vs hand-computed values) plus a
numeric gradcheck per family (§4.2)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as N


def _rs(seed=0):
    return np.random.RandomState(seed)


def numeric_grad(f, x, eps=1e-3):
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def gradcheck(m, x, rtol=2e-2, atol=2e-3):
    """backward (vjp) vs finite differences of sum(out^2)/2."""
    m.evaluate()
    xj = jnp.asarray(x, jnp.float32)

    def scalar(xv):
        out = m.apply(m.params(), m.state(), jnp.asarray(xv, jnp.float32),
                      training=False)[0]
        return float(jnp.sum(out * out)) / 2.0

    out = m.forward(xj)
    grad_in = m.backward(xj, out)
    np.testing.assert_allclose(
        np.asarray(grad_in), numeric_grad(scalar, x), rtol=rtol, atol=atol
    )


# ---------------------------------------------------------------- volumetric


def test_volumetric_convolution_matches_manual():
    rs = _rs(1)
    m = N.VolumetricConvolution(2, 3, 2, 2, 2)
    x = rs.randn(1, 2, 3, 4, 4).astype(np.float32)
    y = np.asarray(m.forward(jnp.asarray(x)))
    assert y.shape == (1, 3, 2, 3, 3)
    w = np.asarray(m.weight)
    b = np.asarray(m.bias)
    # hand-compute one output element: out[0, o, 0, 0, 0]
    for o in range(3):
        patch = x[0, :, 0:2, 0:2, 0:2]
        expect = (patch * w[o]).sum() + b[o]
        np.testing.assert_allclose(y[0, o, 0, 0, 0], expect, rtol=1e-4)


def test_volumetric_conv_gradcheck():
    rs = _rs(2)
    gradcheck(N.VolumetricConvolution(2, 2, 2, 2, 2),
              rs.randn(1, 2, 3, 3, 3).astype(np.float32))


def test_volumetric_full_convolution_inverts_stride():
    m = N.VolumetricFullConvolution(2, 3, 2, 2, 2, 2, 2, 2)
    x = _rs(3).randn(1, 2, 2, 3, 3).astype(np.float32)
    y = m.forward(jnp.asarray(x))
    # transposed conv: out = (in-1)*stride + k
    assert y.shape == (1, 3, 4, 6, 6)


def test_volumetric_pooling():
    x = np.arange(2 * 1 * 2 * 4 * 4, dtype=np.float32).reshape(2, 1, 2, 4, 4)
    mx = N.VolumetricMaxPooling(2).forward(jnp.asarray(x))
    av = N.VolumetricAveragePooling(2).forward(jnp.asarray(x))
    assert mx.shape == (2, 1, 1, 2, 2)
    # max of the 2x2x2 corner block
    np.testing.assert_allclose(
        np.asarray(mx)[0, 0, 0, 0, 0], x[0, 0, 1, 1, 1]
    )
    np.testing.assert_allclose(
        np.asarray(av)[0, 0, 0, 0, 0],
        x[0, 0, 0:2, 0:2, 0:2].mean(),
        rtol=1e-6,
    )


def test_volumetric_batchnorm_normalizes():
    rs = _rs(4)
    m = N.VolumetricBatchNormalization(3)
    x = (rs.randn(4, 3, 2, 5, 5) * 3 + 1).astype(np.float32)
    m.training()
    y = np.asarray(m.forward(jnp.asarray(x)))
    np.testing.assert_allclose(y.mean(axis=(0, 2, 3, 4)), 0.0, atol=1e-4)
    np.testing.assert_allclose(y.std(axis=(0, 2, 3, 4)), 1.0, atol=1e-2)


def test_upsampling3d_and_cropping3d_roundtrip():
    x = _rs(5).randn(1, 2, 2, 3, 3).astype(np.float32)
    up = N.UpSampling3D((2, 2, 2)).forward(jnp.asarray(x))
    assert up.shape == (1, 2, 4, 6, 6)
    np.testing.assert_allclose(np.asarray(up)[0, 0, 0, 0, 0], x[0, 0, 0, 0, 0])
    crop = N.Cropping3D((1, 1), (2, 2), (2, 2)).forward(up)
    assert crop.shape == (1, 2, 2, 2, 2)


# ------------------------------------------------- locally connected / convs


def test_locally_connected_1d_unshared():
    rs = _rs(6)
    m = N.LocallyConnected1D(6, 3, 2, 3)
    x = rs.randn(2, 6, 3).astype(np.float32)
    y = np.asarray(m.forward(jnp.asarray(x)))
    assert y.shape == (2, 4, 2)
    w = np.asarray(m.weight)  # (T_out, kW*F_in, F_out)
    b = np.asarray(m.bias)
    t = 1
    window = x[0, t:t + 3, :].reshape(-1)
    np.testing.assert_allclose(
        y[0, t], window @ w[t] + b[t], rtol=1e-4, atol=1e-5
    )


def test_locally_connected_2d_matches_manual():
    rs = _rs(7)
    m = N.LocallyConnected2D(2, 4, 4, 3, 2, 2)
    x = rs.randn(1, 2, 4, 4).astype(np.float32)
    y = np.asarray(m.forward(jnp.asarray(x)))
    assert y.shape == (1, 3, 3, 3)
    w = np.asarray(m.weight)  # (O, I*kh*kw, out_h, out_w)
    b = np.asarray(m.bias)
    patch = x[0, :, 1:3, 2:4].reshape(-1)
    for o in range(3):
        np.testing.assert_allclose(
            y[0, o, 1, 2], patch @ w[o, :, 1, 2] + b[o, 1, 2],
            rtol=1e-4, atol=1e-5,
        )


def test_locally_connected_2d_gradcheck():
    gradcheck(N.LocallyConnected2D(1, 3, 3, 2, 2, 2),
              _rs(8).randn(1, 1, 3, 3).astype(np.float32))


def test_separable_conv_equals_depthwise_then_pointwise():
    rs = _rs(9)
    m = N.SpatialSeparableConvolution(2, 3, 2, 3, 3, 1, 1, 1, 1)
    x = rs.randn(1, 2, 5, 5).astype(np.float32)
    y = np.asarray(m.forward(jnp.asarray(x)))
    assert y.shape == (1, 3, 5, 5)
    # compose the two convs manually through lax
    import jax.lax as lax

    mid = lax.conv_general_dilated(
        jnp.asarray(x), m.depth_weight, (1, 1), [(1, 1), (1, 1)],
        feature_group_count=2, dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    expect = lax.conv_general_dilated(
        mid, m.point_weight, (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    ) + np.asarray(m.bias).reshape(1, -1, 1, 1)
    np.testing.assert_allclose(y, np.asarray(expect), rtol=1e-4, atol=1e-5)


def test_share_convolution_is_spatial_convolution():
    m = N.SpatialShareConvolution(2, 3, 3, 3)
    assert isinstance(m, N.SpatialConvolution)
    x = _rs(10).randn(1, 2, 5, 5).astype(np.float32)
    assert m.forward(jnp.asarray(x)).shape == (1, 3, 3, 3)


def test_convolution_map_respects_connection_table():
    # one-to-one table: output plane i sees only input plane i
    m = N.SpatialConvolutionMap(
        N.SpatialConvolutionMap.one_to_one(2), 3, 3, 1, 1, 1, 1
    )
    x = np.zeros((1, 2, 5, 5), np.float32)
    x[0, 0] = 1.0  # only plane 0 carries signal
    m.bias = jnp.zeros_like(m.bias)
    y = np.asarray(m.forward(jnp.asarray(x)))
    assert np.abs(y[0, 1]).max() == 0.0  # plane 1 unconnected to plane 0
    assert np.abs(y[0, 0]).max() > 0.0


def test_temporal_max_pooling():
    x = np.arange(12, dtype=np.float32).reshape(1, 6, 2)
    y = np.asarray(N.TemporalMaxPooling(2).forward(jnp.asarray(x)))
    np.testing.assert_allclose(y, x[:, 1::2, :])


# ----------------------------------------------------------- shrink family


def test_shrink_activations_known_values():
    x = jnp.asarray([-2.0, -0.3, 0.0, 0.3, 2.0])
    np.testing.assert_allclose(
        np.asarray(N.SoftShrink(0.5).forward(x)),
        [-1.5, 0.0, 0.0, 0.0, 1.5],
    )
    np.testing.assert_allclose(
        np.asarray(N.HardShrink(0.5).forward(x)),
        [-2.0, 0.0, 0.0, 0.0, 2.0],
    )
    np.testing.assert_allclose(
        np.asarray(N.TanhShrink().forward(x)),
        np.asarray(x) - np.tanh(np.asarray(x)),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(N.LogSigmoid().forward(x)),
        np.log(1.0 / (1.0 + np.exp(-np.asarray(x)))),
        rtol=1e-5, atol=1e-6,
    )


def test_rrelu_train_bounds_and_eval_slope():
    x = -np.ones((400,), np.float32)
    m = N.RReLU(0.1, 0.4)
    m.training()
    y = np.asarray(m.forward(jnp.asarray(x)))
    assert (y <= -0.1 + 1e-6).all() and (y >= -0.4 - 1e-6).all()
    assert y.std() > 0.0  # actually random
    m.evaluate()
    y = np.asarray(m.forward(jnp.asarray(x)))
    np.testing.assert_allclose(y, -0.25, rtol=1e-6)


# -------------------------------------------------------------- noise layers


def test_gaussian_noise_and_dropout_train_eval():
    x = np.ones((2000,), np.float32)
    gn = N.GaussianNoise(0.5)
    gn.training()
    y = np.asarray(gn.forward(jnp.asarray(x)))
    assert abs(y.std() - 0.5) < 0.1
    gn.evaluate()
    np.testing.assert_allclose(np.asarray(gn.forward(jnp.asarray(x))), x)

    gd = N.GaussianDropout(0.5)
    gd.training()
    y = np.asarray(gd.forward(jnp.asarray(x)))
    assert abs(y.mean() - 1.0) < 0.15  # multiplicative noise, mean 1
    gd.evaluate()
    np.testing.assert_allclose(np.asarray(gd.forward(jnp.asarray(x))), x)


def test_gaussian_sampler_statistics():
    mean = np.full((4000,), 2.0, np.float32)
    log_var = np.full((4000,), np.log(0.25), np.float32)
    m = N.GaussianSampler()
    m.training()
    y = np.asarray(m.forward((jnp.asarray(mean), jnp.asarray(log_var))))
    assert abs(y.mean() - 2.0) < 0.1
    assert abs(y.std() - 0.5) < 0.1


# ---------------------------------------------------------- spatial dropout


def test_spatial_dropout2d_drops_whole_maps():
    m = N.SpatialDropout2D(0.5)
    m.training()
    x = np.ones((4, 16, 5, 5), np.float32)
    y = np.asarray(m.forward(jnp.asarray(x)))
    # each (b, c) map is all-zero or all-2.0 (1/keep scaling)
    per_map = y.reshape(4, 16, -1)
    for b in range(4):
        for c in range(16):
            vals = np.unique(per_map[b, c])
            assert len(vals) == 1 and vals[0] in (0.0, 2.0)
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(x))), x)


def test_spatial_dropout1d_shares_mask_over_time():
    m = N.SpatialDropout1D(0.5)
    m.training()
    x = np.ones((2, 10, 8), np.float32)
    y = np.asarray(m.forward(jnp.asarray(x)))
    # mask constant along T
    assert (y.std(axis=1) < 1e-6).all()


# ------------------------------------------------------------ crop / resize


def test_cropping2d():
    x = _rs(11).randn(1, 2, 6, 8).astype(np.float32)
    y = np.asarray(N.Cropping2D((1, 2), (3, 1)).forward(jnp.asarray(x)))
    np.testing.assert_allclose(y, x[:, :, 1:4, 3:7])


def test_upsampling_1d_2d():
    x = np.arange(4, dtype=np.float32).reshape(1, 2, 2)
    y = np.asarray(N.UpSampling1D(2).forward(jnp.asarray(x)))
    np.testing.assert_allclose(y[0, :, 0], [0, 0, 2, 2])
    img = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    y = np.asarray(N.UpSampling2D((2, 2)).forward(jnp.asarray(img)))
    assert y.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(y[0, 0, :2, :2], 0.0)


def test_resize_bilinear_align_corners_endpoints():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    y = np.asarray(
        N.ResizeBilinear(7, 7, align_corners=True).forward(jnp.asarray(x))
    )
    # corners map exactly onto input corners
    np.testing.assert_allclose(y[0, 0, 0, 0], 0.0, atol=1e-5)
    np.testing.assert_allclose(y[0, 0, -1, -1], 15.0, atol=1e-5)
    np.testing.assert_allclose(y[0, 0, 0, -1], 3.0, atol=1e-5)


# ------------------------------------------------------------ normalizations


def test_within_channel_lrn_formula():
    rs = _rs(12)
    x = rs.rand(1, 2, 5, 5).astype(np.float32)
    m = N.SpatialWithinChannelLRN(3, alpha=2.0, beta=0.5)
    y = np.asarray(m.forward(jnp.asarray(x)))
    # center pixel: window sum of squares over 3x3
    sq = (x[0, 0, 1:4, 1:4] ** 2).sum()
    expect = x[0, 0, 2, 2] / np.sqrt(1.0 + (2.0 / 9) * sq)
    np.testing.assert_allclose(y[0, 0, 2, 2], expect, rtol=1e-4)


def test_subtractive_normalization_zeroes_constant_input():
    x = np.full((1, 2, 7, 7), 3.25, np.float32)
    y = np.asarray(
        N.SpatialSubtractiveNormalization(2).forward(jnp.asarray(x))
    )
    np.testing.assert_allclose(y, 0.0, atol=1e-5)


def test_divisive_normalization_scales_down():
    rs = _rs(13)
    x = rs.randn(1, 1, 9, 9).astype(np.float32) * 4
    y = np.asarray(N.SpatialDivisiveNormalization(1).forward(jnp.asarray(x)))
    assert np.abs(y).mean() < np.abs(x).mean()


def test_contrastive_is_sub_then_div():
    rs = _rs(14)
    x = jnp.asarray(rs.randn(1, 1, 7, 7), jnp.float32)
    m = N.SpatialContrastiveNormalization(1)
    y = np.asarray(m.forward(x))
    expect = m.div.update_output_pure({}, m.sub.update_output_pure({}, x))
    np.testing.assert_allclose(y, np.asarray(expect), rtol=1e-6)


# ------------------------------------------------------------- shape utils


def test_expand_size_infer_reshape_tile_reverse():
    v = jnp.asarray([[1.0], [2.0]])
    y = np.asarray(N.ExpandSize([-1, 3]).forward(v))
    np.testing.assert_allclose(y, [[1, 1, 1], [2, 2, 2]])

    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 6))
    y = N.InferReshape([0, 2, 3]).forward(x)
    assert y.shape == (2, 2, 3)
    y = N.InferReshape([3, -1], batch_mode=True).forward(x)
    assert y.shape == (2, 3, 2)

    y = np.asarray(N.Tile(2, 2).forward(jnp.asarray([[1.0, 2.0]])))
    np.testing.assert_allclose(y, [[1, 2, 1, 2]])

    y = np.asarray(N.Reverse(2).forward(jnp.asarray([[1.0, 2.0, 3.0]])))
    np.testing.assert_allclose(y, [[3, 2, 1]])


def test_masked_select_eager():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    mask = jnp.asarray([[1, 0], [0, 1]])
    y = np.asarray(N.MaskedSelect().forward((x, mask)))
    np.testing.assert_allclose(y, [1.0, 4.0])


def test_pairwise_distance_p1_p2():
    a = jnp.asarray([[0.0, 0.0], [1.0, 1.0]])
    b = jnp.asarray([[3.0, 4.0], [1.0, 1.0]])
    np.testing.assert_allclose(
        np.asarray(N.PairwiseDistance(2).forward((a, b))), [5.0, 0.0],
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(N.PairwiseDistance(1).forward((a, b))), [7.0, 0.0],
        atol=1e-6,
    )


# ---------------------------------------------------------------- table ops


def test_new_table_ops():
    a = jnp.asarray([[1.0, 2.0]])
    b = jnp.asarray([[3.0, 6.0]])
    np.testing.assert_allclose(
        np.asarray(N.CAveTable().forward((a, b))), [[2.0, 4.0]]
    )
    parts = N.SplitTable(2).forward(jnp.asarray([[1.0, 2.0, 3.0]]))
    assert len(parts) == 3 and parts[0].shape == (1,)
    l, r = N.BifurcateSplitTable(2).forward(jnp.asarray([[1.0, 2.0, 3.0, 4.0]]))
    np.testing.assert_allclose(np.asarray(l), [[1.0, 2.0]])
    sel = N.NarrowTable(2, 2).forward((a, b, a))
    assert len(sel) == 2
    packed = N.Pack(1).forward((a[0], b[0]))
    assert packed.shape == (2, 2)
    stacked = N.Pack(2).forward((a, b))
    assert stacked.shape == (1, 2, 2)


def test_mixture_table_weights_experts():
    g = jnp.asarray([[0.25, 0.75]])
    e1 = jnp.asarray([[1.0, 1.0]])
    e2 = jnp.asarray([[3.0, 5.0]])
    y = np.asarray(N.MixtureTable().forward((g, (e1, e2))))
    np.testing.assert_allclose(y, [[2.5, 4.0]])
    # tensor-expert variant (B, K, F)
    experts = jnp.stack([e1, e2], axis=1)
    y2 = np.asarray(N.MixtureTable().forward((g, experts)))
    np.testing.assert_allclose(y2, y)


def test_map_table_shares_weights():
    m = N.MapTable(N.Linear(3, 2))
    a = jnp.ones((1, 3))
    y1, y2 = m.forward((a, a * 2))
    np.testing.assert_allclose(np.asarray(y2 - y1), np.asarray(y1) -
                               np.asarray(m.modules[0].bias)[None],
                               rtol=1e-5, atol=1e-6)


def test_bottle_folds_leading_dims():
    m = N.Bottle(N.Linear(4, 3), 2, 2)
    x = jnp.asarray(_rs(15).randn(2, 5, 4), jnp.float32)
    y = m.forward(x)
    assert y.shape == (2, 5, 3)
    direct = m.modules[0].forward(x.reshape(10, 4)).reshape(2, 5, 3)
    np.testing.assert_allclose(np.asarray(y), np.asarray(direct), rtol=1e-6)


# --------------------------------------------------------------- criterions


def test_cosine_distance_criterion():
    c = N.CosineDistanceCriterion()
    x = jnp.asarray([[1.0, 0.0]])
    same = c.forward(x, jnp.asarray([[2.0, 0.0]]))
    orth = c.forward(x, jnp.asarray([[0.0, 3.0]]))
    np.testing.assert_allclose(float(same), 0.0, atol=1e-6)
    np.testing.assert_allclose(float(orth), 1.0, atol=1e-6)


def test_dice_criterion_perfect_overlap():
    c = N.DiceCoefficientCriterion(epsilon=0.0)
    x = jnp.asarray([[1.0, 1.0, 0.0]])
    assert float(c.forward(x, x)) < 1e-6
    disjoint = c.forward(x, jnp.asarray([[0.0, 0.0, 1.0]]))
    np.testing.assert_allclose(float(disjoint), 1.0, atol=1e-6)


def test_soft_margin_criterion_value():
    c = N.SoftMarginCriterion()
    x = jnp.asarray([[0.5, -0.5]])
    t = jnp.asarray([[1.0, -1.0]])
    expect = np.log(1 + np.exp(-0.5))
    np.testing.assert_allclose(float(c.forward(x, t)), expect, rtol=1e-5)


def test_multilabel_margin_criterion_manual():
    c = N.MultiLabelMarginCriterion(size_average=False)
    x = jnp.asarray([[0.1, 0.2, 0.4, 0.8]])
    t = jnp.asarray([[3.0, 0.0, 0.0, 0.0]])  # target class 3 (1-based)
    # loss = sum_{j != 3} max(0, 1 - (x[2] - x[j])) / 4
    xs = np.asarray(x)[0]
    expect = sum(max(0.0, 1.0 - (xs[2] - xs[j])) for j in (0, 1, 3)) / 4
    np.testing.assert_allclose(float(c.forward(x, t)), expect, rtol=1e-5)


def test_gaussian_and_kld_criterion_values():
    mean = jnp.zeros((1, 2))
    log_var = jnp.zeros((1, 2))
    target = jnp.zeros((1, 2))
    g = N.GaussianCriterion()
    np.testing.assert_allclose(
        float(g.forward((mean, log_var), target)),
        0.5 * np.log(2 * np.pi) * 2,
        rtol=1e-5,
    )
    k = N.KLDCriterion()
    np.testing.assert_allclose(
        float(k.forward((mean, log_var), target)), 0.0, atol=1e-6
    )
    # nonzero mean increases KL by 0.5*mean^2
    np.testing.assert_allclose(
        float(k.forward((mean + 2.0, log_var), target)), 4.0, atol=1e-5
    )


def test_l1_hinge_embedding_criterion():
    c = N.L1HingeEmbeddingCriterion(margin=2.0)
    x1 = jnp.asarray([[1.0, 1.0]])
    x2 = jnp.asarray([[0.0, 0.5]])
    d = 1.5
    np.testing.assert_allclose(
        float(c.forward((x1, x2), jnp.asarray([1.0]))), d, rtol=1e-6
    )
    np.testing.assert_allclose(
        float(c.forward((x1, x2), jnp.asarray([-1.0]))), 0.5, rtol=1e-6
    )


def test_criterion_backwards_run():
    rs = _rs(16)
    v = jnp.asarray(rs.randn(2, 4), jnp.float32)
    t = jnp.asarray(rs.randn(2, 4), jnp.float32)
    for c, inp, tgt in [
        (N.CosineDistanceCriterion(), v, t),
        (N.SoftMarginCriterion(), v, jnp.sign(t)),
        (N.GaussianCriterion(), (v, t * 0), t),
        (N.KLDCriterion(), (v, t * 0), t),
        (N.L1HingeEmbeddingCriterion(), (v, t), jnp.asarray([1.0, -1.0])),
    ]:
        g = c.backward(inp, tgt)
        flat = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(x)).all() for x in flat)


# ---------------------------------------------------------------- recurrent


def test_multi_rnn_cell_stacks():
    rs = _rs(17)
    cell = N.MultiRNNCell([N.LSTM(4, 6), N.GRU(6, 3)])
    rec = N.Recurrent().add(cell)
    x = jnp.asarray(rs.randn(2, 5, 4), jnp.float32)
    y = rec.forward(x)
    assert y.shape == (2, 5, 3)
    # equals running the two Recurrents in sequence with the same weights
    r1 = N.Recurrent().add(cell.cells[0])
    r2 = N.Recurrent().add(cell.cells[1])
    expect = r2.forward(r1.forward(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                               rtol=1e-4, atol=1e-5)


def test_conv_lstm_shapes_and_grad():
    rs = _rs(18)
    cell = N.ConvLSTMPeephole(2, 3, 3, 3)
    rec = N.Recurrent().add(cell)
    x = jnp.asarray(rs.randn(1, 4, 2, 5, 5), jnp.float32)
    y = rec.forward(x)
    assert y.shape == (1, 4, 3, 5, 5)
    g = rec.backward(x, y)
    assert np.isfinite(np.asarray(g)).all()


def test_conv_lstm_no_peephole():
    cell = N.ConvLSTMPeephole(2, 3, 3, 3, with_peephole=False)
    assert cell.p_i is None
    rec = N.Recurrent().add(cell)
    x = jnp.ones((1, 2, 2, 4, 4))
    assert rec.forward(x).shape == (1, 2, 3, 4, 4)


def test_multi_rnn_and_conv_lstm_roundtrip(tmp_path):
    from bigdl_tpu.utils.serializer import load_module, save_module

    rs = _rs(19)
    m = N.Sequential().add(
        N.Recurrent().add(N.MultiRNNCell([N.LSTM(4, 6), N.GRU(6, 3)]))
    )
    m.evaluate()
    x = jnp.asarray(rs.randn(2, 5, 4), jnp.float32)
    out1 = np.asarray(m.forward(x))
    loaded = load_module(save_module(m, str(tmp_path / "mrnn")))
    loaded.evaluate()
    np.testing.assert_allclose(out1, np.asarray(loaded.forward(x)),
                               rtol=1e-5, atol=1e-6)

    m2 = N.Recurrent().add(N.ConvLSTMPeephole(2, 3, 3, 3))
    m2.evaluate()
    xc = jnp.asarray(rs.randn(1, 3, 2, 5, 5), jnp.float32)
    out2 = np.asarray(m2.forward(xc))
    loaded2 = load_module(save_module(m2, str(tmp_path / "clstm")))
    loaded2.evaluate()
    np.testing.assert_allclose(out2, np.asarray(loaded2.forward(xc)),
                               rtol=1e-5, atol=1e-6)


def test_exported_module_breadth():
    """VERDICT round-1 item 2 gate: >= 180 exported module classes."""
    from bigdl_tpu.nn.module import AbstractModule
    from bigdl_tpu.nn.criterion import AbstractCriterion

    mods = [
        name for name in dir(N)
        if isinstance(getattr(N, name), type)
        and issubclass(getattr(N, name),
                       (AbstractModule, AbstractCriterion))
        and not name.startswith("_")
    ]
    assert len(mods) >= 180, f"only {len(mods)} exported module classes"


# ----------------------------------------------- round-2 review regressions


def test_split_table_negative_dim():
    x = jnp.asarray(_rs(20).randn(2, 3, 4), jnp.float32)
    parts = N.SplitTable(-1, 2).forward(x)
    assert len(parts) == 4 and parts[0].shape == (2, 3)
    np.testing.assert_allclose(np.asarray(parts[1]), np.asarray(x[:, :, 1]))


def test_multi_rnn_cell_upper_dropout_active():
    """Per-gate input dropout of upper cells must fire in training."""
    cell = N.MultiRNNCell([N.LSTM(4, 6), N.LSTM(6, 5, p=0.9)])
    rec = N.Recurrent().add(cell)
    rec.training()
    x = jnp.asarray(_rs(21).randn(2, 5, 4), jnp.float32)
    y1 = np.asarray(rec.forward(x))
    y2 = np.asarray(rec.forward(x))
    assert np.abs(y1 - y2).max() > 1e-6  # dropout varies across forwards
    rec.evaluate()
    e1 = np.asarray(rec.forward(x))
    e2 = np.asarray(rec.forward(x))
    np.testing.assert_allclose(e1, e2)


def test_multilabel_margin_stops_at_first_zero():
    c = N.MultiLabelMarginCriterion(size_average=False)
    x = jnp.asarray([[0.1, 0.2, 0.4, 0.8]])
    # torch semantics: [3, 0, 2, 0] targets only class 3 — the 2 after
    # the terminating zero is ignored
    t_terminated = jnp.asarray([[3.0, 0.0, 2.0, 0.0]])
    t_clean = jnp.asarray([[3.0, 0.0, 0.0, 0.0]])
    np.testing.assert_allclose(
        float(c.forward(x, t_terminated)), float(c.forward(x, t_clean)),
        rtol=1e-6,
    )


def test_bottle_rejects_rank_mismatch():
    m = N.Bottle(N.Reshape([2, 2]), 2, 2)  # child outputs rank 3
    with pytest.raises(ValueError, match="n_output_dim"):
        m.forward(jnp.ones((3, 5, 4)))


def test_logger_filter_keeps_shared_handler_open():
    import logging
    from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs

    redirect_spark_info_logs(chatty=("_lf_a", "_lf_b"))
    redirect_spark_info_logs(chatty=("_lf_a",))
    # the handler from call 1 is still attached to _lf_b: must be open
    for h in logging.getLogger("_lf_b").handlers:
        if isinstance(h, logging.FileHandler):
            assert not h.stream.closed
    logging.getLogger("_lf_b").info("must not raise on a closed stream")


def test_maxout_reduces_groups():
    m = N.Maxout(6, 4, 3)
    x = jnp.asarray(np.random.RandomState(0).randn(5, 6).astype(np.float32))
    y = np.asarray(m.forward(x))
    assert y.shape == (5, 4)
    # equals max over the 3 affine maps computed by hand
    w = np.asarray(m.weight).reshape(6, 3, 4)
    b = np.asarray(m.bias).reshape(3, 4)
    ref = (np.asarray(x) @ w.reshape(6, 12) + b.reshape(12)).reshape(5, 3, 4)
    np.testing.assert_allclose(y, ref.max(axis=1), rtol=1e-5)


def test_srelu_piecewise():
    m = N.SReLU((4,))
    # fix the thresholds for a deterministic check
    m.t_left = jnp.asarray([-1.0, -1.0, -1.0, -1.0])
    m.a_left = jnp.asarray([0.5, 0.5, 0.5, 0.5])
    m.t_right = jnp.asarray([1.0, 1.0, 1.0, 1.0])
    m.a_right = jnp.asarray([2.0, 2.0, 2.0, 2.0])
    x = jnp.asarray([[-3.0, 0.0, 0.5, 3.0]])
    y = np.asarray(m.forward(x))[0]
    np.testing.assert_allclose(y, [-2.0, 0.0, 0.5, 5.0], rtol=1e-6)


def test_roi_pooling_forward_backward_and_roundtrip(tmp_path):
    from bigdl_tpu.utils.serializer import load_module, save_module

    data = jnp.arange(2 * 16, dtype=jnp.float32).reshape(2, 1, 4, 4)
    rois = jnp.asarray(
        [[1, 0, 0, 3, 3], [2, 1, 0, 3, 1], [2, 2, 2, 3, 3]], jnp.float32
    )
    m = N.RoiPooling(2, 2, 1.0)
    y = np.asarray(m.forward([data, rois]))
    assert y.shape == (3, 1, 2, 2)
    np.testing.assert_allclose(y[0, 0], [[5, 7], [13, 15]])
    # roi 2: image 2 (offset 16), x in [1,3], y in [0,1] -> rows 0..1
    np.testing.assert_allclose(y[1, 0], [[18, 19], [22, 23]])
    import jax

    g = jax.grad(lambda d: m.forward([d, rois]).sum())(data)
    assert float(np.asarray(g).sum()) == 12.0  # one unit per pooled cell
    loaded = load_module(save_module(m, str(tmp_path / "roi")))
    np.testing.assert_allclose(
        np.asarray(loaded.forward([data, rois])), y)
