"""Fault-tolerant serving data plane (serving/router.py + friends):
placement policy, shared retry budget, exactly-once drain/handoff, the
router core over fake and real replicas, the serving chaos scenarios,
and the 503 + Retry-After backpressure contract.

The load-bearing contract: temperature-0 output routed through the
router — including across a mid-decode drain/handoff onto another
replica — must BIT-MATCH the direct ``TransformerLM.generate()``.
The heavy chaos matrix lives in ``scripts/router_smoke.py``
(``run-tests.sh --router``); tier-1 runs the unit surface plus one
fast scenario — the full matrix is ``-m slow``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import obs
from bigdl_tpu.resilience.retry import RetryBudget, backoff_delay
from bigdl_tpu.serving.drain import (HANDOFF_ERROR, HandoffLedger,
                                     HandoffRecord)
from bigdl_tpu.serving.placement import (NoReplicaAvailable,
                                         PlacementPolicy, ReplicaView)
from bigdl_tpu.serving.router import (EngineReplica, ReplicaDraining,
                                      ReplicaUnavailable, Router,
                                      RouterShed, _claim_key)
from bigdl_tpu.sim import VirtualClock, run_serve_scenario
from bigdl_tpu.sim.serve import SimServeReplica


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    for var in ("BIGDL_OBS", "BIGDL_TRACE_DIR", "BIGDL_METRICS_DIR",
                "BIGDL_ROUTER_REPLICAS", "BIGDL_ROUTER_AFFINITY_TTL",
                "BIGDL_ROUTER_RETRY_BUDGET", "BIGDL_ROUTER_RETRY_BURST",
                "BIGDL_ROUTER_MAX_RETRIES", "BIGDL_ROUTER_TIMEOUT"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


# ----------------------------------------------------------- placement
class TestPlacement:
    def _views(self, **depths):
        return {n: ReplicaView(n, queue_depth=float(d))
                for n, d in depths.items()}

    def test_least_loaded_with_kv_weight(self):
        pol = PlacementPolicy(kv_weight=4.0)
        views = {
            "a": ReplicaView("a", queue_depth=2.0, kv_frac=0.0),
            "b": ReplicaView("b", queue_depth=0.0, kv_frac=0.9),
        }
        # b has the empty queue but its KV pool is nearly exhausted:
        # 0 + 4*0.9 = 3.6 > a's 2.0 — admitting onto b buys a preempt
        assert pol.choose(views) == "a"

    def test_affinity_sticks_and_ttl_expires(self):
        vc = VirtualClock()
        pol = PlacementPolicy(affinity_ttl_s=10.0, clock=vc)
        views = self._views(a=0, b=5)
        assert pol.choose(views, session="s") == "a"
        # the bound replica stays chosen even once it is the slower one
        views["a"].queue_depth = 50.0
        assert pol.choose(views, session="s") == "a"
        assert pol.affinity_hits == 1
        vc.advance(11.0)  # TTL expired -> re-place least-loaded
        assert pol.choose(views, session="s") == "b"

    def test_rebind_after_replica_removed(self):
        pol = PlacementPolicy()
        views = self._views(a=0, b=1)
        assert pol.choose(views, session="s") == "a"
        dropped = pol.unbind_replica("a")
        assert dropped == ["s"]
        del views["a"]
        assert pol.choose(views, session="s") == "b"
        assert pol.bindings()["s"] == "b"
        assert pol.rebinds == 0  # unbind cleared it; fresh bind, not a
        #                          rebind of a live binding

    def test_draining_and_down_ineligible(self):
        views = {
            "a": ReplicaView("a", draining=True),
            "b": ReplicaView("b", up=False),
            "c": ReplicaView("c", queue_depth=9.0),
        }
        pol = PlacementPolicy()
        assert pol.choose(views) == "c"
        with pytest.raises(NoReplicaAvailable):
            pol.choose(views, exclude={"c"})

    def test_affinity_to_drained_replica_falls_through(self):
        pol = PlacementPolicy()
        views = self._views(a=0, b=1)
        assert pol.choose(views, session="s") == "a"
        views["a"].draining = True
        assert pol.choose(views, session="s") == "b"
        assert pol.bindings()["s"] == "b"


# -------------------------------------------------------- retry budget
class TestRetryBudget:
    def test_deposit_capped_at_burst(self):
        b = RetryBudget(ratio=0.5, burst=2.0, initial=0.0)
        for _ in range(100):
            b.record_request()
        assert b.tokens() == 2.0

    def test_spend_denied_when_dry(self):
        b = RetryBudget(ratio=0.1, burst=1.0, initial=1.0)
        assert b.try_spend()
        assert not b.try_spend()
        s = b.stats()
        assert s["retries_granted"] == 1 and s["retries_denied"] == 1

    def test_arithmetic_ceiling(self):
        # the invariant the brownout scenario leans on: granted
        # retries can never exceed burst + ratio x requests
        b = RetryBudget(ratio=0.2, burst=4.0)
        granted = 0
        for _ in range(200):
            b.record_request()
            while b.try_spend():   # adversarial: drain after every req
                granted += 1
        assert granted <= 4.0 + 0.2 * 200 + 1e-9
        assert b.stats()["retries_granted"] == granted

    def test_backoff_delay_exponential_with_jitter(self):
        import random

        rng = random.Random(3)
        for attempt, base_delay in ((1, 0.5), (2, 1.0), (3, 2.0)):
            d = backoff_delay(attempt, base=0.5, cap=30.0, jitter=0.1,
                              rng=rng)
            assert base_delay <= d <= base_delay * 1.1
        assert backoff_delay(50, base=0.5, cap=3.0, jitter=0.0) == 3.0


# ------------------------------------------------------ handoff ledger
class TestHandoffLedger:
    def test_claim_exactly_once(self):
        led = HandoffLedger()
        assert led.claim("r1")
        assert not led.claim("r1")   # the losing recovery path

    def test_claim_refused_after_delivery(self):
        led = HandoffLedger()
        assert led.deliver("r1")
        assert not led.claim("r1")

    def test_release_reopens_claim(self):
        led = HandoffLedger()
        assert led.claim("r1")
        led.release("r1")
        assert led.claim("r1")

    def test_deliver_dedup_counts(self):
        led = HandoffLedger()
        assert led.deliver("r1")
        assert not led.deliver("r1")
        assert led.stats()["duplicates"] == 1

    def test_claim_key_distinguishes_handoff_epochs(self):
        # the same request handed off twice (from two drains) builds
        # two distinct claim keys — but the same event surfacing on
        # two recovery paths builds the same one
        hd1 = HandoffRecord(prompt=[1, 2], max_new_tokens=8,
                            request_id="r9", source="a")
        hd1_dup = HandoffRecord(prompt=[1, 2], max_new_tokens=8,
                                request_id="r9", source="a")
        hd2 = HandoffRecord(prompt=[1, 2, 3, 4], max_new_tokens=6,
                            request_id="r9", source="b")
        assert _claim_key(hd1) == _claim_key(hd1_dup)
        assert _claim_key(hd1) != _claim_key(hd2)

    def test_roundtrip_dict(self):
        hd = HandoffRecord(prompt=[1, 2], max_new_tokens=4,
                           temperature=0.0, tokens_done=[7],
                           request_id="x", source="a")
        assert HandoffRecord.from_dict(
            json.loads(json.dumps(hd.to_dict()))) == hd


# ------------------------------------------------- router (fake fleet)
class _FakeReplica:
    """Scriptable replica: each generate() pops the next outcome —
    a token list (success) or an exception to raise."""

    def __init__(self, name, outcomes=None):
        self.name = name
        self.outcomes = list(outcomes or [])
        self.calls = []
        self.drained = False

    def generate(self, prompt, max_new_tokens, *, temperature=0.0,
                 timeout_s=30.0, request_id=None):
        self.calls.append(list(prompt))
        out = self.outcomes.pop(0) if self.outcomes else [0] * 2
        if isinstance(out, Exception):
            raise out
        return {"tokens": list(out), "ttft_s": 0.0, "e2e_s": 0.0}

    def signals(self):
        return {"up": True, "draining": False, "queue_depth": 0.0,
                "kv_frac": 0.0}

    def drain(self, deadline_s=10.0):
        self.drained = True
        return []


def _router(replicas, **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("backoff_base_s", 0.0)
    return Router(replicas, **kw)


class TestRouterCore:
    def test_routes_and_returns_tokens(self):
        r = _router([_FakeReplica("a", [[5, 6, 7]])])
        out = r.route([1, 2], 3)
        assert out["tokens"] == [5, 6, 7] and out["replica"] == "a"
        assert out["retries"] == 0 and out["handoffs"] == 0

    def test_retry_lands_on_other_replica(self):
        a = _FakeReplica("a", [ReplicaUnavailable("a: boom")])
        b = _FakeReplica("b", [[9]])
        r = _router([a, b])
        out = r.route([1], 1)
        assert out["replica"] == "b" and out["retries"] == 1
        assert a.calls and b.calls

    def test_budget_exhaustion_sheds_with_retry_after(self):
        a = _FakeReplica("a", [ReplicaUnavailable("x")] * 5)
        b = _FakeReplica("b", [ReplicaUnavailable("x")] * 5)
        r = _router([a, b], retry_budget_ratio=0.0,
                    retry_budget_burst=0.0, max_retries=3,
                    retry_after_s=2.5)
        with pytest.raises(RouterShed) as ei:
            r.route([1], 1)
        assert ei.value.retry_after_s == 2.5
        assert r.budget.stats()["retries_denied"] == 1

    def test_max_retries_exhaustion_sheds(self):
        a = _FakeReplica("a", [ReplicaUnavailable("x")] * 9)
        b = _FakeReplica("b", [ReplicaUnavailable("x")] * 9)
        r = _router([a, b], max_retries=1)
        with pytest.raises(RouterShed):
            r.route([1], 1)

    def test_handoff_replays_elsewhere_with_prefix(self):
        hd = HandoffRecord(prompt=[1, 2, 7, 8], max_new_tokens=2,
                           tokens_done=[7, 8], request_id=None,
                           source="a")
        a = _FakeReplica("a")
        b = _FakeReplica("b", [[9, 10]])
        r = _router([a, b])
        a.outcomes = [ReplicaDraining(
            HandoffRecord(**{**hd.to_dict(), "request_id": None}))]

        def gen(prompt, n, **kw):
            a.calls.append(list(prompt))
            ex = a.outcomes.pop(0)
            ex.handoff.request_id = kw.get("request_id")
            raise ex
        a.generate = gen
        out = r.route([1, 2], 4)
        # generated-so-far prefix + the survivor's continuation
        assert out["tokens"] == [7, 8, 9, 10]
        assert out["handoffs"] == 1 and out["replica"] == "b"
        assert b.calls == [[1, 2, 7, 8]]   # refolded prompt replayed

    def test_dying_mid_handoff_lands_exactly_once(self):
        """The race: a replica dies mid-handoff and the same
        checkpoint surfaces on two recovery paths.  The claim gate
        lets exactly one replay."""
        hd = HandoffRecord(prompt=[1, 2], max_new_tokens=2,
                           request_id="rid-1", source="a")
        a = _FakeReplica("a", [ReplicaDraining(hd)])
        b = _FakeReplica("b", [[3, 4]])
        r = _router([a, b])
        # the drain sweep already claimed this checkpoint...
        assert r.ledger.claim(_claim_key(hd))
        # ...so the per-request path must stand down, not double-land
        with pytest.raises(RouterShed, match="already replayed"):
            r.route([1, 2], 2, request_id="rid-1")
        assert not b.calls

    def test_affinity_rebind_after_remove_replica(self):
        a = _FakeReplica("a", [[1], [1]])
        b = _FakeReplica("b", [[2], [2]])
        r = _router([a, b])
        first = r.route([5], 1, session="s")["replica"]
        dropped = r.remove_replica(first)
        assert dropped == ["s"]
        other = "b" if first == "a" else "a"
        assert r.route([5], 1, session="s")["replica"] == other
        assert r.placement.bindings()["s"] == other

    def test_begin_drain_stops_placement(self):
        a = _FakeReplica("a", [[1]] * 4)
        b = _FakeReplica("b", [[2]] * 4)
        r = _router([a, b])
        summary = r.begin_drain("a")
        assert a.drained and summary["replica"] == "a"
        for _ in range(3):
            assert r.route([1], 1)["replica"] == "b"
        r.undrain("a")
        assert any(r.route([1], 1)["replica"] == "a" for _ in range(2))

    def test_no_replica_sheds(self):
        r = _router([])
        with pytest.raises(RouterShed):
            r.route([1], 1)


# ------------------------------------------------------ serving chaos
class TestServeSim:
    def test_replica_throughput_independent_of_tick(self):
        # slots/service_s regardless of quantum: 4 lanes x 0.25s jobs
        # must finish 16 jobs per virtual second even at 0.5s ticks
        rep = SimServeReplica("r", slots=4)
        for i in range(64):
            assert rep.admit(f"q{i}", 0.25)
        done = []
        for _ in range(4):
            done += rep.tick(0.5)
        assert len(done) == 32

    def test_preempt_dumps_everything(self):
        rep = SimServeReplica("r", slots=2)
        for i in range(6):
            rep.admit(f"q{i}", 1.0)
        rep.tick(0.5)
        dumped = rep.preempt()
        assert len(dumped) == 6 and not rep.up
        # in-flight progress rides the checkpoint (remaining < full)
        assert min(rem for _rid, rem in dumped) == pytest.approx(0.5)
        assert not rep.admit("q9", 1.0)

    def test_drain_refuses_admissions_and_checkpoints(self):
        rep = SimServeReplica("r", slots=2)
        rep.admit("q0", 1.0)
        dumped = rep.drain()
        assert dumped == [("q0", 1.0)] and rep.draining
        assert not rep.admit("q1", 1.0)
        rep.undrain()
        assert rep.admit("q1", 1.0)

    def test_drain_wave_scenario_conserves_requests(self):
        res = run_serve_scenario("drain_wave", seed=7)
        assert res.ok, [str(i) for i in res.invariants if not i.ok]
        assert res.lost == 0 and res.duplicates == 0 and res.shed == 0
        assert res.handoff_replays >= 1 and res.drains >= 3
        assert res.completed == res.requests

    def test_amplification_invariant_catches_violation(self):
        from bigdl_tpu.sim.invariants import check_retry_amplification

        bad = {"amplification": 2.0,
               "budget": {"ratio": 0.2, "burst": 4.0, "requests": 100,
                          "retries_granted": 150, "retries_denied": 0}}
        r = check_retry_amplification(bad, {})
        assert not r.ok and "amplification" in r.detail
        assert "arithmetic" in r.detail  # 150 > 4 + 0.2*100 too

    @pytest.mark.slow
    def test_full_matrix(self):
        from bigdl_tpu.sim import SERVE_SCENARIOS

        for name in SERVE_SCENARIOS:
            res = run_serve_scenario(name, seed=7)
            assert res.ok, (name, [str(i) for i in res.invariants])
            assert res.lost == 0 and res.duplicates == 0


# --------------------------------------------------- real engine tier
def _model():
    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm

    RandomGenerator.RNG.set_seed(13)
    return build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                max_len=64, attn_impl="xla")


@pytest.fixture(scope="module")
def lm_model():
    return _model()


@pytest.fixture(scope="module")
def lm_params(lm_model):
    return lm_model.params()


def _ref(model, params, prompt, n):
    return list(np.asarray(model.generate(
        params, np.asarray(prompt)[None, :], n))[0])


class TestRouterOverEngines:
    def test_temperature0_bit_equal_through_router(self, lm_model,
                                                   lm_params):
        from bigdl_tpu.serving import LMEngine

        e1 = LMEngine(lm_model, max_batch=2, page_size=8).start()
        e2 = LMEngine(lm_model, max_batch=2, page_size=8).start()
        r = _router([EngineReplica("r1", e1), EngineReplica("r2", e2)],
                    request_timeout_s=120.0)
        try:
            rs = np.random.RandomState(2)
            for n_p, n_new in ((5, 8), (9, 4), (4, 6)):
                p = rs.randint(0, 48, (n_p,)).tolist()
                out = r.route(p, n_new, session="t0")
                assert [int(t) for t in list(p) + out["tokens"]] \
                    == _ref(lm_model, lm_params, p, n_new)
            assert r.placement.stats()["affinity_hits"] >= 2
        finally:
            e1.close()
            e2.close()

    def test_queued_request_hands_off_before_decode_starts(
            self, lm_model, lm_params):
        """Drain edge case: admitted but decode never started (still
        queued behind the batch) — the checkpoint carries zero
        generated tokens and the replay elsewhere is bit-exact."""
        from bigdl_tpu.serving import LMEngine

        e1 = LMEngine(lm_model, max_batch=2, page_size=8)
        e2 = LMEngine(lm_model, max_batch=2, page_size=8)
        p = [1, 2, 3, 4]
        req = e1.submit(p, 6)          # queued; nothing pumped yet
        records = e1.drain(deadline_s=0.0)
        assert len(records) == 1
        hd = records[0]
        assert hd.tokens_done == [] and hd.prompt == p
        assert hd.max_new_tokens == 6
        assert req.error == HANDOFF_ERROR
        # replay the checkpoint on the second engine: bit-equal
        req2 = e2.submit(hd.prompt, hd.max_new_tokens,
                         temperature=hd.temperature)
        e2.run_until_idle(60)
        assert [int(t) for t in list(hd.prompt) + req2.tokens] \
            == _ref(lm_model, lm_params, p, 6)
        e1.close()
        e2.close()

    @pytest.mark.slow
    def test_mid_decode_drain_replays_bit_equal(self, lm_model,
                                                lm_params):
        from bigdl_tpu.serving import LMEngine

        e1 = LMEngine(lm_model, max_batch=2, page_size=8).start()
        e2 = LMEngine(lm_model, max_batch=2, page_size=8).start()
        r = _router([EngineReplica("r1", e1), EngineReplica("r2", e2)],
                    request_timeout_s=120.0)
        try:
            p = [3, 1, 4, 1, 5]
            r.route(p, 2, session="s")   # bind the session
            bound = r.placement.lookup("s")
            res = {}
            t = threading.Thread(target=lambda: res.update(
                r.route(p, 24, session="s")))
            t.start()
            time.sleep(0.3)
            r.begin_drain(bound, deadline_s=0.05)
            t.join(60)
            assert res.get("handoffs", 0) >= 1
            assert res["replica"] != bound
            assert [int(x) for x in list(p) + res["tokens"]] \
                == _ref(lm_model, lm_params, p, 24)
            assert r.ledger.stats()["duplicates"] == 0
        finally:
            e1.close()
            e2.close()

    def test_server_queue_full_answers_503_retry_after(self, lm_model):
        from bigdl_tpu.obs import names
        from bigdl_tpu.obs.metrics import parse_prometheus, sample_value
        from bigdl_tpu.serving import LMEngine, ServingServer

        eng = LMEngine(lm_model, max_batch=1, page_size=8,
                       queue_capacity=1)
        srv = ServingServer(lm=eng, request_timeout_s=0.05)
        try:
            eng.submit([1, 2, 3], 4)    # fills the queue; never pumped
            code, retry_after = None, None
            try:
                urllib.request.urlopen(urllib.request.Request(
                    srv.url("/v1/generate"),
                    data=json.dumps({"prompt": [1],
                                     "max_new_tokens": 2}).encode(),
                    headers={"Content-Type": "application/json"}),
                    timeout=10)
            except urllib.error.HTTPError as e:
                code = e.code
                retry_after = e.headers.get("Retry-After")
            assert code == 503
            assert retry_after is not None and int(retry_after) >= 1
            snap = parse_prometheus(obs.get_registry().to_prometheus())
            assert sample_value(
                snap, names.SERVE_REJECTS_TOTAL) >= 1.0
        finally:
            srv.close()
            eng.close()

    def test_draining_engine_refuses_admissions(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=1, page_size=8)
        eng.draining = True
        with pytest.raises(RuntimeError, match="draining"):
            eng.submit([1, 2], 2)
        stats = eng.stats()
        assert stats["draining"] is True
        assert "kv_pages_in_use" in stats and "kv_pages_total" in stats
        eng.close()
