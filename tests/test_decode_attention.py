"""ops/decode_attention.py — flash-decode over the paged KV cache
(ISSUE 13).

The load-bearing contracts:

* the dense path is the PR 12 math verbatim (the engine's bit-match
  tests in test_serving.py pin that end to end);
* fused (every page-block chunking) and the Pallas kernel (interpret
  mode here) agree with dense within f32 tolerance across ragged
  lengths, page boundaries and arbitrary page-table permutations;
* the trash page is never READ into an output: arbitrary finite
  garbage in page 0 changes no live slot's result, on every impl;
* the ``decode_attn`` / ``int8_mm`` auto-tuner sites: golden keys,
  model dispatch flips dense -> fused (the analytic gather-tax model),
  the measured prewarm cycle persists and then serves from cache, and
  tuner-off ``impl="auto"`` is exactly the static dense policy.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.ops import autotune
from bigdl_tpu.ops import decode_attention as D
from bigdl_tpu.ops.decode_attention import (decode_hbm_bytes,
                                            paged_decode_attention,
                                            static_decode_dispatch,
                                            used_page_bucket)


@pytest.fixture(autouse=True)
def _tuner_off_by_default(monkeypatch):
    monkeypatch.delenv("BIGDL_TUNER", raising=False)
    monkeypatch.delenv("BIGDL_TUNER_CACHE", raising=False)
    monkeypatch.delenv("BIGDL_TUNER_MEASURE", raising=False)
    autotune.reset()
    yield
    autotune.reset()


@pytest.fixture
def tuner(tmp_path, monkeypatch):
    cache = tmp_path / "tuner.json"
    monkeypatch.setenv("BIGDL_TUNER", "1")
    monkeypatch.setenv("BIGDL_TUNER_CACHE", str(cache))
    autotune.reset()
    yield cache
    autotune.reset()


def _state(b=4, h=4, d=16, p=8, maxp=8, pool=24, seed=0,
           lengths=None):
    """Random paged K/V state with ragged lengths (incl. a page
    boundary) and a permuted page table; slot 0 is inactive (length 0,
    trash table row) like a released engine slot."""
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, h, d).astype(np.float32))
    kp = jnp.asarray(rs.randn(pool, h, p, d).astype(np.float32))
    vp = jnp.asarray(rs.randn(pool, h, p, d).astype(np.float32))
    if lengths is None:
        lengths = [0, p - 1, p, min(3 * p - 1, maxp * p - 1)][:b]
        lengths += [1] * (b - len(lengths))
    tbl = np.zeros((b, maxp), np.int32)
    free = list(range(1, pool))
    rs.shuffle(free)
    for i, ln in enumerate(lengths):
        need = ln // p + 1 if ln else 0
        for j in range(min(need, maxp)):
            tbl[i, j] = free.pop()
    return (q, kp, vp, jnp.asarray(tbl),
            jnp.asarray(np.asarray(lengths, np.int32)))


def _numpy_reference(q, kp, vp, tables, lengths, p):
    """Independent numpy oracle (float64 softmax over the masked
    gathered window)."""
    q, kp, vp = (np.asarray(x, np.float64) for x in (q, kp, vp))
    tables, lengths = np.asarray(tables), np.asarray(lengths)
    b, h, d = q.shape
    maxp = tables.shape[1]
    out = np.zeros((b, h, d))
    scale = d ** -0.5
    for i in range(b):
        k = np.concatenate([kp[tables[i, j]] for j in range(maxp)],
                           axis=1)          # (H, maxp*P, Dh)
        v = np.concatenate([vp[tables[i, j]] for j in range(maxp)],
                           axis=1)
        n = int(lengths[i]) + 1
        s = np.einsum("hd,hkd->hk", q[i], k[:, :n]) * scale
        s -= s.max(axis=-1, keepdims=True)
        pr = np.exp(s)
        pr /= pr.sum(axis=-1, keepdims=True)
        out[i] = np.einsum("hk,hkd->hd", pr, v[:, :n])
    return out


class TestPagedDecodeParity:
    def test_dense_matches_numpy_oracle(self):
        q, kp, vp, tbl, lens = _state()
        got = paged_decode_attention(q, kp, vp, tbl, lens, page_size=8,
                                     impl="dense")
        want = _numpy_reference(q, kp, vp, tbl, lens, 8)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    @pytest.mark.parametrize("bp", [0, 1, 2, 4])
    def test_fused_matches_dense_ragged(self, bp):
        q, kp, vp, tbl, lens = _state()
        dense = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl="dense")
        fused = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl="fused",
                                       block_pages=bp)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=1e-5)

    def test_fused_matches_dense_across_page_boundaries(self):
        # every length around each page boundary of a 3-page window
        for ln in (1, 7, 8, 9, 15, 16, 17, 23):
            q, kp, vp, tbl, lens = _state(b=2, maxp=3, seed=ln,
                                          lengths=[ln, 1])
            dense = paged_decode_attention(q, kp, vp, tbl, lens,
                                           page_size=8, impl="dense")
            fused = paged_decode_attention(q, kp, vp, tbl, lens,
                                           page_size=8, impl="fused",
                                           block_pages=1)
            np.testing.assert_allclose(np.asarray(fused),
                                       np.asarray(dense), atol=1e-5)

    def test_fused_fori_path_matches(self):
        # > 4 chunks takes the lax.fori_loop branch
        q, kp, vp, tbl, lens = _state(maxp=8)
        dense = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl="dense")
        fused = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl="fused",
                                       block_pages=1)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=1e-5)

    def test_pallas_interpret_matches_dense(self):
        q, kp, vp, tbl, lens = _state(b=3, h=2, d=8, p=4, maxp=4,
                                      pool=16)
        dense = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=4, impl="dense")
        pal = paged_decode_attention(q, kp, vp, tbl, lens, page_size=4,
                                     impl="pallas_interpret")
        np.testing.assert_allclose(np.asarray(pal), np.asarray(dense),
                                   atol=1e-5)

    @pytest.mark.parametrize("impl", ["dense", "fused",
                                      "pallas_interpret"])
    def test_trash_page_never_read(self, impl):
        """Finite garbage in page 0 (the reserved trash page) must not
        change any live slot's output — the `pos <= length` mask
        contract every impl shares."""
        q, kp, vp, tbl, lens = _state()
        clean = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl=impl)
        kp2 = kp.at[0].set(1e30)
        vp2 = vp.at[0].set(1e30)
        dirty = paged_decode_attention(q, kp2, vp2, tbl, lens,
                                       page_size=8, impl=impl)
        live = np.asarray(lens) > 0
        np.testing.assert_array_equal(np.asarray(dirty)[live],
                                      np.asarray(clean)[live])
        assert np.isfinite(np.asarray(dirty)[live]).all()

    def test_invalid_impl_raises(self):
        q, kp, vp, tbl, lens = _state(b=1, maxp=1)
        with pytest.raises(ValueError, match="impl"):
            paged_decode_attention(q, kp, vp, tbl, lens, page_size=8,
                                   impl="nope")


class TestBucketHelpers:
    def test_used_page_bucket_pow2_and_clamp(self):
        assert used_page_bucket(0, 8, 8) == 1
        assert used_page_bucket(7, 8, 8) == 1
        assert used_page_bucket(8, 8, 8) == 2
        assert used_page_bucket(23, 8, 8) == 4
        assert used_page_bucket(24, 8, 8) == 4
        assert used_page_bucket(32, 8, 8) == 8
        assert used_page_bucket(63, 8, 8) == 8
        assert used_page_bucket(1000, 8, 8) == 8  # clamped

    def test_chunk_pages(self):
        assert D._chunk_pages(8, 0) == 8
        assert D._chunk_pages(8, 16) == 8
        assert D._chunk_pages(8, 3) == 2   # largest divisor <= request
        assert D._chunk_pages(8, 4) == 4
        assert D._chunk_pages(1, 1) == 1

    def test_decode_hbm_bytes_dense_carries_gather_tax(self):
        d = decode_hbm_bytes("dense", 8, 8, 16, 16, 4)
        f = decode_hbm_bytes("fused", 8, 8, 16, 16, 4)
        p = decode_hbm_bytes("pallas", 8, 8, 16, 16, 4)
        assert d > 2 * f        # the materialized copy + score plane
        assert f == p

    def test_static_dispatch_is_dense(self):
        assert static_decode_dispatch() == ("dense", 0)


class TestDecodeAttnTunerSite:
    def test_golden_key_and_model_flips_to_fused(self, tuner):
        rec = autotune.decide_decode_attn((4, 4, 16), 8, 4, jnp.float32)
        assert rec is not None
        assert rec["key"] == "decode_attn|b4h4d16p8m4|float32|cpu"
        assert rec["impl"] == "fused"        # analytic gather-tax model
        assert rec["source"] == "model"
        assert rec["static"] == "dense"
        assert rec["block_pages"] >= 1

    def test_auto_dispatch_consults_and_caches(self, tuner):
        q, kp, vp, tbl, lens = _state()
        out = paged_decode_attention(q, kp, vp, tbl, lens, page_size=8,
                                     impl="auto")
        dense = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl="dense")
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5)
        doc = json.loads(tuner.read_text())
        sites = {r["site"] for r in doc["decisions"].values()}
        assert "decode_attn" in sites

    def test_measured_prewarm_cold_then_warm(self, tuner, monkeypatch):
        monkeypatch.setenv("BIGDL_TUNER_MEASURE", "1")
        monkeypatch.setenv("BIGDL_TUNER_MEASURE_ITERS", "1")
        autotune.reset()
        autotune.prewarm_decode_attn(2, 2, 8, page_size=4, maxp=2)
        doc = json.loads(tuner.read_text())
        recs = [r for r in doc["decisions"].values()
                if r["site"] == "decode_attn"]
        assert recs and recs[0]["source"] == "measured"
        assert recs[0]["measured_s"]
        # pallas is measurable (interpret) so it must have been probed
        assert any(lbl.startswith("pallas")
                   for lbl in recs[0]["measured_s"])
        autotune.reset()    # fresh process: everything from the cache
        autotune.prewarm_decode_attn(2, 2, 8, page_size=4, maxp=2)
        st = autotune.get_cache().stats()
        assert st["misses"] == 0 and st["hits"] >= 1

    def test_tuner_off_auto_is_static_dense(self):
        # with the tuner off, impl="auto" must never consult the site:
        # no cache, no decisions, numerics == dense
        q, kp, vp, tbl, lens = _state(b=2, maxp=2)
        out = paged_decode_attention(q, kp, vp, tbl, lens, page_size=8,
                                     impl="auto")
        dense = paged_decode_attention(q, kp, vp, tbl, lens,
                                       page_size=8, impl="dense")
        np.testing.assert_array_equal(np.asarray(out), np.asarray(dense))
        assert autotune.get_cache().decisions == {}


class TestInt8MMSite:
    def _mats(self, m=4, k=32, n=64, seed=0):
        from bigdl_tpu.ops.quantized_matmul import quantize_per_channel

        rs = np.random.RandomState(seed)
        x = jnp.asarray(rs.randn(m, k).astype(np.float32))
        w = jnp.asarray((rs.randn(n, k) * 0.1).astype(np.float32))
        w_q, w_s = quantize_per_channel(w, axis=0)
        return x, w, w_q, w_s

    def test_dequant_impl_close_to_float(self):
        from bigdl_tpu.ops.quantized_matmul import int8_matmul

        x, w, w_q, w_s = self._mats()
        want = np.asarray(jnp.matmul(x, w.T))
        got = np.asarray(int8_matmul(x, w_q, w_s, impl="dequant"))
        np.testing.assert_allclose(got, want, atol=0.05, rtol=0.05)
        # int8 and dequant agree with each other within activation-
        # quantization noise
        i8 = np.asarray(int8_matmul(x, w_q, w_s))
        np.testing.assert_allclose(got, i8, atol=0.1, rtol=0.1)

    def test_auto_is_int8_when_tuner_off(self):
        from bigdl_tpu.ops.quantized_matmul import int8_matmul

        x, _w, w_q, w_s = self._mats()
        np.testing.assert_array_equal(
            np.asarray(int8_matmul(x, w_q, w_s, impl="auto")),
            np.asarray(int8_matmul(x, w_q, w_s)))
        assert autotune.get_cache().decisions == {}

    def test_invalid_impl_raises(self):
        from bigdl_tpu.ops.quantized_matmul import int8_matmul

        x, _w, w_q, w_s = self._mats()
        with pytest.raises(ValueError, match="impl"):
            int8_matmul(x, w_q, w_s, impl="bogus")

    def test_site_golden_key_and_never_lose(self, tuner):
        rec = autotune.decide_int8_mm((4, 32), (64, 32), jnp.float32)
        assert rec is not None
        assert rec["key"] == "int8_mm|m4k32n64|float32|cpu"
        # model-only: the static int8 path wins (dequant's f32 weight
        # round trip costs more bytes at decode shapes)
        assert rec["impl"] == "int8" and rec["static"] == "int8"

    def test_measured_prewarm_persists(self, tuner, monkeypatch):
        monkeypatch.setenv("BIGDL_TUNER_MEASURE", "1")
        monkeypatch.setenv("BIGDL_TUNER_MEASURE_ITERS", "1")
        autotune.reset()
        autotune.prewarm_int8_mm(4, 16, 32)
        doc = json.loads(tuner.read_text())
        recs = [r for r in doc["decisions"].values()
                if r["site"] == "int8_mm"]
        assert recs and recs[0]["source"] == "measured"
        assert set(recs[0]["measured_s"]) == {"int8", "dequant"}
