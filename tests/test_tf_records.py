"""TFRecord / tf.train.Example codec + graph-side input-pipeline tests.

Reference analogue: «bigdl»/utils/tf/BigDLSessionImpl — the session's
stated purpose is running TF graphs whose input side is a reader/queue/
ParseExample pipeline (SURVEY.md §2.1 "TensorFlow interop").  VERDICT
r4 item 5's done-gate lives here: import a frozen graph WITH its input
pipeline attached and fine-tune under DistriOptimizer in one test.
"""

import numpy as np
import pytest

from bigdl_tpu.utils.tf_interop import (
    _DT_FLOAT,
    _DT_INT64,
    _DT_STRING,
    GraphDefBuilder,
    TensorflowLoader,
)
from bigdl_tpu.utils.tf_records import (
    FixedLenFeature,
    TFRecordExampleDataset,
    TFRecordWriter,
    encode_example,
    parse_example,
    tfrecord_iterator,
)


# ------------------------------------------------------------------ codec


def test_tfrecord_roundtrip(tmp_path):
    path = str(tmp_path / "data.tfrecord")
    records = [b"alpha", b"", b"x" * 1000]
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
    assert list(tfrecord_iterator(path)) == records


def test_tfrecord_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "bad.tfrecord")
    with TFRecordWriter(path) as w:
        w.write(b"payload-payload")
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError):
        list(tfrecord_iterator(path))
    # verify_crc=False reads it anyway
    assert len(list(tfrecord_iterator(path, verify_crc=False))) == 1


def test_example_roundtrip_all_kinds():
    ex = encode_example({
        "img": np.arange(6, dtype=np.float32),
        "label": np.asarray([3], dtype=np.int64),
        "neg": [-5, 7],
        "raw": b"\x01\x02\xff",
        "name": "sample-1",
    })
    spec = {
        "img": FixedLenFeature((2, 3), np.float32),
        "label": FixedLenFeature((), np.int64),
        "neg": FixedLenFeature((2,), np.int64),
        "raw": FixedLenFeature((), bytes),
        "name": FixedLenFeature((), bytes),
    }
    out = parse_example(ex, spec)
    np.testing.assert_allclose(
        out["img"], np.arange(6, dtype=np.float32).reshape(2, 3))
    assert out["label"].tolist() == [3]
    assert out["neg"].tolist() == [-5, 7]  # zigzag-free two's complement
    assert out["raw"] == b"\x01\x02\xff"
    assert out["name"] == b"sample-1"


def test_example_default_and_missing():
    ex = encode_example({"a": np.ones(2, np.float32)})
    spec = {
        "a": FixedLenFeature((2,), np.float32),
        "b": FixedLenFeature((3,), np.float32, default_value=0.5),
    }
    out = parse_example(ex, spec)
    np.testing.assert_allclose(out["b"], np.full(3, 0.5, np.float32))
    with pytest.raises(KeyError):
        parse_example(ex, {"c": FixedLenFeature((1,), np.float32)})


def test_example_dataset_batches(tmp_path):
    path = str(tmp_path / "ds.tfrecord")
    with TFRecordWriter(path) as w:
        for i in range(10):
            w.write(encode_example({
                "x": np.full(4, i, np.float32),
                "y": np.asarray([i % 3], np.int64),
            }))
    ds = TFRecordExampleDataset(
        [path],
        {"x": FixedLenFeature((4,), np.float32),
         "y": FixedLenFeature((1,), np.int64)},
        batch_size=4,
    )
    batches = list(ds.batches())
    assert [b["x"].shape[0] for b in batches] == [4, 4, 2]
    assert list(ds.batches(drop_remainder=True))[-1]["x"].shape[0] == 4
    table = ds.materialize()
    assert table["x"].shape == (10, 4)
    np.testing.assert_allclose(table["x"][:, 0], np.arange(10))


# ------------------------------------------------- pipeline graph helpers


def _pipeline_graphdef(filenames, d=8, k=4, raw_features=False, rs=None):
    """A TF1-style training graph WITH its input pipeline attached:

    Const(files) -> FIFOQueue(fq) <- QueueEnqueueMany
    TFRecordReader + ReaderRead(fq) -> FIFOQueue(eq) <- QueueEnqueue
    QueueDequeueMany(eq, 16) -> ParseExample -> [DecodeRaw ->] model
    """
    rs = rs or np.random.RandomState(3)
    b = GraphDefBuilder()
    b.const("files", np.asarray(filenames, dtype=object))
    b.op("fq", "FIFOQueueV2", [],
         component_types=b.attr_types([_DT_STRING]))
    b.op("enq_files", "QueueEnqueueManyV2", ["fq", "files"])
    b.op("reader", "TFRecordReaderV2", [])
    b.op("read", "ReaderReadV2", ["reader", "fq"])
    b.op("eq", "FIFOQueueV2", [],
         component_types=b.attr_types([_DT_STRING]))
    b.op("enq_ex", "QueueEnqueueV2", ["eq", "read:1"])
    b.const("batch", np.asarray(16, np.int32))
    b.op("deq", "QueueDequeueManyV2", ["eq", "batch"],
         component_types=b.attr_types([_DT_STRING]))
    b.const("key_x", np.asarray(["x"], dtype=object))
    b.const("key_y", np.asarray(["y"], dtype=object))
    b.const("names", np.asarray([], dtype=object))
    if raw_features:
        b.const("def_x", np.asarray([], dtype=object))
    else:
        b.const("def_x", np.zeros(0, np.float32))
    b.const("def_y", np.zeros(0, np.float32))
    x_type = _DT_STRING if raw_features else _DT_FLOAT
    b.op("parse", "ParseExample",
         ["deq", "names", "key_x", "key_y", "def_x", "def_y"],
         Nsparse=b.attr_i(0), Ndense=b.attr_i(2),
         Tdense=b.attr_types([x_type, _DT_FLOAT]),
         dense_shapes=b.attr_shapes(
             [[] if raw_features else [d], [1]]))
    feat = "parse"
    if raw_features:
        b.op("decoded", "DecodeRaw", ["parse"],
             out_type=b.attr_type(_DT_FLOAT))
        feat = "decoded"
    # the model: Linear(d->k) + LogSoftmax, deliberately random init
    w1 = (rs.randn(d, 32) * 0.3).astype(np.float32)
    w2 = (rs.randn(32, k) * 0.3).astype(np.float32)
    b.const("w1", w1)
    b.const("w2", w2)
    b.op("mm1", "MatMul", [feat, "w1"])
    b.op("r", "Relu", ["mm1"])
    b.op("mm2", "MatMul", ["r", "w2"])
    b.op("logp", "LogSoftmax", ["mm2"])
    return b.tobytes()


def _write_records(tmp_path, x, y, raw=False, shard=1):
    files = []
    shards = np.array_split(np.arange(len(x)), shard)
    for si, idx in enumerate(shards):
        path = str(tmp_path / f"train-{si}.tfrecord")
        with TFRecordWriter(path) as w:
            for i in idx:
                feats = {"y": np.asarray([y[i]], np.float32)}
                if raw:
                    feats["x"] = x[i].astype("<f4").tobytes()
                else:
                    feats["x"] = x[i]
                w.write(encode_example(feats))
        files.append(path)
    return files


# ------------------------------------------------------- extraction tests


def test_extract_input_pipeline(tmp_path):
    rs = np.random.RandomState(0)
    x = rs.randn(32, 8).astype(np.float32)
    y = rs.randint(1, 5, 32).astype(np.float32)
    files = _write_records(tmp_path, x, y, shard=2)
    loader = TensorflowLoader(data=_pipeline_graphdef(files))
    pipe = loader.extract_input_pipeline()
    # filename consts discovered from the graph, dequeue batch size kept
    assert pipe.dataset.filenames == files
    assert pipe.batch_size == 16
    # only the feature tensor is model input; the label seam is
    # host-side only (nothing downstream consumes it)
    assert pipe.seam_refs == ["parse"]
    assert pipe.seam_keys == ["x"]
    xs, table = pipe.feature_table()
    np.testing.assert_allclose(xs[0], x, rtol=1e-6)
    np.testing.assert_allclose(table["y"].reshape(-1), y)


def test_pipeline_model_outputs_exclude_queue_sinks(tmp_path):
    rs = np.random.RandomState(0)
    x = rs.randn(8, 8).astype(np.float32)
    y = np.ones(8, np.float32)
    files = _write_records(tmp_path, x, y)
    loader = TensorflowLoader(data=_pipeline_graphdef(files))
    pipe = loader.extract_input_pipeline()
    # enqueue ops are sinks but NOT model outputs
    assert loader.model_outputs(exclude=pipe.nodes) == ["logp"]


def test_session_trains_from_graph_input_pipeline(tmp_path):
    """The VERDICT r4 item-5 gate: frozen graph + its own input
    pipeline, fine-tuned end-to-end under DistriOptimizer."""
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import evaluate_dataset
    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.utils.tf_interop import BigDLSessionImpl

    rs = np.random.RandomState(11)
    d, k, n = 8, 4, 256
    wtrue = rs.randn(d, k)
    x = rs.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ wtrue, axis=1) + 1).astype(np.float32)
    files = _write_records(tmp_path, x, y, shard=3)

    Engine.reset()
    Engine.init()
    try:
        sess = BigDLSessionImpl(data=_pipeline_graphdef(files, d=d, k=k))
        assert sess.pipeline is not None
        trained = sess.train_with_pipeline(
            ClassNLLCriterion(), label_key="y",
            label_transform=lambda a: a.reshape(-1),
            optim_method=SGD(learningrate=0.5),
            end_trigger=Trigger.max_epoch(8), distributed=True)
        (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, 64),
                                  [Top1Accuracy()])
        value, _ = acc.result()
        assert value > 0.9, f"pipeline fine-tune accuracy {value}"
    finally:
        Engine.reset()


def test_session_pipeline_decode_raw(tmp_path):
    """Bytes features + DecodeRaw: the decode happens host-side, the
    DecodeRaw node becomes the model's Input seam."""
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.utils.tf_interop import BigDLSessionImpl

    rs = np.random.RandomState(5)
    d, k, n = 8, 4, 64
    x = rs.randn(n, d).astype(np.float32)
    y = (rs.randint(0, k, n) + 1).astype(np.float32)
    files = _write_records(tmp_path, x, y, raw=True)

    sess = BigDLSessionImpl(
        data=_pipeline_graphdef(files, d=d, k=k, raw_features=True))
    assert sess.pipeline.seam_refs == ["decoded"]
    xs, table = sess.pipeline.feature_table()
    np.testing.assert_allclose(xs[0], x, rtol=1e-6)
    loss = sess.train_with_pipeline(
        ClassNLLCriterion(), label_key="y",
        label_transform=lambda a: a.reshape(-1),
        optim_method=SGD(learningrate=0.1),
        end_trigger=Trigger.max_epoch(1))
    assert loss is not None


def test_pipeline_filename_override(tmp_path):
    """filenames= beats the paths baked into the graph (the graph may
    ship cluster paths that do not exist locally)."""
    rs = np.random.RandomState(0)
    x = rs.randn(8, 8).astype(np.float32)
    y = np.ones(8, np.float32)
    files = _write_records(tmp_path, x, y)
    gd = _pipeline_graphdef(["/nonexistent/path.tfrecord"])
    loader = TensorflowLoader(data=gd)
    pipe = loader.extract_input_pipeline(filenames=files)
    assert pipe.dataset.filenames == files
    xs, _ = pipe.feature_table()
    assert xs[0].shape == (8, 8)
