"""Test harness config.

The reference's distributed tests run the REAL DistriOptimizer on a
``local[4]`` Spark master — no fake comms backend (SURVEY.md §4.5).  The
rebuild's identical trick: force 8 virtual CPU devices so the real
shard_map + psum_scatter/all_gather path executes in one process.

Note: this machine's sitecustomize registers an `axon` TPU PJRT plugin
and force-sets jax_platforms="axon,cpu" at interpreter start, so the env
var alone is not enough — we must update the config after importing jax.
XLA_FLAGS still has to be set before the CPU backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.common import RandomGenerator

    RandomGenerator.RNG.set_seed(1)
    yield


def pytest_configure(config):
    assert jax.default_backend() == "cpu", "tests must run on CPU devices"
    assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"
