"""Criterion specs (reference pattern: «test»/nn/<Criterion>Spec.scala)."""

import numpy as np
import jax.numpy as jnp

from bigdl_tpu.nn import (
    AbsCriterion, BCECriterion, BCECriterionWithLogits, ClassNLLCriterion,
    CrossEntropyCriterion, DistKLDivCriterion, HingeEmbeddingCriterion,
    L1Cost, MarginCriterion, MSECriterion, MultiCriterion,
    ParallelCriterion, SmoothL1Criterion, TimeDistributedCriterion,
)


def test_class_nll_one_based():
    logp = jnp.log(jnp.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]]))
    target = jnp.array([1.0, 2.0])  # 1-based
    c = ClassNLLCriterion()
    loss = float(c.forward(logp, target))
    expected = -(np.log(0.7) + np.log(0.8)) / 2
    np.testing.assert_allclose(loss, expected, rtol=1e-4)
    grad = np.asarray(c.backward(logp, target))
    assert grad.shape == logp.shape
    # gradient only on the target entries, -1/N
    np.testing.assert_allclose(grad[0, 0], -0.5, rtol=1e-4)
    np.testing.assert_allclose(grad[0, 1], 0.0, atol=1e-8)


def test_class_nll_weights_and_sum():
    logp = jnp.log(jnp.array([[0.5, 0.5], [0.5, 0.5]]))
    target = jnp.array([1.0, 2.0])
    c = ClassNLLCriterion(weights=[1.0, 3.0], size_average=True)
    loss = float(c.forward(logp, target))
    # weighted mean: (1*log2 + 3*log2)/(1+3) = log2
    np.testing.assert_allclose(loss, np.log(2), rtol=1e-6)


def test_cross_entropy_equals_logsoftmax_nll():
    logits = jnp.array([[1.0, 2.0, 0.5], [0.1, -1.0, 3.0]])
    target = jnp.array([2.0, 3.0])
    ce = float(CrossEntropyCriterion().forward(logits, target))
    import jax

    nll = float(
        ClassNLLCriterion().forward(jax.nn.log_softmax(logits, -1), target)
    )
    np.testing.assert_allclose(ce, nll, rtol=1e-6)


def test_mse_and_abs():
    x = jnp.array([[1.0, 2.0]])
    t = jnp.array([[0.0, 0.0]])
    np.testing.assert_allclose(float(MSECriterion().forward(x, t)), 2.5)
    np.testing.assert_allclose(float(AbsCriterion().forward(x, t)), 1.5)
    np.testing.assert_allclose(
        float(MSECriterion(size_average=False).forward(x, t)), 5.0
    )


def test_smooth_l1():
    x = jnp.array([0.5, 3.0])
    t = jnp.array([0.0, 0.0])
    # 0.5*0.25 and 3-0.5 -> mean = (0.125 + 2.5)/2
    np.testing.assert_allclose(
        float(SmoothL1Criterion().forward(x, t)), (0.125 + 2.5) / 2, rtol=1e-6
    )


def test_bce_variants():
    p = jnp.array([0.9, 0.1])
    t = jnp.array([1.0, 0.0])
    v = float(BCECriterion().forward(p, t))
    np.testing.assert_allclose(v, -np.log(0.9), rtol=1e-4)
    logits = jnp.log(p / (1 - p))
    v2 = float(BCECriterionWithLogits().forward(logits, t))
    np.testing.assert_allclose(v2, v, rtol=1e-4)


def test_margin_and_hinge():
    x = jnp.array([0.5, -0.5])
    t = jnp.array([1.0, -1.0])
    np.testing.assert_allclose(
        float(MarginCriterion().forward(x, t)), 0.5, rtol=1e-6
    )
    h = HingeEmbeddingCriterion(margin=1.0)
    np.testing.assert_allclose(
        float(h.forward(jnp.array([0.3, 0.4]), jnp.array([1.0, -1.0]))),
        (0.3 + 0.6) / 2, rtol=1e-6,
    )


def test_kl_div():
    logq = jnp.log(jnp.array([[0.5, 0.5]]))
    p = jnp.array([[0.25, 0.75]])
    v = float(DistKLDivCriterion().forward(logq, p))
    expected = (0.25 * np.log(0.25 / 0.5) + 0.75 * np.log(0.75 / 0.5)) / 2
    np.testing.assert_allclose(v, expected, rtol=1e-3)


def test_l1cost():
    np.testing.assert_allclose(
        float(L1Cost().forward(jnp.array([-1.0, 2.0]), None)), 3.0
    )


def test_multi_criterion():
    x = jnp.array([[0.0, 1.0]])
    t = jnp.array([[1.0, 1.0]])
    mc = MultiCriterion().add(MSECriterion(), 0.5).add(AbsCriterion(), 2.0)
    v = float(mc.forward(x, t))
    np.testing.assert_allclose(v, 0.5 * 0.5 + 2.0 * 0.5, rtol=1e-6)


def test_parallel_criterion():
    pc = ParallelCriterion().add(MSECriterion(), 1.0).add(AbsCriterion(), 1.0)
    inp = (jnp.array([1.0]), jnp.array([2.0]))
    tgt = (jnp.array([0.0]), jnp.array([0.0]))
    np.testing.assert_allclose(float(pc.forward(inp, tgt)), 1.0 + 2.0)
    g = pc.backward(inp, tgt)
    assert len(g) == 2


def test_time_distributed_criterion():
    # (batch=2, time=3, classes=2) log-probs
    logp = jnp.log(jnp.full((2, 3, 2), 0.5))
    target = jnp.ones((2, 3))
    inner = ClassNLLCriterion(size_average=True)
    c = TimeDistributedCriterion(inner, size_average=True)
    v = float(c.forward(logp, target))
    np.testing.assert_allclose(v, np.log(2), rtol=1e-6)
    c2 = TimeDistributedCriterion(inner, size_average=False)
    np.testing.assert_allclose(float(c2.forward(logp, target)), 3 * np.log(2),
                               rtol=1e-6)


def test_poisson_criterion():
    from bigdl_tpu.nn import PoissonCriterion

    p = jnp.asarray([[1.0, 2.0], [0.5, 3.0]])
    t = jnp.asarray([[1.0, 1.0], [2.0, 2.0]])
    got = float(PoissonCriterion().loss(p, t))
    expect = float(np.mean(np.asarray(p) - np.asarray(t) * np.log(np.asarray(p))))
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_cosine_proximity_criterion():
    from bigdl_tpu.nn import CosineProximityCriterion

    p = jnp.asarray([[1.0, 0.0], [0.0, 2.0]])
    t = jnp.asarray([[1.0, 0.0], [0.0, -1.0]])
    got = float(CosineProximityCriterion().loss(p, t))
    # rows: cos=1 and cos=-1 -> -mean = 0
    np.testing.assert_allclose(got, 0.0, atol=1e-6)
    # reduction semantics pin (ADVICE r3 #1): Keras cosine_proximity
    # averages the normalized elementwise PRODUCT over all elements —
    # two perfectly-aligned 2-D rows give -0.5, not the per-row-mean -1
    pa = jnp.asarray([[3.0, 0.0], [0.0, 5.0]])
    got_aligned = float(CosineProximityCriterion().loss(pa, pa))
    np.testing.assert_allclose(got_aligned, -0.5, atol=1e-6)
    # gradient exists and is finite — including for an all-zero row
    # (ReLU tails emit those; linalg.norm's grad at 0 is NaN and a
    # maximum() clamp would not mask it)
    import jax

    g = jax.grad(lambda x: CosineProximityCriterion().loss(x, t))(p)
    assert np.isfinite(np.asarray(g)).all()
    pz = jnp.asarray([[0.0, 0.0], [1.0, 2.0]])
    gz = jax.grad(lambda x: CosineProximityCriterion().loss(x, t))(pz)
    assert np.isfinite(np.asarray(gz)).all()


def test_mape_and_msle_criterions():
    from bigdl_tpu.nn import (
        MeanAbsolutePercentageCriterion, MeanSquaredLogarithmicCriterion,
    )

    p = jnp.asarray([[90.0], [110.0]])
    t = jnp.asarray([[100.0], [100.0]])
    mape = float(MeanAbsolutePercentageCriterion().loss(p, t))
    np.testing.assert_allclose(mape, 10.0, rtol=1e-5)
    msle = float(MeanSquaredLogarithmicCriterion().loss(p, t))
    expect = np.mean(
        (np.log(101.0) - np.log(np.asarray([91.0, 111.0]))) ** 2)
    np.testing.assert_allclose(msle, expect, rtol=1e-5)
