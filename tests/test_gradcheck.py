"""Gradient checking — numeric vs. analytic.

Mirrors the reference's perturbation-based GradientChecker (SURVEY.md
§4.2) that guards hand-written backwards.  Here backwards come from
``jax.vjp``, so this suite instead guards the *module contract*: that
``backward`` (vjp of the pure apply) matches finite differences through
``forward``, including layers with custom vjps (GradientReversal,
L1Penalty) and table inputs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.nn import (
    BatchNormalization, Bilinear, CAddTable, GradientReversal, L1Penalty,
    Linear, LogSoftMax, ReLU, Sequential, Sigmoid, SpatialConvolution,
    SpatialMaxPooling, Tanh,
)


def numeric_grad(f, x, eps=1e-3):
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


@pytest.mark.parametrize("layer_fn", [
    lambda: Linear(4, 3),
    lambda: Sequential().add(Linear(4, 5)).add(Tanh()).add(Linear(5, 2)),
    lambda: Sigmoid(),
    lambda: LogSoftMax(),
])
def test_input_gradients_match_numeric(layer_fn):
    m = layer_fn()
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)

    def scalar_out(xv):
        out = m.apply(m.params(), m.state(), jnp.asarray(xv, jnp.float32),
                      training=False)[0]
        return float(jnp.sum(out * out))

    xj = jnp.asarray(x)
    out, _ = m.apply(m.params(), m.state(), xj, training=False)
    m.is_training = False
    m.forward(xj)
    grad_in = m.backward(xj, 2 * out)
    num = numeric_grad(scalar_out, x)
    np.testing.assert_allclose(np.asarray(grad_in), num, rtol=1e-2, atol=1e-3)


def test_conv_param_gradients_match_numeric():
    m = SpatialConvolution(1, 2, 3, 3)
    x = jnp.asarray(np.random.RandomState(1).randn(1, 1, 5, 5), jnp.float32)
    m.zero_grad_parameters()
    m.is_training = False
    out = m.forward(x)
    m.backward(x, 2 * out)
    gw = np.asarray(m._grad_params["weight"])

    w0 = np.asarray(m.weight)

    def loss_at(wv):
        p = {"weight": jnp.asarray(wv, jnp.float32), "bias": m.bias}
        out = m.apply(p, {}, x, training=False)[0]
        return float(jnp.sum(out * out))

    num = numeric_grad(loss_at, w0)
    np.testing.assert_allclose(gw, num, rtol=1e-2, atol=1e-2)


def test_gradient_reversal():
    m = GradientReversal(0.5)
    x = jnp.array([1.0, 2.0])
    m.forward(x)
    g = m.backward(x, jnp.array([1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(g), [-0.5, -0.5])


def test_l1_penalty_gradient():
    m = L1Penalty(0.1)
    x = jnp.array([2.0, -3.0])
    out = m.forward(x)
    np.testing.assert_allclose(np.asarray(out), [2.0, -3.0])
    g = m.backward(x, jnp.array([1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(g), [1.1, 0.9], rtol=1e-6)


def test_table_input_gradients():
    m = CAddTable()
    a = jnp.array([1.0, 2.0])
    b = jnp.array([3.0, 4.0])
    m.forward((a, b))
    ga, gb = m.backward((a, b), jnp.array([1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(ga), [1, 1])
    np.testing.assert_allclose(np.asarray(gb), [1, 1])

    bl = Bilinear(3, 4, 2)
    x1 = jnp.asarray(np.random.RandomState(0).randn(2, 3), jnp.float32)
    x2 = jnp.asarray(np.random.RandomState(1).randn(2, 4), jnp.float32)
    bl.forward((x1, x2))
    g1, g2 = bl.backward((x1, x2), jnp.ones((2, 2)))
    assert g1.shape == (2, 3) and g2.shape == (2, 4)


def test_standalone_update_grad_input_vs_acc_grad():
    """Reference users call updateGradInput / accGradParameters
    separately (SURVEY.md §7 hard part 1)."""
    m = Linear(3, 2)
    x = jnp.ones((4, 3))
    m.forward(x)
    gi = m.update_grad_input(x, jnp.ones((4, 2)))
    assert gi.shape == (4, 3)
    m.zero_grad_parameters()
    m.acc_grad_parameters(x, jnp.ones((4, 2)))
    gw = m._grad_params["weight"]
    np.testing.assert_allclose(np.asarray(gw), 4.0)  # sum over batch of x=1
    # acc accumulates
    m.acc_grad_parameters(x, jnp.ones((4, 2)))
    np.testing.assert_allclose(np.asarray(m._grad_params["weight"]), 8.0)


def test_highway_gradients_match_numeric():
    from bigdl_tpu.nn import Highway, ReLU as _ReLU

    m = Highway(4, activation=_ReLU())
    x = np.random.RandomState(1).randn(3, 4).astype(np.float32)

    def scalar_out(xv):
        out = m.apply(m.params(), m.state(), jnp.asarray(xv, jnp.float32),
                      training=False)[0]
        return float(jnp.sum(out * out))

    g_num = numeric_grad(scalar_out, x)

    def f(xv):
        out = m.apply(m.params(), m.state(), xv, training=False)[0]
        return jnp.sum(out * out)

    g_ana = np.asarray(jax.grad(f)(jnp.asarray(x)))
    np.testing.assert_allclose(g_ana, g_num, rtol=2e-2, atol=2e-3)


def test_resize_bilinear_gradients_match_numeric():
    from bigdl_tpu.nn import ResizeBilinear

    m = ResizeBilinear(5, 7)
    x = np.random.RandomState(2).randn(1, 2, 3, 4).astype(np.float32)

    def scalar_out(xv):
        out = m.apply(m.params(), m.state(), jnp.asarray(xv, jnp.float32),
                      training=False)[0]
        return float(jnp.sum(out * out))

    g_num = numeric_grad(scalar_out, x)

    def f(xv):
        out = m.apply(m.params(), m.state(), xv, training=False)[0]
        return jnp.sum(out * out)

    g_ana = np.asarray(jax.grad(f)(jnp.asarray(x)))
    np.testing.assert_allclose(g_ana, g_num, rtol=2e-2, atol=2e-3)


def test_remat_gradients_match_numeric():
    from bigdl_tpu.nn import Remat

    m = Remat(Sequential().add(Linear(4, 6)).add(Tanh()).add(Linear(6, 2)))
    x = np.random.RandomState(3).randn(2, 4).astype(np.float32)

    def scalar_out(xv):
        out = m.apply(m.params(), m.state(), jnp.asarray(xv, jnp.float32),
                      training=False)[0]
        return float(jnp.sum(out * out))

    g_num = numeric_grad(scalar_out, x)

    def f(xv):
        out = m.apply(m.params(), m.state(), xv, training=False)[0]
        return jnp.sum(out * out)

    g_ana = np.asarray(jax.grad(f)(jnp.asarray(x)))
    np.testing.assert_allclose(g_ana, g_num, rtol=2e-2, atol=2e-3)
