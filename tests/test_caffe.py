"""Caffe interop tests — prototxt parsing, caffemodel wire round-trip,
loader graph construction (reference test analogue: CaffeLoaderSpec /
CaffePersisterSpec)."""

import numpy as np
import pytest

from bigdl_tpu.utils.caffe import (
    CaffeLoader,
    CaffePersister,
    format_prototxt,
    load_caffe_weights,
    load_caffemodel,
    parse_prototxt,
)

ALEXNETISH = """
name: "TestNet"
input: "data"
input_shape { dim: 1 dim: 3 dim: 32 dim: 32 }
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "relu1" }
layer {
  name: "norm1" type: "LRN" bottom: "relu1" top: "norm1"
  lrn_param { local_size: 5 alpha: 0.0001 beta: 0.75 }
}
layer {
  name: "pool1" type: "Pooling" bottom: "norm1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "bn1" type: "BatchNorm" bottom: "pool1" top: "bn1"
  batch_norm_param { eps: 0.001 }
}
layer {
  name: "scale1" type: "Scale" bottom: "bn1" top: "scale1"
  scale_param { bias_term: true }
}
layer {
  name: "fc" type: "InnerProduct" bottom: "scale1" top: "fc"
  inner_product_param { num_output: 10 }
}
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def test_parse_prototxt_roundtrip():
    net = parse_prototxt(ALEXNETISH)
    assert net["name"] == ["TestNet"]
    assert len(net["layer"]) == 8
    conv = net["layer"][0]
    assert conv["type"] == ["Convolution"]
    assert conv["convolution_param"][0]["num_output"] == [8]
    # format -> reparse -> same structure
    again = parse_prototxt(format_prototxt(net))
    assert again == net


def test_loader_builds_runnable_graph():
    model = CaffeLoader(prototxt_text=ALEXNETISH).load()
    model.evaluate()
    x = np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32)
    out = np.asarray(model.forward(x))
    assert out.shape == (2, 10)
    # softmax output sums to 1
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_eltwise_and_concat():
    txt = """
    name: "Branchy"
    input: "data"
    input_shape { dim: 1 dim: 4 dim: 8 dim: 8 }
    layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
      convolution_param { num_output: 4 kernel_size: 1 } }
    layer { name: "c2" type: "Convolution" bottom: "data" top: "c2"
      convolution_param { num_output: 4 kernel_size: 1 } }
    layer { name: "sum" type: "Eltwise" bottom: "c1" bottom: "c2" top: "sum"
      eltwise_param { operation: SUM } }
    layer { name: "cat" type: "Concat" bottom: "sum" bottom: "data" top: "cat"
      concat_param { axis: 1 } }
    """
    model = CaffeLoader(prototxt_text=txt).load()
    x = np.random.RandomState(1).randn(2, 4, 8, 8).astype(np.float32)
    out = np.asarray(model.forward(x))
    assert out.shape == (2, 8, 8, 8)


def test_persister_loader_roundtrip(tmp_path):
    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn.graph import Graph, Input

    inp = Input("data")
    c = L.SpatialConvolution(3, 6, 3, 3, 1, 1, 1, 1).set_name("conv1")(inp)
    r = L.ReLU().set_name("relu1")(c)
    p = L.SpatialMaxPooling(2, 2, 2, 2).set_name("pool1")(r)
    bn = L.SpatialBatchNormalization(6).set_name("bn1")(p)
    fl = L.Reshape([6 * 8 * 8]).set_name("flat")(bn)
    fc = L.Linear(6 * 8 * 8, 5).set_name("fc")(fl)
    g = Graph(inp, fc)
    # make BN stats non-trivial
    mod_bn = bn.module
    mod_bn.running_mean = mod_bn.running_mean + 0.3
    mod_bn.running_var = mod_bn.running_var * 2.0
    g.evaluate()

    proto = tmp_path / "net.prototxt"
    cm = tmp_path / "net.caffemodel"
    CaffePersister.save(g, str(proto), str(cm), input_shape=(3, 16, 16))

    blobs = load_caffemodel(str(cm))
    assert "conv1" in blobs and len(blobs["conv1"]["blobs"]) == 2
    assert blobs["conv1"]["blobs"][0].shape == (6, 3, 3, 3)

    reloaded = CaffeLoader(prototxt_path=str(proto), model_path=str(cm)).load()
    reloaded.evaluate()
    x = np.random.RandomState(2).randn(2, 3, 16, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(reloaded.forward(x)), np.asarray(g.forward(x)),
        rtol=2e-4, atol=2e-5,
    )


def test_load_weights_by_name(tmp_path):
    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn.graph import Graph, Input

    inp = Input("data")
    fc = L.Linear(4, 3).set_name("ip")(inp)
    g = Graph(inp, fc)
    proto = tmp_path / "a.prototxt"
    cm = tmp_path / "a.caffemodel"
    CaffePersister.save(g, str(proto), str(cm))

    inp2 = Input("data")
    fc2 = L.Linear(4, 3).set_name("ip")(inp2)
    g2 = Graph(inp2, fc2)
    load_caffe_weights(g2, str(cm))
    x = np.random.RandomState(3).randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(g2.forward(x)), np.asarray(g.forward(x)), rtol=1e-5
    )


def test_v1_legacy_text_format():
    txt = """
    name: "V1Net"
    input: "data"
    input_dim: 1 input_dim: 2 input_dim: 6 input_dim: 6
    layers { name: "c" type: CONVOLUTION bottom: "data" top: "c"
      convolution_param { num_output: 3 kernel_size: 3 } }
    layers { name: "r" type: RELU bottom: "c" top: "r" }
    layers { name: "s" type: SOFTMAX bottom: "r" top: "s" }
    """
    model = CaffeLoader(prototxt_text=txt).load()
    x = np.random.RandomState(4).randn(1, 2, 6, 6).astype(np.float32)
    out = np.asarray(model.forward(x))
    assert out.shape == (1, 3, 4, 4)
