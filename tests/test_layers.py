"""Per-layer unit specs.

Mirrors the reference's «test»/nn/<Layer>Spec.scala pattern (SURVEY.md
§4.1): fixed seed, small hand-sized tensors, assert forward values (and
backward via the gradcheck suite in test_gradcheck.py).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.nn import (
    Abs, AddConstant, BatchNormalization, CAdd, CMul, Dropout, ELU, HardTanh,
    Identity, LeakyReLU, Linear, LogSoftMax, LookupTable, MulConstant,
    Narrow, Normalize, PReLU, ReLU, ReLU6, Reshape, Select, Sequential,
    Sigmoid, SoftMax, SoftMin, SoftPlus, SoftSign, SpatialAveragePooling,
    SpatialBatchNormalization, SpatialConvolution, SpatialCrossMapLRN,
    SpatialDilatedConvolution, SpatialFullConvolution, SpatialMaxPooling,
    SpatialZeroPadding, Squeeze, Sum, Tanh, TemporalConvolution, Threshold,
    Transpose, Unsqueeze, View,
)


def test_linear_forward():
    m = Linear(3, 2, init_weight=np.array([[1., 2., 3.], [4., 5., 6.]]),
               init_bias=np.array([0.5, -0.5]))
    x = jnp.array([[1., 1., 1.]])
    out = m.forward(x)
    np.testing.assert_allclose(np.asarray(out), [[6.5, 14.5]], rtol=1e-6)


def test_linear_shapes_and_grad_api():
    m = Linear(4, 3)
    x = jnp.ones((5, 4))
    out = m.forward(x)
    assert out.shape == (5, 3)
    m.zero_grad_parameters()
    grad_in = m.backward(x, jnp.ones((5, 3)))
    assert grad_in.shape == (5, 4)
    w, g = m.parameters()
    assert len(w) == len(g) == 2


def test_relu_family():
    x = jnp.array([[-1.0, 0.0, 2.0, 7.0]])
    np.testing.assert_allclose(np.asarray(ReLU().forward(x)), [[0, 0, 2, 7]])
    np.testing.assert_allclose(np.asarray(ReLU6().forward(x)), [[0, 0, 2, 6]])
    np.testing.assert_allclose(
        np.asarray(LeakyReLU(0.1).forward(x)), [[-0.1, 0, 2, 7]], rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(HardTanh().forward(x)), [[-1, 0, 1, 1]]
    )
    np.testing.assert_allclose(
        np.asarray(Threshold(1.0, -5.0).forward(x)), [[-5, -5, 2, 7]]
    )


def test_softmax_logsoftmax():
    x = jnp.array([[1.0, 2.0, 3.0]])
    sm = np.asarray(SoftMax().forward(x))
    np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-5)
    ls = np.asarray(LogSoftMax().forward(x))
    np.testing.assert_allclose(np.exp(ls), sm, rtol=1e-4)
    smin = np.asarray(SoftMin().forward(x))
    np.testing.assert_allclose(smin, sm[:, ::-1], rtol=1e-4)


def test_elementwise_misc():
    x = jnp.array([[-2.0, 4.0]])
    np.testing.assert_allclose(np.asarray(Abs().forward(x)), [[2, 4]])
    np.testing.assert_allclose(np.asarray(AddConstant(1.0).forward(x)), [[-1, 5]])
    np.testing.assert_allclose(np.asarray(MulConstant(2.0).forward(x)), [[-4, 8]])
    np.testing.assert_allclose(
        np.asarray(SoftSign().forward(x)), [[-2 / 3, 4 / 5]], rtol=1e-6
    )
    sp = np.asarray(SoftPlus().forward(x))
    np.testing.assert_allclose(sp, np.log1p(np.exp([[-2.0, 4.0]])), rtol=1e-4)


def test_spatial_convolution_known_values():
    # 1x1x3x3 input, 1 output plane, 2x2 kernel of ones -> sums of windows
    m = SpatialConvolution(1, 1, 2, 2, with_bias=True)
    m.set_weights([np.ones((1, 1, 2, 2), np.float32), np.zeros(1, np.float32)])
    x = jnp.arange(9.0).reshape(1, 1, 3, 3)
    out = np.asarray(m.forward(x))
    expected = np.array([[[[8.0, 12.0], [20.0, 24.0]]]])
    np.testing.assert_allclose(out, expected)


def test_spatial_convolution_same_padding_and_stride():
    m = SpatialConvolution(2, 3, 3, 3, 2, 2, -1, -1)
    x = jnp.ones((2, 2, 8, 8))
    assert m.forward(x).shape == (2, 3, 4, 4)


def test_spatial_convolution_groups():
    m = SpatialConvolution(4, 4, 3, 3, 1, 1, 1, 1, n_group=2)
    x = jnp.ones((1, 4, 5, 5))
    assert m.forward(x).shape == (1, 4, 5, 5)


def test_dilated_and_full_convolution_shapes():
    d = SpatialDilatedConvolution(2, 3, 3, 3, 1, 1, 2, 2, 2, 2)
    assert d.forward(jnp.ones((1, 2, 9, 9))).shape == (1, 3, 9, 9)
    f = SpatialFullConvolution(3, 2, 4, 4, 2, 2, 1, 1)
    # out = (in-1)*2 - 2 + 4 = 2*in
    assert f.forward(jnp.ones((1, 3, 5, 5))).shape == (1, 2, 10, 10)


def test_temporal_convolution():
    m = TemporalConvolution(4, 6, 3)
    out = m.forward(jnp.ones((2, 10, 4)))
    assert out.shape == (2, 8, 6)


def test_max_pooling_values_and_ceil():
    m = SpatialMaxPooling(2, 2, 2, 2)
    x = jnp.arange(16.0).reshape(1, 1, 4, 4)
    out = np.asarray(m.forward(x))
    np.testing.assert_allclose(out, [[[[5, 7], [13, 15]]]])
    # 5x5 with ceil -> 3x3; floor -> 2x2
    x5 = jnp.arange(25.0).reshape(1, 1, 5, 5)
    assert SpatialMaxPooling(2, 2, 2, 2).forward(x5).shape == (1, 1, 2, 2)
    assert SpatialMaxPooling(2, 2, 2, 2).ceil().forward(x5).shape == (1, 1, 3, 3)


def test_avg_pooling_count_include_pad():
    x = jnp.ones((1, 1, 4, 4))
    m = SpatialAveragePooling(3, 3, 2, 2, 1, 1)
    out = np.asarray(m.forward(x))
    # corner window covers 4 real cells of 9 -> 4/9 with countIncludePad
    np.testing.assert_allclose(out[0, 0, 0, 0], 4.0 / 9.0, rtol=1e-6)
    m2 = SpatialAveragePooling(3, 3, 2, 2, 1, 1, count_include_pad=False)
    np.testing.assert_allclose(np.asarray(m2.forward(x))[0, 0, 0, 0], 1.0, rtol=1e-6)


def test_batchnorm_train_and_eval():
    m = BatchNormalization(3)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 3).astype(np.float32) * 3 + 1)
    m.training()
    out = np.asarray(m.forward(x))
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(axis=0), 1.0, atol=1e-2)
    # running stats moved toward batch stats
    assert not np.allclose(np.asarray(m.running_mean), 0.0)
    m.evaluate()
    out_eval = m.forward(x)
    assert out_eval.shape == x.shape


def test_batchnorm_stale_shift_self_heals():
    # Numerics contract of the shifted single-pass statistics
    # (layers.py BatchNormalization.apply): with a catastrophically
    # stale shift (zero-init running_mean, activations at 3000 with
    # std 0.01 — d^2/var ~ 1e11) the step-0 variance cancels, BUT
    # (a) the output stays finite (never NaN/Inf),
    # (b) the running MEAN update is exact at any shift, so it
    #     converges geometrically at the momentum rate, and
    # (c) once the shift has warmed, normalization is accurate again —
    #     the failure is transient by construction, unlike the
    #     uncentered E[x^2]-E[x]^2 form (flax/haiku) whose shift is
    #     pinned at zero forever.
    m = BatchNormalization(3)
    rs = np.random.RandomState(0)
    x = jnp.asarray(
        (rs.randn(64, 3) * 0.01 + 3000.0).astype(np.float32)
    )
    m.training()
    out0 = np.asarray(m.forward(x))
    assert np.all(np.isfinite(out0))
    # exact mean recursion: rm_1 = 0.9*0 + 0.1*batch_mean
    np.testing.assert_allclose(
        np.asarray(m.running_mean), 0.1 * np.asarray(x).mean(axis=0),
        rtol=1e-5,
    )
    # warm the running mean (~0.9^k * 3000 staleness); momentum 0.1
    for _ in range(200):
        m.forward(x)
    out = np.asarray(m.forward(x))
    # one f32 ulp of x (~2.4e-4 at 3000) is ~2.4% of the 0.01 std, and
    # eps=1e-5 vs var~1e-4 shrinks the output std to sqrt(1/1.1)~0.95:
    # input representation + eps bound achievable accuracy here
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=8e-2)
    np.testing.assert_allclose(out.std(axis=0), 1.0, atol=1.5e-1)
    # running_var healed to the true batch variance scale, not m2
    rv = np.asarray(m.running_var)
    assert np.all(rv < 1.0), rv


def test_batchnorm_constant_channel():
    # a constant channel (e.g. padding) has zero variance; both stats
    # paths must keep it finite (normalize by rsqrt(eps))
    m = BatchNormalization(2)
    x = jnp.asarray(
        np.stack(
            [np.full(32, 5.0), np.random.RandomState(1).randn(32)], axis=1
        ).astype(np.float32)
    )
    m.training()
    out = np.asarray(m.forward(x))
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out[:, 0], 0.0, atol=1e-3)


def test_spatial_batchnorm():
    m = SpatialBatchNormalization(4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 5, 5).astype(np.float32))
    out = np.asarray(m.forward(x))
    np.testing.assert_allclose(out.mean(axis=(0, 2, 3)), 0.0, atol=1e-5)


def test_dropout_train_eval():
    m = Dropout(0.5)
    x = jnp.ones((4, 100))
    m.training()
    out = np.asarray(m.forward(x))
    zeros = (out == 0).mean()
    assert 0.2 < zeros < 0.8
    kept = out[out != 0]
    np.testing.assert_allclose(kept, 2.0, rtol=1e-6)  # inverted scaling
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m.forward(x)), 1.0)


def test_lookup_table_one_based():
    m = LookupTable(5, 3)
    w = np.arange(15.0).reshape(5, 3).astype(np.float32)
    m.set_weights([w])
    idx = jnp.array([[1.0, 5.0]])
    out = np.asarray(m.forward(idx))
    np.testing.assert_allclose(out[0, 0], w[0])
    np.testing.assert_allclose(out[0, 1], w[4])


def test_shape_ops():
    x = jnp.arange(24.0).reshape(2, 3, 4)
    assert Reshape([12]).forward(x).shape == (2, 12)
    assert Reshape([3, 4]).forward(jnp.arange(12.0)).shape == (3, 4)
    assert View(-1, 6).forward(x).shape == (4, 6)
    assert Squeeze(2).forward(jnp.ones((2, 1, 4))).shape == (2, 4)
    assert Unsqueeze(2).forward(jnp.ones((2, 4))).shape == (2, 1, 4)
    assert Transpose([(1, 2)]).forward(x).shape == (3, 2, 4)
    assert Select(2, -1).forward(x).shape == (2, 4)
    np.testing.assert_allclose(
        np.asarray(Select(2, 1).forward(x)), np.asarray(x)[:, 0]
    )
    assert Narrow(2, 2, 2).forward(x).shape == (2, 2, 4)
    assert Sum(2).forward(x).shape == (2, 4)
    assert SpatialZeroPadding(1).forward(jnp.ones((1, 1, 3, 3))).shape == (1, 1, 5, 5)


def test_learnable_elementwise():
    c = CMul([3])
    c.set_weights([np.array([1.0, 2.0, 3.0], np.float32)])
    np.testing.assert_allclose(
        np.asarray(c.forward(jnp.ones((2, 3)))), [[1, 2, 3], [1, 2, 3]]
    )
    a = CAdd([3])
    a.set_weights([np.array([1.0, -1.0, 0.0], np.float32)])
    np.testing.assert_allclose(
        np.asarray(a.forward(jnp.zeros((1, 3)))), [[1, -1, 0]]
    )


def test_prelu():
    m = PReLU()
    x = jnp.array([[-4.0, 4.0]])
    np.testing.assert_allclose(np.asarray(m.forward(x)), [[-1.0, 4.0]])


def test_lrn_shape():
    m = SpatialCrossMapLRN(5, 1.0, 0.75, 1.0)
    assert m.forward(jnp.ones((2, 8, 4, 4))).shape == (2, 8, 4, 4)


def test_normalize():
    m = Normalize(2.0)
    x = jnp.array([[3.0, 4.0]])
    np.testing.assert_allclose(
        np.asarray(m.forward(x)), [[0.6, 0.8]], rtol=1e-5
    )


def test_sequential_and_find():
    model = Sequential().add(Linear(4, 8).set_name("l1")).add(ReLU()) \
        .add(Linear(8, 2).set_name("l2"))
    out = model.forward(jnp.ones((3, 4)))
    assert out.shape == (3, 2)
    assert model.find_module("l2") is model.modules[2]
    # params pytree shape
    p = model.params()
    assert set(p.keys()) == {"0", "1", "2"}
    assert "weight" in p["0"]


def test_get_set_weights_roundtrip():
    m = Sequential().add(Linear(3, 4)).add(Linear(4, 2))
    w = m.get_weights()
    w2 = [np.ones_like(a) for a in w]
    m.set_weights(w2)
    for a, b in zip(m.get_weights(), w2):
        np.testing.assert_allclose(a, b)


def test_identity_and_training_mode_propagation():
    m = Sequential().add(Identity()).add(Dropout(0.9))
    m.evaluate()
    assert not m.modules[1].is_training
    m.training()
    assert m.modules[1].is_training
