"""Quantized collectives v2 specs (parallel/wire.py — ISSUE 9).

The tentpole contracts, each cheap and deterministic on the 8-virtual-
device CPU mesh:

* quantize/dequantize roundtrips honour the per-block error bound for
  every wire dtype (int8, fp8_e4m3, fp8_e5m2) and bfloat16 casts;
* the staged ring reduce-scatter matches ``psum_scatter`` within the
  per-hop quantization bound, for every dtype, with f32 accumulation
  (the owner's final add is exact);
* error feedback: repeated reduces with the residual carried converge
  in the mean — the long-run bias is an order of magnitude below the
  single-shot quantization error — and the own-chunk residual row
  stays identically zero;
* ``psum`` / ``all_to_all`` / ``ppermute`` reproduce their lax
  counterparts' layouts exactly and stay differentiable (the
  cotangent rides the compressed wire through the custom_vjp);
* the opt-in compressed wires on the TP (``gradient_psum``), MoE
  (dispatch/combine) and ring-attention (K/V rotation) paths stay
  close to their exact counterparts and publish per-path golden byte
  counts + ``bigdl_collective_wire_savings_ratio{path=...}``;
* DistriOptimizer under fp8/int8-EF wires: EF state is created next
  to the flat ZeRO-1 vectors, updated by the step, dropped when EF is
  off, and the 200-step trajectory-agreement acceptance is sampled in
  miniature (scripts/wire_smoke.py runs the full A/B).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from bigdl_tpu import obs
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import (
    ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
)
from bigdl_tpu.obs import collectives as C
from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger
from bigdl_tpu.optim.distri_optimizer import _shard_map
from bigdl_tpu.parallel import wire
from bigdl_tpu.parallel.wire import WireSpec

N = 8


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("BIGDL_WIRE_DTYPE", "BIGDL_WIRE_BLOCK", "BIGDL_WIRE_EF"):
        monkeypatch.delenv(var, raising=False)
    from bigdl_tpu.config import refresh_from_env

    refresh_from_env()
    obs.reset()
    if not Engine.is_initialized():
        Engine.init()
    yield
    obs.reset()


def _mesh(n=N):
    return Engine.build_mesh({"data": n}, devices=jax.devices()[:n])


def _heavy(shape, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(*shape) * np.exp(rs.randn(*shape))).astype(np.float32)


def _gauge(path):
    fam = obs.get_registry().snapshot()["metrics"].get(
        "bigdl_collective_wire_savings_ratio")
    if not fam:
        return None
    for s in fam["samples"]:
        if s["labels"] == {"path": path}:
            return s["value"]
    return None


def _counter(op, dtype):
    fam = obs.get_registry().counter(
        "bigdl_collective_bytes_total", labels=("op", "dtype"))
    return fam.labels(op=op, dtype=dtype).value


SCALED = ("int8", "fp8_e4m3", "fp8_e5m2")


# ============================================================== WireSpec
class TestWireSpec:
    def test_rejects_unknown_dtype(self):
        with pytest.raises(ValueError, match="wire dtype"):
            WireSpec("fp16")

    def test_rejects_bad_block(self):
        with pytest.raises(ValueError, match="block"):
            WireSpec("int8", block=0)

    def test_rejects_ef_on_uncompressed(self):
        with pytest.raises(ValueError, match="error feedback"):
            WireSpec("float32", error_feedback=True)

    def test_classification(self):
        assert WireSpec("int8").scaled and WireSpec("int8").compressed
        assert not WireSpec("bfloat16").scaled
        assert WireSpec("bfloat16").compressed
        assert not WireSpec("none").compressed
        assert WireSpec("fp8_e4m3").wire_name == "float8_e4m3fn"
        assert WireSpec("fp8_e5m2").wire_name == "float8_e5m2"

    def test_resolve(self):
        assert wire.resolve(None) is None
        assert wire.resolve("none") is None
        assert wire.resolve("float32") is None
        spec = wire.resolve("int8")
        assert isinstance(spec, WireSpec) and spec.dtype == "int8"
        assert wire.resolve(spec) is spec
        with pytest.raises(TypeError):
            wire.resolve(8)

    def test_from_config_env(self, monkeypatch):
        monkeypatch.setenv("BIGDL_WIRE_DTYPE", "fp8_e5m2")
        monkeypatch.setenv("BIGDL_WIRE_BLOCK", "128")
        monkeypatch.setenv("BIGDL_WIRE_EF", "1")
        from bigdl_tpu.config import refresh_from_env

        refresh_from_env()
        spec = WireSpec.from_config()
        assert spec.dtype == "fp8_e5m2"
        assert spec.block == 128
        assert spec.error_feedback

    def test_padded_elems_and_layout(self):
        spec = WireSpec("int8", block=64)
        assert wire.padded_elems(676, spec, 8) == 1024
        assert wire.padded_elems(1024, spec, 8) == 1024
        assert wire.padded_elems(676, None, 8) == 680
        # psum_layout shrinks the block for small operands
        assert wire.psum_layout(16, spec, 8) == (16, 2)
        assert wire.psum_layout(512, spec, 8) == (512, 64)
        assert wire.effective_block(96, 64) == 48
        assert wire.effective_block(7, 64) == 7


# ============================================================ quantizers
class TestQuantize:
    @pytest.mark.parametrize("dtype", SCALED)
    def test_roundtrip_error_bound(self, dtype):
        spec = WireSpec(dtype, block=32)
        x = jnp.asarray(_heavy((4, 96)))
        payload, scales = wire.quantize(x, spec)
        back = wire.dequantize(payload, scales, spec, shape=x.shape)
        bm = np.abs(np.asarray(x)).reshape(-1, 32).max(-1)
        # symmetric scaled quantization: elementwise error <=
        # blockmax / (2 * qmax) — fp8 mantissa rounding is coarser
        # than the grid midpoint, so allow its relative step too
        step = {"int8": 1.0 / 254, "fp8_e4m3": 1.0 / 16,
                "fp8_e5m2": 1.0 / 4}[dtype]
        err = np.abs(np.asarray(back) - np.asarray(x)).reshape(-1, 32)
        assert np.all(err <= bm[:, None] * step + 1e-6)

    def test_zero_block_is_exact(self):
        spec = WireSpec("int8", block=16)
        x = jnp.zeros((32,))
        back = wire.dequantize(*wire.quantize(x, spec), spec,
                               shape=x.shape)
        np.testing.assert_array_equal(np.asarray(back), 0.0)

    def test_padding_dropped(self):
        spec = WireSpec("int8", block=32)
        x = jnp.asarray(_heavy((50,)))  # 50 -> padded to 64 internally
        back = wire.dequantize(*wire.quantize(x, spec), spec,
                               shape=x.shape)
        assert back.shape == (50,)

    def test_bfloat16_is_cast(self):
        spec = WireSpec("bfloat16")
        x = jnp.asarray(_heavy((64,)))
        payload, scales = wire.quantize(x, spec)
        assert scales is None and payload.dtype == jnp.bfloat16

    def test_roundtrip_grad_is_compressed(self):
        """The custom_vjp compresses the cotangent too — the backward
        'wire' quantizes, it does not pass f32 through."""
        spec = WireSpec("int8", block=8)
        x = jnp.asarray(_heavy((64,)))
        ct = jnp.asarray(_heavy((64,), seed=1))
        _, vjp = jax.vjp(lambda v: wire.roundtrip(v, spec), x)
        (got,) = vjp(ct)
        want = wire.dequantize(*wire.quantize(ct, spec), spec,
                               shape=ct.shape)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)


# ===================================================== staged ring reduce
class TestStagedRing:
    def _run(self, g_all, spec):
        mesh = _mesh()
        f = lambda gl: wire.reduce_scatter(gl[0], "data", N, spec)[0][None]
        sm = _shard_map(f, mesh, in_specs=(P("data", None),),
                        out_specs=P("data", None))
        return np.asarray(jax.jit(sm)(jnp.asarray(g_all))).reshape(-1)

    def _exact(self, g_all):
        mesh = _mesh()
        f = lambda gl: lax.psum_scatter(
            gl[0], "data", scatter_dimension=0, tiled=True)[None]
        sm = _shard_map(f, mesh, in_specs=(P("data", None),),
                        out_specs=P("data", None))
        return np.asarray(sm(jnp.asarray(g_all))).reshape(-1)

    @pytest.mark.parametrize("dtype", SCALED + ("bfloat16",))
    def test_matches_psum_scatter(self, dtype):
        block = 32
        g_all = _heavy((N, N * block * 3))
        spec = WireSpec(dtype, block=block)
        got = self._run(g_all, spec)
        want = self._exact(g_all)
        rel = np.abs(got - want).mean() / np.abs(want).mean()
        # e5m2 has 2 mantissa bits; everything else is much tighter
        assert rel < {"fp8_e5m2": 0.15}.get(dtype, 0.08), (dtype, rel)

    def test_uncompressed_spec_is_exact(self):
        g_all = _heavy((N, N * 16))
        got = self._run(g_all, None)
        np.testing.assert_allclose(got, self._exact(g_all), rtol=1e-6)

    def test_single_shard_is_exact_identity(self):
        """n == 1: no wire, no quantization — compression would cost
        error for zero bytes moved."""
        g = jnp.asarray(_heavy((128,)))
        out, ef = wire.reduce_scatter(g, "data", 1,
                                      WireSpec("int8", block=16))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(g))
        assert ef is None

    def test_rejects_misaligned_chunk(self):
        mesh = _mesh()
        spec = WireSpec("int8", block=64)
        g_all = _heavy((N, N * 16))  # chunk 16 < block 64

        def f(gl):
            return wire.reduce_scatter(gl[0], "data", N, spec)[0][None]

        sm = _shard_map(f, mesh, in_specs=(P("data", None),),
                        out_specs=P("data", None))
        with pytest.raises(ValueError, match="block"):
            sm(jnp.asarray(g_all))


# ========================================================= error feedback
class TestErrorFeedback:
    def test_bias_cancels_and_own_row_stays_zero(self):
        """The EF acceptance in miniature: reducing the SAME gradient
        R times with the residual carried, the running mean converges
        to the exact sum (quantization error dithers instead of
        biasing) — and the own-chunk residual row is identically zero
        (the owner's add is exact)."""
        mesh = _mesh()
        block = 16
        L = N * block * 3
        g_all = _heavy((N, N * L // N))
        spec = WireSpec("int8", block=block, error_feedback=True)

        def step(gl, efl):
            out, nef = wire.reduce_scatter(gl[0], "data", N, spec,
                                           ef=efl[0])
            return out[None], nef[None]

        sm = jax.jit(_shard_map(
            step, mesh,
            in_specs=(P("data", None), P("data", None, None)),
            out_specs=(P("data", None), P("data", None, None))))
        want = np.asarray(_shard_map(
            lambda gl: lax.psum_scatter(gl[0], "data",
                                        scatter_dimension=0,
                                        tiled=True)[None],
            mesh, in_specs=(P("data", None),),
            out_specs=P("data", None))(jnp.asarray(g_all))).reshape(-1)

        ef = jnp.zeros((N, N, L // N), jnp.float32)
        cum = np.zeros_like(want)
        rounds = 10
        single_err = None
        for i in range(rounds):
            out, ef = sm(jnp.asarray(g_all), ef)
            flat = np.asarray(out).reshape(-1)
            if i == 0:
                single_err = np.abs(flat - want).mean() / \
                    np.abs(want).mean()
            cum += flat
        bias = np.abs(cum / rounds - want).mean() / np.abs(want).mean()
        assert bias < single_err / 5, (bias, single_err)
        # own-chunk rows: device d's residual for chunk d is never
        # written — the final add is exact
        ef_np = np.asarray(ef)  # (N, N, L//N): [device, chunk, :]
        for d in range(N):
            np.testing.assert_array_equal(ef_np[d, d], 0.0)
        # the other rows are live (the residual really carries error)
        assert np.abs(ef_np).sum() > 0

    def test_ef_requires_compressed(self):
        with pytest.raises(ValueError, match="error feedback"):
            WireSpec("none", error_feedback=True)


# ================================================================= psum
class TestWirePsum:
    def test_matches_lax_psum(self):
        mesh = _mesh()
        x_all = _heavy((N, 5, 37))
        spec = WireSpec("int8", block=32)

        def f(xl):
            return wire.psum(xl[0], "data", N, spec)[0][None]

        sm = _shard_map(f, mesh, in_specs=(P("data", None, None),),
                        out_specs=P("data", None, None))
        got = np.asarray(jax.jit(sm)(jnp.asarray(x_all)))[0]
        want = x_all.sum(0)
        rel = np.abs(got - want).mean() / np.abs(want).mean()
        assert got.shape == want.shape and rel < 0.1, rel

    def test_uncompressed_is_lax_psum(self):
        mesh = _mesh()
        x_all = _heavy((N, 24))

        def f(xl):
            return wire.psum(xl[0], "data", N, None)[0][None]

        sm = _shard_map(f, mesh, in_specs=(P("data", None),),
                        out_specs=P("data", None))
        got = np.asarray(sm(jnp.asarray(x_all)))[0]
        np.testing.assert_allclose(got, x_all.sum(0), rtol=2e-5)


# ========================================================== data movers
class TestCompressedMoves:
    @pytest.mark.parametrize("shape,sa,ca", [
        ((8, 6, 4), 0, 0),       # in-place slice swap (ca == sa)
        ((16, 8, 4), 1, 2),      # ulysses fwd (ca > sa)
        ((8, 4, 16), 2, 1),      # ulysses bwd (ca < sa)
    ])
    def test_all_to_all_layout_matches_lax(self, shape, sa, ca):
        mesh = _mesh()
        x = _heavy((N,) + shape)
        spec = WireSpec("int8", block=8)
        inspec = P(*(("data",) + (None,) * len(shape)))

        def mine(xl):
            return wire.all_to_all(xl[0], "data", N, spec,
                                   split_axis=sa, concat_axis=ca)[None]

        def ref(xl):
            return lax.all_to_all(xl[0], "data", sa, ca, tiled=True)[None]

        sm = lambda f: jax.jit(_shard_map(
            f, mesh, in_specs=(inspec,), out_specs=inspec))
        got = np.asarray(sm(mine)(jnp.asarray(x)))
        want = np.asarray(sm(ref)(jnp.asarray(x)))
        assert got.shape == want.shape
        rel = np.abs(got - want).mean() / np.abs(want).mean()
        assert rel < 0.02, rel

    def test_all_to_all_uncompressed_delegates(self):
        mesh = _mesh()
        x = _heavy((N, 8, 4))
        inspec = P("data", None, None)

        def mine(xl):
            return wire.all_to_all(xl[0], "data", N, None,
                                   split_axis=0, concat_axis=1)[None]

        def ref(xl):
            return lax.all_to_all(xl[0], "data", 0, 1, tiled=True)[None]

        sm = lambda f: _shard_map(f, mesh, in_specs=(inspec,),
                                  out_specs=inspec)
        np.testing.assert_array_equal(
            np.asarray(sm(mine)(jnp.asarray(x))),
            np.asarray(sm(ref)(jnp.asarray(x))))

    def test_ppermute_matches_roll_and_grads(self):
        mesh = _mesh()
        x = _heavy((N, 4, 6))
        spec = WireSpec("int8", block=8)
        perm = [(j, (j + 1) % N) for j in range(N)]
        inspec = P("data", None, None)

        def f(xl):
            return wire.ppermute(xl[0], "data", perm, spec)[None]

        sm = _shard_map(f, mesh, in_specs=(inspec,), out_specs=inspec)
        got = np.asarray(jax.jit(sm)(jnp.asarray(x)))
        want = np.roll(x, 1, axis=0)
        rel = np.abs(got - want).mean() / np.abs(want).mean()
        assert rel < 0.02, rel

        def loss(xg):
            def inner(xl):
                y = wire.ppermute(xl[0], "data", perm, spec)
                return jnp.sum(y * y)[None]

            return jnp.sum(_shard_map(
                inner, mesh, in_specs=(inspec,),
                out_specs=P("data"))(xg))

        g = np.asarray(jax.grad(loss)(jnp.asarray(x)))
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ====================================================== path: TP psum
class TestTPGradientPsum:
    # 4-way mesh: the staged ring unrolls n-1 hops, and the eager
    # shard_map dispatch cost scales with both — 4 devices cover the
    # same code paths at a fraction of the tier-1 wall clock
    NT = 4

    def _grads(self):
        return {"w": jnp.asarray(_heavy((self.NT, 32, 16))),
                "b": jnp.asarray(_heavy((self.NT, 16), seed=1))}

    def test_exact_without_wire(self):
        from bigdl_tpu.parallel import gradient_psum

        mesh = _mesh(self.NT)
        grads = self._grads()
        got = gradient_psum(grads, mesh, axis="data")
        for k, v in grads.items():
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(v).sum(0), rtol=2e-5)

    def test_compressed_close_savings_and_golden_bytes(self):
        """Compressed sum stays close; the byte account matches the
        hand-computed staged-ring + quantized-gather budget (w: 512
        local elems at block 64; b: 16 local elems — psum_layout
        shrinks its block to 4)."""
        from bigdl_tpu.parallel import gradient_psum

        n = self.NT
        mesh = _mesh(n)
        grads = self._grads()
        got = gradient_psum(grads, mesh, axis="data",
                            wire=WireSpec("int8", block=64))
        for k, v in grads.items():
            exact = np.asarray(v).sum(0)
            rel = np.abs(np.asarray(got[k]) - exact).mean() / \
                np.abs(exact).mean()
            assert rel < 0.1, (k, rel)

        # per leaf: ring (n-1)*chunk payload + (n-1)*(chunk/blk) f32
        # scales, then gather payload padded*(n-1)/n + scales
        spec = WireSpec("int8", block=64)

        def leaf_bytes(sz):
            padded, blk = wire.psum_layout(sz, spec, n)
            chunk = padded // n
            ring = (n - 1) * chunk + (n - 1) * (chunk // blk) * 4
            gather = (padded + (padded // blk) * 4) * (n - 1) / n
            return ring + gather

        expect = leaf_bytes(512) + leaf_bytes(16)
        assert wire.psum_layout(16, spec, n) == (16, 4)
        assert _counter("psum", "int8") == expect
        baseline = C.all_reduce_bytes(512, "float32", n) \
            + C.all_reduce_bytes(16, "float32", n)
        assert _gauge("tp") == pytest.approx(baseline / expect)
        assert _gauge("tp") > 3.0

    def test_leaf_shape_validation(self):
        from bigdl_tpu.parallel import gradient_psum

        mesh = _mesh()
        with pytest.raises(ValueError, match="leading"):
            gradient_psum({"w": jnp.zeros((3, 4))}, mesh, axis="data")


# ======================================================== path: MoE a2a
class TestMoEWire:
    def _moe(self, mesh, **kw):
        from bigdl_tpu.common import RandomGenerator
        from bigdl_tpu.parallel import MoE

        RandomGenerator.RNG.set_seed(3)
        return MoE(8, 16, 4, top_k=2, capacity_factor=4.0, mesh=mesh,
                   **kw)

    def test_wire_output_close_and_savings(self):
        mesh = Engine.build_mesh({"expert": 4},
                                 devices=jax.devices()[:4])
        moe = self._moe(mesh)
        moew = self._moe(mesh, wire=WireSpec("fp8_e4m3", block=32))
        x = jnp.asarray(np.random.RandomState(0).randn(
            2, 16, 8).astype(np.float32))
        p = {k: getattr(moe, k) for k in moe.param_names}
        # jit both forwards (savings gauge + a2a counters are recorded
        # at trace time — once per call either way)
        y0 = np.asarray(jax.jit(moe.update_output_pure)(p, x))
        y1 = np.asarray(jax.jit(moew.update_output_pure)(p, x))
        rel = np.abs(y0 - y1).mean() / (np.abs(y0).mean() + 1e-9)
        assert 0 < rel < 0.15, rel
        assert _gauge("moe") is not None and _gauge("moe") > 3.0
        assert _counter("all_to_all", "float8_e4m3fn") > 0

    def test_actual_dtype_accounted_not_f32(self):
        """Satellite fix: bf16 activations must be billed at 2 bytes,
        not recorded as float32 unconditionally."""
        mesh = Engine.build_mesh({"expert": 4},
                                 devices=jax.devices()[:4])
        moe = self._moe(mesh)
        x = jnp.asarray(np.random.RandomState(0).randn(
            2, 16, 8)).astype(jnp.bfloat16)
        p = {k: getattr(moe, k) for k in moe.param_names}
        moe.update_output_pure(p, x)
        e, d, n_exp = 4, 8, 4
        s = 2 * 16
        cap = int(np.ceil(4.0 * s * 2 / e))
        expect = 2 * C.all_to_all_bytes(e * cap * d, "bfloat16", n_exp)
        assert _counter("all_to_all", "bfloat16") == expect
        assert _counter("all_to_all", "float32") == 0.0

    def test_wire_grads_flow(self):
        mesh = Engine.build_mesh({"expert": 4},
                                 devices=jax.devices()[:4])
        moew = self._moe(mesh, wire=WireSpec("int8", block=32))
        x = jnp.asarray(np.random.RandomState(0).randn(
            2, 16, 8).astype(np.float32))
        p = {k: getattr(moew, k) for k in moew.param_names}

        def loss(pp):
            y, aux = moew.forward_with_aux(pp, x)
            return jnp.sum(y * y) + aux

        g = jax.jit(jax.grad(loss))(p)
        leaves = jax.tree.leaves(g)
        assert all(bool(np.isfinite(np.asarray(t)).all())
                   for t in leaves)
        assert any(float(np.abs(np.asarray(t)).sum()) > 0
                   for t in leaves)


# ====================================================== path: ring K/V
class TestRingWire:
    # 4-way ring, small blocks: the compressed-hop graph is built per
    # unrolled hop for K and V — sized for tier-1 wall clock, same
    # code paths as a pod-wide ring
    NR = 4

    def _mesh(self):
        return Engine.build_mesh({"seq": self.NR},
                                 devices=jax.devices()[:self.NR])

    def _qkv(self):
        rs = np.random.RandomState(0)
        mk = lambda: jnp.asarray(rs.randn(1, 2, 32, 8)
                                 .astype(np.float32))
        return mk(), mk(), mk()

    def test_wire_close_savings_and_golden_bytes(self):
        from bigdl_tpu.parallel import ring_attention_sharded

        mesh = self._mesh()
        q, k, v = self._qkv()
        # jit: the compressed ring unrolls per-hop quantize graphs —
        # one compile beats eager op-by-op dispatch by ~10x wall clock;
        # byte accounting rides trace time either way (once per call)
        base = np.asarray(jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, mesh, causal=True))(q, k, v))
        obs.reset()
        wired = np.asarray(jax.jit(lambda a, b, c: ring_attention_sharded(
            a, b, c, mesh, causal=True,
            wire=WireSpec("int8", block=64)))(q, k, v))
        rel = np.abs(base - wired).mean() / np.abs(base).mean()
        assert 0 < rel < 0.1, rel
        # local K block 1*2*8*8 = 128 elems (block-aligned): K and V
        # each ride 3 hops at 1 byte + 128/64 f32 scales per hop
        payload = 2 * 128 * 3
        scales = 2 * (128 // 64) * 4 * 3
        assert _counter("ppermute", "int8") == payload
        assert _counter("ppermute", "float32") == scales
        baseline = 2 * 128 * 4 * 3
        assert _gauge("ring") == pytest.approx(
            baseline / (payload + scales))
        assert _gauge("ring") > 3.0

    def test_wire_grads_flow(self):
        from bigdl_tpu.parallel import ring_attention_sharded

        mesh = self._mesh()
        q, k, v = self._qkv()

        def loss(kk):
            out = ring_attention_sharded(
                q, kk, v, mesh, wire=WireSpec("int8", block=64))
            return jnp.sum(out * out)

        # jitted: the grad of the unrolled compressed-hop graph is the
        # single slowest eager dispatch in the suite (>100s); compiled
        # it is ~1s with identical gradients
        g = np.asarray(jax.jit(jax.grad(loss))(k))
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ================================================== DistriOptimizer e2e
def _toy(n=128, d=16, k=4, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, k)
    x = rs.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    return x, y


def _model(seed=7):
    from bigdl_tpu.common import RandomGenerator

    RandomGenerator.RNG.set_seed(seed)
    return Sequential().add(Linear(16, 32)).add(ReLU()) \
        .add(Linear(32, 4)).add(LogSoftMax())


class _Tape:
    def __init__(self):
        self.loss = {}

    def add_scalar(self, tag, value, step):
        if tag == "Loss":
            self.loss[step] = float(value)

    def add_histogram(self, *a, **k):
        pass

    def get_summary_trigger(self, name):
        return None

    def add_resilience(self, step, **counters):
        pass


class TestDistriWire:
    def _run(self, epochs=8, **kw):
        x, y = _toy()
        opt = DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                              batch_size=32, **kw)
        opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
        opt.set_end_when(Trigger.max_epoch(epochs))
        tape = _Tape()
        opt.set_train_summary(tape)
        opt.optimize()
        return tape.loss, opt

    def test_fp8_ef_tracks_f32(self):
        """The acceptance criterion in miniature (the 200-step A/B —
        and the fp8_e5m2 variant — is scripts/wire_smoke.py +
        TestStagedRing): with EF on, the fp8 trajectory tracks the f32
        wire closely."""
        base, _ = self._run(epochs=5, wire_dtype="float32")
        traj, opt = self._run(epochs=5, wire_dtype="fp8_e4m3",
                              wire_block=64, wire_ef=True)
        worst = max(abs(traj[s] - base[s]) / (abs(base[s]) + 1e-9)
                    for s in base)
        assert worst < 0.05, worst
        assert "wire_ef" in opt.optim_method.state

    def test_ef_state_lives_next_to_zero1_vectors(self):
        _, opt = self._run(epochs=1, wire_dtype="int8", wire_block=64,
                           wire_ef=True)
        st = opt.optim_method.state
        padded = opt._flat_elems + opt._pad
        ef = st["wire_ef"]
        assert tuple(ef.shape) == (8, padded)
        assert str(ef.dtype) == "float32"
        # the residual is live after training (steps really update it)
        assert float(jnp.abs(ef).sum()) > 0
        # velocity rides next to it in the same flat layout
        assert st["velocity"].shape == (padded,)
        # ... and the topology tag says so
        topo = opt._topology()
        assert topo["wire"] == {"dtype": "int8", "block": 64,
                                "ef": True}

    def test_no_ef_no_state(self):
        _, opt = self._run(epochs=1, wire_dtype="int8", wire_block=64)
        assert "wire_ef" not in opt.optim_method.state

    def test_ef_off_drops_checkpointed_residual(self):
        """Resume a run trained with EF under an EF-off config: the
        dead residual must not be threaded through the step."""
        _, opt = self._run(epochs=1, wire_dtype="int8", wire_block=64,
                           wire_ef=True)
        method = opt.optim_method
        assert "wire_ef" in method.state
        x, y = _toy()
        opt2 = DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                               batch_size=32, wire_dtype="int8",
                               wire_block=64)
        opt2.set_optim_method(method)
        opt2.set_end_when(Trigger.max_epoch(1))
        opt2.optimize()
        assert "wire_ef" not in method.state

    def test_env_default_wire(self, monkeypatch):
        monkeypatch.setenv("BIGDL_WIRE_DTYPE", "fp8_e4m3")
        monkeypatch.setenv("BIGDL_WIRE_EF", "1")
        monkeypatch.setenv("BIGDL_WIRE_BLOCK", "64")
        from bigdl_tpu.config import refresh_from_env

        refresh_from_env()
        x, y = _toy(64)
        opt = DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                              batch_size=32)
        assert opt.wire_dtype == "fp8_e4m3"
        assert opt.wire.error_feedback and opt.wire.block == 64

    def test_fp8_validation_and_hierarchical_guard(self):
        x, y = _toy(64)
        with pytest.raises(ValueError, match="wire_dtype"):
            DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                            batch_size=32, wire_dtype="fp9")
        with pytest.raises(ValueError, match="error feedback"):
            DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                            batch_size=32, wire_dtype="none",
                            wire_ef=True)
        mesh = Engine.build_mesh({"dcn": 2, "data": 4})
        with pytest.raises(NotImplementedError, match="staged-ring"):
            DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                            batch_size=32, mesh=mesh,
                            data_axes=("dcn", "data"),
                            wire_dtype="fp8_e4m3")

    def test_nonfinite_guard_with_ef_stays_finite(self, monkeypatch):
        """An injected NaN gradient under the EF wire: the guard skips
        the update (reverting the residual with the rest of the state
        through the same where-map) and training stays finite."""
        from bigdl_tpu.resilience import reset_injector

        monkeypatch.setenv("BIGDL_FAULT_PLAN", "step:2:nan_grad")
        reset_injector()
        try:
            traj, opt = self._run(epochs=1, wire_dtype="int8",
                                  wire_block=64, wire_ef=True)
            # the injected step records its NaN loss (by design); the
            # guard skips the update, so every LATER step is finite
            assert traj and not np.isfinite(traj[2])
            assert all(np.isfinite(v) for s, v in traj.items() if s > 2)
            assert bool(np.isfinite(
                np.asarray(opt.optim_method.state["wire_ef"])).all())
        finally:
            monkeypatch.delenv("BIGDL_FAULT_PLAN", raising=False)
            reset_injector()


# ==================================================== overlap bucketing
class TestBucketPlan:
    """ISSUE 11: the bucketed-overlap plan and the shard-major layout
    map the elastic re-partition path keys on."""

    def test_plan_covers_and_aligns(self):
        plan = wire.plan_buckets(1024, quantum=128, target_elems=300)
        # sizes round UP to whole quanta and cover [0, padded) exactly
        assert plan == [(0, 384), (384, 384), (768, 256)]
        assert sum(z for _, z in plan) == 1024
        assert all(s % 128 == 0 and z % 128 == 0 for s, z in plan)

    def test_plan_monolithic_when_target_unset(self):
        assert wire.plan_buckets(1024, 128, 0) == [(0, 1024)]
        assert wire.plan_buckets(1024, 128, None) == [(0, 1024)]
        # a target below one quantum still yields whole quanta
        assert wire.plan_buckets(256, 128, 1) == [(0, 128), (128, 128)]

    def test_plan_rejects_misaligned_padded(self):
        with pytest.raises(ValueError, match="quantum"):
            wire.plan_buckets(1000, 128, 300)

    def test_param_coords_identity_for_single_bucket(self):
        coords = wire.bucket_param_coords([(0, 20)], 4)
        np.testing.assert_array_equal(coords, np.arange(20))

    def test_param_coords_roundtrip(self):
        buckets = [(0, 8), (8, 8), (16, 4)]
        coords = wire.bucket_param_coords(buckets, 2)
        param = np.arange(20, dtype=np.float32) * 10
        shard_major = param[coords]
        # device 0 owns the first half of every bucket, ascending
        np.testing.assert_array_equal(
            shard_major[:10],
            np.array([0, 1, 2, 3, 8, 9, 10, 11, 16, 17]) * 10.0)
        back = np.empty_like(param)
        back[coords] = shard_major
        np.testing.assert_array_equal(back, param)

    def test_buckets_equal_normalizes_single_and_none(self):
        assert wire.buckets_equal(None, None)
        assert wire.buckets_equal(None, [(0, 640)])  # mono == identity
        assert wire.buckets_equal([[0, 64], [64, 64]], [(0, 64), (64, 64)])
        assert not wire.buckets_equal(None, [(0, 64), (64, 64)])
        assert not wire.buckets_equal([[0, 32], [32, 96]],
                                      [[0, 64], [64, 64]])

    def test_bucketed_staged_ring_bytes_match_monolithic(self):
        """Byte-count parity: the per-bucket staged-ring exchanges sum
        to EXACTLY the monolithic model — bucketing changes when bytes
        move, never how many."""
        padded, n, block = 1536, 4, 64
        mono = C.staged_ring_exchange_bytes(padded, n, block, "int8")
        plan = wire.plan_buckets(padded, n * block, 512)
        assert len(plan) > 1
        summed: dict = {}
        for _s, z in plan:
            for k, v in C.staged_ring_exchange_bytes(
                    z, n, block, "int8").items():
                summed[k] = summed.get(k, 0.0) + v
        assert summed == mono
