"""nn/attention.py + models/transformer.py tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import (
    LayerNorm,
    MultiHeadAttention,
    PositionalEmbedding,
    TransformerBlock,
)
from bigdl_tpu.models import build_transformer_lm
from bigdl_tpu.nn.criterion import ClassNLLCriterion


class TestLayerNorm:
    def test_normalizes(self):
        ln = LayerNorm(8)
        x = jnp.asarray(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        y = ln.forward(x)
        np.testing.assert_allclose(np.asarray(y).mean(-1), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y).std(-1), 1.0, atol=1e-2)

    def test_affine(self):
        ln = LayerNorm(4)
        ln.weight = jnp.full(4, 2.0)
        ln.bias = jnp.full(4, 1.0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        y0 = (np.asarray(x) - np.asarray(x).mean(-1, keepdims=True)) / np.sqrt(
            np.asarray(x).var(-1, keepdims=True) + 1e-5
        )
        np.testing.assert_allclose(np.asarray(ln.forward(x)), y0 * 2 + 1,
                                   atol=1e-5)


class TestMultiHeadAttention:
    def test_shape_and_determinism(self):
        mha = MultiHeadAttention(16, 4, causal=True).evaluate()
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
        y1, y2 = mha.forward(x), mha.forward(x)
        assert y1.shape == (2, 8, 16)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))

    def test_causal_prefix_invariance(self):
        # causal attention: output at position i must not change when the
        # suffix after i changes
        mha = MultiHeadAttention(16, 2, causal=True).evaluate()
        r = np.random.RandomState(0)
        x = r.randn(1, 8, 16).astype(np.float32)
        x2 = x.copy()
        x2[:, 4:] = r.randn(1, 4, 16)
        y1 = np.asarray(mha.forward(jnp.asarray(x)))
        y2 = np.asarray(mha.forward(jnp.asarray(x2)))
        np.testing.assert_allclose(y1[:, :4], y2[:, :4], atol=1e-5)

    def test_gradcheck(self):
        mha = MultiHeadAttention(8, 2, causal=False, with_bias=True)
        x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 8).astype(np.float32))
        p = mha.params()

        def f(p):
            out, _ = mha.apply(p, {}, x)
            return jnp.sum(out * out)

        g = jax.grad(f)(p)
        # numeric check on one weight entry
        eps = 1e-3
        p2 = dict(p)
        w = np.asarray(p["wq"]).copy()
        w[0, 0] += eps
        p2["wq"] = jnp.asarray(w)
        num = (f(p2) - f(p)) / eps
        np.testing.assert_allclose(np.asarray(g["wq"])[0, 0], float(num),
                                   atol=1e-1, rtol=1e-1)


class TestTransformerLM:
    def test_forward_shape(self):
        lm = build_transformer_lm(vocab_size=50, dim=32, n_head=2, n_layer=2,
                                  max_len=16)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 50, (2, 12)), jnp.int32
        )
        logits, _ = lm.apply(lm.params(), lm.state(), tokens)
        assert logits.shape == (2, 12, 50)

    def test_tiny_lm_learns_constant_sequence(self):
        # convergence smoke (SURVEY.md §4.6 role): repeatable next-token
        # pattern must be learnable in a few dozen steps
        lm = build_transformer_lm(vocab_size=8, dim=32, n_head=2, n_layer=1,
                                  max_len=8)
        tokens = np.tile(np.arange(8, dtype=np.int32), (4, 1))
        x = jnp.asarray(tokens[:, :-1])
        y = jnp.asarray(tokens[:, 1:])
        params = lm.params()

        def loss_fn(p):
            logits, _ = lm.apply(p, {}, x, training=False)
            logp = jax.nn.log_softmax(logits)
            ll = jnp.take_along_axis(logp, y[..., None], axis=-1)
            return -jnp.mean(ll)

        step = jax.jit(
            lambda p: jax.tree.map(
                lambda w, g: w - 0.1 * g, p, jax.grad(loss_fn)(p)
            )
        )
        l0 = float(loss_fn(params))
        for _ in range(60):
            params = step(params)
        l1 = float(loss_fn(params))
        assert l1 < l0 * 0.2, (l0, l1)

    def test_serialization_roundtrip(self):
        import tempfile, os

        from bigdl_tpu.utils.serializer import save_module, load_module

        lm = build_transformer_lm(vocab_size=20, dim=16, n_head=2, n_layer=1,
                                  max_len=8)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 20, (1, 6)), jnp.int32
        )
        out1, _ = lm.apply(lm.params(), lm.state(), tokens)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "lm.bigdl")
            save_module(lm, path)
            lm2 = load_module(path)
        out2, _ = lm2.apply(lm2.params(), lm2.state(), tokens)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-6)
