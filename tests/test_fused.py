"""Fused 1x1-conv+BN specs (ops/conv_bn.py + nn/fused.py).

The contract under test: the fused module is bit-compatible (within
float tolerance) with the ``SpatialConvolution(1x1) ->
SpatialBatchNormalization (-> ReLU)`` chain it replaces — forward,
running-stat updates, gradients, eval mode, and the model-level
``fuse_conv_bn`` rewrite of ResNet-50.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import (
    ReLU,
    Sequential,
    SpatialBatchNormalization,
    SpatialConvolution,
    SpatialConvolutionBatchNorm,
    fuse_conv_bn,
)
from bigdl_tpu.nn.layers import MsraFiller
from bigdl_tpu.ops.conv_bn import _reference, conv1x1_bn_stats


def test_kernel_matches_reference():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(3, 16, 8, 8).astype(np.float32))
    w = jnp.asarray(rs.randn(32, 16).astype(np.float32) * 0.1)
    shift = jnp.asarray(rs.randn(32).astype(np.float32) * 0.01)
    y, s1, s2 = conv1x1_bn_stats(x, w, shift, interpret=True)
    yr, s1r, s2r = _reference(x, w[:, :, None, None], shift, 1, 0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s1r),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s2r),
                               rtol=1e-4, atol=1e-3)


def test_custom_vjp_matches_autodiff():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 8, 4, 4).astype(np.float32))
    w = jnp.asarray(rs.randn(16, 8).astype(np.float32) * 0.2)
    shift = jnp.asarray(rs.randn(16).astype(np.float32) * 0.1)
    coef = jnp.arange(16, dtype=jnp.float32)

    def loss_k(x, w, shift):
        y, s1, s2 = conv1x1_bn_stats(x, w, shift, interpret=True)
        return 0.5 * jnp.sum(y ** 2) + jnp.sum(s1 * coef) + 0.1 * jnp.sum(s2)

    def loss_r(x, w, shift):
        y, s1, s2 = _reference(x, w[:, :, None, None], shift, 1, 0)
        return 0.5 * jnp.sum(y ** 2) + jnp.sum(s1 * coef) + 0.1 * jnp.sum(s2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, w, shift)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, w, shift)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-3)


def test_kxk_kernel_matches_reference():
    """3x3 kernel (the other half of ResNet-50's BN inputs) at both
    strides, plus O-padding (O=20 is not a tile multiple)."""
    from bigdl_tpu.ops.conv_bn import conv_bn_stats

    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(2, 16, 8, 8).astype(np.float32))
    for o, stride in [(32, 1), (32, 2), (20, 1)]:
        w = jnp.asarray(rs.randn(o, 16, 3, 3).astype(np.float32) * 0.1)
        shift = jnp.asarray(rs.randn(o).astype(np.float32) * 0.01)
        y, s1, s2 = conv_bn_stats(x, w, shift, stride=stride, pad=1,
                                  interpret=True)
        yr, s1r, s2r = _reference(x, w, shift, stride, 1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s1r),
                                   rtol=1e-4, atol=1e-2)
        np.testing.assert_allclose(np.asarray(s2), np.asarray(s2r),
                                   rtol=1e-4, atol=1e-2)


def test_kxk_vjp_matches_autodiff():
    from bigdl_tpu.ops.conv_bn import conv_bn_stats

    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 8, 6, 6).astype(np.float32))
    w = jnp.asarray(rs.randn(16, 8, 3, 3).astype(np.float32) * 0.2)
    shift = jnp.asarray(rs.randn(16).astype(np.float32) * 0.1)
    coef = jnp.arange(16, dtype=jnp.float32)

    def loss_k(x, w, shift):
        y, s1, s2 = conv_bn_stats(x, w, shift, stride=2, pad=1,
                                  interpret=True)
        return 0.5 * jnp.sum(y ** 2) + jnp.sum(s1 * coef) + 0.1 * jnp.sum(s2)

    def loss_r(x, w, shift):
        y, s1, s2 = _reference(x, w, shift, 2, 1)
        return 0.5 * jnp.sum(y ** 2) + jnp.sum(s1 * coef) + 0.1 * jnp.sum(s2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, w, shift)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, w, shift)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-3)


def test_1x1_odd_shapes_no_fallback():
    """r03 fell back to plain XLA when block_o didn't divide O or the
    tile blew the VMEM heuristic; the rewrite pads + masks instead."""
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(2, 12, 5, 7).astype(np.float32))  # hw=35
    w = jnp.asarray(rs.randn(20, 12).astype(np.float32) * 0.1)  # o=20
    shift = jnp.asarray(rs.randn(20).astype(np.float32) * 0.01)
    y, s1, s2 = conv1x1_bn_stats(x, w, shift, interpret=True)
    yr, s1r, s2r = _reference(x, w[:, :, None, None], shift, 1, 0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s1r),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s2r),
                               rtol=1e-4, atol=1e-3)


def _pair_and_fused(cin=16, cout=32, with_relu=True, stride=1, kernel=1):
    pad = (kernel - 1) // 2
    conv = SpatialConvolution(cin, cout, kernel, kernel, stride, stride,
                              pad, pad, with_bias=False,
                              init_method=MsraFiller(False))
    bn = SpatialBatchNormalization(cout)
    pair = Sequential().add(conv).add(bn)
    if with_relu:
        pair.add(ReLU())
    fused = SpatialConvolutionBatchNorm.from_pair(conv, bn, with_relu)
    return pair, fused


@pytest.mark.parametrize("stride,kernel", [(1, 1), (2, 1), (1, 3), (2, 3)])
def test_module_parity_train_eval_state(stride, kernel):
    pair, fused = _pair_and_fused(stride=stride, kernel=kernel)
    x = jnp.asarray(
        np.random.RandomState(0).randn(4, 16, 8, 8).astype(np.float32))
    p1, s1 = pair.params(), pair.state()
    o1, ns1 = pair.apply(p1, s1, x, training=True, rng=jax.random.key(0))
    p2, s2 = fused.params(), fused.state()
    o2, ns2 = fused.apply(p2, s2, x, training=True, rng=jax.random.key(0))
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ns1["1"]["running_mean"]),
                               np.asarray(ns2["running_mean"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ns1["1"]["running_var"]),
                               np.asarray(ns2["running_var"]),
                               rtol=1e-4, atol=1e-5)
    pair.evaluate()
    fused.evaluate()
    np.testing.assert_allclose(np.asarray(pair.forward(x)),
                               np.asarray(fused.forward(x)),
                               rtol=2e-5, atol=2e-5)


def test_module_gradient_parity():
    pair, fused = _pair_and_fused()
    x = jnp.asarray(
        np.random.RandomState(2).randn(4, 16, 8, 8).astype(np.float32))
    p1, s1 = pair.params(), pair.state()
    p2, s2 = fused.params(), fused.state()

    def loss_pair(p):
        out, _ = pair.apply(p, s1, x, training=True, rng=jax.random.key(0))
        return jnp.sum(out ** 2)

    def loss_fused(p):
        out, _ = fused.apply(p, s2, x, training=True, rng=jax.random.key(0))
        return jnp.sum(out ** 2)

    g1 = jax.grad(loss_pair)(p1)
    g2 = jax.grad(loss_fused)(p2)
    np.testing.assert_allclose(np.asarray(g1["0"]["weight"])[:, :, 0, 0],
                               np.asarray(g2["weight"]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(g1["1"]["weight"]),
                               np.asarray(g2["bn_weight"]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(g1["1"]["bias"]),
                               np.asarray(g2["bn_bias"]),
                               rtol=2e-4, atol=2e-4)


def test_fuse_resnet50_eval_parity_and_train():
    from bigdl_tpu.models import build_resnet_imagenet
    from bigdl_tpu.nn import CrossEntropyCriterion
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    m = build_resnet_imagenet(depth=50, class_num=10)
    x = jnp.asarray(
        np.random.RandomState(0).rand(2, 3, 64, 64).astype(np.float32))
    m.evaluate()
    ref = np.asarray(m.forward(x))
    fuse_conv_bn(m)

    fused_count = [0]

    def count(mod):
        for c in getattr(mod, "modules", []):
            count(c)
            if isinstance(c, SpatialConvolutionBatchNorm):
                fused_count[0] += 1

    count(m)
    # 16 bottleneck c1 + 16 c2 (3x3) + 16 c3 + 4 strided shortcuts
    # (the 7x7 stem stays on XLA)
    assert fused_count[0] == 52, fused_count[0]
    m.evaluate()
    np.testing.assert_allclose(ref, np.asarray(m.forward(x)),
                               rtol=5e-4, atol=5e-4)

    m.modules = m.modules[:-1]  # drop LogSoftMax for CE
    y = (np.random.RandomState(1).randint(0, 10, 2) + 1).astype(np.float32)
    opt = LocalOptimizer(m, (np.asarray(x), y), CrossEntropyCriterion(),
                         batch_size=2)
    opt.set_optim_method(SGD(learningrate=0.01))
    opt.set_end_when(Trigger.max_iteration(2))
    opt.optimize()
    assert np.isfinite(float(opt.state["loss"]))


def test_fused_serialization_roundtrip(tmp_path):
    from bigdl_tpu.utils.serializer import load_module, save_module

    m = SpatialConvolutionBatchNorm(8, 16, stride=2, with_relu=True)
    m.evaluate()
    x = jnp.asarray(
        np.random.RandomState(0).randn(2, 8, 6, 6).astype(np.float32))
    o1 = np.asarray(m.forward(x))
    loaded = load_module(save_module(m, str(tmp_path / "fused")))
    loaded.evaluate()
    np.testing.assert_allclose(o1, np.asarray(loaded.forward(x)), rtol=1e-6)


def test_fused_resnet50_traces_at_production_shapes():
    """Abstract-eval the fused train step at the bench operating point
    (batch 128, 224px): exercises every kernel's tile selection and
    padding arithmetic at real dims without executing (the chip isn't
    needed to catch a shape/VMEM bug in _tiles_1x1/_fwd_kxk)."""
    import jax

    from bigdl_tpu.models import build_resnet_imagenet
    from bigdl_tpu.nn import CrossEntropyCriterion

    m = build_resnet_imagenet(depth=50, class_num=1000)
    fuse_conv_bn(m)
    m.modules = m.modules[:-1]
    crit = CrossEntropyCriterion()
    params = m.params()
    state = m.state()

    def loss_fn(p, x, y):
        pc = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
        out, _ = m.apply(pc, state, x, training=True,
                         rng=jax.random.key(0))
        return crit.loss(out.astype(jnp.float32), y)

    x = jax.ShapeDtypeStruct((128, 3, 224, 224), jnp.bfloat16)
    y = jax.ShapeDtypeStruct((128,), jnp.float32)
    shapes = jax.eval_shape(jax.grad(loss_fn), params, x, y)
    flat = jax.tree_util.tree_leaves(shapes)
    assert flat, "no gradients traced"
