"""Serving tier (ISSUE 12): paged KV cache, continuous batching,
int8/TP decode, queue machinery, and the obs/autoscale loop closure.

The load-bearing contract: paged decode must BIT-MATCH the contiguous-
cache ``TransformerLM.generate`` at temperature 0 for identical
prompts — including requests admitted into the middle of an in-flight
batch, and across a page-exhaustion preemption."""

import numpy as np
import pytest


def _model(max_len=64):
    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm

    RandomGenerator.RNG.set_seed(13)
    return build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                max_len=max_len, attn_impl="xla")


@pytest.fixture(scope="module")
def lm_model():
    return _model()


@pytest.fixture(scope="module")
def lm_params(lm_model):
    return lm_model.params()


def _ref(model, params, prompt, n):
    return list(np.asarray(model.generate(
        params, np.asarray(prompt)[None, :], n))[0])


def _out(prompt, req):
    return [int(t) for t in list(prompt) + req.tokens]


# ---------------------------------------------------------------- cache
class TestPagedKVCache:
    def _cache(self, **kw):
        from bigdl_tpu.serving import PagedKVCache

        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 9)
        kw.setdefault("max_slots", 2)
        kw.setdefault("max_len", 32)
        return PagedKVCache(2, 4, 8, **kw)

    def test_alloc_release_roundtrip(self):
        c = self._cache()
        assert c.free_pages() == 8  # page 0 reserved as trash
        pages = c.alloc(0, 10)      # ceil(10/4) = 3 pages
        assert len(pages) == 3 and 0 not in pages
        assert c.free_pages() == 5
        assert list(c.page_tables[0][:3]) == pages
        c.release(0)
        assert c.free_pages() == 8
        assert not c.page_tables[0].any()

    def test_grow_and_exhaustion(self):
        c = self._cache(num_pages=4)  # 3 usable
        c.alloc(0, 4)
        c.lengths[0] = 4
        assert c.needs_growth(0)
        assert c.grow(0) and c.grow(0)
        assert not c.grow(0)  # pool empty
        assert c.free_pages() == 0

    def test_gather_pages_layout(self):
        import jax.numpy as jnp

        from bigdl_tpu.serving import gather_pages

        pages = jnp.arange(3 * 2 * 4 * 5, dtype=jnp.float32).reshape(
            3, 2, 4, 5)
        table = jnp.asarray([[2, 1], [0, 0]], jnp.int32)
        g = gather_pages(pages, table)
        assert g.shape == (2, 2, 8, 5)
        np.testing.assert_array_equal(
            np.asarray(g[0, :, :4]), np.asarray(pages[2]))
        np.testing.assert_array_equal(
            np.asarray(g[0, :, 4:]), np.asarray(pages[1]))


# --------------------------------------------------------------- engine
class TestContinuousBatching:
    def test_mid_batch_admission_bit_matches(self, lm_model, lm_params):
        """Paged decode must bit-match the contiguous-cache generate()
        — for the initial batch (different prompt lengths) AND for a
        request admitted into a freed slot mid-flight."""
        from bigdl_tpu.serving import LMEngine

        rs = np.random.RandomState(1)
        p1, p2, p3 = (rs.randint(0, 48, (n,)) for n in (5, 9, 4))
        eng = LMEngine(lm_model, max_batch=2, page_size=8)
        r1 = eng.submit(p1, 10)
        r2 = eng.submit(p2, 3)
        for _ in range(3):     # r2 completes, r1 still in flight
            eng.pump()
        assert r2.done and not r1.done
        r3 = eng.submit(p3, 7)  # admitted into the freed slot
        eng.pump()
        assert eng.active_count() == 2
        eng.run_until_idle(60)
        eng.close()
        assert _out(p1, r1) == _ref(lm_model, lm_params, p1, 10)
        assert _out(p2, r2) == _ref(lm_model, lm_params, p2, 3)
        assert _out(p3, r3) == _ref(lm_model, lm_params, p3, 7)

    def test_slot_and_page_reuse(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8, num_pages=9)
        total = eng.cache.free_pages()
        for wave in range(3):
            reqs = [eng.submit([1 + wave, 2, 3], 4) for _ in range(2)]
            eng.run_until_idle(60)
            assert all(r.done for r in reqs)
            # everything returned to the pool between waves
            assert eng.cache.free_pages() == total
            assert eng.active_count() == 0
        assert eng.stats()["requests"] == 6
        eng.close()

    def test_preemption_bit_exact_and_counted(self, lm_model, lm_params):
        from bigdl_tpu.serving import LMEngine

        rs = np.random.RandomState(2)
        p1, p2 = rs.randint(0, 48, (5,)), rs.randint(0, 48, (9,))
        # contended-but-feasible pool: both requests cannot be resident
        # together at full length, so the youngest gets preempted and
        # re-prefilled — output must still match the uninterrupted run
        eng = LMEngine(lm_model, max_batch=2, page_size=4, num_pages=8)
        a, b = eng.submit(p1, 12), eng.submit(p2, 12)
        eng.run_until_idle(120)
        assert eng.stats()["preemptions"] >= 1
        eng.close()
        assert _out(p1, a) == _ref(lm_model, lm_params, p1, 12)
        assert _out(p2, b) == _ref(lm_model, lm_params, p2, 12)

    def test_infeasible_request_rejected(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=4, num_pages=5)
        with pytest.raises(ValueError, match="KV pages"):
            eng.submit([1, 2, 3], 40)  # needs 11 pages, pool has 4
        with pytest.raises(ValueError, match="max_len"):
            eng.submit([1, 2, 3], 100)
        eng.close()

    def test_static_admission_drains_first(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8,
                       admission="static")
        r1 = eng.submit([1, 2, 3], 6)
        r2 = eng.submit([4, 5, 6], 2)
        for _ in range(3):
            eng.pump()
        assert r2.done and not r1.done
        r3 = eng.submit([7, 8, 9], 2)
        eng.pump()
        # the freed slot stays empty until the whole batch drains
        assert eng.active_count() == 1 and not r3.done
        eng.run_until_idle(60)
        assert r3.done
        eng.close()

    def test_int8_decode(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8, int8=True)
        assert eng._qparams is not None
        assert eng._qparams["h0"]["attn"]["wq"][0].dtype.name == "int8"
        r = eng.submit([3, 1, 4, 1, 5], 8)
        eng.run_until_idle(60)
        eng.close()
        assert r.done and len(r.tokens) == 8
        assert all(0 <= t < 48 for t in r.tokens)

    def test_int8_excludes_tp(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        with pytest.raises(ValueError, match="exclusive"):
            LMEngine(lm_model, int8=True, tp=2)


class TestTPDecode:
    def test_tp_decode_bit_matches(self, lm_model, lm_params):
        from bigdl_tpu.serving import LMEngine

        rs = np.random.RandomState(3)
        p1, p2 = rs.randint(0, 48, (5,)), rs.randint(0, 48, (9,))
        eng = LMEngine(lm_model, max_batch=2, page_size=8, tp=4)
        r1, r2 = eng.submit(p1, 6), eng.submit(p2, 3)
        eng.run_until_idle(120)
        eng.close()
        assert _out(p1, r1) == _ref(lm_model, lm_params, p1, 6)
        assert _out(p2, r2) == _ref(lm_model, lm_params, p2, 3)

    def test_tp_wire_accounting(self, lm_model):
        from bigdl_tpu import obs
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8, tp=4,
                       wire="int8")
        r = eng.submit([5, 6, 7], 6)
        eng.run_until_idle(120)
        eng.close()
        assert r.done and len(r.tokens) == 6
        snap = obs.get_registry().snapshot()["metrics"]
        sv = {tuple(s["labels"].items()): s["value"] for s in
              snap["bigdl_collective_wire_savings_ratio"]["samples"]}
        assert sv[(("path", "serve"),)] > 2.0
        ops = {s["labels"]["op"] for s in
               snap["bigdl_collective_bytes_total"]["samples"]}
        assert "serve_tp_psum" in ops

    def test_tp_must_divide_heads(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        with pytest.raises(ValueError, match="divide"):
            LMEngine(lm_model, tp=3)


# ------------------------------------------- decode kernels (ISSUE 13)
class TestDecodeKernelDispatch:
    """paged_decode_math's attention body is now
    ops.decode_attention.paged_decode_attention — the fused flash-
    decode path must reproduce the dense bit-match semantics through
    every engine scenario (ragged admission, preemption refold, TP
    head sharding, int8), and the used-page bucket must be observable.
    """

    def test_fused_engine_matches_generate_mid_batch(self, lm_model,
                                                     lm_params):
        from bigdl_tpu.serving import LMEngine

        rs = np.random.RandomState(21)
        p1, p2, p3 = (rs.randint(0, 48, (n,)) for n in (5, 9, 4))
        eng = LMEngine(lm_model, max_batch=2, page_size=8,
                       decode_attn="fused")
        r1 = eng.submit(p1, 10)
        r2 = eng.submit(p2, 3)
        for _ in range(3):
            eng.pump()
        assert r2.done and not r1.done
        r3 = eng.submit(p3, 7)     # admitted mid-flight
        eng.run_until_idle(60)
        eng.close()
        assert _out(p1, r1) == _ref(lm_model, lm_params, p1, 10)
        assert _out(p2, r2) == _ref(lm_model, lm_params, p2, 3)
        assert _out(p3, r3) == _ref(lm_model, lm_params, p3, 7)

    def test_fused_engine_survives_preemption_refold(self, lm_model,
                                                     lm_params):
        from bigdl_tpu.serving import LMEngine

        rs = np.random.RandomState(22)
        p1, p2 = rs.randint(0, 48, (5,)), rs.randint(0, 48, (9,))
        eng = LMEngine(lm_model, max_batch=2, page_size=4, num_pages=8,
                       decode_attn="fused")
        a, b = eng.submit(p1, 12), eng.submit(p2, 12)
        eng.run_until_idle(120)
        assert eng.stats()["preemptions"] >= 1
        eng.close()
        assert _out(p1, a) == _ref(lm_model, lm_params, p1, 12)
        assert _out(p2, b) == _ref(lm_model, lm_params, p2, 12)

    def test_tp_fused_agrees(self, lm_model, lm_params):
        from bigdl_tpu.serving import LMEngine

        rs = np.random.RandomState(23)
        p1, p2 = rs.randint(0, 48, (5,)), rs.randint(0, 48, (9,))
        eng = LMEngine(lm_model, max_batch=2, page_size=8, tp=4,
                       decode_attn="fused")
        r1, r2 = eng.submit(p1, 6), eng.submit(p2, 3)
        eng.run_until_idle(120)
        eng.close()
        assert _out(p1, r1) == _ref(lm_model, lm_params, p1, 6)
        assert _out(p2, r2) == _ref(lm_model, lm_params, p2, 3)

    def test_int8_fused_passthrough(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8, int8=True,
                       decode_attn="fused")
        r = eng.submit([3, 1, 4, 1, 5], 8)
        eng.run_until_idle(60)
        eng.close()
        assert r.done and len(r.tokens) == 8
        assert all(0 <= t < 48 for t in r.tokens)

    def test_bucket_slices_tables_and_gauges_publish(self, lm_model):
        from bigdl_tpu import obs
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8)
        assert eng.decode_bucket       # default ON
        r = eng.submit([1, 2, 3], 4)   # short: 1 page in use
        eng.run_until_idle(60)
        st = eng.stats()
        eng.close()
        assert r.done
        assert st["last_bucket_pages"] < eng.cache.max_pages_per_slot
        assert st["decode_ms_mean"] and st["decode_ms_mean"] > 0
        assert st["decode_hbm_bytes_per_token"] > 0
        reg = obs.get_registry()
        assert reg.gauge(
            "bigdl_serve_decode_attn_ms")._solo().value > 0
        assert reg.gauge(
            "bigdl_serve_decode_hbm_bytes_per_token")._solo().value > 0

    def test_bucket_off_ships_full_tables(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8,
                       decode_bucket=False)
        r = eng.submit([1, 2, 3], 3)
        eng.run_until_idle(60)
        st = eng.stats()
        eng.close()
        assert r.done
        assert st["last_bucket_pages"] == eng.cache.max_pages_per_slot

    def test_invalid_decode_attn_rejected(self, lm_model):
        from bigdl_tpu.serving import LMEngine

        with pytest.raises(ValueError, match="decode_attn"):
            LMEngine(lm_model, decode_attn="nope")

    def test_tuner_dispatches_fused_in_engine(self, lm_model, lm_params,
                                              tmp_path, monkeypatch):
        from bigdl_tpu.ops import autotune
        from bigdl_tpu.serving import LMEngine

        monkeypatch.setenv("BIGDL_TUNER", "1")
        monkeypatch.setenv("BIGDL_TUNER_CACHE",
                           str(tmp_path / "tuner.json"))
        autotune.reset()
        try:
            rs = np.random.RandomState(24)
            p1 = rs.randint(0, 48, (5,))
            eng = LMEngine(lm_model, max_batch=2, page_size=8)
            assert eng.decode_attn == "auto"
            r1 = eng.submit(p1, 8)
            eng.run_until_idle(60)
            st = eng.stats()
            eng.close()
            # the analytic gather-tax model flips every bucket to the
            # fused flash-decode path — and tokens still match the
            # contiguous-cache generate()
            assert st["decode_impl_by_bucket"]
            assert set(st["decode_impl_by_bucket"].values()) == {"fused"}
            assert _out(p1, r1) == _ref(lm_model, lm_params, p1, 8)
            sites = {d["site"] for d in autotune.summary()["decisions"]}
            assert "decode_attn" in sites
        finally:
            autotune.reset()


# ----------------------------------------------------- queue / batcher
class TestRequestQueue:
    def test_fifo_and_depth_gauge(self):
        from bigdl_tpu import obs
        from bigdl_tpu.serving import RequestQueue, ServeRequest

        q = RequestQueue(capacity=8)
        reqs = [q.submit(ServeRequest(payload=i)) for i in range(5)]
        assert q.depth() == 5
        gauge = obs.get_registry().gauge("bigdl_serve_queue_depth")
        assert gauge._solo().value == 5.0
        got = q.take(3, timeout=1.0)
        assert [r.payload for r in got] == [0, 1, 2]
        got += q.take(8, timeout=1.0)
        assert [r.payload for r in got] == [0, 1, 2, 3, 4]
        assert q.depth() == 0
        assert all(r is s for r, s in zip(got, reqs))
        q.close()

    def test_backpressure_blocks_submit(self):
        from bigdl_tpu import obs
        from bigdl_tpu.serving import RequestQueue, ServeRequest

        q = RequestQueue(capacity=1)
        waits0 = obs.get_registry().counter(
            "bigdl_serve_admission_waits_total")._solo().value
        with pytest.raises(TimeoutError):
            for i in range(5):  # no consumer: must block within 5
                q.submit(ServeRequest(payload=i), timeout=0.15)
        assert obs.get_registry().counter(
            "bigdl_serve_admission_waits_total")._solo().value > waits0
        q.close()

    def test_closed_queue_rejects(self):
        from bigdl_tpu.serving import RequestQueue, ServeRequest

        q = RequestQueue(capacity=2)
        q.close()
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(ServeRequest(payload=0))


# ------------------------------------------------------ classifier tier
class TestClassifierEngine:
    def _mlp(self):
        from bigdl_tpu.common import RandomGenerator
        from bigdl_tpu.nn import Linear, LogSoftMax, ReLU, Sequential

        RandomGenerator.RNG.set_seed(7)
        return Sequential().add(Linear(16, 32)).add(ReLU()) \
            .add(Linear(32, 4)).add(LogSoftMax())

    def test_batches_match_direct_forward(self):
        from bigdl_tpu.serving import ClassifierEngine

        mod = self._mlp()
        eng = ClassifierEngine(mod, max_batch=4, batch_window_s=0.0)
        x = np.random.RandomState(0).randn(6, 16).astype(np.float32)
        reqs = [eng.submit(row) for row in x]
        while any(not r.done for r in reqs):
            eng.pump(wait_s=0.05)
        got = np.stack([r.result for r in reqs])
        want = np.asarray(mod.forward(x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        st = eng.stats()
        assert st["requests"] == 6 and st["batches"] >= 2
        eng.close()

    def test_int8_rides_quantize_path(self):
        from bigdl_tpu.nn.quantized import QuantizedLinear
        from bigdl_tpu.serving import ClassifierEngine

        mod = self._mlp()
        want_cls = np.argmax(np.asarray(mod.forward(
            np.random.RandomState(1).randn(4, 16).astype(np.float32))),
            axis=-1)
        eng = ClassifierEngine(mod, max_batch=4, int8=True,
                               batch_window_s=0.0)
        assert any(isinstance(m, QuantizedLinear)
                   for m in eng.module.modules)
        x = np.random.RandomState(1).randn(4, 16).astype(np.float32)
        reqs = [eng.submit(row) for row in x]
        while any(not r.done for r in reqs):
            eng.pump(wait_s=0.05)
        got = np.stack([r.result for r in reqs])
        assert np.isfinite(got).all()
        # per-channel int8 on a tiny MLP: classes survive quantization
        assert (np.argmax(got, axis=-1) == want_cls).mean() >= 0.75
        eng.close()


# ----------------------------------------------------- http front-end
class TestServingServer:
    def test_generate_classify_stats_roundtrip(self, lm_model):
        import json
        import urllib.request

        from bigdl_tpu.serving import (ClassifierEngine, LMEngine,
                                       ServingServer)

        lm = LMEngine(lm_model, max_batch=2, page_size=8).start()
        clf = ClassifierEngine(TestClassifierEngine()._mlp(),
                               max_batch=2).start()
        srv = ServingServer(lm=lm, classifier=clf, port=0)
        try:
            url = f"http://127.0.0.1:{srv.port}"

            def post(path, payload):
                req = urllib.request.Request(
                    url + path, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                return json.loads(urllib.request.urlopen(
                    req, timeout=60).read())

            g = post("/v1/generate", {"prompt": [1, 2, 3],
                                      "max_new_tokens": 4})
            assert len(g["tokens"]) == 4 and g["e2e_s"] > 0
            c = post("/v1/classify",
                     {"inputs": np.zeros((2, 16)).tolist()})
            assert len(c["classes"]) == 2
            st = json.loads(urllib.request.urlopen(
                url + "/stats", timeout=10).read())
            assert st["lm"]["requests"] >= 1
            assert st["classifier"]["requests"] >= 2
            bad = urllib.request.Request(
                url + "/v1/generate", data=b'{"prompt": []}',
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(bad, timeout=10)
        finally:
            srv.close()
            lm.close()
            clf.close()


# ------------------------------------------ obs / autoscale loop closure
class TestServingLoopClosure:
    def test_report_serving_section(self, lm_model, tmp_path):
        from bigdl_tpu import obs
        from bigdl_tpu.obs.report import build_report, render_text
        from bigdl_tpu.serving import LMEngine

        eng = LMEngine(lm_model, max_batch=2, page_size=8, slo_s=30.0)
        reqs = [eng.submit([1 + i, 2, 3], 3) for i in range(3)]
        eng.run_until_idle(60)
        eng.close()
        assert all(r.done for r in reqs)
        obs.get_registry().write_snapshot(str(tmp_path), host_id=0)
        rep = build_report(str(tmp_path))
        sv = rep["serving"]
        assert sv is not None
        assert sv["latency"]["lm:e2e"]["count"] >= 3
        assert sv["latency"]["lm:ttft"]["p99_s"] is not None
        assert sv["latency"]["lm:per_token"]["count"] >= 3
        assert sv["tokens_total"] >= 9
        assert sv["slo_ratio"] is not None
        text = render_text(rep)
        assert "-- serving --" in text
        assert "latency lm:e2e" in text

    def test_autoscale_p99_and_queue_signals(self):
        from bigdl_tpu.resilience.autoscale import derive_signals

        buckets = [(0.05, 90.0), (0.25, 96.0), (1.0, 100.0),
                   (float("inf"), 100.0)]
        samples = [{"name": "bigdl_serve_queue_depth", "labels": {},
                    "value": 17.0}]
        for le, c in buckets:
            samples.append(
                {"name": "bigdl_request_latency_seconds_bucket",
                 "labels": {"engine": "lm", "kind": "e2e",
                            "le": "+Inf" if le == float("inf")
                            else str(le)},
                 "value": c})
        # a ttft histogram must NOT leak into the e2e p99
        samples.append({"name": "bigdl_request_latency_seconds_bucket",
                        "labels": {"engine": "lm", "kind": "ttft",
                                   "le": "+Inf"}, "value": 5.0})
        peer = {"ok": True, "addr": "h:1", "health": {},
                "metrics": {"samples": samples}}
        sig = derive_signals([peer], {}, 1)
        assert sig["queue_depth"] == 17.0
        # 99% of 100 falls in the (0.25, 1.0] bucket
        assert sig["p99_latency_s"] == 1.0

    def test_autoscale_default_rules_gain_latency_band(self):
        import dataclasses

        from bigdl_tpu.config import AutoscaleConfig
        from bigdl_tpu.resilience.autoscale import default_rules

        cfg = dataclasses.replace(AutoscaleConfig(), p99_high=0.5,
                                  p99_low=0.05, queue_high=10)
        names = [r["name"] for r in default_rules(cfg)]
        assert "latency_p99_high" in names
        assert "latency_p99_low" in names
        by = {r["name"]: r for r in default_rules(cfg)}
        assert by["latency_p99_high"]["signal"] == "p99_latency_s"
        assert by["latency_p99_high"]["action"] == "up"
        assert by["latency_p99_low"]["action"] == "down"

    def test_queue_breach_drives_decision(self):
        from bigdl_tpu.config import AutoscaleConfig
        from bigdl_tpu.resilience.autoscale import (AutoscaleController,
                                                    load_rules)
        import dataclasses

        cfg = dataclasses.replace(
            AutoscaleConfig(), queue_high=8, hysteresis=1,
            cooldown_s=0.0, dry_run=True)
        ctl = AutoscaleController(cfg=cfg, world=1,
                                  rules=load_rules(None, cfg),
                                  scrape=lambda: [])
        d = ctl.evaluate({"world": 1, "queue_depth": 20.0,
                          "alerts": [], "stragglers": []})
        assert d is not None and d.direction == "up" \
            and d.reason == "queue_high" and d.dry_run

    def test_alert_pack_serve_slo_burn(self):
        from bigdl_tpu.obs.alerts import AlertEngine, default_rules
        from bigdl_tpu.obs.metrics import MetricsRegistry

        rules = [r for r in default_rules()
                 if r["name"] == "serve_latency_slo_burn"]
        assert rules and rules[0]["type"] == "burn_rate"
        reg = MetricsRegistry()
        eng = AlertEngine(rules, registry=reg)
        # absent gauge: a non-serving run can never fire this rule
        assert eng.evaluate() == []
        reg.gauge("bigdl_serve_latency_slo_ratio").set(0.5)
        assert eng.evaluate() == []          # for: 2 debounce
        trans = eng.evaluate()
        assert [t["state"] for t in trans] == ["firing"]
        reg.gauge("bigdl_serve_latency_slo_ratio").set(1.0)
        trans = eng.evaluate()
        assert [t["state"] for t in trans] == ["resolved"]


# ---------------------------------------------- generate() cache dtype
def test_generate_cache_honors_model_dtype(lm_model, lm_params):
    """Satellite: the decode KV buffers follow the model dtype instead
    of hardcoded f32 — and a bf16 cache reproduces the f32 greedy
    tokens on this model (parity)."""
    import jax
    import jax.numpy as jnp

    prompt = np.random.RandomState(4).randint(0, 48, (2, 5))
    ref = np.asarray(lm_model.generate(lm_params, prompt, 8))
    bf = np.asarray(lm_model.generate(lm_params, prompt, 8,
                                      cache_dtype=jnp.bfloat16))
    np.testing.assert_array_equal(ref, bf)
    # the default (no cache_dtype arg) follows the model dtype: bf16
    # params must yield bf16 cache buffers, not hardcoded f32
    cast = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
        lm_params)
    del jax  # buffers are internal to generate(); pin via the engine
    from bigdl_tpu.serving import LMEngine

    eng = LMEngine(lm_model, params=cast, max_batch=1, page_size=8)
    assert eng.cache.kp.dtype == jnp.bfloat16
    eng.close()


def test_engine_cache_dtype_follows_params(lm_model):
    from bigdl_tpu.serving import LMEngine
    import jax.numpy as jnp

    eng = LMEngine(lm_model, max_batch=2, page_size=8,
                   cache_dtype=jnp.bfloat16)
    assert eng.cache.kp.dtype == jnp.bfloat16
    r = eng.submit([1, 2, 3], 4)
    eng.run_until_idle(60)
    eng.close()
    assert r.done and len(r.tokens) == 4
