"""ops/ kernel tests — lax reference vs Pallas (interpret mode on CPU).

Plays the role of the reference's Torch7 oracle specs (SURVEY.md §4.3):
the lax implementation is the oracle; the Pallas kernel must match it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.ops import dot_product_attention, int8_matmul, quantize_per_channel
from bigdl_tpu.ops.attention import _reference_attention, flash_attention


def _qkv(b=2, h=2, t=64, d=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(b, h, t, d).astype(np.float32))
    return mk(), mk(), mk()


class TestAttention:
    def test_reference_matches_naive_softmax(self):
        q, k, v = _qkv()
        out = _reference_attention(q, k, v, causal=False, scale=0.25)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.25
        want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        np.testing.assert_allclose(out, want, atol=1e-5)

    def test_causal_masks_future(self):
        q, k, v = _qkv(t=16)
        out = _reference_attention(q, k, v, causal=True, scale=0.25)
        # position 0 attends only to key 0
        want0 = v[:, :, 0, :]
        np.testing.assert_allclose(out[:, :, 0, :], want0, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_matches_reference(self, causal):
        q, k, v = _qkv(t=64, d=16)
        scale = 1.0 / np.sqrt(16)
        ref = _reference_attention(q, k, v, causal=causal, scale=scale)
        got = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flash_grad_matches_reference(self):
        q, k, v = _qkv(t=32, d=8)

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True, interpret=True) ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(
                _reference_attention(
                    q, k, v, causal=True, scale=8 ** -0.5
                ) ** 2
            )

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_blockwise_backward_multiblock(self, causal):
        # T=256 -> two 128-blocks: exercises the blockwise dq and dk/dv
        # kernels' inner loops, the causal block-skip bounds, and the
        # (bh, T//bq, bq) logsumexp layout across block boundaries.
        # distinct q/k/v gradients (not the q=k=v fold) via argnums.
        q, k, v = _qkv(t=256, d=16)
        rs = np.random.RandomState(7)
        g = jnp.asarray(rs.randn(*q.shape).astype(np.float32))

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=causal, interpret=True) * g
            )

        def loss_ref(q, k, v):
            return jnp.sum(
                _reference_attention(
                    q, k, v, causal=causal, scale=16 ** -0.5
                ) * g
            )

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")

    def test_seq_offset_matches_full_causal(self):
        # ring-attention building block: computing the second half of the
        # queries with seq_offset must equal the full causal slice
        q, k, v = _qkv(t=32, d=8)
        full = _reference_attention(q, k, v, causal=True, scale=0.5)
        half = _reference_attention(
            q[:, :, 16:], k, v, causal=True, scale=0.5, seq_offset=16
        )
        np.testing.assert_allclose(np.asarray(half),
                                   np.asarray(full[:, :, 16:]), atol=1e-5)

    def test_dispatcher_lax_path(self):
        q, k, v = _qkv(t=24, d=8)  # 24 not a multiple of 128 -> lax
        out = dot_product_attention(q, k, v, causal=False)
        assert out.shape == q.shape


class TestInt8Matmul:
    def test_quantize_roundtrip(self):
        w = jnp.asarray(np.random.RandomState(0).randn(16, 32).astype(np.float32))
        q, scale = quantize_per_channel(w, axis=0)
        assert q.dtype == jnp.int8
        np.testing.assert_allclose(np.asarray(q * scale), np.asarray(w),
                                   atol=np.abs(w).max() / 100)

    def test_matmul_close_to_fp32(self):
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(4, 32).astype(np.float32))
        w = jnp.asarray(r.randn(8, 32).astype(np.float32))
        wq, ws = quantize_per_channel(w, axis=0)
        got = int8_matmul(x, wq, ws)
        want = x @ w.T
        err = np.abs(np.asarray(got - want)).max()
        assert err < 0.05 * np.abs(np.asarray(want)).max() + 0.05


def test_flash_untileable_t_falls_back_with_working_grad():
    # T=27 tiles to nothing: the vjp must carry the lse=None
    # reference-fallback residual and still produce correct gradients
    # (attention.py _flash_bwd_rule's fallback arm).  Distinct q/k/v +
    # per-argument grads so a permuted (dq, dk, dv) wiring in the
    # fallback arm cannot cancel out in a shared-input sum.
    rs = np.random.RandomState(11)
    q = jnp.asarray(rs.randn(1, 2, 27, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 2, 27, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 2, 27, 8).astype(np.float32))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True,
                                            scale=8 ** -0.5) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name}")


def test_flash_forward_lse_matches_reference_logsumexp():
    # the blockwise backward trusts the forward's saved logsumexp; pin
    # it against a direct computation (causal, multi-block)
    from bigdl_tpu.ops.attention import _flash_forward

    rs = np.random.RandomState(5)
    b, h, t, d = 1, 2, 256, 16
    q = jnp.asarray(rs.randn(b, h, t, d).astype(np.float32) * 0.5)
    k = jnp.asarray(rs.randn(b, h, t, d).astype(np.float32) * 0.5)
    v = jnp.asarray(rs.randn(b, h, t, d).astype(np.float32))
    scale = d ** -0.5
    out, lse = _flash_forward(q, k, v, True, scale, True, with_lse=True)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    qpos = jnp.arange(t)[:, None]
    kpos = jnp.arange(t)[None, :]
    s = jnp.where(qpos >= kpos, s, -jnp.inf)
    want = jax.scipy.special.logsumexp(s, axis=-1).reshape(b * h, -1)
    got = np.asarray(lse).reshape(b * h, -1)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-4, rtol=1e-4)


def test_flash_chunked_seq_offset_matches_full():
    # chunked causal attention: two query chunks at static seq_offsets
    # against the full kv must reproduce the full causal pass, forward
    # and per-argument gradients (the long-context chunked-training
    # surface of the flash kernels)
    rs = np.random.RandomState(21)
    B, H, Tk, D = 1, 2, 256, 16
    k = jnp.asarray(rs.randn(B, H, Tk, D).astype(np.float32) * 0.5)
    v = jnp.asarray(rs.randn(B, H, Tk, D).astype(np.float32))
    q = jnp.asarray(rs.randn(B, H, Tk, D).astype(np.float32) * 0.5)
    g = jnp.asarray(rs.randn(B, H, Tk, D).astype(np.float32))

    full = flash_attention(q, k, v, causal=True, interpret=True)
    chunks = [
        flash_attention(q[:, :, i:i + 128], k, v, causal=True,
                        interpret=True, seq_offset=i)
        for i in (0, 128)
    ]
    np.testing.assert_allclose(np.asarray(jnp.concatenate(chunks, axis=2)),
                               np.asarray(full), atol=1e-5)

    q1, g1 = q[:, :, 128:], g[:, :, 128:]

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True, seq_offset=128) * g1)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(
            q, k, v, causal=True, scale=16 ** -0.5, seq_offset=128) * g1)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q1, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q1, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name}")


def test_flash_cross_length_non_causal():
    # Tq != Tk (cross-attention shape) on the kernel path
    rs = np.random.RandomState(22)
    q = jnp.asarray(rs.randn(1, 2, 64, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 2, 256, 16).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 2, 256, 16).astype(np.float32))
    out = flash_attention(q, k, v, causal=False, interpret=True)
    ref = _reference_attention(q, k, v, causal=False, scale=16 ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
