"""DistriOptimizer specs — the real sharded step on 8 virtual devices.

Mirrors the reference's DistriOptimizerSpec / AllReduceParameterSpec run
on a local[4] Spark master (SURVEY.md §4.5): the REAL collective path
(psum_scatter + owner update + all_gather via shard_map), no mocks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.dataset import ArrayDataSet, DistributedDataSet
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential
from bigdl_tpu.optim import (
    DistriOptimizer, LocalOptimizer, Optimizer, SGD, Top1Accuracy, Trigger,
)
from bigdl_tpu.optim.evaluator import evaluate_dataset


@pytest.fixture(autouse=True)
def _engine():
    Engine.reset()
    Engine.init()
    yield
    Engine.reset()


def _toy(n=512, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, k)
    x = rng.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    return x, y


def _model(d=16, k=4):
    return Sequential().add(Linear(d, 32)).add(ReLU()).add(Linear(32, k)) \
        .add(LogSoftMax())


def test_mesh_has_8_devices():
    assert Engine.mesh().shape["data"] == 8


def test_distri_optimizer_converges():
    x, y = _toy()
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(10))
    trained = opt.optimize()
    ds = ArrayDataSet(x, y, 64)
    (acc,) = evaluate_dataset(trained, ds, [Top1Accuracy()])
    value, _ = acc.result()
    assert value > 0.9, f"accuracy {value}"


def test_distri_matches_local_single_step():
    """ZeRO-1 sharded update must equal the local update exactly
    (modulo float assoc): same batch, same init, one step, compare
    weights — the reference's semantics-parity requirement
    (SURVEY.md §7 hard part 2)."""
    from bigdl_tpu.common import RandomGenerator

    x, y = _toy(64)
    RandomGenerator.RNG.set_seed(7)
    m1 = _model()
    RandomGenerator.RNG.set_seed(7)
    m2 = _model()
    for a, b in zip(m1.get_weights(), m2.get_weights()):
        np.testing.assert_allclose(a, b)

    ds = ArrayDataSet(x, y, 64, shuffle=False)
    lo = LocalOptimizer(m1, ds, ClassNLLCriterion(), batch_size=64)
    lo.set_optim_method(SGD(learningrate=0.1))
    lo.set_end_when(Trigger.max_iteration(1))
    lo.optimize()

    ds2 = ArrayDataSet(x, y, 64, shuffle=False)
    do = DistriOptimizer(m2, ds2, ClassNLLCriterion(), batch_size=64,
                         wire_dtype="none")
    do.set_optim_method(SGD(learningrate=0.1))
    do.set_end_when(Trigger.max_iteration(1))
    do.optimize()

    for a, b in zip(m1.get_weights(), m2.get_weights()):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_distri_bf16_wire_still_converges():
    x, y = _toy(256)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64,
                          wire_dtype="bfloat16")
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(8))
    trained = opt.optimize()
    ds = ArrayDataSet(x, y, 64)
    (acc,) = evaluate_dataset(trained, ds, [Top1Accuracy()])
    assert acc.result()[0] > 0.85


def test_distri_gradient_clipping():
    x, y = _toy(128)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_gradient_clipping_by_l2_norm(0.1)
    opt.set_end_when(Trigger.max_epoch(2))
    opt.optimize()  # just exercises the psum-based global-norm path


def test_optimizer_factory_dispatches_distributed():
    x, y = _toy(64)
    model = _model()
    ds = DistributedDataSet(x, y, 32)
    opt = Optimizer(model=model, training_set=ds,
                    criterion=ClassNLLCriterion(), batch_size=32)
    assert isinstance(opt, DistriOptimizer)


def test_distri_momentum_state_sharded():
    """Optimizer state must live sharded over the mesh (ZeRO-1) — check
    the velocity buffer's sharding spec."""
    x, y = _toy(64)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.1, momentum=0.9))
    opt.set_end_when(Trigger.max_iteration(2))
    opt.optimize()
    vel = opt.optim_method.state["velocity"]
    sharding = vel.sharding
    spec = sharding.spec
    assert spec[0] == "data", f"velocity not sharded: {spec}"


class _RaggedDataSet(ArrayDataSet):
    """Yields the ragged tail batch even in train mode — models custom
    user DataSets whose generators are not tail-trimmed."""

    def data(self, train: bool = True):
        bs = self.batch_size
        for b in range(0, self._n, bs):
            yield self.features[b: b + bs], self.labels[b: b + bs]


def test_distri_partial_batch_padded(caplog):
    """VERDICT r1 weak 3 / r3 weak 7: batches not divisible by the mesh
    are PADDED with masked copies (reference SampleToMiniBatch
    semantics) — never trimmed — and training still converges."""
    import logging

    x, y = _toy(n=166)  # 166 = 2*64 + 38; 38 % 8 = 6 -> pad to 40
    model = _model()
    ds = _RaggedDataSet(x, y, 64)
    opt = DistriOptimizer(model, ds, ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(6))
    with caplog.at_level(logging.INFO, logger="bigdl_tpu.optim"):
        trained = opt.optimize()
    assert any("padding with" in r.message for r in caplog.records)
    eval_ds = ArrayDataSet(x, y, 64)
    (acc,) = evaluate_dataset(trained, eval_ds, [Top1Accuracy()])
    value, _ = acc.result()
    assert value > 0.85, f"accuracy {value}"


def test_distri_batch_smaller_than_mesh_padded(caplog):
    """A batch smaller than the mesh was previously dropped outright;
    now it pads to one sample-per-device with the rest masked."""
    import logging

    x, y = _toy(n=64 + 5)  # last batch of 5 < 8 devices -> pad to 8
    model = _model()
    ds = _RaggedDataSet(x, y, 64)
    opt = DistriOptimizer(model, ds, ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(2))
    with caplog.at_level(logging.INFO, logger="bigdl_tpu.optim"):
        opt.optimize()
    assert any("padding with" in r.message for r in caplog.records)


class _LossTape:
    """Minimal train-summary stub capturing the per-iteration Loss."""

    def __init__(self):
        self.losses = []

    def add_scalar(self, tag, value, step):
        if tag == "Loss":
            self.losses.append(float(value))

    def add_histogram(self, *a, **k):
        pass

    def get_summary_trigger(self, name):
        return None


def test_partial_batch_loss_trajectory_matches_local():
    """VERDICT r3 item 5 'done' gate: the loss trajectory must be
    IDENTICAL (fp tolerance) whether or not the dataset size divides
    the mesh — i.e. the masked padded step computes the same
    mean-over-valid-samples gradient a single-device run does on the
    ragged tail."""
    x, y = _toy(n=64 + 37, seed=3)  # tail batch of 37: 37 % 8 = 5
    losses = {}
    for cls in (LocalOptimizer, DistriOptimizer):
        model = _model()  # same RandomGenerator seed via autouse fixture
        from bigdl_tpu.common import RandomGenerator

        RandomGenerator.RNG.set_seed(7)
        model = _model()
        ds = _RaggedDataSet(x, y, 64)
        opt = cls(model, ds, ClassNLLCriterion(), batch_size=64)
        if isinstance(opt, DistriOptimizer):
            opt.wire_dtype = "none"  # bf16 wire would blur the comparison
        opt.set_optim_method(SGD(learningrate=0.3))
        opt.set_end_when(Trigger.max_epoch(3))
        tape = _LossTape()
        opt.set_train_summary(tape)
        opt.optimize()
        losses[cls.__name__] = tape.losses
    local, distri = losses["LocalOptimizer"], losses["DistriOptimizer"]
    assert len(local) == len(distri) == 6  # 2 batches x 3 epochs
    np.testing.assert_allclose(local, distri, rtol=2e-4, atol=2e-5)


def test_distri_metrics_phases():
    """VERDICT r1 weak 2: Distri runs expose >= 3 host phases under the
    reference Metrics naming."""
    x, y = _toy(n=128)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_end_when(Trigger.max_epoch(2))
    opt.optimize()
    s = opt.metrics.summary()
    for phase in ("data wait time", "put batch time", "computing time"):
        assert phase in s, s
    assert opt.metrics.value("computing time") > 0


def test_distri_plateau_schedule_applies():
    """VERDICT r1 weak 6: Plateau's host-side lr_scale poke must reach
    the sharded optimizer state between jitted steps."""
    from bigdl_tpu.optim.optim_method import Plateau

    x, y = _toy(n=256)
    model = _model()
    # epsilon=0.5: "improvement" requires +0.5 accuracy — impossible
    # after epoch 1, so the schedule must decay deterministically
    method = SGD(learningrate=0.5,
                 learningrate_schedule=Plateau(monitor="score", factor=0.5,
                                               patience=0, mode="max",
                                               epsilon=0.5))
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(method)
    opt.set_end_when(Trigger.max_epoch(6))
    opt.set_validation(
        trigger=Trigger.every_epoch(),
        dataset=(x, y),
        methods=[Top1Accuracy()],
    )
    opt.optimize()
    # patience=0: any non-improving epoch halves the lr; after 6 epochs
    # of a near-converged toy the scale must have dropped at least once
    assert float(method.state["lr_scale"]) < 1.0
    # and training still behaves
    ds = ArrayDataSet(x, y, 64)
    (acc,) = evaluate_dataset(model, ds, [Top1Accuracy()])
    assert acc.result()[0] > 0.85


def test_distributed_dataset_per_process_slices():
    """DistributedDataSet's iterator contract: every process derives the
    same global permutation and takes its contiguous slice of each
    global batch."""
    from bigdl_tpu.common import RandomGenerator

    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    y = np.arange(64, dtype=np.float32)
    views = []
    for pid in range(2):
        RandomGenerator.RNG.set_seed(7)  # same seed on every "process"
        ds = DistributedDataSet(x, y, batch_size=16, process_id=pid,
                                num_processes=2)
        views.append(list(ds.data(train=True)))
    assert len(views[0]) == 4  # 64 / 16 global batches
    for (f0, l0), (f1, l1) in zip(*views):
        assert f0.shape == (8, 1) and f1.shape == (8, 1)  # local slices
        # slices are disjoint rows of the same global batch
        assert not set(l0.tolist()) & set(l1.tolist())
    # union over one epoch covers every sample exactly once
    seen = np.concatenate(
        [l for view in views for _, l in view]
    )
    assert sorted(seen.tolist()) == list(range(64))


def test_distributed_dataset_trains_single_process():
    x, y = _toy(n=256)
    model = _model()
    ds = DistributedDataSet(x, y, batch_size=64, process_id=0,
                            num_processes=1)
    opt = DistriOptimizer(model, ds, ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(8))
    trained = opt.optimize()
    (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, 64),
                              [Top1Accuracy()])
    assert acc.result()[0] > 0.9


def test_sharded_evaluate_matches_single_device():
    """Distributed evaluate (VERDICT r2 #3): the P(data)-sharded eval
    forward over the 8-device mesh must reproduce single-device results
    exactly, including a ragged tail batch (padded + sliced)."""
    x, y = _toy(100)  # 100 % 8 != 0: exercises the pad/slice path
    model = _model()
    model.evaluate()
    ds = ArrayDataSet(x, y, 32, shuffle=False)
    (single,) = evaluate_dataset(model, ds, [Top1Accuracy()])
    (sharded,) = evaluate_dataset(model, ds, [Top1Accuracy()],
                                  mesh=Engine.mesh())
    assert single.result() == sharded.result()


def test_sharded_predict_matches_single_device():
    from bigdl_tpu.optim.evaluator import predict

    x, _ = _toy(37)
    model = _model()
    np.testing.assert_allclose(
        predict(model, x, batch_size=16),
        predict(model, x, batch_size=16, mesh=Engine.mesh()),
        rtol=1e-6,
    )


def test_distri_validation_uses_device_resident_params():
    """_run_validation must not round-trip weights through the host:
    _write_back is only called at the end of optimize(), not per
    validation trigger."""
    x, y = _toy(256)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(3))
    opt.set_validation(Trigger.every_epoch(), (x, y), [Top1Accuracy()])

    calls = {"write_back": 0, "validate": 0}
    orig_wb = opt._write_back
    orig_rv = opt._run_validation

    def counting_wb(pvar, mstate):
        calls["write_back"] += 1
        return orig_wb(pvar, mstate)

    def counting_rv(pvar=None, mstate=None):
        calls["validate"] += 1
        assert pvar is not None, "validation must receive device params"
        return orig_rv(pvar, mstate)

    opt._write_back = counting_wb
    opt._run_validation = counting_rv
    opt.optimize()
    assert calls["validate"] >= 3
    assert calls["write_back"] == 1, calls  # only the final write-back
    assert opt.state["score"] is not None


def test_distri_retry_from_checkpoint(tmp_path):
    """Failure semantics (VERDICT r2 #4; SURVEY.md §5): inject a failure
    mid-training; DistriOptimizer must reload the last checkpoint, rewind
    epoch/neval, and converge to EXACTLY the same weights as an
    uninterrupted run (same data order, same per-step RNG folding)."""
    from bigdl_tpu.common import RandomGenerator

    x, y = _toy(256)
    ds = ArrayDataSet(x, y, 64, shuffle=False)  # 4 iterations / epoch

    def build(seed=11):
        RandomGenerator.RNG.set_seed(seed)
        return _model()

    # --- uninterrupted reference run ---
    m_ref = build()
    ref = DistriOptimizer(m_ref, ds, ClassNLLCriterion(), batch_size=64)
    ref.set_optim_method(SGD(learningrate=0.2, momentum=0.9))
    ref.set_end_when(Trigger.max_epoch(3))
    ref.optimize()

    # --- run with injected failure at epoch 2, first batch ---
    m = build()
    opt = DistriOptimizer(m, ds, ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.2, momentum=0.9))
    opt.set_end_when(Trigger.max_epoch(3))
    opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())

    armed = {"on": True}
    orig_put = opt._put_batch

    def poisoned_put(inp, tgt):
        if armed["on"] and opt.state["neval"] == 5:
            armed["on"] = False
            raise RuntimeError("injected executor loss")
        return orig_put(inp, tgt)

    opt._put_batch = poisoned_put
    opt.optimize()

    assert not armed["on"], "failure was never injected"
    # resumed counters continued correctly (3 epochs * 4 iters + 1)
    assert opt.state["neval"] == 13, opt.state
    for a, b in zip(m.get_weights(), m_ref.get_weights()):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_metrics_logged_per_epoch(caplog):
    """VERDICT r2 #7: metrics.summary() phase averages must appear in
    the training log each epoch, with the reference's metric names."""
    import logging

    x, y = _toy(128)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.1))
    opt.set_end_when(Trigger.max_epoch(1))
    with caplog.at_level(logging.INFO, logger="bigdl_tpu.optim"):
        opt.optimize()
    lines = [r.message for r in caplog.records if r.message.startswith("Metrics:")]
    assert lines, "no Metrics summary line logged"
    assert "computing time average" in lines[-1]
    assert "data wait time average" in lines[-1]


def test_hierarchical_data_axes_multislice():
    """Multi-slice seam: data parallelism over a 2-level ('dcn','ici')
    mesh — batch and ZeRO shards split over BOTH axes, XLA free to build
    the hierarchical collective.  Must converge like the flat 8-way run."""
    x, y = _toy(n=256, seed=5)
    flat_losses, hier_losses = [], []
    from bigdl_tpu.common import RandomGenerator

    for mode in ("flat", "hier"):
        RandomGenerator.RNG.set_seed(11)
        model = _model()
        if mode == "flat":
            mesh = Engine.build_mesh({"data": 8})
            opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                                  batch_size=64, mesh=mesh,
                                  wire_dtype="none")
        else:
            mesh = Engine.build_mesh({"dcn": 2, "ici": 4})
            opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                                  batch_size=64, mesh=mesh,
                                  wire_dtype="none",
                                  data_axes=("dcn", "ici"))
        opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
        opt.set_end_when(Trigger.max_epoch(4))
        tape = _LossTape()
        opt.set_train_summary(tape)
        opt.optimize()
        (flat_losses if mode == "flat" else hier_losses).extend(tape.losses)
        if mode == "hier":
            vel = opt.optim_method.state["velocity"]
            spec = vel.sharding.spec
            flat_axes = []
            for entry in spec:
                if isinstance(entry, (tuple, list)):
                    flat_axes.extend(entry)
                elif entry:
                    flat_axes.append(entry)
            assert set(flat_axes) == {"dcn", "ici"}, spec
    # same data order (shared seeded RNG), same math to fp tolerance
    np.testing.assert_allclose(flat_losses, hier_losses,
                               rtol=2e-4, atol=2e-5)


def test_freeze_and_parameters_table():
    """Reference module.freeze / getParametersTable: frozen subtrees
    take zero updates (incl. no weight-decay drift) under BOTH
    optimizers; unfreeze resumes learning."""
    from bigdl_tpu.optim.regularizer import L2Regularizer

    x, y = _toy(n=128, seed=6)

    def build():
        from bigdl_tpu.common import RandomGenerator

        RandomGenerator.RNG.set_seed(21)
        m = Sequential() \
            .add(Linear(16, 32, w_regularizer=L2Regularizer(1e-2))
                 .set_name("stem")) \
            .add(ReLU()) \
            .add(Linear(32, 4).set_name("head")) \
            .add(LogSoftMax())
        return m

    for cls in (LocalOptimizer, DistriOptimizer):
        model = build()
        model.freeze("stem")
        w_before = np.asarray(model.modules[0].weight).copy()
        h_before = np.asarray(model.modules[2].weight).copy()
        opt = cls(model, (x, y), ClassNLLCriterion(), batch_size=32)
        opt.set_optim_method(SGD(learningrate=0.5))
        opt.set_end_when(Trigger.max_epoch(2))
        opt.optimize()
        np.testing.assert_array_equal(
            np.asarray(model.modules[0].weight), w_before,
            err_msg=f"{cls.__name__} moved frozen weights")
        assert not np.allclose(np.asarray(model.modules[2].weight),
                               h_before), f"{cls.__name__} head frozen too"

        model.unfreeze("stem")
        opt2 = cls(model, (x, y), ClassNLLCriterion(), batch_size=32)
        opt2.set_optim_method(SGD(learningrate=0.5))
        opt2.set_end_when(Trigger.max_epoch(1))
        opt2.optimize()
        assert not np.allclose(np.asarray(model.modules[0].weight),
                               w_before), f"{cls.__name__} unfreeze inert"

    table = build().get_parameters_table()
    assert "stem" in table and "head" in table
    assert set(table["stem"]) == {"weight", "bias"}


def test_freeze_survives_optimizer_weight_decay():
    """Freeze must hold against optimizer-INTERNAL weight decay (wd*p
    added past the zeroed gradient) in both optimizers."""
    x, y = _toy(n=64, seed=8)
    from bigdl_tpu.common import RandomGenerator

    for cls in (LocalOptimizer, DistriOptimizer):
        RandomGenerator.RNG.set_seed(23)
        model = Sequential() \
            .add(Linear(16, 8).set_name("stem")) \
            .add(ReLU()).add(Linear(8, 4)).add(LogSoftMax())
        model.freeze("stem")
        w_before = np.asarray(model.modules[0].weight).copy()
        opt = cls(model, (x, y), ClassNLLCriterion(), batch_size=32)
        opt.set_optim_method(SGD(learningrate=0.5, weightdecay=1e-2))
        opt.set_end_when(Trigger.max_epoch(2))
        opt.optimize()
        np.testing.assert_array_equal(
            np.asarray(model.modules[0].weight), w_before,
            err_msg=f"{cls.__name__}: weight decay moved frozen weights")


def test_int8_blockwise_reduce_scatter_matches_exact():
    """Unit spec for the quantized wire: the staged-ring int8 exchange
    (parallel/wire.py) reproduces psum_scatter within the per-hop
    quantization bound.  The partial for chunk ``c`` is quantized once
    per hop; at hop ``h`` it holds peers ``c+1..c+h``, so each hop's
    element error is bounded by that running partial's blockmax/254 —
    the bound is the triangular cumsum of peer blockmaxes, not the old
    quantize-once sum."""
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.optim.distri_optimizer import (
        _shard_map,
        int8_blockwise_reduce_scatter,
    )

    mesh = Engine.mesh()
    n, block = 8, 64
    L = n * block * 3  # 3 blocks per shard
    rs = np.random.RandomState(0)
    # heavy-tailed gradients: mix of tiny and large magnitudes
    g_all = (rs.randn(n, L) * np.exp(rs.randn(n, L))).astype(np.float32)

    def quantized(gl):
        return int8_blockwise_reduce_scatter(gl[0], "data", n, block)[None]

    def exact(gl):
        return jax.lax.psum_scatter(
            gl[0], "data", scatter_dimension=0, tiled=True)[None]

    sm = lambda f: _shard_map(f, mesh=mesh, in_specs=(P("data", None),),
                              out_specs=P("data", None))
    got = np.asarray(sm(quantized)(jnp.asarray(g_all))).reshape(-1)
    want = np.asarray(sm(exact)(jnp.asarray(g_all))).reshape(-1)

    # blockmax[p, c, b]: device p's max |g| in block b of chunk c
    bm = np.abs(g_all.reshape(n, n, -1, block)).max(-1)
    # hop h of chunk c quantizes the partial over peers c+1..c+h:
    # error <= partial blockmax / 254 <= cumsum of peer blockmaxes/254
    bound = np.zeros_like(bm[0])  # (n_chunks, nblocks)
    for c in range(n):
        run = np.zeros_like(bm[0, 0])
        for h in range(1, n):
            run = run + bm[(c + h) % n, c]
            bound[c] += run / 254.0
    # 1% headroom: earlier hops' errors enter later partials' amax
    bound = bound * 1.01 + 1e-6
    err = np.abs(got - want).reshape(bound.shape + (block,))
    assert np.all(err <= bound[..., None]), (err.max(), bound.min())
    # and close in aggregate — per-hop staging compounds ~n/2 vs the
    # quantize-once shape on this deliberately heavy-tailed data; the
    # error-feedback residual is what cancels it across steps
    # (tests/test_wire.py TestErrorFeedback)
    rel = np.abs(got - want).mean() / (np.abs(want).mean() + 1e-9)
    assert rel < 0.15, rel


def test_distri_int8_wire_converges_and_tracks_exact():
    """End-to-end: training under the int8 wire reaches the same
    accuracy as the uncompressed wire and its loss trajectory stays
    close — the FP16CompressedTensor parity claim at int8."""
    x, y = _toy()

    losses = {}
    for wire in ("none", "int8"):
        model = _model()
        opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                              batch_size=64, wire_dtype=wire,
                              int8_block=128)
        opt.set_optim_method(SGD(learningrate=0.5))
        opt.set_end_when(Trigger.max_epoch(6))
        trained = opt.optimize()
        losses[wire] = opt.state["loss"]
        (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, 64),
                                  [Top1Accuracy()])
        value, _ = acc.result()
        assert value > 0.95, f"{wire} wire accuracy {value}"
    assert abs(losses["int8"] - losses["none"]) < 0.15, losses


def test_int8_wire_pads_to_block_multiple():
    """A parameter count far from a block multiple still shards: the
    pad rounds the flat vector up to n*block."""
    x, y = _toy(d=13, k=3)
    model = Sequential().add(Linear(13, 7)).add(ReLU()) \
        .add(Linear(7, 3)).add(LogSoftMax())
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                          batch_size=64, wire_dtype="int8",
                          int8_block=32)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(2))
    opt.optimize()
    n_params = sum(int(np.size(p)) for p in jax.tree.leaves(model.params()))
    assert (n_params + opt._pad) % (8 * 32) == 0


def test_wire_dtype_validation():
    x, y = _toy(64)
    with pytest.raises(ValueError, match="wire_dtype"):
        DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                        batch_size=64, wire_dtype="fp16")
    with pytest.raises(ValueError, match="int8_block"):
        DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                        batch_size=64, wire_dtype="int8", int8_block=0)


def test_int8_wire_with_ragged_masked_batches(caplog):
    """Combination seam: the quantized exchange under the MASKED final
    -batch step (pad + masked-mean) — both features at once."""
    import logging

    x, y = _toy(n=166)  # ragged tail: 38 -> padded to 40
    model = _model()
    ds = _RaggedDataSet(x, y, 64)
    opt = DistriOptimizer(model, ds, ClassNLLCriterion(), batch_size=64,
                          wire_dtype="int8", int8_block=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(6))
    with caplog.at_level(logging.INFO, logger="bigdl_tpu.optim"):
        trained = opt.optimize()
    assert any("padding with" in r.message for r in caplog.records)
    (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, 64),
                              [Top1Accuracy()])
    assert acc.result()[0] > 0.85, acc.result()


def test_background_checkpoint_with_distri_retry(tmp_path):
    """Combination seam: background checkpoint writes + the
    retry-from-checkpoint path — the retry must see complete files."""
    x, y = _toy(256)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(),
                          batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(4))
    opt.set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                       background=True)
    opt.max_retry = 1

    # inject one failure after epoch 2's checkpoint: monkeypatch the
    # step dispatcher to throw once
    orig_build = opt._build_train_step
    calls = {"n": 0, "failed": False}

    def flaky_build():
        dispatch = orig_build()

        def wrapper(*a, **k):
            calls["n"] += 1
            if calls["n"] == 10 and not calls["failed"]:
                calls["failed"] = True
                raise RuntimeError("injected executor loss")
            return dispatch(*a, **k)

        return wrapper

    opt._build_train_step = flaky_build
    trained = opt.optimize()  # retries from the background checkpoint
    assert calls["failed"]
    (acc,) = evaluate_dataset(trained, ArrayDataSet(x, y, 64),
                              [Top1Accuracy()])
    assert acc.result()[0] > 0.9, acc.result()


# ---------------------------------------------- overlapped step (ISSUE 11)
def _seeded_model(seed=7):
    from bigdl_tpu.common import RandomGenerator

    RandomGenerator.RNG.set_seed(seed)
    return _model()


def _small_mesh(n):
    return Engine.build_mesh({"data": n}, devices=jax.devices()[:n])


def _overlap_run(**kw):
    x, y = _toy(128)
    opt = DistriOptimizer(_seeded_model(), ArrayDataSet(x, y, 32,
                                                        shuffle=False),
                          ClassNLLCriterion(), batch_size=32,
                          mesh=_small_mesh(2), **kw)
    opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
    opt.set_end_when(Trigger.max_epoch(2))

    class Tape:
        loss: dict = {}

        def __init__(self):
            self.loss = {}

        def add_scalar(self, tag, v, s):
            if tag == "Loss":
                self.loss[s] = float(v)

        def add_histogram(self, *a, **k):
            pass

        def get_summary_trigger(self, name):
            return None

        def add_resilience(self, *a, **k):
            pass

    tape = Tape()
    opt.set_train_summary(tape)
    opt.optimize()
    return tape.loss, opt


def test_bucketed_exchange_matches_monolithic_trajectory():
    """ISSUE 11 tentpole: splitting the f32 gradient exchange into
    last-layer-first buckets changes WHEN bytes move, not the math —
    the per-step loss trajectory matches the monolithic exchange."""
    base, mono = _overlap_run(wire_dtype="none")
    over, bopt = _overlap_run(wire_dtype="none", overlap_bucket_mb=0.0005)
    assert len(bopt._buckets) > 1, bopt._buckets
    assert mono._buckets == [(0, mono._flat_elems + mono._pad)]
    worst = max(abs(over[s] - base[s]) / (abs(base[s]) + 1e-9)
                for s in base)
    assert worst < 1e-5, worst
    # the shard-major layout is recorded for the resize path
    topo = bopt._topology()
    assert topo["buckets"] == [[s, z] for s, z in bopt._buckets]
    assert "buckets" not in mono._topology()


def test_bucketed_wire_bytes_match_monolithic_golden():
    """Golden byte-count parity: the bucketed int8 staged ring ships
    EXACTLY the monolithic wire's bytes (payload and scales) — overlap
    is free on the wire."""
    from bigdl_tpu import obs
    from bigdl_tpu.obs import collectives as C

    def ring_bytes():
        fam = obs.get_registry().counter(
            "bigdl_collective_bytes_total", labels=("op", "dtype"))
        return {d: fam.labels(op="ring_rs", dtype=d).value
                for d in ("int8", "float32")}

    obs.reset()
    _, mono = _overlap_run(wire_dtype="int8", wire_block=64)
    mono_bytes = ring_bytes()
    obs.reset()
    _, bopt = _overlap_run(wire_dtype="int8", wire_block=64,
                           overlap_bucket_mb=0.001)
    over_bytes = ring_bytes()
    assert len(bopt._buckets) > 1
    assert over_bytes == mono_bytes and mono_bytes["int8"] > 0
    # and both match the static model exactly
    padded = mono._flat_elems + mono._pad
    model = C.staged_ring_exchange_bytes(padded, 2, 64, "int8")
    steps = 8  # 2 epochs x 128/32 batches over the 2-shard mesh
    assert mono_bytes["int8"] == model["int8"] * steps
    assert mono_bytes["float32"] >= model["float32"] * steps


def test_exposed_comm_gauges_published_with_buckets():
    """Satellite: the overlap gauges say how much of the wire stays
    exposed — 1/K of the exchange with K buckets (plus the serialized
    gathers), and nothing is published for monolithic runs."""
    from bigdl_tpu import obs

    obs.reset()
    _, mono = _overlap_run(wire_dtype="none")
    reg = obs.get_registry()
    assert reg.gauge(
        "bigdl_overlap_buckets", "x").labels().value == 1.0
    obs.reset()
    _, bopt = _overlap_run(wire_dtype="none", overlap_bucket_mb=0.0005)
    reg = obs.get_registry()
    k = len(bopt._buckets)
    assert reg.gauge("bigdl_overlap_buckets", "x").labels().value == float(k)
    frac = reg.gauge("bigdl_overlap_exposed_comm_fraction",
                     "x").labels().value
    assert 0.0 < frac < 1.0, frac
    # exposed = total - hidden exchange share
    fp = bopt._collective_footprint
    exchange = sum(b for op, _d, b in fp.entries if op == "ring_rs"
                   or op == "psum_scatter")
    expected = (fp.total() - exchange * (k - 1) / k) / fp.total()
    assert abs(frac - expected) < 1e-4, (frac, expected)
