"""DistriOptimizer specs — the real sharded step on 8 virtual devices.

Mirrors the reference's DistriOptimizerSpec / AllReduceParameterSpec run
on a local[4] Spark master (SURVEY.md §4.5): the REAL collective path
(psum_scatter + owner update + all_gather via shard_map), no mocks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.dataset import ArrayDataSet, DistributedDataSet
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential
from bigdl_tpu.optim import (
    DistriOptimizer, LocalOptimizer, Optimizer, SGD, Top1Accuracy, Trigger,
)
from bigdl_tpu.optim.evaluator import evaluate_dataset


@pytest.fixture(autouse=True)
def _engine():
    Engine.reset()
    Engine.init()
    yield
    Engine.reset()


def _toy(n=512, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, k)
    x = rng.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    return x, y


def _model(d=16, k=4):
    return Sequential().add(Linear(d, 32)).add(ReLU()).add(Linear(32, k)) \
        .add(LogSoftMax())


def test_mesh_has_8_devices():
    assert Engine.mesh().shape["data"] == 8


def test_distri_optimizer_converges():
    x, y = _toy()
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(10))
    trained = opt.optimize()
    ds = ArrayDataSet(x, y, 64)
    (acc,) = evaluate_dataset(trained, ds, [Top1Accuracy()])
    value, _ = acc.result()
    assert value > 0.9, f"accuracy {value}"


def test_distri_matches_local_single_step():
    """ZeRO-1 sharded update must equal the local update exactly
    (modulo float assoc): same batch, same init, one step, compare
    weights — the reference's semantics-parity requirement
    (SURVEY.md §7 hard part 2)."""
    from bigdl_tpu.common import RandomGenerator

    x, y = _toy(64)
    RandomGenerator.RNG.set_seed(7)
    m1 = _model()
    RandomGenerator.RNG.set_seed(7)
    m2 = _model()
    for a, b in zip(m1.get_weights(), m2.get_weights()):
        np.testing.assert_allclose(a, b)

    ds = ArrayDataSet(x, y, 64, shuffle=False)
    lo = LocalOptimizer(m1, ds, ClassNLLCriterion(), batch_size=64)
    lo.set_optim_method(SGD(learningrate=0.1))
    lo.set_end_when(Trigger.max_iteration(1))
    lo.optimize()

    ds2 = ArrayDataSet(x, y, 64, shuffle=False)
    do = DistriOptimizer(m2, ds2, ClassNLLCriterion(), batch_size=64,
                         wire_dtype="none")
    do.set_optim_method(SGD(learningrate=0.1))
    do.set_end_when(Trigger.max_iteration(1))
    do.optimize()

    for a, b in zip(m1.get_weights(), m2.get_weights()):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_distri_bf16_wire_still_converges():
    x, y = _toy(256)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64,
                          wire_dtype="bfloat16")
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_end_when(Trigger.max_epoch(8))
    trained = opt.optimize()
    ds = ArrayDataSet(x, y, 64)
    (acc,) = evaluate_dataset(trained, ds, [Top1Accuracy()])
    assert acc.result()[0] > 0.85


def test_distri_gradient_clipping():
    x, y = _toy(128)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.5))
    opt.set_gradient_clipping_by_l2_norm(0.1)
    opt.set_end_when(Trigger.max_epoch(2))
    opt.optimize()  # just exercises the psum-based global-norm path


def test_optimizer_factory_dispatches_distributed():
    x, y = _toy(64)
    model = _model()
    ds = DistributedDataSet(x, y, 32)
    opt = Optimizer(model=model, training_set=ds,
                    criterion=ClassNLLCriterion(), batch_size=32)
    assert isinstance(opt, DistriOptimizer)


def test_distri_momentum_state_sharded():
    """Optimizer state must live sharded over the mesh (ZeRO-1) — check
    the velocity buffer's sharding spec."""
    x, y = _toy(64)
    model = _model()
    opt = DistriOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learningrate=0.1, momentum=0.9))
    opt.set_end_when(Trigger.max_iteration(2))
    opt.optimize()
    vel = opt.optim_method.state["velocity"]
    sharding = vel.sharding
    spec = sharding.spec
    assert spec[0] == "data", f"velocity not sharded: {spec}"
