"""Tensor façade specs (VERDICT r2 #8; reference DenseTensorSpec
patterns — 1-based narrow/select/transpose, mutation-style ops,
max/min returning 1-based indices)."""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.tensor import Tensor, rand, randn


class TestConstruction:
    def test_sized(self):
        t = Tensor(2, 3)
        assert t.size() == (2, 3)
        assert t.dim() == 2
        assert t.n_element() == 6
        np.testing.assert_allclose(t.to_ndarray(), 0.0)

    def test_wrap_ndarray(self):
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        t = Tensor.from_ndarray(a)
        np.testing.assert_allclose(t.to_ndarray(), a)

    def test_wrap_list(self):
        t = Tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.size() == (2, 2)
        assert t.dtype == jnp.float32

    def test_size_1_based_dim(self):
        t = Tensor(4, 5, 6)
        assert t.size(1) == 4 and t.size(2) == 5 and t.size(3) == 6

    def test_seeded_randn(self):
        from bigdl_tpu.common import RandomGenerator

        RandomGenerator.RNG.set_seed(42)
        a = randn(3, 3).to_ndarray()
        RandomGenerator.RNG.set_seed(42)
        b = randn(3, 3).to_ndarray()
        np.testing.assert_allclose(a, b)


class TestSlicing:
    def setup_method(self, _):
        self.t = Tensor.from_ndarray(
            np.arange(24, dtype=np.float32).reshape(4, 6))

    def test_narrow(self):
        n = self.t.narrow(1, 2, 2)  # rows 2..3 (1-based)
        np.testing.assert_allclose(
            n.to_ndarray(), np.arange(24).reshape(4, 6)[1:3])

    def test_select(self):
        s = self.t.select(1, 3)  # third row
        np.testing.assert_allclose(
            s.to_ndarray(), np.arange(24).reshape(4, 6)[2])

    def test_index_select(self):
        s = self.t.index_select(2, [1, 6])
        np.testing.assert_allclose(
            s.to_ndarray(), np.arange(24).reshape(4, 6)[:, [0, 5]])

    def test_transpose_1_based(self):
        tt = self.t.transpose(1, 2)
        assert tt.size() == (6, 4)
        np.testing.assert_allclose(
            tt.to_ndarray(), np.arange(24).reshape(4, 6).T)

    def test_view_and_squeeze(self):
        v = self.t.view(2, 12)
        assert v.size() == (2, 12)
        u = Tensor(1, 4).squeeze()
        assert u.size() == (4,)
        w = Tensor(4).unsqueeze(1)
        assert w.size() == (1, 4)


class TestMutation:
    def test_fill_zero(self):
        t = Tensor(2, 2).fill(7.0)
        np.testing.assert_allclose(t.to_ndarray(), 7.0)
        t.zero()
        np.testing.assert_allclose(t.to_ndarray(), 0.0)

    def test_copy(self):
        t = Tensor(2, 3)
        src = Tensor.from_ndarray(np.ones((2, 3), np.float32) * 5)
        t.copy(src)
        np.testing.assert_allclose(t.to_ndarray(), 5.0)

    def test_set_aliases(self):
        a = Tensor(2, 2).fill(1.0)
        b = Tensor(0)
        b.set(a)
        assert b.size() == (2, 2)

    def test_resize(self):
        t = Tensor.from_ndarray(np.arange(6, dtype=np.float32))
        t.resize(2, 3)  # same element count: reshape keeps content
        np.testing.assert_allclose(
            t.to_ndarray(), np.arange(6).reshape(2, 3))
        t.resize(4, 4)  # grows: reallocates zeros
        np.testing.assert_allclose(t.to_ndarray(), 0.0)

    def test_set_value_value_at_1_based(self):
        t = Tensor(3, 3)
        t.set_value(2, 3, 9.5)
        assert t.value_at(2, 3) == pytest.approx(9.5)
        assert t.to_ndarray()[1, 2] == pytest.approx(9.5)


class TestMath:
    def test_inplace_chain(self):
        t = Tensor.from_ndarray(np.full((2, 2), 4.0, np.float32))
        t.add(1.0).mul(2.0).sqrt()
        np.testing.assert_allclose(t.to_ndarray(), np.sqrt(10.0))

    def test_addmm(self):
        a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        b = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        t = Tensor(2, 4).fill(1.0).add_mm(Tensor(a), Tensor(b))
        np.testing.assert_allclose(t.to_ndarray(), 1.0 + a @ b, rtol=1e-5)

    def test_max_with_dim_returns_1_based(self):
        t = Tensor.from_ndarray(
            np.asarray([[1.0, 5.0, 2.0], [7.0, 0.0, 3.0]], np.float32))
        vals, idx = t.max(2)
        np.testing.assert_allclose(vals.to_ndarray(), [[5.0], [7.0]])
        np.testing.assert_allclose(idx.to_ndarray(), [[2], [1]])

    def test_operators(self):
        a = Tensor([1.0, 2.0])
        b = Tensor([3.0, 4.0])
        np.testing.assert_allclose((a + b).to_ndarray(), [4.0, 6.0])
        np.testing.assert_allclose((b - a).to_ndarray(), [2.0, 2.0])
        np.testing.assert_allclose((a * 2).to_ndarray(), [2.0, 4.0])
        np.testing.assert_allclose((-a).to_ndarray(), [-1.0, -2.0])
        assert a.dot(b) == pytest.approx(11.0)

    def test_reductions(self):
        t = Tensor.from_ndarray(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert t.sum() == pytest.approx(15.0)
        assert t.mean() == pytest.approx(2.5)
        np.testing.assert_allclose(t.sum(1).to_ndarray(), [[3.0, 5.0, 7.0]])
        assert t.norm(2) == pytest.approx(np.sqrt(55.0), rel=1e-5)

    def test_apply1_and_map(self):
        t = Tensor([1.0, 2.0, 3.0]).apply1(lambda v: v * v)
        np.testing.assert_allclose(t.to_ndarray(), [1.0, 4.0, 9.0])
        u = Tensor([1.0, 2.0, 3.0])
        u.map(Tensor([10.0, 20.0, 30.0]), lambda a, b: a + b)
        np.testing.assert_allclose(u.to_ndarray(), [11.0, 22.0, 33.0])


class TestInterop:
    def test_feeds_layers_directly(self):
        """A Tensor passes into the module stack via __jax_array__."""
        from bigdl_tpu.nn import Linear

        m = Linear(3, 2)
        x = Tensor.from_ndarray(np.ones((4, 3), np.float32))
        out = m.forward(jnp.asarray(x))
        assert out.shape == (4, 2)

    def test_set_weights_accepts_tensors(self):
        from bigdl_tpu.nn import Linear

        m = Linear(2, 2)
        w = Tensor.from_ndarray(np.eye(2, dtype=np.float32))
        b = Tensor(2).fill(0.5)
        m.set_weights([w, b])
        out = m.forward(jnp.ones((1, 2)))
        np.testing.assert_allclose(np.asarray(out), [[1.5, 1.5]])

    def test_jtensor_roundtrip(self):
        from bigdl.util.common import JTensor

        a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        jt = JTensor.from_ndarray(Tensor.from_ndarray(a))
        np.testing.assert_allclose(jt.to_ndarray(), a)


# ---------------------------------------------------------------------------
# VERDICT r3 item 9: reference Tensor API parity —
# gather/scatter/masked*/index*/math/topk/sort/expand/random fills
# ---------------------------------------------------------------------------


def test_gather_scatter():
    t = Tensor.from_ndarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    idx = np.array([[1, 2], [3, 1], [4, 4]], np.float32)  # 1-based
    g = t.gather(2, idx)
    np.testing.assert_allclose(
        g.to_ndarray(),
        np.take_along_axis(np.arange(12, dtype=np.float32).reshape(3, 4),
                           idx.astype(int) - 1, axis=1))
    s = Tensor.from_ndarray(np.zeros((3, 4), np.float32))
    s.scatter(2, idx, g)
    expect = np.zeros((3, 4), np.float32)
    np.put_along_axis(expect, idx.astype(int) - 1, g.to_ndarray(), axis=1)
    np.testing.assert_allclose(s.to_ndarray(), expect)


def test_masked_fill_select_copy():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    mask = (a % 2 == 0).astype(np.float32)
    t = Tensor.from_ndarray(a.copy()).masked_fill(mask, -1.0)
    np.testing.assert_allclose(
        t.to_ndarray(), np.where(a % 2 == 0, -1.0, a))
    sel = Tensor.from_ndarray(a).masked_select(mask)
    np.testing.assert_allclose(sel.to_ndarray(), a[a % 2 == 0])
    cp = Tensor.from_ndarray(a.copy()).masked_copy(
        mask, np.array([10.0, 20.0, 30.0], np.float32))
    expect = a.copy()
    expect[a % 2 == 0] = [10.0, 20.0, 30.0]
    np.testing.assert_allclose(cp.to_ndarray(), expect)


def test_index_fill_copy_add():
    a = np.zeros((3, 4), np.float32)
    t = Tensor.from_ndarray(a.copy()).index_fill(1, [1, 3], 7.0)
    assert (t.to_ndarray()[[0, 2]] == 7.0).all()
    assert (t.to_ndarray()[1] == 0.0).all()
    src = np.ones((3, 2), np.float32)
    t2 = Tensor.from_ndarray(a.copy()).index_copy(2, [2, 4], src)
    assert (t2.to_ndarray()[:, [1, 3]] == 1.0).all()
    t3 = Tensor.from_ndarray(np.ones((3, 4), np.float32)) \
        .index_add(2, [1, 2], src)
    np.testing.assert_allclose(t3.to_ndarray()[:, :2], 2 * src)


def test_math_parity_surface():
    a = np.array([[-2.0, 0.5], [1.5, -0.25]], np.float32)
    t = Tensor.from_ndarray(a.copy())
    np.testing.assert_allclose(
        Tensor.from_ndarray(a.copy()).cmax(0.0).to_ndarray(),
        np.maximum(a, 0))
    np.testing.assert_allclose(
        Tensor.from_ndarray(a.copy()).clamp(-1, 1).to_ndarray(),
        np.clip(a, -1, 1))
    np.testing.assert_allclose(
        Tensor.from_ndarray(a.copy()).sign().to_ndarray(), np.sign(a))
    t1 = np.full((2, 2), 2.0, np.float32)
    t2 = np.full((2, 2), 3.0, np.float32)
    np.testing.assert_allclose(
        Tensor.from_ndarray(a.copy()).addcmul(0.5, t1, t2).to_ndarray(),
        a + 0.5 * 6.0)
    np.testing.assert_allclose(
        Tensor.from_ndarray(np.zeros((2, 3), np.float32))
        .addr([1.0, 2.0], [1.0, 10.0, 100.0]).to_ndarray(),
        np.outer([1, 2], [1, 10, 100]))


def test_topk_sort_nonzero():
    a = np.array([[3.0, 1.0, 2.0], [0.0, -1.0, 5.0]], np.float32)
    t = Tensor.from_ndarray(a)
    vals, idx = t.topk(2)
    np.testing.assert_allclose(vals.to_ndarray(),
                               np.array([[3, 2], [5, 0]], np.float32))
    np.testing.assert_allclose(idx.to_ndarray(),
                               np.array([[1, 3], [3, 1]], np.float32))
    svals, sidx = t.sort()
    np.testing.assert_allclose(svals.to_ndarray(), np.sort(a, -1))
    nz = Tensor.from_ndarray(np.array([[0.0, 2.0], [3.0, 0.0]])).nonzero()
    np.testing.assert_allclose(nz.to_ndarray(), [[1, 2], [2, 1]])


def test_expand_repeat_split_chunk_reshape():
    a = np.arange(3, dtype=np.float32).reshape(1, 3)
    t = Tensor.from_ndarray(a)
    np.testing.assert_allclose(t.expand(4, 3).to_ndarray(),
                               np.broadcast_to(a, (4, 3)))
    np.testing.assert_allclose(t.repeat_tensor(2, 2).to_ndarray(),
                               np.tile(a, (2, 2)))
    b = np.arange(10, dtype=np.float32)
    parts = Tensor.from_ndarray(b).split(4, 1)
    assert [p.n_element() for p in parts] == [4, 4, 2]
    chunks = Tensor.from_ndarray(b).chunk(3, 1)
    assert [c.n_element() for c in chunks] == [4, 4, 2]
    np.testing.assert_allclose(
        Tensor.from_ndarray(b).reshape(2, 5).to_ndarray(),
        b.reshape(2, 5))


def test_random_fills_and_camelcase():
    from bigdl_tpu.common import RandomGenerator

    RandomGenerator.RNG.set_seed(9)
    t = Tensor(1000).uniform(2.0, 4.0)
    arr = t.to_ndarray()
    assert arr.min() >= 2.0 and arr.max() <= 4.0
    assert 2.8 < arr.mean() < 3.2
    n = Tensor(1000).normal(1.0, 0.5).to_ndarray()
    assert 0.9 < n.mean() < 1.1
    bern = Tensor(1000).bernoulli(0.3).to_ndarray()
    assert 0.2 < bern.mean() < 0.4
    # camelCase aliases exist
    for nm in ("maskedFill", "maskedSelect", "indexSelect", "indexFill",
               "repeatTensor"):
        assert hasattr(Tensor(1), nm)
