"""Tensor façade specs (VERDICT r2 #8; reference DenseTensorSpec
patterns — 1-based narrow/select/transpose, mutation-style ops,
max/min returning 1-based indices)."""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.tensor import Tensor, rand, randn


class TestConstruction:
    def test_sized(self):
        t = Tensor(2, 3)
        assert t.size() == (2, 3)
        assert t.dim() == 2
        assert t.n_element() == 6
        np.testing.assert_allclose(t.to_ndarray(), 0.0)

    def test_wrap_ndarray(self):
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        t = Tensor.from_ndarray(a)
        np.testing.assert_allclose(t.to_ndarray(), a)

    def test_wrap_list(self):
        t = Tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.size() == (2, 2)
        assert t.dtype == jnp.float32

    def test_size_1_based_dim(self):
        t = Tensor(4, 5, 6)
        assert t.size(1) == 4 and t.size(2) == 5 and t.size(3) == 6

    def test_seeded_randn(self):
        from bigdl_tpu.common import RandomGenerator

        RandomGenerator.RNG.set_seed(42)
        a = randn(3, 3).to_ndarray()
        RandomGenerator.RNG.set_seed(42)
        b = randn(3, 3).to_ndarray()
        np.testing.assert_allclose(a, b)


class TestSlicing:
    def setup_method(self, _):
        self.t = Tensor.from_ndarray(
            np.arange(24, dtype=np.float32).reshape(4, 6))

    def test_narrow(self):
        n = self.t.narrow(1, 2, 2)  # rows 2..3 (1-based)
        np.testing.assert_allclose(
            n.to_ndarray(), np.arange(24).reshape(4, 6)[1:3])

    def test_select(self):
        s = self.t.select(1, 3)  # third row
        np.testing.assert_allclose(
            s.to_ndarray(), np.arange(24).reshape(4, 6)[2])

    def test_index_select(self):
        s = self.t.index_select(2, [1, 6])
        np.testing.assert_allclose(
            s.to_ndarray(), np.arange(24).reshape(4, 6)[:, [0, 5]])

    def test_transpose_1_based(self):
        tt = self.t.transpose(1, 2)
        assert tt.size() == (6, 4)
        np.testing.assert_allclose(
            tt.to_ndarray(), np.arange(24).reshape(4, 6).T)

    def test_view_and_squeeze(self):
        v = self.t.view(2, 12)
        assert v.size() == (2, 12)
        u = Tensor(1, 4).squeeze()
        assert u.size() == (4,)
        w = Tensor(4).unsqueeze(1)
        assert w.size() == (1, 4)


class TestMutation:
    def test_fill_zero(self):
        t = Tensor(2, 2).fill(7.0)
        np.testing.assert_allclose(t.to_ndarray(), 7.0)
        t.zero()
        np.testing.assert_allclose(t.to_ndarray(), 0.0)

    def test_copy(self):
        t = Tensor(2, 3)
        src = Tensor.from_ndarray(np.ones((2, 3), np.float32) * 5)
        t.copy(src)
        np.testing.assert_allclose(t.to_ndarray(), 5.0)

    def test_set_aliases(self):
        a = Tensor(2, 2).fill(1.0)
        b = Tensor(0)
        b.set(a)
        assert b.size() == (2, 2)

    def test_resize(self):
        t = Tensor.from_ndarray(np.arange(6, dtype=np.float32))
        t.resize(2, 3)  # same element count: reshape keeps content
        np.testing.assert_allclose(
            t.to_ndarray(), np.arange(6).reshape(2, 3))
        t.resize(4, 4)  # grows: reallocates zeros
        np.testing.assert_allclose(t.to_ndarray(), 0.0)

    def test_set_value_value_at_1_based(self):
        t = Tensor(3, 3)
        t.set_value(2, 3, 9.5)
        assert t.value_at(2, 3) == pytest.approx(9.5)
        assert t.to_ndarray()[1, 2] == pytest.approx(9.5)


class TestMath:
    def test_inplace_chain(self):
        t = Tensor.from_ndarray(np.full((2, 2), 4.0, np.float32))
        t.add(1.0).mul(2.0).sqrt()
        np.testing.assert_allclose(t.to_ndarray(), np.sqrt(10.0))

    def test_addmm(self):
        a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        b = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        t = Tensor(2, 4).fill(1.0).add_mm(Tensor(a), Tensor(b))
        np.testing.assert_allclose(t.to_ndarray(), 1.0 + a @ b, rtol=1e-5)

    def test_max_with_dim_returns_1_based(self):
        t = Tensor.from_ndarray(
            np.asarray([[1.0, 5.0, 2.0], [7.0, 0.0, 3.0]], np.float32))
        vals, idx = t.max(2)
        np.testing.assert_allclose(vals.to_ndarray(), [[5.0], [7.0]])
        np.testing.assert_allclose(idx.to_ndarray(), [[2], [1]])

    def test_operators(self):
        a = Tensor([1.0, 2.0])
        b = Tensor([3.0, 4.0])
        np.testing.assert_allclose((a + b).to_ndarray(), [4.0, 6.0])
        np.testing.assert_allclose((b - a).to_ndarray(), [2.0, 2.0])
        np.testing.assert_allclose((a * 2).to_ndarray(), [2.0, 4.0])
        np.testing.assert_allclose((-a).to_ndarray(), [-1.0, -2.0])
        assert a.dot(b) == pytest.approx(11.0)

    def test_reductions(self):
        t = Tensor.from_ndarray(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert t.sum() == pytest.approx(15.0)
        assert t.mean() == pytest.approx(2.5)
        np.testing.assert_allclose(t.sum(1).to_ndarray(), [[3.0, 5.0, 7.0]])
        assert t.norm(2) == pytest.approx(np.sqrt(55.0), rel=1e-5)

    def test_apply1_and_map(self):
        t = Tensor([1.0, 2.0, 3.0]).apply1(lambda v: v * v)
        np.testing.assert_allclose(t.to_ndarray(), [1.0, 4.0, 9.0])
        u = Tensor([1.0, 2.0, 3.0])
        u.map(Tensor([10.0, 20.0, 30.0]), lambda a, b: a + b)
        np.testing.assert_allclose(u.to_ndarray(), [11.0, 22.0, 33.0])


class TestInterop:
    def test_feeds_layers_directly(self):
        """A Tensor passes into the module stack via __jax_array__."""
        from bigdl_tpu.nn import Linear

        m = Linear(3, 2)
        x = Tensor.from_ndarray(np.ones((4, 3), np.float32))
        out = m.forward(jnp.asarray(x))
        assert out.shape == (4, 2)

    def test_set_weights_accepts_tensors(self):
        from bigdl_tpu.nn import Linear

        m = Linear(2, 2)
        w = Tensor.from_ndarray(np.eye(2, dtype=np.float32))
        b = Tensor(2).fill(0.5)
        m.set_weights([w, b])
        out = m.forward(jnp.ones((1, 2)))
        np.testing.assert_allclose(np.asarray(out), [[1.5, 1.5]])

    def test_jtensor_roundtrip(self):
        from bigdl.util.common import JTensor

        a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        jt = JTensor.from_ndarray(Tensor.from_ndarray(a))
        np.testing.assert_allclose(jt.to_ndarray(), a)
