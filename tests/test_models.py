"""Model-zoo specs (reference: «test»/models/*Spec.scala — shape checks
on small inputs + convergence smokes)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.models import (
    build_alexnet, build_autoencoder, build_inception_v1, build_lenet5,
    build_ptb_lm, build_resnet_cifar, build_resnet_imagenet, build_vgg16,
    build_vgg_cifar, imagenet_recipe_optim,
)


def _count_params(model):
    return sum(int(np.prod(w.shape)) for w in model.get_weights())


def test_lenet_shape():
    m = build_lenet5()
    out = m.forward(jnp.ones((2, 28, 28)))
    assert out.shape == (2, 10)


def test_resnet_cifar_shape_and_params():
    m = build_resnet_cifar(depth=20)
    m.evaluate()
    out = m.forward(jnp.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)
    n = _count_params(m)
    # ResNet-20 CIFAR is ~0.27M params
    assert 0.25e6 < n < 0.3e6, n


def test_resnet50_imagenet_param_count():
    m = build_resnet_imagenet(depth=50)
    n = _count_params(m)
    # canonical ResNet-50: 25.56M
    assert 25.0e6 < n < 26.2e6, n


def test_resnet50_forward_tiny():
    m = build_resnet_imagenet(depth=50, class_num=10)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 64, 64)))  # global pool handles size
    assert out.shape == (1, 10)


def test_resnet18_basic_blocks():
    m = build_resnet_imagenet(depth=18, class_num=10)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 64, 64)))
    assert out.shape == (1, 10)


def test_vgg16_param_count():
    m = build_vgg16()
    n = _count_params(m)
    # canonical VGG-16: 138.36M
    assert 138e6 < n < 139e6, n


def test_vgg_cifar_shape():
    m = build_vgg_cifar()
    m.evaluate()
    out = m.forward(jnp.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_alexnet_shape():
    m = build_alexnet(class_num=100)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 227, 227)))
    assert out.shape == (1, 100)


def test_inception_v1_shape_and_params():
    m = build_inception_v1(class_num=1000)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    assert out.shape == (1, 1000)
    n = _count_params(m)
    # GoogLeNet main tower ~ 6-7M params
    assert 5e6 < n < 8e6, n


def test_inception_v2_shape_and_params():
    from bigdl_tpu.models import build_inception_v2

    m = build_inception_v2(class_num=1000)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    assert out.shape == (1, 1000)
    n = _count_params(m)
    # BN-Inception ~ 11M params
    assert 10e6 < n < 13e6, n


def test_inception_v2_train_step_decreases_loss():
    from bigdl_tpu.models.inception import inception_layer_v2
    from bigdl_tpu.nn import (
        ClassNLLCriterion, Linear, LogSoftMax, Reshape, Sequential,
        SpatialAveragePooling,
    )
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    # a tiny v2 tower (one regular + one grid-reduction module) so the
    # double-3x3/stride-2/pool-pass-through paths all run fwd+bwd
    model = (
        Sequential()
        .add(inception_layer_v2(3, ([8], [8, 8], [8, 8], ("avg", 8)), "a/"))
        .add(inception_layer_v2(32, ([0], [8, 8], [8, 8], ("max", 0)), "b/"))
        .add(SpatialAveragePooling(8, 8, 1, 1))
        .add(Reshape([48]))
        .add(Linear(48, 4))
        .add(LogSoftMax())
    )
    rs = np.random.RandomState(0)
    x = rs.rand(32, 3, 16, 16).astype(np.float32)
    y = (rs.randint(0, 4, 32) + 1).astype(np.float32)
    opt = LocalOptimizer(model, (x, y), ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(SGD(learningrate=0.1))
    opt.set_end_when(Trigger.max_epoch(4))
    opt.optimize()
    assert opt.state["loss"] < np.log(4)  # below chance-level NLL


def test_autoencoder_trains():
    from bigdl_tpu.models.autoencoder import train_autoencoder

    model, opt = train_autoencoder(max_epoch=2, batch_size=64)
    assert opt.state["loss"] < 0.1


def test_ptb_lm_shape_and_perplexity_drops():
    from bigdl_tpu.models.rnn import train_ptb

    model, opt, ppl = train_ptb(vocab_size=50, batch_size=16, num_steps=10,
                                max_epoch=2, hidden_size=64,
                                learning_rate=1.0)
    # random baseline perplexity = vocab_size (50); Markov structure is
    # learnable well below that
    assert ppl < 40, f"perplexity {ppl}"


def test_imagenet_recipe_schedule():
    opt = imagenet_recipe_optim(batch_size=256, iterations_per_epoch=10,
                                n_epochs=90, warmup_epochs=5)
    state = opt.init_state(jnp.zeros(4))
    # during warmup lr climbs from 0.1 toward base (0.1 * 256/256 = 0.1,
    # so flat here); after epoch 30 boundary it decays 10x
    state["neval"] = jnp.asarray(31.0 * 10)
    lr_after_30 = float(opt.current_rate(state))
    state["neval"] = jnp.asarray(61.0 * 10)
    lr_after_60 = float(opt.current_rate(state))
    assert abs(lr_after_30 - 0.01) < 1e-6
    assert abs(lr_after_60 - 0.001) < 1e-6


def test_module_level_evaluate_and_predict():
    """Reference parity: model.evaluate(data, methods) and
    model.predict/predictClass as MODULE methods (SURVEY §3.6)."""
    import numpy as np
    from bigdl_tpu.nn import Linear, LogSoftMax, Sequential
    from bigdl_tpu.optim import Top1Accuracy

    rs = np.random.RandomState(0)
    x = rs.randn(40, 6).astype(np.float32)
    y = (rs.randint(0, 3, 40) + 1).astype(np.float32)
    m = Sequential().add(Linear(6, 3)).add(LogSoftMax())

    # no-arg evaluate keeps the mode-switch contract
    assert m.evaluate() is m
    assert not m.is_training

    (acc,) = m.evaluate((x, y), [Top1Accuracy()])
    value, count = acc.result()
    assert count == 40
    preds = m.predict(x, batch_size=16)
    assert preds.shape == (40, 3)
    classes = m.predict_class(x)
    assert classes.min() >= 1 and classes.max() <= 3
    # predictions and the accuracy agree
    assert value == np.mean(classes == y)


def test_ncf_forward_and_learns():
    from bigdl_tpu.models import build_ncf
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Adam, Trigger
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from examples.recommendation.ncf_train import (
        synthetic_interactions, training_pairs,
    )

    pos = synthetic_interactions(50, 80, per_user=10)
    x, y = training_pairs(pos, 80, neg_per_pos=2)
    m = build_ncf(50, 80, class_num=2)
    out = m.forward(jnp.asarray(x[:8]))
    assert out.shape == (8, 2)
    opt = LocalOptimizer(m, (x, y), ClassNLLCriterion(), batch_size=128)
    opt.set_optim_method(Adam(learningrate=1e-2))
    opt.set_end_when(Trigger.max_epoch(3))
    opt.optimize()
    assert opt.state["loss"] < 0.63  # below the all-negative prior NLL


def test_remat_container_matches_plain():
    """Remat(module) must be numerically IDENTICAL (fwd + grads) to the
    plain module — only the memory/recompute schedule differs."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.nn import Linear, ReLU, Remat, Sequential

    RandomGenerator.RNG.set_seed(3)
    inner = Sequential().add(Linear(8, 16)).add(ReLU()).add(Linear(16, 8))
    wrapped = Remat(inner)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)

    p_plain = inner.params()
    p_wrap = wrapped.params()

    def loss_plain(p, x):
        out, _ = inner.apply(p, inner.state(), x)
        return jnp.sum(out ** 2)

    def loss_wrap(p, x):
        out, _ = wrapped.apply(p, wrapped.state(), x)
        return jnp.sum(out ** 2)

    l1, g1 = jax.value_and_grad(loss_plain)(p_plain, x)
    l2, g2 = jax.value_and_grad(loss_wrap)(p_wrap, x)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(g1["0"]["weight"]), np.asarray(g2["0"]["0"]["weight"]),
        rtol=1e-6)


def test_transformer_remat_matches_plain():
    """remat=True changes the backward schedule, not the math: same
    loss and same gradients as the stored-activation path."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (2, 16)).astype(np.float32))
    tgt = rs.randint(0, 64, (2, 16))

    grads = {}
    losses = {}
    rng = jax.random.key(17)
    for remat in (False, True):
        RandomGenerator.RNG.set_seed(9)
        # training=True with dropout exercises the riskiest remat
        # interaction: a traced PRNG key closed over jax.checkpoint —
        # identical fold_in keys on both paths give identical masks
        model = build_transformer_lm(64, dim=32, n_head=2, n_layer=2,
                                     max_len=16, dropout=0.1, remat=remat)
        params = model.params()

        def loss_fn(p):
            logits, _ = model.apply(p, model.state(), ids,
                                    training=True, rng=rng)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(
                logp, jnp.asarray(tgt)[:, :, None], 2))

        l, g = jax.value_and_grad(loss_fn)(params)
        losses[remat] = float(l)
        grads[remat] = g
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)
    flat_a = jax.tree_util.tree_leaves_with_path(grads[False])
    flat_b = jax.tree_util.tree_leaves_with_path(grads[True])
    key = lambda kv: jax.tree_util.keystr(kv[0])
    for (ka, a), (kb, b) in zip(sorted(flat_a, key=key),
                                sorted(flat_b, key=key)):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_transformer_generate_matches_incremental_forward():
    """The KV-cache scan decode must produce exactly the tokens a naive
    loop (full forward over the growing prefix, argmax of the last
    logits) produces."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm

    RandomGenerator.RNG.set_seed(13)
    model = build_transformer_lm(48, dim=32, n_head=4, n_layer=2,
                                 max_len=24, attn_impl="xla")
    params = model.params()
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 48, (2, 5))

    got = np.asarray(model.generate(params, prompt, 8))
    assert got.shape == (2, 13)
    np.testing.assert_array_equal(got[:, :5], prompt)

    # prefill IS the training forward: block.prefill output must equal
    # apply() on the prompt exactly (same projection + attention path)
    x = jnp.take(params["wte"]["weight"],
                 jnp.asarray(prompt, jnp.int32), axis=0)
    x = x + params["wpe"]["weight"][:5][None]
    xa = x
    for i in range(model.n_layer):
        blk = model._children[f"h{i}"]
        x, _, _ = blk.prefill(params[f"h{i}"], x)
        xa, _ = blk.apply(params[f"h{i}"], {}, xa)
    np.testing.assert_allclose(np.asarray(x), np.asarray(xa),
                               rtol=1e-6, atol=1e-6)

    # naive reference: grow the sequence one full forward at a time
    seq = prompt.copy()
    for _ in range(8):
        logits, _ = model.apply(
            params, model.state(), jnp.asarray(seq.astype(np.float32)))
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1))
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq)


def test_transformer_generate_sampling_reproducible():
    import jax

    from bigdl_tpu.common import RandomGenerator
    from bigdl_tpu.models.transformer import build_transformer_lm

    RandomGenerator.RNG.set_seed(13)
    model = build_transformer_lm(32, dim=16, n_head=2, n_layer=1,
                                 max_len=16)
    params = model.params()
    prompt = np.random.RandomState(1).randint(0, 32, (1, 3))
    a = np.asarray(model.generate(params, prompt, 6, temperature=0.8,
                                  rng=jax.random.key(5)))
    b = np.asarray(model.generate(params, prompt, 6, temperature=0.8,
                                  rng=jax.random.key(5)))
    np.testing.assert_array_equal(a, b)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="rng"):
        model.generate(params, prompt, 2, temperature=0.5)
    with _pytest.raises(ValueError, match="max_len"):
        model.generate(params, prompt, 100)
