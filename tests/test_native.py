"""Native runtime tests — fp16 codec, gather/normalize, image ops,
prefetcher; each native path is diffed against its numpy reference
(reference analogue: BigDL-core is tested through the JVM wrappers)."""

import numpy as np
import pytest

from bigdl_tpu import native


def test_native_library_builds_and_loads():
    # the image ships g++, so the native path must actually be live here
    assert native.available()


def test_fp16_roundtrip_matches_numpy_half():
    rs = np.random.RandomState(0)
    x = np.concatenate([
        rs.randn(1000).astype(np.float32) * 10,
        np.asarray([0.0, -0.0, 1e-8, -1e-8, 65504.0, -65504.0, 1e9, -1e9,
                    np.inf, -np.inf], np.float32),
    ])
    comp = native.fp16_compress(x)
    assert comp.dtype == np.uint16
    with np.errstate(over="ignore"):
        half = x.astype(np.float16)
    # bit-exact against IEEE round-to-nearest-even (numpy half)
    np.testing.assert_array_equal(comp, half.view(np.uint16))
    dec = native.fp16_decompress(comp)
    np.testing.assert_array_equal(dec, half.astype(np.float32))


def test_fp16_nan():
    comp = native.fp16_compress(np.asarray([np.nan], np.float32))
    assert np.isnan(native.fp16_decompress(comp)[0])


def test_gather_rows():
    rs = np.random.RandomState(1)
    src = rs.randn(50, 3, 4).astype(np.float32)
    idx = rs.permutation(50)[:20]
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_normalize_u8():
    rs = np.random.RandomState(2)
    src = rs.randint(0, 256, (30, 3, 8, 8), dtype=np.uint8)
    idx = rs.permutation(30)[:10]
    mean = np.asarray([125.0, 122.0, 114.0], np.float32)
    std = np.asarray([63.0, 62.0, 66.0], np.float32)
    out = native.gather_normalize_u8(src, idx, mean, std)
    expect = (src[idx].astype(np.float32)
              - mean[None, :, None, None]) / std[None, :, None, None]
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_resize_bilinear_identity_and_scale():
    rs = np.random.RandomState(3)
    img = rs.rand(3, 8, 8).astype(np.float32)
    same = native.resize_bilinear(img, 8, 8)
    np.testing.assert_allclose(same, img, atol=1e-6)
    up = native.resize_bilinear(img, 16, 16)
    assert up.shape == (3, 16, 16)
    # bilinear preserves the mean approximately
    assert abs(up.mean() - img.mean()) < 0.02


def test_crop_and_hflip():
    rs = np.random.RandomState(4)
    img = rs.rand(2, 10, 12).astype(np.float32)
    c = native.crop(img, 2, 3, 5, 6)
    np.testing.assert_array_equal(c, img[:, 2:7, 3:9])
    f = native.hflip(img)
    np.testing.assert_array_equal(f, img[:, :, ::-1])


def test_normalize():
    rs = np.random.RandomState(5)
    img = rs.rand(3, 6, 6).astype(np.float32)
    out = native.normalize(img, [0.5, 0.4, 0.3], [0.2, 0.2, 0.25])
    expect = (img - np.asarray([0.5, 0.4, 0.3], np.float32)[:, None, None]) \
        / np.asarray([0.2, 0.2, 0.25], np.float32)[:, None, None]
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_prefetch_iterator_order_and_errors():
    items = list(range(20))
    out = list(native.PrefetchIterator(iter(items)))
    assert out == items

    def boom():
        yield 1
        raise ValueError("producer failed")

    it = native.PrefetchIterator(boom())
    got = []
    with pytest.raises(ValueError):
        for x in it:
            got.append(x)
    assert got == [1]


def test_prefetch_iterator_early_break_releases_producer():
    import threading
    import time

    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = native.PrefetchIterator(gen(), depth=2)
    for x in it:
        if x == 3:
            break
    # producer must wind down instead of blocking forever on the queue
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before
    assert len(produced) < 100
