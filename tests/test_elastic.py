"""Elastic-training specs — preemption-safe shutdown, heartbeat
peer-liveness, world-resize checkpoint resume, and the restart
supervisor (resilience/elastic.py + resilience/supervisor.py).

ISSUE acceptance: train 2-host to step k, checkpoint, resume 1-host
(and 1→2) with a loss trajectory matching the uninterrupted run;
SIGTERM mid-run produces an intact emergency checkpoint and the
distinct "preempted" exit code; a silenced peer raises PeerLostError
within the timeout instead of deadlocking the next collective.  All
multi-"host" worlds are mesh-sized over the 8 virtual CPU devices —
the same real shard_map data plane, deterministic on CPU.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import jax
import numpy as np
import pytest

from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import ArrayDataSet
from bigdl_tpu.nn import (
    ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
)
from bigdl_tpu.optim import DistriOptimizer, LocalOptimizer, SGD, Trigger
from bigdl_tpu.resilience import (
    EXIT_FATAL,
    EXIT_PREEMPTED,
    EXIT_TRANSIENT,
    HeartbeatMonitor,
    PeerLostError,
    Preempted,
    classify,
    elastic,
)
from bigdl_tpu.resilience.supervisor import Supervisor
from bigdl_tpu.utils.serializer import (
    read_checkpoint_topology,
    verify_checkpoint,
)

pytestmark = pytest.mark.chaos  # deterministic chaos — runs in tier-1


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("BIGDL_FAULT_PLAN", raising=False)
    monkeypatch.delenv("BIGDL_HEARTBEAT_DIR", raising=False)
    elastic.clear_preemption()
    yield
    elastic.clear_preemption()


@pytest.fixture
def _engine():
    Engine.reset()
    Engine.init()
    yield
    Engine.reset()


def _model(seed=7):
    from bigdl_tpu.common import RandomGenerator

    RandomGenerator.RNG.set_seed(seed)
    return Sequential().add(Linear(16, 32)).add(ReLU()) \
        .add(Linear(32, 4)).add(LogSoftMax())


def _toy(n=128, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, k)
    x = rng.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    return x, y


class _Tape:
    """Summary stub recording per-step loss; optionally requests a
    preemption when a given step's loss resolves (the flag is then
    handled at the next iteration boundary — the in-flight step always
    finishes, exactly like a real SIGTERM)."""

    def __init__(self, preempt_at=None):
        self.loss = {}
        self.preempt_at = preempt_at

    def add_scalar(self, tag, value, step):
        if tag == "Loss":
            self.loss[step] = float(value)
            if self.preempt_at is not None and step == self.preempt_at:
                elastic.request_preemption()

    def add_histogram(self, *a, **k):
        pass

    def get_summary_trigger(self, name):
        return None

    def add_resilience(self, step, **counters):
        pass


def _mesh(n):
    return Engine.build_mesh({"data": n}, devices=jax.devices()[:n])


def _distri(world, ckpt_dir=None, epochs=4, tape=None, **kw):
    x, y = _toy(128)
    ds = ArrayDataSet(x, y, 32, shuffle=False)
    kw.setdefault("wire_dtype", "none")
    opt = DistriOptimizer(_model(), ds, ClassNLLCriterion(),
                          batch_size=32, mesh=_mesh(world), **kw)
    # momentum => a param-sized velocity vector in the ZeRO state, so
    # resize-resume actually re-partitions state (plain SGD would make
    # the resize trivially stateless)
    opt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
    opt.set_end_when(Trigger.max_epoch(epochs))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir), Trigger.every_epoch())
    if tape is not None:
        opt.set_train_summary(tape)
    return opt


def _counter_value(name, **labels):
    from bigdl_tpu import obs

    fam = obs.get_registry().snapshot()["metrics"].get(name)
    if not fam:
        return 0.0
    for s in fam["samples"]:
        if s["labels"] == labels:
            return s["value"]
    return 0.0


def _assert_trajectories_match(base, resumed, rtol=1e-4):
    assert resumed, "resumed run recorded no losses"
    for step in sorted(resumed):
        assert step in base, f"resumed step {step} beyond the baseline"
        np.testing.assert_allclose(
            resumed[step], base[step], rtol=rtol,
            err_msg=f"loss diverged at step {step}")


# =========================================================== preemption
class TestPreemption:
    def test_preempt_finishes_step_checkpoints_and_exits_preempted(
            self, _engine, tmp_path):
        """ISSUE acceptance: a preemption request mid-run finishes the
        in-flight step, writes an INTACT topology-tagged emergency
        checkpoint, and surfaces as Preempted (SystemExit with the
        distinct exit code)."""
        tape = _Tape(preempt_at=6)
        opt = _distri(2, tmp_path, tape=tape)
        with pytest.raises(Preempted) as ei:
            opt.optimize()
        exc = ei.value
        assert exc.code == EXIT_PREEMPTED
        assert exc.checkpoint, "no emergency checkpoint recorded"
        ok, reason = verify_checkpoint(exc.checkpoint)
        assert ok, reason
        topo = read_checkpoint_topology(exc.checkpoint)
        assert topo["world_size"] == 2
        assert topo["shard_layout"] == "zero1_flat"
        assert topo["step"] == exc.step
        # the step that resolved the preempting loss still ran; the
        # shutdown happened at a later iteration boundary
        assert exc.step > 6
        assert _counter_value("bigdl_preemptions_total") >= 1

    def test_preempted_is_not_retried(self, _engine, tmp_path):
        """Preempted subclasses SystemExit: the classified retry loop
        (except Exception) must never swallow it and burn checkpoint
        reloads on an eviction."""
        assert classify(Preempted("x")) == "fatal"
        tape = _Tape(preempt_at=2)
        opt = _distri(1, tmp_path, tape=tape)
        opt.max_retry = 5
        with pytest.raises(Preempted):
            opt.optimize()
        # loss keys stop right after the preemption point — no replay
        assert max(tape.loss) <= 4

    def test_real_sigterm_exit_code(self, tmp_path):
        """A real SIGTERM delivered to a real training process: the
        handler Engine.init installed drains the loop, writes the
        emergency checkpoint, and the process exits EXIT_PREEMPTED."""
        script = textwrap.dedent(f"""
            import os, signal, sys
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") \\
                + " --xla_force_host_platform_device_count=2"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            from bigdl_tpu.engine import Engine
            Engine.init()
            from bigdl_tpu.dataset import ArrayDataSet
            from bigdl_tpu.nn import (ClassNLLCriterion, Linear,
                                      LogSoftMax, Sequential)
            from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger
            rng = np.random.RandomState(0)
            x = rng.randn(64, 8).astype(np.float32)
            y = (rng.randint(0, 3, 64) + 1).astype(np.float32)
            model = Sequential().add(Linear(8, 3)).add(LogSoftMax())
            opt = DistriOptimizer(model, ArrayDataSet(x, y, 32,
                                  shuffle=False), ClassNLLCriterion(),
                                  batch_size=32, wire_dtype="none")
            opt.set_optim_method(SGD(learningrate=0.1))
            opt.set_end_when(Trigger.max_epoch(100000))
            opt.set_checkpoint({str(tmp_path / "ck")!r})

            class Kicker:
                def add_scalar(self, tag, value, step):
                    if tag == "Loss" and step == 5:
                        os.kill(os.getpid(), signal.SIGTERM)
                def add_histogram(self, *a, **k): pass
                def get_summary_trigger(self, name): return None
                def add_resilience(self, *a, **k): pass
            opt.set_train_summary(Kicker())
            opt.optimize()
            print("NOT_PREEMPTED", flush=True)
        """)
        p = tmp_path / "worker.py"
        p.write_text(script)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        proc = subprocess.run([sys.executable, str(p)],
                              capture_output=True, text=True, env=env,
                              timeout=300)
        assert proc.returncode == EXIT_PREEMPTED, (
            f"rc={proc.returncode}\n{proc.stdout[-2000:]}"
            f"\n{proc.stderr[-2000:]}")
        assert "NOT_PREEMPTED" not in proc.stdout
        # the emergency checkpoint landed and is intact
        from bigdl_tpu.utils.serializer import (
            checkpoint_prefixes, load_latest_checkpoint,
        )

        ckdir = str(tmp_path / "ck")
        assert checkpoint_prefixes(ckdir)
        model = Sequential().add(Linear(8, 3)).add(LogSoftMax())
        extra = load_latest_checkpoint(ckdir, model, SGD())
        assert extra["neval"] > 1
        assert extra["topology"]["shard_layout"] == "zero1_flat"

    def test_sigint_outside_training_keeps_keyboard_interrupt(self):
        """SIGINT with no active training loop must still behave like
        Ctrl-C (KeyboardInterrupt), not a silent preempted exit."""
        elastic.install_preemption_handler()
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGINT)
            # the handler runs at the next bytecode boundary
            for _ in range(100):
                time.sleep(0.01)
        elastic.clear_preemption()


# =============================================================== resize
class TestResizeResume:
    def _preempt_then_resume(self, tmp_path, from_world, to_world, **kw):
        base_tape = _Tape()
        _distri(to_world, tape=base_tape, **kw).optimize()

        tape = _Tape(preempt_at=6)
        with pytest.raises(Preempted):
            _distri(from_world, tmp_path, tape=tape, **kw).optimize()

        resumed = _distri(to_world, tmp_path, tape=None, **kw)
        extra = elastic.restore_latest(resumed)
        assert extra is not None
        assert extra["topology"]["world_size"] == from_world
        tape2 = _Tape()
        resumed.set_train_summary(tape2)
        resumed.optimize()
        return base_tape.loss, tape2.loss

    def test_resume_2_host_checkpoint_on_1_host(self, _engine, tmp_path):
        """ISSUE acceptance: 2-host to step k -> emergency checkpoint
        -> resume 1-host; continued losses match an uninterrupted run
        within tolerance, and the resize is counted."""
        before = _counter_value("bigdl_resumes_total", resize="2to1")
        base, resumed = self._preempt_then_resume(tmp_path, 2, 1)
        _assert_trajectories_match(base, resumed)
        assert _counter_value("bigdl_resumes_total",
                              resize="2to1") == before + 1

    def test_resume_1_host_checkpoint_on_2_hosts(self, _engine, tmp_path):
        before = _counter_value("bigdl_resumes_total", resize="1to2")
        base, resumed = self._preempt_then_resume(tmp_path, 1, 2)
        _assert_trajectories_match(base, resumed)
        assert _counter_value("bigdl_resumes_total",
                              resize="1to2") == before + 1

    def test_resume_4_host_checkpoint_on_2_hosts(self, _engine, tmp_path):
        base, resumed = self._preempt_then_resume(tmp_path, 4, 2)
        _assert_trajectories_match(base, resumed)

    def test_resize_strips_and_rebuilds_padding(self, _engine, tmp_path):
        """int8 wire pads the flat vector to whole quantization blocks
        (quantum = n_shards * block), so a 2-shard int8 checkpoint's
        optimizer state is LONGER than the 1-shard layout — the resume
        must strip the old padding, not just re-slice.  (No trajectory
        comparison here: the int8 wire quantizes gradients by design;
        value-level repartition correctness is the unit test below.)"""
        from bigdl_tpu.utils.serializer import checkpoint_prefixes

        tape = _Tape(preempt_at=6)
        with pytest.raises(Preempted):
            _distri(2, tmp_path, tape=tape, wire_dtype="int8",
                    int8_block=64).optimize()
        newest = checkpoint_prefixes(str(tmp_path))[-1]
        topo = read_checkpoint_topology(
            os.path.join(str(tmp_path), newest))
        assert topo["pad"] > 0  # the checkpoint really is padded
        padded_saved = topo["flat_elems"] + topo["pad"]
        resumed = _distri(1, tmp_path)
        assert elastic.restore_latest(resumed) is not None
        assert resumed.optim_method.state["velocity"].shape[0] == \
            padded_saved  # loaded as written (re-partition is lazy)
        tape2 = _Tape()
        resumed.set_train_summary(tape2)
        resumed.optimize()
        # the step build re-partitioned to the 1-shard layout (quantum
        # 1 => zero padding) and training continued with finite losses
        assert resumed.optim_method.state["velocity"].shape[0] == \
            topo["flat_elems"]
        assert tape2.loss and all(np.isfinite(v)
                                  for v in tape2.loss.values())

    def test_ensure_shard_layout_unit(self, _engine):
        """Value-level re-partition check: true entries survive, the
        new padding is zeros, replicated scalars pass through."""
        import jax.numpy as jnp

        flat = 10
        old = {"velocity": jnp.arange(12, dtype=jnp.float32),  # pad 2
               "neval": jnp.asarray(3.0)}
        mesh = _mesh(2)
        new = elastic.ensure_shard_layout(
            old, flat_elems=flat, pad=4, n_shards=2, mesh=mesh,
            axis="data", topology={"world_size": 3})
        v = np.asarray(new["velocity"])
        assert v.shape == (14,)
        np.testing.assert_array_equal(v[:flat], np.arange(10))
        np.testing.assert_array_equal(v[flat:], np.zeros(4))
        assert float(new["neval"]) == 3.0
        # matching layout passes through by identity
        again = elastic.ensure_shard_layout(
            new, flat_elems=flat, pad=4, n_shards=2, mesh=mesh,
            axis="data")
        assert again is new or again == new

    def test_local_tree_state_still_guarded(self, _engine):
        """A LocalOptimizer (tree-layout) state handed to the ZeRO data
        plane keeps its informative error — resize handling must not
        swallow the layout guard."""
        x, y = _toy(64)
        lopt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                              batch_size=32)
        lopt.set_optim_method(SGD(learningrate=0.5, momentum=0.9))
        lopt.set_end_when(Trigger.max_iteration(2))
        lopt.optimize()
        dopt = _distri(2)
        dopt.set_optim_method(lopt.optim_method)
        with pytest.raises(ValueError, match="LocalOptimizer"):
            dopt.optimize()


# ================================================= wire EF elasticity
class TestWireEFElasticity:
    """ISSUE 9 satellite: the error-feedback residual
    (parallel/wire.py) rides checkpoints next to the flat ZeRO-1
    vectors, survives a crash without double-applying, and is re-laid
    -out (reset) by ensure_shard_layout on world resize."""

    EF_KW = dict(wire_dtype="int8", int8_block=64, wire_ef=True)

    def test_same_world_crash_resume_matches_uninterrupted(
            self, _engine, tmp_path):
        """Crash mid-run, resume at the SAME world: the residual
        restores exactly as checkpointed (applied once, not twice, not
        dropped), so the deterministic quantized arithmetic reproduces
        the uninterrupted trajectory."""
        import numpy as _np

        base_tape = _Tape()
        _distri(2, epochs=3, tape=base_tape, **self.EF_KW).optimize()

        tape = _Tape(preempt_at=6)
        with pytest.raises(Preempted):
            _distri(2, tmp_path, epochs=3, tape=tape,
                    **self.EF_KW).optimize()

        # the emergency checkpoint carries the residual
        from bigdl_tpu.utils.serializer import checkpoint_prefixes

        newest = checkpoint_prefixes(str(tmp_path))[-1]
        ckpt = np.load(os.path.join(str(tmp_path),
                                    newest + ".optim.npz"))
        assert "wire_ef" in ckpt.files
        saved_ef = np.asarray(ckpt["wire_ef"])
        assert saved_ef.ndim == 2 and saved_ef.shape[0] == 2
        assert _np.abs(saved_ef).sum() > 0  # live residual, not zeros

        resumed = _distri(2, tmp_path, epochs=3, **self.EF_KW)
        assert elastic.restore_latest(resumed) is not None
        # restored exactly as written — the crash did not double-apply
        np.testing.assert_array_equal(
            np.asarray(resumed.optim_method.state["wire_ef"]), saved_ef)
        tape2 = _Tape()
        resumed.set_train_summary(tape2)
        resumed.optimize()
        _assert_trajectories_match(base_tape.loss, tape2.loss)

    def test_resize_2to1_resets_ef_and_matches_uninterrupted(
            self, _engine, tmp_path):
        """ISSUE satellite: 2→1 resize resume with the int8-EF wire
        reproduces the uninterrupted 1-host trajectory.  An N-world
        residual has no positional meaning at M devices, so the resize
        resets it to zeros (one step of un-fed-back quantization error
        — bounded, and at world 1 the exchange is exact anyway)."""
        base_tape = _Tape()
        _distri(1, epochs=3, tape=base_tape, **self.EF_KW).optimize()

        tape = _Tape(preempt_at=6)
        with pytest.raises(Preempted):
            _distri(2, tmp_path, epochs=3, tape=tape,
                    **self.EF_KW).optimize()

        resumed = _distri(1, tmp_path, epochs=3, **self.EF_KW)
        assert elastic.restore_latest(resumed) is not None
        tape2 = _Tape()
        resumed.set_train_summary(tape2)
        resumed.optimize()
        # pre-crash steps ran 2-world quantized vs the baseline's
        # 1-world exact exchange: the trajectories agree within the
        # accumulated quantization tolerance, not bit-for-bit
        _assert_trajectories_match(base_tape.loss, tape2.loss,
                                   rtol=5e-2)
        ef = resumed.optim_method.state["wire_ef"]
        padded = resumed._flat_elems + resumed._pad
        assert tuple(ef.shape) == (1, padded)

    def test_ensure_shard_layout_resets_stale_ef(self, _engine):
        """Unit: a wrong-world residual is reset to zeros in the new
        layout; a matching one passes through untouched; the 1-D flat
        vectors keep their existing re-partition semantics."""
        import jax.numpy as jnp

        mesh = _mesh(2)
        flat, pad = 10, 4
        padded = flat + pad
        old = {"velocity": jnp.arange(12, dtype=jnp.float32),
               "wire_ef": jnp.ones((3, 12), jnp.float32),
               "neval": jnp.asarray(3.0)}
        new = elastic.ensure_shard_layout(
            old, flat_elems=flat, pad=pad, n_shards=2, mesh=mesh,
            axis="data", topology={"world_size": 3})
        assert tuple(new["wire_ef"].shape) == (2, padded)
        np.testing.assert_array_equal(np.asarray(new["wire_ef"]), 0.0)
        np.testing.assert_array_equal(
            np.asarray(new["velocity"])[:flat], np.arange(10))
        # matching layout: identity pass-through keeps the residual
        keep = {"velocity": new["velocity"],
                "wire_ef": jnp.full((2, padded), 0.5),
                "neval": jnp.asarray(3.0)}
        again = elastic.ensure_shard_layout(
            keep, flat_elems=flat, pad=pad, n_shards=2, mesh=mesh,
            axis="data")
        np.testing.assert_array_equal(np.asarray(again["wire_ef"]), 0.5)


# ============================================================ heartbeat
class TestHeartbeat:
    def test_peer_lost_classified_fatal(self):
        assert classify(PeerLostError("x")) == "fatal"

    def test_monitor_flags_silent_peer(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), host=0, n_hosts=2,
                               timeout_s=0.2, every_steps=1)
        mon.beat(force=True)
        # peer 1 beats once...
        peer = HeartbeatMonitor(str(tmp_path), host=1, n_hosts=2,
                                timeout_s=0.2)
        peer.beat(force=True)
        mon.check()  # fresh: no raise
        # ...then goes silent past the timeout
        old = time.time() - 10.0
        os.utime(mon.path(1), (old, old))
        with pytest.raises(PeerLostError, match="host 1"):
            mon.check()
        assert _counter_value("bigdl_peer_lost_total") >= 1

    def test_monitor_counts_never_started_peer(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), host=0, n_hosts=2,
                               timeout_s=0.05)
        time.sleep(0.1)
        with pytest.raises(PeerLostError):
            mon.check()

    def test_beat_respects_step_cadence(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), host=0, n_hosts=1,
                               timeout_s=60, every_steps=5)
        mon.beat(1)
        t1 = os.path.getmtime(mon.path(0))
        mon.beat(3)  # within cadence: no touch
        assert os.path.getmtime(mon.path(0)) == t1
        os.utime(mon.path(0), (t1 - 5, t1 - 5))
        mon.beat(6)  # 6 - 1 >= 5: touches
        assert os.path.getmtime(mon.path(0)) > t1 - 5

    def test_silent_peer_raises_from_optimize_not_deadlock(
            self, _engine, tmp_path, monkeypatch):
        """ISSUE acceptance: wired end-to-end — a 2-"host" run whose
        peer never heartbeats raises PeerLostError from optimize()
        within the timeout (classified fatal: NO checkpoint-reload
        retries), instead of hanging in the next collective."""
        monkeypatch.setenv("BIGDL_HEARTBEAT_DIR", str(tmp_path / "hb"))
        monkeypatch.setenv("BIGDL_HEARTBEAT_TIMEOUT", "0.3")
        monkeypatch.setenv("BIGDL_NUM_PROCESSES", "2")
        monkeypatch.setenv("BIGDL_PROCESS_ID", "0")
        tape = _Tape()
        opt = _distri(2, tmp_path / "ck", epochs=100000, tape=tape)
        t0 = time.monotonic()
        with pytest.raises(PeerLostError):
            opt.optimize()
        assert time.monotonic() - t0 < 120  # raised, not deadlocked
        # fatal classification: surfaced on the first attempt
        assert _counter_value("bigdl_retry_attempts_total",
                              classification="fatal",
                              error="PeerLostError") >= 1

    def test_own_heartbeat_is_written_during_training(
            self, _engine, tmp_path, monkeypatch):
        hb = tmp_path / "hb"
        monkeypatch.setenv("BIGDL_HEARTBEAT_DIR", str(hb))
        monkeypatch.setenv("BIGDL_HEARTBEAT_TIMEOUT", "3600")
        monkeypatch.setenv("BIGDL_NUM_PROCESSES", "2")
        monkeypatch.setenv("BIGDL_PROCESS_ID", "1")
        opt = _distri(2, epochs=1)
        opt.optimize()
        assert (hb / "heartbeat.h1").exists()


# =========================================================== supervisor
class _FakeRunner:
    def __init__(self, codes):
        self.codes = list(codes)
        self.envs = []

    def __call__(self, cmd, env):
        self.envs.append({k: env[k] for k in
                          ("BIGDL_ELASTIC_ATTEMPT",
                           "BIGDL_ELASTIC_PREEMPTIONS")})
        return self.codes.pop(0)


class TestSupervisor:
    def _sup(self, codes, **kw):
        runner = _FakeRunner(codes)
        kw.setdefault("sleep", lambda s: None)
        sup = Supervisor(["train"], runner=runner, **kw)
        return sup, runner

    def test_preempted_then_transient_then_done(self):
        sup, runner = self._sup([EXIT_PREEMPTED, EXIT_TRANSIENT, 0])
        assert sup.run() == 0
        assert sup.preemptions == 1
        assert [e["BIGDL_ELASTIC_ATTEMPT"] for e in runner.envs] == \
            ["0", "1", "2"]
        assert [e["BIGDL_ELASTIC_PREEMPTIONS"] for e in runner.envs] == \
            ["0", "1", "1"]

    def test_preemptions_do_not_consume_retry_budget(self):
        codes = [EXIT_PREEMPTED] * 20 + [0]
        sup, _ = self._sup(codes, max_retries=1)
        assert sup.run() == 0
        assert sup.preemptions == 20
        assert sup.policy.attempts == 0

    def test_fatal_exit_stops_immediately(self):
        sup, runner = self._sup([EXIT_FATAL, 0])
        assert sup.run() == EXIT_FATAL
        assert len(runner.envs) == 1

    def test_transient_budget_exhaustion_returns_child_code(self):
        sup, runner = self._sup([7] * 10, max_retries=2)
        assert sup.run() == 7
        assert len(runner.envs) == 3  # initial + 2 retries

    def test_max_preemptions_cap(self):
        sup, _ = self._sup([EXIT_PREEMPTED] * 5, max_preemptions=2)
        assert sup.run() == EXIT_PREEMPTED
        assert sup.preemptions == 3

    def test_run_main_maps_exceptions_to_exit_codes(self):
        def fatal():
            raise ValueError("bad config")

        def transient():
            raise OSError("blip")

        with pytest.raises(SystemExit) as ei:
            elastic.run_main(fatal)
        assert ei.value.code == EXIT_FATAL
        with pytest.raises(SystemExit) as ei:
            elastic.run_main(transient)
        assert ei.value.code == EXIT_TRANSIENT
        assert elastic.run_main(lambda: None) == 0


# =============================================== obs atexit-flush satellite
class TestObsAtexitFlush:
    def test_crashed_process_keeps_telemetry(self, tmp_path):
        """ISSUE satellite: a process that dies WITHOUT reaching any
        clean close (unhandled SystemExit here; the preemption path
        rides the same hook) must still land its metrics snapshot and
        Chrome trace for the post-mortem — the obs atexit hook flushes
        them."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = textwrap.dedent(f"""
            import os, sys
            sys.path.insert(0, {repo!r})
            os.environ["BIGDL_METRICS_DIR"] = {str(tmp_path)!r}
            os.environ["BIGDL_TRACE_DIR"] = {str(tmp_path)!r}
            os.environ["JAX_PLATFORMS"] = "cpu"
            from bigdl_tpu import obs
            obs.get_registry().counter(
                "bigdl_smoke_crash_total", "crash smoke").inc()
            obs.get_tracer().event("smoke.crash")
            raise SystemExit(9)  # no flush, no optimize() finally
        """)
        p = tmp_path / "crasher.py"
        p.write_text(script)
        proc = subprocess.run([sys.executable, str(p)],
                              capture_output=True, text=True,
                              timeout=120)
        assert proc.returncode == 9, proc.stderr[-1500:]
        import glob as _glob

        proms = _glob.glob(str(tmp_path / "metrics.*.prom"))
        assert proms, f"no metrics snapshot: {os.listdir(tmp_path)}"
        blob = "".join(open(f, encoding="utf-8").read() for f in proms)
        assert "bigdl_smoke_crash_total 1" in blob
        traces = _glob.glob(str(tmp_path / "*.trace.json"))
        assert traces, "no Chrome trace written by the atexit flush"
        assert any("smoke.crash" in open(f, encoding="utf-8").read()
                   for f in traces)


# ===================================================== regress satellite
class TestRegressNoBaseline:
    """ISSUE satellite: an empty/missing BIGDL_REGRESS_TRAJECTORY is a
    clean "no baseline" verdict, never an exception."""

    def test_empty_trajectory_list(self):
        from bigdl_tpu.obs import regress

        v = regress.check({"extras": {"step_time_s": 0.1}}, trajectory=[])
        assert v["status"] == "no_baseline"
        assert v["violations"] == []

    def test_none_and_missing_trajectory_dir(self, tmp_path):
        from bigdl_tpu.obs import regress

        for traj in (None, "", str(tmp_path / "nope")):
            v = regress.gate({"extras": {}}, traj)
            assert v["status"] == "no_baseline", traj
        assert regress.load_trajectory(None) == []
        assert regress.load_trajectory("") == []


# ============================================ overlapped-step elasticity
class TestBucketedOverlapElasticity:
    """ISSUE 11: the bucketed exchange leaves the ZeRO-1 vectors (and
    the per-bucket EF residual semantics) in a shard-major layout the
    topology manifest records — same-plan resumes restore bit-for-bit,
    plan/world changes re-permute the vectors and reset the residual."""

    KW = dict(wire_dtype="int8", int8_block=64, wire_ef=True,
              overlap_bucket_mb=0.001)

    def test_bucketed_same_world_crash_resume_matches(
            self, _engine, tmp_path):
        """Same world, same bucket plan: the EF residual and the
        shard-major state restore bit-for-bit, reproducing the
        uninterrupted bucketed trajectory exactly."""
        base_tape = _Tape()
        _distri(2, epochs=3, tape=base_tape, **self.KW).optimize()

        tape = _Tape(preempt_at=6)
        with pytest.raises(Preempted):
            _distri(2, tmp_path, epochs=3, tape=tape,
                    **self.KW).optimize()

        from bigdl_tpu.utils.serializer import checkpoint_prefixes

        newest = checkpoint_prefixes(str(tmp_path))[-1]
        topo = read_checkpoint_topology(os.path.join(str(tmp_path),
                                                     newest))
        assert len(topo.get("buckets") or []) > 1, topo
        ckpt = np.load(os.path.join(str(tmp_path),
                                    newest + ".optim.npz"))
        saved_ef = np.asarray(ckpt["wire_ef"])
        assert np.abs(saved_ef).sum() > 0

        resumed = _distri(2, tmp_path, epochs=3, **self.KW)
        assert elastic.restore_latest(resumed) is not None
        np.testing.assert_array_equal(
            np.asarray(resumed.optim_method.state["wire_ef"]), saved_ef)
        tape2 = _Tape()
        resumed.set_train_summary(tape2)
        resumed.optimize()
        _assert_trajectories_match(base_tape.loss, tape2.loss)

    def test_plan_change_resets_ef_and_repartitions(self, _engine,
                                                    tmp_path):
        """Resuming a bucketed checkpoint monolithic (same world): the
        velocity vector is un-permuted back to flat-parameter order,
        the residual resets per the contract, and training stays
        finite."""
        tape = _Tape(preempt_at=6)
        with pytest.raises(Preempted):
            _distri(2, tmp_path, epochs=3, tape=tape,
                    **self.KW).optimize()
        from bigdl_tpu.parallel import wire as W
        from bigdl_tpu.utils.serializer import checkpoint_prefixes

        newest = checkpoint_prefixes(str(tmp_path))[-1]
        ckpt = np.load(os.path.join(str(tmp_path),
                                    newest + ".optim.npz"))
        saved_vel = np.asarray(ckpt["velocity"])  # shard-major @ plan
        topo = read_checkpoint_topology(os.path.join(str(tmp_path),
                                                     newest))
        coords = W.bucket_param_coords(topo["buckets"], 2)
        kw = dict(self.KW)
        kw.pop("overlap_bucket_mb")
        resumed = _distri(2, tmp_path, epochs=3, **kw)
        assert elastic.restore_latest(resumed) is not None
        # drive the lazy re-partition and inspect the result directly
        flat = resumed._init_params()
        state = resumed._init_opt_state(flat)
        assert resumed._buckets == [(0, resumed._flat_elems
                                     + resumed._pad)]
        # the plan change reset the residual (same shape, new layout)
        np.testing.assert_array_equal(np.asarray(state["wire_ef"]), 0.0)
        # and un-permuted the velocity back to flat-parameter order
        expected = np.empty_like(saved_vel)
        expected[coords] = saved_vel
        np.testing.assert_array_equal(np.asarray(state["velocity"]),
                                      expected)
        tape2 = _Tape()
        resumed.set_train_summary(tape2)
        resumed.optimize()
        assert tape2.loss and all(np.isfinite(v)
                                  for v in tape2.loss.values())

    def test_ensure_shard_layout_bucket_permutation_unit(self, _engine):
        """Value-level: shard-major state written under one plan comes
        back element-exact under another plan/world."""
        import jax.numpy as jnp

        from bigdl_tpu.parallel import wire as W

        flat, pad = 18, 2
        old_buckets = [[0, 8], [8, 8], [16, 4]]
        coords = W.bucket_param_coords(old_buckets, 2)
        v_param = np.arange(20, dtype=np.float32)
        old = {"velocity": jnp.asarray(v_param[coords]),
               "neval": jnp.asarray(3.0)}
        # bucketed @2 -> monolithic @2: un-permute only
        new = elastic.ensure_shard_layout(
            dict(old), flat_elems=flat, pad=pad, n_shards=2,
            mesh=_mesh(2), axis="data",
            topology={"world_size": 2, "buckets": old_buckets},
            buckets=[(0, 20)])
        got = np.asarray(new["velocity"])
        np.testing.assert_array_equal(got[:flat], v_param[:flat])
        np.testing.assert_array_equal(got[flat:], 0.0)
        assert float(new["neval"]) == 3.0
        # bucketed @2 -> a different plan @1: un-permute + re-permute
        nb = [(0, 10), (10, 10)]
        new2 = elastic.ensure_shard_layout(
            dict(old), flat_elems=flat, pad=2, n_shards=1,
            mesh=_mesh(1), axis="data",
            topology={"world_size": 2, "buckets": old_buckets},
            buckets=nb)
        c2 = W.bucket_param_coords(nb, 1)
        exp = np.concatenate([v_param[:flat],
                              np.zeros(2, np.float32)])[c2]
        np.testing.assert_array_equal(np.asarray(new2["velocity"]), exp)
        # same plan, same world: identity pass-through
        again = elastic.ensure_shard_layout(
            {"velocity": old["velocity"]}, flat_elems=flat, pad=pad,
            n_shards=2, mesh=_mesh(2), axis="data",
            topology={"world_size": 2, "buckets": old_buckets},
            buckets=[tuple(b) for b in old_buckets])
        np.testing.assert_array_equal(np.asarray(again["velocity"]),
                                      v_param[coords])
