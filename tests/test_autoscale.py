"""Autoscaling policy-loop specs (resilience/autoscale.py +
supervisor integration) and the hardened alert sink.

The resize-under-load edge cases the ISSUE names are here: a decision
landing while the child is already writing its emergency checkpoint,
cooldown suppressing an immediate reverse decision, dry-run never
restarting, and (in test_stream.py) scale-down below the streaming
buffer's prefetched frontier.
"""

import dataclasses
import json
import os
import sys
import time

import pytest

from bigdl_tpu import obs
from bigdl_tpu.config import AutoscaleConfig
from bigdl_tpu.resilience.autoscale import (
    AutoscaleController,
    Decision,
    EndpointScraper,
    derive_signals,
    load_rules,
)
from bigdl_tpu.resilience.elastic import EXIT_PREEMPTED, EXIT_TRANSIENT
from bigdl_tpu.resilience.supervisor import Supervisor


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    for var in ("BIGDL_OBS", "BIGDL_TRACE_DIR", "BIGDL_METRICS_DIR",
                "BIGDL_AUTOSCALE", "BIGDL_AUTOSCALE_WORLD",
                "BIGDL_OBS_PORT", "BIGDL_OBS_PORT_FILE",
                "BIGDL_RETRY_BACKOFF_BASE"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


def _cfg(**kw):
    base = dict(enabled=True, min_world=1, max_world=8, factor=2,
                interval_s=0.0, warmup_s=0.0, cooldown_s=10.0,
                hysteresis=2)
    base.update(kw)
    return AutoscaleConfig(**base)


def _counter_value(name, **labels):
    for fam in obs.get_registry().families():
        if fam.name == name:
            for key, child in fam.child_items():
                if dict(zip(fam.labelnames, key)) == labels:
                    return child.value
    return None


# ---------------------------------------------------------------- rules
class TestRules:
    def test_default_pack_from_band_knobs(self):
        cfg = _cfg(queue_high=100, queue_low=5, step_time_high=0.5,
                   step_time_low=0.05, goodput_floor=0.3,
                   evict_stragglers=True)
        names = [r["name"] for r in load_rules(None, cfg)]
        assert names == ["straggler_evict", "queue_high", "queue_low",
                         "step_time_high", "step_time_low",
                         "cost_goodput_floor"]

    def test_band_knobs_off_mean_empty_pack(self):
        assert load_rules(None, _cfg()) == []

    def test_inline_json_and_hysteresis_default(self):
        cfg = _cfg(hysteresis=3)
        rules = load_rules(
            '[{"name":"q","signal":"queue_depth","op":">",'
            '"value":7,"action":"up"}]', cfg)
        assert rules[0]["for"] == 3 and rules[0]["value"] == 7.0

    def test_file_pack(self, tmp_path):
        p = tmp_path / "rules.json"
        p.write_text(json.dumps([
            {"name": "g", "signal": "goodput_ratio", "op": "<",
             "value": 0.2, "action": "down", "for": 1}]))
        assert load_rules(str(p), _cfg())[0]["name"] == "g"

    @pytest.mark.parametrize("bad,msg", [
        ('[{"signal":"queue_depth","op":">","value":1,"action":"up"}]',
         "missing"),
        ('[{"name":"x","signal":"queue_depth","op":"~","value":1,'
         '"action":"up"}]', "unknown op"),
        ('[{"name":"x","signal":"queue_depth","op":">","value":1,'
         '"action":"sideways"}]', "action"),
        ('[{"name":"x","signal":"nope","op":">","value":1,'
         '"action":"up"}]', "unknown signal"),
        ('[{"name":"x","signal":"queue_depth","op":">","action":"up"}]',
         "needs a 'value'"),
        ('[{"name":"x","signal":"alerts","op":"nonempty","action":"up"},'
         '{"name":"x","signal":"alerts","op":"nonempty","action":"up"}]',
         "duplicate"),
        ('{"name":"x"}', "JSON list"),
    ])
    def test_validation_is_loud(self, bad, msg):
        with pytest.raises(ValueError, match=msg):
            load_rules(bad, _cfg())


# -------------------------------------------------------------- signals
def _peer(addr="h0:1", step=None, t=None, ratio=None, alerts=(),
          status="ok", samples=()):
    return {"addr": addr, "ok": True,
            "health": {"host": 0, "step": step, "time": t,
                       "goodput_ratio": ratio, "status": status,
                       "alerts": [{"rule": a} for a in alerts]},
            "metrics": {"samples": list(samples)}}


class TestSignals:
    def test_step_time_from_stamp_deltas(self):
        prev = {}
        s1 = derive_signals([_peer(step=10, t=100.0)], prev, 1)
        assert "step_time_s" not in s1  # one observation is no rate
        s2 = derive_signals([_peer(step=20, t=102.0)], prev, 1)
        assert s2["step_time_s"] == pytest.approx(0.2)

    def test_slowest_host_gates(self):
        prev = {}
        derive_signals([_peer("a", step=0, t=0.0),
                        _peer("b", step=0, t=0.0)], prev, 2)
        s = derive_signals([_peer("a", step=10, t=1.0),
                            _peer("b", step=10, t=4.0)], prev, 2)
        assert s["step_time_s"] == pytest.approx(0.4)

    def test_queue_depth_max_over_gauges(self):
        s = derive_signals([_peer(samples=[
            {"name": "bigdl_stream_buffer_depth", "labels": {},
             "value": 12.0},
            {"name": "bigdl_stream_lag_records", "labels": {},
             "value": 400.0}])], {}, 1)
        assert s["queue_depth"] == 400.0

    def test_goodput_alerts_stragglers(self):
        s = derive_signals(
            [_peer("a", ratio=0.9, alerts=("r1",)),
             _peer("b", ratio=0.4, status="stalled")], {}, 2)
        assert s["goodput_ratio"] == 0.4
        assert s["alerts"] == ["r1"]
        assert s["stragglers"] == [0]

    def test_dead_peer_contributes_nothing(self):
        s = derive_signals([{"addr": "x", "ok": False}], {}, 1)
        assert "step_time_s" not in s and "queue_depth" not in s

    def test_router_replicas_sums_up_state_only(self):
        s = derive_signals([
            _peer("r0:1", samples=[
                {"name": "bigdl_router_replicas",
                 "labels": {"state": "up"}, "value": 3.0},
                {"name": "bigdl_router_replicas",
                 "labels": {"state": "draining"}, "value": 1.0},
                {"name": "bigdl_router_replicas",
                 "labels": {"state": "down"}, "value": 2.0}]),
            _peer("r1:1", samples=[
                {"name": "bigdl_router_replicas",
                 "labels": {"state": "up"}, "value": 2.0}]),
        ], {}, 2, {})
        assert s["router_replicas"] == 5.0

    def test_shed_rate_from_counter_deltas(self):
        prev = {}
        shed = [{"name": "bigdl_router_shed_total", "labels": {},
                 "value": 10.0}]
        s1 = derive_signals([_peer("r0:1", t=100.0, samples=shed)],
                            {}, 1, prev)
        assert "router_shed_rate" not in s1  # one observation, no rate
        shed2 = [{"name": "bigdl_router_shed_total", "labels": {},
                  "value": 30.0}]
        s2 = derive_signals([_peer("r0:1", t=104.0, samples=shed2)],
                            {}, 1, prev)
        assert s2["router_shed_rate"] == pytest.approx(5.0)

    def test_shed_rate_counter_rewind_reads_quiet(self):
        # a restarted router rewinds bigdl_router_shed_total to zero;
        # the delta clamps at 0 instead of poisoning the signal
        prev = {"r0:1": (500.0, 100.0)}
        s = derive_signals([_peer("r0:1", t=110.0, samples=[
            {"name": "bigdl_router_shed_total", "labels": {},
             "value": 3.0}])], {}, 1, prev)
        assert s["router_shed_rate"] == 0.0
        assert prev["r0:1"] == (3.0, 110.0)  # memory re-anchors

    def test_shed_rate_absent_without_memory_dict(self):
        # backward-compatible: callers without a prev_counters dict
        # simply never derive the rate (absent signal, no breach)
        s = derive_signals([_peer("r0:1", t=100.0, samples=[
            {"name": "bigdl_router_shed_total", "labels": {},
             "value": 10.0}])], {}, 1)
        assert "router_shed_rate" not in s

    def test_router_rules_validate(self):
        rules = load_rules(
            '[{"name": "shed_storm", "signal": "router_shed_rate", '
            '"op": ">", "value": 2.0, "action": "up"}, '
            '{"name": "replica_floor", "signal": "router_replicas", '
            '"op": "<", "value": 2, "action": "up"}]', _cfg())
        assert [r["signal"] for r in rules] == [
            "router_shed_rate", "router_replicas"]


# ----------------------------------------------------------- controller
class TestController:
    def _ctl(self, cfg, world=1, t0=1000.0):
        clock = {"t": t0}
        ctl = AutoscaleController(cfg=cfg, world=world,
                                  scrape=lambda: [],
                                  clock=lambda: clock["t"])
        return ctl, clock

    def test_hysteresis_then_decision_and_counter(self):
        ctl, _ = self._ctl(_cfg(queue_high=100))
        assert ctl.evaluate({"queue_depth": 500.0}) is None  # streak 1
        d = ctl.evaluate({"queue_depth": 500.0})
        assert d.direction == "up" and (d.old_world, d.new_world) == (1, 2)
        assert d.reason == "queue_high" and not d.dry_run
        assert _counter_value("bigdl_autoscale_decisions_total",
                              direction="up", reason="queue_high") == 1.0

    def test_flapping_signal_resets_streak(self):
        ctl, _ = self._ctl(_cfg(queue_high=100))
        ctl.evaluate({"queue_depth": 500.0})
        ctl.evaluate({"queue_depth": 1.0})  # breach streak resets
        assert ctl.evaluate({"queue_depth": 500.0}) is None

    def test_cooldown_suppresses_immediate_reverse_decision(self):
        """The thrash case: scale-up followed at once by the opposite
        rule breaching must NOT bounce the world back."""
        cfg = _cfg(queue_high=100, queue_low=5, cooldown_s=50.0,
                   hysteresis=1)
        ctl, clock = self._ctl(cfg)
        up = ctl.evaluate({"queue_depth": 500.0})
        assert up is not None
        ctl.commit(up)
        assert ctl.world == 2
        # queue drains instantly after the resize — reverse rule breaches
        clock["t"] += 1.0
        assert ctl.evaluate({"queue_depth": 0.0}) is None  # cooldown
        clock["t"] += 100.0  # past the cooldown: now it may decide
        down = ctl.evaluate({"queue_depth": 0.0})
        assert down.direction == "down" and down.new_world == 1

    def test_clamped_at_bound_is_no_decision(self):
        ctl, _ = self._ctl(_cfg(queue_high=100, max_world=2,
                                hysteresis=1), world=2)
        assert ctl.evaluate({"queue_depth": 500.0}) is None
        assert _counter_value("bigdl_autoscale_decisions_total",
                              direction="up", reason="queue_high") is None

    def test_min_world_clamps_down(self):
        ctl, _ = self._ctl(_cfg(queue_low=5, hysteresis=1), world=1)
        assert ctl.evaluate({"queue_depth": 0.0}) is None

    def test_straggler_evict_rule(self):
        ctl, _ = self._ctl(_cfg(evict_stragglers=True, hysteresis=1),
                           world=4)
        d = ctl.evaluate({"stragglers": [2]})
        assert d.direction == "down" and d.reason == "straggler_evict"
        assert d.new_world == 2

    def test_dry_run_decision_flagged_and_counted(self):
        ctl, _ = self._ctl(_cfg(queue_high=100, hysteresis=1,
                                dry_run=True))
        d = ctl.evaluate({"queue_depth": 500.0})
        assert d is not None and d.dry_run
        assert _counter_value("bigdl_autoscale_decisions_total",
                              direction="up", reason="queue_high") == 1.0

    def test_tick_gates_warmup_interval_and_scrape_failure(self):
        cfg = _cfg(queue_high=100, warmup_s=10.0, interval_s=5.0,
                   hysteresis=1)
        clock = {"t": 0.0}
        calls = []

        def scrape():
            calls.append(clock["t"])
            return [_peer(samples=[{"name": "bigdl_stream_buffer_depth",
                                    "labels": {}, "value": 500.0}])]

        ctl = AutoscaleController(cfg=cfg, world=1, scrape=scrape,
                                  clock=lambda: clock["t"])
        assert ctl.tick() is None and not calls     # warmup
        clock["t"] = 11.0
        d = ctl.tick()
        assert d is not None and calls == [11.0]
        ctl.commit(d)
        clock["t"] = 12.0
        assert ctl.tick() is None and len(calls) == 1  # interval gate

    def test_tick_conservative_on_empty_or_failing_scrape(self):
        cfg = _cfg(queue_high=100, hysteresis=1)
        clock = {"t": 100.0}

        def boom():
            raise OSError("scrape died")

        ctl = AutoscaleController(cfg=cfg, world=1, scrape=boom,
                                  clock=lambda: clock["t"])
        assert ctl.tick() is None  # failure is data-free, not fatal

    def test_on_launch_resets_memory(self):
        ctl, clock = self._ctl(_cfg(queue_high=100, warmup_s=5.0))
        ctl.evaluate({"queue_depth": 500.0})
        assert ctl._streaks["queue_high"] == 1
        clock["t"] += 100.0
        ctl.on_launch()
        assert ctl._streaks["queue_high"] == 0
        assert ctl.tick() is None  # fresh warmup


# ----------------------------------------------------- endpoint scraper
class TestEndpointScraper:
    def test_port_file_resolution_and_scrape_shape(self, tmp_path):
        pf = tmp_path / "port"

        def fetch(url):
            if url.endswith("/healthz"):
                return json.dumps({"host": 0, "step": 3, "time": 1.0,
                                   "status": "ok"})
            return ("# HELP bigdl_stream_buffer_depth d\n"
                    "# TYPE bigdl_stream_buffer_depth gauge\n"
                    "bigdl_stream_buffer_depth 7.0\n")

        sc = EndpointScraper(port_file=str(pf), fetch=fetch)
        assert sc() == []  # no port yet: no data, no decision
        pf.write_text("12345")
        out = sc()
        assert out[0]["ok"] and out[0]["health"]["step"] == 3
        assert out[0]["metrics"]["samples"][0]["value"] == 7.0


# ------------------------------------------------- supervisor execution
class _StubScaler:
    """Controller stand-in for supervisor unit tests."""

    def __init__(self, world=1, decisions=()):
        self.cfg = _cfg(interval_s=0.1, warmup_s=0.0)
        self.world = world
        self._decisions = list(decisions)
        self.launches = 0

    def bind_endpoint(self, **kw):
        pass

    def on_launch(self):
        self.launches += 1

    def tick(self, now=None):
        return self._decisions.pop(0) if self._decisions else None

    def commit(self, decision):
        self.world = decision.new_world


def _decision(old=1, new=2, dry=False):
    return Decision(direction="up" if new > old else "down",
                    reason="queue_high", old_world=old, new_world=new,
                    dry_run=dry)


class TestSupervisorResize:
    def test_resize_restart_free_of_retry_budget(self, monkeypatch):
        """The fake runner plays the poll loop's part (it sets the
        pending decision) and exits like a gracefully-preempted child;
        run() must restart at the new world without burning retries."""
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_BASE", "0")
        scaler = _StubScaler()
        worlds, rcs = [], [EXIT_PREEMPTED, 0]

        def runner(cmd, env):
            worlds.append(env["BIGDL_AUTOSCALE_WORLD"])
            rc = rcs.pop(0)
            if rc == EXIT_PREEMPTED:
                sup._resize_decision = _decision()
            return rc

        sup = Supervisor(["cmd"], runner=runner, sleep=lambda s: None,
                         autoscaler=scaler)
        assert sup.run() == 0
        assert worlds == ["1", "2"]
        assert sup.resizes == 1 and scaler.world == 2
        assert sup.policy.attempts == 0      # no retry budget consumed
        assert sup.preemptions == 0          # and not counted preempted
        assert _counter_value("bigdl_supervisor_restarts_total",
                              kind="resize") == 1.0

    def test_decision_during_inflight_emergency_checkpoint(self,
                                                           monkeypatch):
        """The child was ALREADY preempting (external SIGTERM, its
        emergency checkpoint in flight) when the decision landed: one
        resize restart, no double handling, any rc accepted."""
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_BASE", "0")
        scaler = _StubScaler()
        rcs = [EXIT_TRANSIENT, 0]  # even a non-graceful rc is a resize

        def runner(cmd, env):
            rc = rcs.pop(0)
            if rc != 0:
                sup._resize_decision = _decision()
            return rc

        sup = Supervisor(["cmd"], runner=runner, sleep=lambda s: None,
                         autoscaler=scaler)
        assert sup.run() == 0
        assert sup.resizes == 1 and sup.policy.attempts == 0

    def test_resize_backoff_uses_retry_policy_shape(self, monkeypatch):
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_BASE", "0.5")
        scaler = _StubScaler()
        sleeps = []
        rcs = [EXIT_PREEMPTED, EXIT_PREEMPTED, 0]

        def runner(cmd, env):
            rc = rcs.pop(0)
            if rc != 0:
                sup._resize_decision = _decision(
                    old=scaler.world, new=scaler.world * 2)
            return rc

        sup = Supervisor(["cmd"], runner=runner,
                         sleep=lambda s: sleeps.append(s),
                         autoscaler=scaler)
        assert sup.run() == 0
        assert len(sleeps) == 2
        # deterministic-jitter exponential: second sleep ~2x the first
        assert sleeps[1] > sleeps[0] >= 0.5

    def test_dry_run_never_restarts_spawned_child(self):
        """_spawn path with a real child: dry-run decisions must leave
        the child alone — it runs to its own completion."""
        scaler = _StubScaler(
            decisions=[_decision(dry=True)] * 50)
        sup = Supervisor([sys.executable, "-c",
                          "import time; time.sleep(1.0)"],
                         autoscaler=scaler, sleep=lambda s: None)
        assert sup.run() == 0
        assert sup.resizes == 0 and scaler.world == 1

    def test_spawn_executes_decision_by_graceful_stop(self):
        """_spawn path end to end: the poll loop ticks, stops the child
        (SIGTERM), and run() relaunches at the new world — the child
        observes BIGDL_AUTOSCALE_WORLD=2 and completes."""
        scaler = _StubScaler(decisions=[_decision()])
        child = ("import os, sys, time\n"
                 "sys.exit(0) if os.environ.get('BIGDL_AUTOSCALE_WORLD')"
                 " == '2' else time.sleep(60)\n")
        sup = Supervisor([sys.executable, "-c", child],
                         autoscaler=scaler, sleep=lambda s: None,
                         stop_grace_s=5.0)
        t0 = time.monotonic()
        assert sup.run() == 0
        assert time.monotonic() - t0 < 30.0
        assert sup.resizes == 1 and scaler.world == 2
        assert scaler.launches == 2


# ------------------------------------------------- hardened alert sink
class TestAlertSinkHardening:
    def test_webhook_retries_once_then_counts_failure(self, monkeypatch):
        from bigdl_tpu.obs import alerts

        attempts = []

        def boom(req, timeout=None):
            attempts.append(timeout)
            raise OSError("connection refused")

        import urllib.request

        monkeypatch.setattr(urllib.request, "urlopen", boom)
        alerts._sink_write("http://127.0.0.1:1/alerts", {"a": 1},
                           timeout=0.25)
        assert attempts == [0.25, 0.25]  # bounded timeout, one retry
        assert _counter_value("bigdl_alert_sink_failures_total") == 1.0

    def test_webhook_success_after_retry_not_counted(self, monkeypatch):
        from bigdl_tpu.obs import alerts

        calls = []

        class _Resp:
            def close(self):
                pass

        def flaky(req, timeout=None):
            calls.append(1)
            if len(calls) == 1:
                raise OSError("blip")
            return _Resp()

        import urllib.request

        monkeypatch.setattr(urllib.request, "urlopen", flaky)
        alerts._sink_write("http://127.0.0.1:1/alerts", {"a": 1},
                           timeout=0.25)
        assert len(calls) == 2
        assert _counter_value("bigdl_alert_sink_failures_total") is None

    def test_file_sink_failure_counted(self, tmp_path):
        from bigdl_tpu.obs import alerts

        alerts._sink_write(str(tmp_path), {"a": 1})  # a dir: open fails
        assert _counter_value("bigdl_alert_sink_failures_total") == 1.0

    def test_timeout_default_from_config(self, monkeypatch):
        monkeypatch.setenv("BIGDL_ALERT_SINK_TIMEOUT", "0.125")
        from bigdl_tpu.config import refresh_from_env

        assert refresh_from_env().obs.alert_sink_timeout == 0.125
