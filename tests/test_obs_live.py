"""Live telemetry plane specs (ISSUE 8) — per-host /metrics + /healthz
+ /trace endpoints, the declarative alert/SLO engine, live fleet
aggregation, and the supervisor hang watchdog.

The acceptance pins live here: a LocalOptimizer run with
``BIGDL_OBS_PORT=0`` serves a scrapeable Prometheus exposition (with
the HELP/TYPE family headers real scrapers require) and a /healthz
whose step stamp tracks the loop; a synthetic nan_grad fault drives an
alert through its full firing→resolved lifecycle; with the port unset
the process holds no server thread and no socket; and a deliberately
stalled child is killed and restarted by the supervisor's hang
watchdog — the failure class heartbeats and exit codes cannot see.
"""

import json
import math
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import obs
from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential
from bigdl_tpu.obs import alerts, server
from bigdl_tpu.obs.aggregate import FleetAggregator, ShardTailer
from bigdl_tpu.obs.metrics import (
    MetricsRegistry,
    parse_prometheus,
    sample_value,
)
from bigdl_tpu.optim import SGD, LocalOptimizer, Trigger
from bigdl_tpu.resilience import reset_injector
from bigdl_tpu.resilience.supervisor import HangWatchdog, Supervisor

pytestmark = pytest.mark.obs

_LIVE_VARS = (
    "BIGDL_OBS", "BIGDL_TRACE_DIR", "BIGDL_METRICS_DIR",
    "BIGDL_FAULT_PLAN", "BIGDL_OBS_PORT", "BIGDL_OBS_PORT_FILE",
    "BIGDL_OBS_PEERS", "BIGDL_ALERT_RULES", "BIGDL_ALERT_SINK",
    "BIGDL_HANG_TIMEOUT", "BIGDL_GOODPUT_WINDOW",
)


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    for var in _LIVE_VARS:
        monkeypatch.delenv(var, raising=False)
    reset_injector()
    obs.reset()
    yield
    obs.reset()
    reset_injector()


def _toy(n=128, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, k)
    x = rng.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    return x, y


def _model(d=16, k=4):
    return Sequential().add(Linear(d, 32)).add(ReLU()).add(Linear(32, k)) \
        .add(LogSoftMax())


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


def _obs_threads():
    return [t for t in threading.enumerate()
            if t.name == "bigdl-obs-server"]


# ================================================ exposition reader
class TestParsePrometheus:
    def test_roundtrip_families_and_samples(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "counts a", labels=("k",)).labels(
            k='va"l\\ue\n').inc(3)
        reg.gauge("g", "a gauge").set(-2.5)
        reg.histogram("h_seconds", "lat", buckets=(0.5, 1.0)).observe(0.7)
        parsed = parse_prometheus(reg.to_prometheus())
        # every family carries BOTH headers (the satellite contract)
        for fam in ("a_total", "g", "h_seconds"):
            assert parsed["families"][fam]["type"]
            assert parsed["families"][fam]["help"]
        # label escaping round-trips exactly
        assert sample_value(parsed, "a_total", k='va"l\\ue\n') == 3
        assert sample_value(parsed, "g") == -2.5
        assert sample_value(parsed, "h_seconds_bucket", le="1") == 1
        assert sample_value(parsed, "h_seconds_count") == 1
        assert sample_value(parsed, "h_seconds_sum") == 0.7

    def test_nonfinite_values(self):
        reg = MetricsRegistry()
        reg.gauge("nan_g", "x").set(float("nan"))
        reg.gauge("inf_g", "x").set(float("inf"))
        parsed = parse_prometheus(reg.to_prometheus())
        assert math.isnan(sample_value(parsed, "nan_g"))
        assert sample_value(parsed, "inf_g") == float("inf")

    def test_malformed_line_is_loud(self):
        with pytest.raises(ValueError, match="bad exposition line"):
            parse_prometheus("ok_metric 1\nthis is not exposition\n")

    def test_missing_sample_is_none(self):
        assert sample_value(parse_prometheus("x 1"), "y") is None
        assert sample_value(parse_prometheus('x{a="1"} 1'), "x", a=2) is None


# ==================================================== burn-rate math
class TestBurnRate:
    def test_units(self):
        # SLO 0.5 leaves a 0.5 error budget: observing 0.25 burns
        # 0.75/0.5 = 1.5x sustainable
        assert alerts.burn_rate(0.25, 0.5) == pytest.approx(1.5)
        # exactly at the SLO boundary burns exactly 1.0
        assert alerts.burn_rate(0.9, 0.9) == pytest.approx(1.0)
        # perfect goodput burns nothing
        assert alerts.burn_rate(1.0, 0.9) == 0.0
        # zero budget (slo >= 1): any shortfall is infinite burn
        assert alerts.burn_rate(0.99, 1.0) == float("inf")
        assert alerts.burn_rate(1.0, 1.0) == 0.0
        # no signal yet: no burn (absence is its own rule type)
        assert alerts.burn_rate(None, 0.9) == 0.0

    def test_burn_rate_rule_fires_and_resolves(self):
        reg = MetricsRegistry()
        g = reg.gauge("bigdl_goodput_window_ratio", "w")
        eng = alerts.AlertEngine(
            [{"name": "burn", "type": "burn_rate",
              "metric": "bigdl_goodput_window_ratio", "slo": 0.5,
              "threshold": 1.5, "for": 1, "severity": "warning"}],
            registry=reg, clock=lambda: 100.0)
        g.set(0.25)  # burn 1.5 >= 1.5 -> breach
        t = eng.evaluate()
        assert [x["state"] for x in t] == ["firing"]
        assert t[0]["value"] == pytest.approx(1.5)
        g.set(0.9)   # burn 0.2 -> resolve
        t = eng.evaluate()
        assert [x["state"] for x in t] == ["resolved"]


# =================================================== rule validation
class TestAlertRules:
    def test_default_pack_validates(self):
        rules = alerts.load_rules(None, heartbeat_timeout=60.0)
        names = {r["name"] for r in rules}
        assert {"goodput_below_target", "nonfinite_spike",
                "straggler_flagged", "checkpoint_write_failure",
                "stale_peer_heartbeat", "goodput_slo_burn"} <= names
        assert all(r["type"] in alerts.RULE_TYPES for r in rules)

    def test_inline_json_and_file(self, tmp_path):
        spec = '[{"name": "x", "metric": "m", "op": ">", "value": 1}]'
        rules = alerts.load_rules(spec)
        assert rules[0]["type"] == "threshold"  # defaulted
        assert rules[0]["for"] == 1
        p = tmp_path / "rules.json"
        p.write_text(spec)
        assert alerts.load_rules(str(p)) == rules

    @pytest.mark.parametrize("bad,msg", [
        ('[{"name": "x", "metric": "m", "type": "nope"}]', "unknown type"),
        ('[{"metric": "m"}]', "missing a name"),
        ('[{"name": "x"}]', "missing metric"),
        ('[{"name": "x", "metric": "m", "op": "~", "value": 1}]', "op"),
        ('[{"name": "x", "metric": "m"}]', "missing value"),
        ('[{"name": "x", "metric": "m", "type": "burn_rate"}]',
         "needs slo"),
        ('{"name": "x"}', "JSON list"),
    ])
    def test_typod_pack_fails_at_build(self, bad, msg):
        with pytest.raises(ValueError, match=msg):
            alerts.load_rules(bad)


# ===================================================== alert engine
class TestAlertEngine:
    def _engine(self, rules, reg, clock=None):
        return alerts.AlertEngine(alerts.load_rules(json.dumps(rules)),
                                  registry=reg,
                                  clock=clock or (lambda: 1.0))

    def test_threshold_for_debounce_and_lifecycle(self):
        reg = MetricsRegistry()
        g = reg.gauge("bigdl_goodput_ratio", "r")
        eng = self._engine(
            [{"name": "low", "metric": "bigdl_goodput_ratio",
              "op": "<", "value": 0.5, "for": 2,
              "severity": "warning"}], reg)
        g.set(0.2)
        assert eng.evaluate() == []          # 1st breach: debounced
        t = eng.evaluate()                   # 2nd consecutive: fires
        assert [x["state"] for x in t] == ["firing"]
        assert eng.active()[0]["rule"] == "low"
        # lifecycle metrics on fire
        text = reg.to_prometheus()
        parsed = parse_prometheus(text)
        assert sample_value(parsed, "bigdl_alerts_total", rule="low",
                            severity="warning") == 1
        assert sample_value(parsed, "bigdl_alert_active", rule="low") == 1
        g.set(0.9)
        t = eng.evaluate()
        assert [x["state"] for x in t] == ["resolved"]
        assert eng.active() == []
        parsed = parse_prometheus(reg.to_prometheus())
        assert sample_value(parsed, "bigdl_alerts_resolved_total",
                            rule="low") == 1
        assert sample_value(parsed, "bigdl_alert_active", rule="low") == 0
        # one flaky breach does not re-fire (for=2 resets)
        g.set(0.2)
        assert eng.evaluate() == []

    def test_threshold_picks_worst_labeled_sample(self):
        reg = MetricsRegistry()
        g = reg.gauge("bigdl_heartbeat_age_seconds", "ages",
                      labels=("host",))
        g.labels(host=1).set(2.0)
        g.labels(host=2).set(45.0)
        eng = self._engine(
            [{"name": "stale", "metric": "bigdl_heartbeat_age_seconds",
              "op": ">", "value": 30.0}], reg)
        t = eng.evaluate()
        assert t[0]["state"] == "firing"
        assert t[0]["value"] == 45.0
        assert t[0]["labels"] == {"host": "2"}

    def test_absence_rule(self):
        reg = MetricsRegistry()
        eng = self._engine(
            [{"name": "no_signal", "type": "absence",
              "metric": "bigdl_goodput_ratio"}], reg)
        assert [x["state"] for x in eng.evaluate()] == ["firing"]
        reg.gauge("bigdl_goodput_ratio", "r").set(0.5)
        assert [x["state"] for x in eng.evaluate()] == ["resolved"]

    def test_rate_rule_baselines_existing_counts_at_build(self):
        reg = MetricsRegistry()
        c = reg.counter("bigdl_nonfinite_skips_total", "skips")
        c.inc(10)  # history from before this engine existed
        eng = self._engine(
            [{"name": "spike", "type": "rate",
              "metric": "bigdl_nonfinite_skips_total",
              "op": ">", "value": 0}], reg)
        assert eng.evaluate() == []   # primed: 10 is history
        c.inc(2)
        t = eng.evaluate()
        assert t[0]["state"] == "firing"
        assert t[0]["value"] == 2.0   # the delta, not the total
        t = eng.evaluate()            # no further movement: resolves
        assert t[0]["state"] == "resolved"

    def test_rate_rule_counter_appearing_later_is_a_spike(self):
        """A family registered lazily on its first increment (the
        nonfinite counter) must fire — not be swallowed as history."""
        reg = MetricsRegistry()
        eng = self._engine(
            [{"name": "spike", "type": "rate",
              "metric": "bigdl_nonfinite_skips_total",
              "op": ">", "value": 0}], reg)
        assert eng.evaluate() == []
        reg.counter("bigdl_nonfinite_skips_total", "skips").inc()
        t = eng.evaluate()
        assert [x["state"] for x in t] == ["firing"]
        assert t[0]["value"] == 1.0

    def test_one_bad_rule_does_not_kill_the_pack(self):
        reg = MetricsRegistry()
        reg.gauge("ok_metric", "x").set(99.0)
        eng = alerts.AlertEngine(
            [{"name": "broken", "type": "threshold", "metric": "m",
              "op": ">", "value": "not-a-number", "for": 1,
              "severity": "warning"},
             {"name": "works", "type": "threshold",
              "metric": "ok_metric", "op": ">", "value": 1,
              "for": 1, "severity": "warning"}], registry=reg)
        reg.gauge("m", "x").set(5.0)  # would crash float("not-a-number")
        t = eng.evaluate()
        assert [x["rule"] for x in t] == ["works"]

    def test_file_sink_appends_transitions(self, tmp_path):
        sink = tmp_path / "alerts.jsonl"
        reg = MetricsRegistry()
        g = reg.gauge("m", "x")
        eng = alerts.AlertEngine(
            alerts.load_rules(
                '[{"name": "s", "metric": "m", "op": ">", "value": 1}]'),
            registry=reg, sink=str(sink))
        g.set(5)
        eng.evaluate()
        g.set(0)
        eng.evaluate()
        recs = [json.loads(ln) for ln in
                sink.read_text().strip().splitlines()]
        assert [r["state"] for r in recs] == ["firing", "resolved"]
        assert recs[0]["rule"] == "s"

    def test_engine_singleton_rebuilds_on_rule_change(self, monkeypatch):
        alerts.reset_engine()
        e1 = alerts.get_engine()
        assert alerts.get_engine() is e1
        monkeypatch.setenv(
            "BIGDL_ALERT_RULES",
            '[{"name": "z", "metric": "m", "op": ">", "value": 1}]')
        e2 = alerts.get_engine()
        assert e2 is not e1
        assert [r["name"] for r in e2.rules] == ["z"]


# ======================================================= obs server
class TestObsServer:
    def test_disabled_is_noop_no_thread_no_socket(self):
        assert server.ensure_server() is None
        assert server.get_server() is None
        assert _obs_threads() == []
        assert server.last_step() == (None, None)

    def test_ephemeral_port_serves_all_routes(self, monkeypatch,
                                              tmp_path):
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        s = server.ensure_server()
        assert s is not None and s.port > 0
        assert server.ensure_server() is s  # same config: same server
        obs.get_registry().counter("bigdl_live_total", "live").inc(4)
        obs.get_tracer().event("live.ping", k=1)
        server.note_step(12)
        code, text = _get(s.url("/metrics"))
        assert code == 200
        parsed = parse_prometheus(text)  # loud on malformed lines
        assert sample_value(parsed, "bigdl_live_total") == 4
        assert "# TYPE bigdl_live_total counter" in text
        assert "# HELP bigdl_live_total live" in text
        code, body = _get(s.url("/healthz"))
        h = json.loads(body)
        assert h["status"] == "ok"
        assert h["step"] == 12
        assert h["step_age_s"] is not None and h["step_age_s"] >= 0
        assert h["port"] == s.port
        code, body = _get(s.url("/trace?last=8"))
        tail = json.loads(body)
        assert any(r.get("name") == "live.ping" for r in tail)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(s.url("/nope"))
        assert ei.value.code == 404

    def test_port_file_carries_bound_port(self, monkeypatch, tmp_path):
        pf = tmp_path / "port"
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        monkeypatch.setenv("BIGDL_OBS_PORT_FILE", str(pf))
        s = server.ensure_server()
        assert int(pf.read_text()) == s.port

    def test_rebuild_on_port_change_and_idempotent_stop(self,
                                                        monkeypatch):
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        s1 = server.ensure_server()
        monkeypatch.delenv("BIGDL_OBS_PORT")
        assert server.ensure_server() is None  # config off: torn down
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        s2 = server.ensure_server()
        assert s2 is not s1
        server.stop_server()
        server.stop_server()  # idempotent
        assert _obs_threads() == []

    def test_bind_failure_disables_instead_of_raising(self, monkeypatch):
        blocker = socket.socket()
        blocker.bind(("0.0.0.0", 0))
        blocker.listen(1)
        try:
            monkeypatch.setenv("BIGDL_OBS_PORT",
                               str(blocker.getsockname()[1]))
            assert server.ensure_server() is None  # logged, not raised
        finally:
            blocker.close()

    def test_extra_registry_weakref(self, monkeypatch):
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        server.ensure_server()
        extra = MetricsRegistry()
        extra.gauge("bigdl_phase_smoke", "x").set(1.5)
        server.register_registry(extra)
        server.register_registry(extra)  # dedup
        text = server.metrics_text()
        assert sample_value(parse_prometheus(text),
                            "bigdl_phase_smoke") == 1.5
        del extra
        import gc

        gc.collect()
        assert "bigdl_phase_smoke" not in server.metrics_text()

    def test_healthz_stalled_status_and_heartbeat_census(
            self, monkeypatch):
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        monkeypatch.setenv("BIGDL_HANG_TIMEOUT", "0.05")
        server.ensure_server()
        server.note_step(3)
        time.sleep(0.1)
        obs.get_registry().gauge(
            "bigdl_heartbeat_age_seconds", "ages",
            labels=("host",)).labels(host=1).set(4.2)
        h = server.health_payload()
        assert h["status"] == "stalled"  # stamp older than the budget
        assert h["heartbeat"] == {"1": 4.2}


# ================================= live LocalOptimizer acceptance gate
class TestLiveOptimizerScrape:
    def test_scrape_metrics_healthz_trace_of_live_run(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("BIGDL_METRICS_DIR", str(tmp_path))
        obs.reset()
        x, y = _toy(n=128)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=16)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.optimize()
        s = server.get_server()
        assert s is not None  # brought up by the optimizer, still live
        _, text = _get(s.url("/metrics"))
        parsed = parse_prometheus(text)
        # the run's own registry, served live with family headers
        assert sample_value(parsed, "bigdl_goodput_ratio") is not None
        assert "# TYPE bigdl_goodput_ratio gauge" in text
        # the optimizer's private phase registry rides the same scrape
        assert any(su["name"] == "bigdl_phase_seconds_count"
                   for su in parsed["samples"])
        _, body = _get(s.url("/healthz"))
        h = json.loads(body)
        assert h["step"] == 8  # 128/16 batches resolved
        assert h["status"] == "ok"
        assert 0.0 < h["goodput_ratio"] <= 1.0
        _, body = _get(s.url("/trace?last=32"))
        assert len(json.loads(body)) > 0

    def test_alert_firing_resolved_on_nan_grad_fault(
            self, monkeypatch, tmp_path):
        """The full lifecycle, end to end: a synthetic nan_grad fault
        bumps bigdl_nonfinite_skips_total, the alert engine rides the
        goodput window tick, the nonfinite_spike rate rule fires, and
        the next quiet window resolves it — with matching counters and
        alert.firing/alert.resolved trace events."""
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("BIGDL_METRICS_DIR", str(tmp_path))
        monkeypatch.setenv("BIGDL_GOODPUT_WINDOW", "2")
        monkeypatch.setenv("BIGDL_FAULT_PLAN", "step:2:nan_grad")
        obs.reset()
        reset_injector()
        x, y = _toy(n=128)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=16)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.optimize()
        assert opt.state["nonfinite_skips"] == 1
        parsed = parse_prometheus(obs.get_registry().to_prometheus())
        fired = sample_value(parsed, "bigdl_alerts_total",
                             rule="nonfinite_spike", severity="critical")
        resolved = sample_value(parsed, "bigdl_alerts_resolved_total",
                                rule="nonfinite_spike")
        assert fired == 1
        assert resolved == 1  # matching lifecycle counts
        assert sample_value(parsed, "bigdl_alert_active",
                            rule="nonfinite_spike") == 0
        # both transitions are on the trace, and the report renders them
        from bigdl_tpu.obs.report import build_report, render_text

        rep = build_report(str(tmp_path))
        states = [e["state"] for e in rep["alerts"]["events"]
                  if e.get("rule") == "nonfinite_spike"]
        assert states == ["firing", "resolved"]
        text = render_text(rep)
        assert "-- alerts --" in text
        assert "nonfinite_spike[critical]" in text
        assert "fired 1x, resolved 1x" in text

    def test_disabled_run_binds_nothing_and_stamps_nothing(self):
        """The off-path pin: BIGDL_OBS_PORT unset -> no server object,
        no daemon thread, no socket, no step stamp — the loop's only
        cost is one None check."""
        x, y = _toy(n=64)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=16)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.optimize()
        assert opt._obs_server is None
        assert server.get_server() is None
        assert _obs_threads() == []
        assert server.last_step() == (None, None)


# ==================================================== hang watchdog
class TestHangWatchdog:
    def test_unreachable_or_preStep_child_is_never_hung(self):
        wd = HangWatchdog(1.0, port=1, fetch=lambda url: None)
        assert not wd.stalled()  # cannot tell != hung
        wd = HangWatchdog(1.0, port=1,
                          fetch=lambda url: {"step": None,
                                             "step_age_s": None})
        assert not wd.stalled()  # still compiling: no first stamp yet

    def test_stale_stamp_is_hung_fresh_is_not(self):
        wd = HangWatchdog(1.0, port=1,
                          fetch=lambda url: {"step": 5,
                                             "step_age_s": 3.0})
        assert wd.stalled()
        assert wd.last_payload["step"] == 5
        wd = HangWatchdog(1.0, port=1,
                          fetch=lambda url: {"step": 5,
                                             "step_age_s": 0.2})
        assert not wd.stalled()

    def test_port_file_resolution(self, tmp_path):
        pf = tmp_path / "port"
        seen = []
        wd = HangWatchdog(1.0, port_file=str(pf),
                          fetch=lambda url: seen.append(url) or None)
        assert wd.health() is None      # no file yet: no port, no fetch
        assert seen == []
        pf.write_text("45123")
        wd.health()
        assert seen == ["http://127.0.0.1:45123/healthz"]
        assert wd.port == 45123         # cached after first resolve

    def test_supervisor_counts_hang_restarts_under_budget(
            self, monkeypatch):
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_BASE", "0")
        calls = []

        def runner(cmd, env):
            calls.append(env["BIGDL_ELASTIC_ATTEMPT"])
            if len(calls) == 1:
                sup._hang_detected = True  # what _spawn's kill path sets
                return -15
            return 0

        sup = Supervisor(["x"], max_retries=2, runner=runner,
                         sleep=lambda s: None, hang_timeout=1.0)
        assert sup.run() == 0
        assert calls == ["0", "1"]
        assert sup.hangs == 1
        parsed = parse_prometheus(obs.get_registry().to_prometheus())
        assert sample_value(parsed, "bigdl_supervisor_restarts_total",
                            kind="hang") == 1

    def test_watchdog_disabled_without_port(self, monkeypatch):
        sup = Supervisor(["x"], runner=lambda c, e: 0,
                         hang_timeout=5.0)
        assert sup._make_watchdog({}) is None          # no BIGDL_OBS_PORT
        assert sup._make_watchdog({"BIGDL_OBS_PORT": "0"}) is not None
        sup2 = Supervisor(["x"], runner=lambda c, e: 0, hang_timeout=0)
        assert sup2._make_watchdog({"BIGDL_OBS_PORT": "0"}) is None

    def test_stalled_child_killed_and_restarted(self, monkeypatch,
                                                tmp_path):
        """Acceptance: a real child that stamps one step then wedges is
        killed by the watchdog and restarted; the restarted attempt
        completes.  This is the hang class exit codes cannot catch (the
        child never exits) and heartbeats cannot catch (its heartbeat
        thread would keep beating)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = tmp_path / "stall.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys, time
            sys.path.insert(0, {repo!r})
            from bigdl_tpu.obs import server
            s = server.ensure_server()
            assert s is not None, "child server must bind"
            if int(os.environ.get("BIGDL_ELASTIC_ATTEMPT", "0")) >= 1:
                sys.exit(0)            # the restarted attempt completes
            server.note_step(1)
            time.sleep(120)            # wedged: alive but never advances
        """))
        monkeypatch.setenv("BIGDL_OBS_PORT", "0")
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_BASE", "0")
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        sup = Supervisor([sys.executable, str(script)], max_retries=2,
                         hang_timeout=1.5)
        t0 = time.time()
        assert sup.run() == 0
        assert time.time() - t0 < 60, "watchdog should kill in seconds"
        assert sup.hangs == 1
        assert sup.attempt == 2


# ================================================== fleet aggregation
def _peer_payload(host, step, ratio, alerts_list=()):
    health = json.dumps({
        "status": "ok", "host": host, "step": step, "step_age_s": 0.1,
        "goodput_ratio": ratio, "alerts": list(alerts_list),
        "heartbeat": None})
    reg = MetricsRegistry()
    reg.gauge("bigdl_goodput_ratio", "r").set(ratio)
    reg.counter("bigdl_steps_smoke_total", "s").inc(step)
    return {"/healthz": health, "/metrics": reg.to_prometheus()}


class TestFleetAggregator:
    def test_peer_scrape_merges_hosts_alerts_and_metrics(self):
        peers = {
            "h0:9100": _peer_payload(0, 40, 0.9),
            "h1:9100": _peer_payload(
                1, 38, 0.3,
                [{"rule": "goodput_below_target",
                  "severity": "warning"}]),
        }

        def fetch(url):
            for addr, routes in peers.items():
                if addr in url:
                    return routes[url.split(addr, 1)[1]]
            raise OSError("unknown peer")

        agg = FleetAggregator(peers="h0:9100, h1:9100", fetch=fetch)
        fleet = agg.snapshot()
        assert fleet["mode"] == "peers"
        assert set(fleet["hosts"]) == {"0", "1"}
        assert fleet["hosts"]["1"]["goodput_ratio"] == 0.3
        assert fleet["hosts"]["0"]["step"] == 40
        assert [a["rule"] for a in fleet["alerts"]] == [
            "goodput_below_target"]
        assert fleet["alerts"][0]["host"] == 1
        ratios = {s["source"]: s["value"]
                  for s in fleet["metrics"]["bigdl_goodput_ratio"]}
        assert ratios == {"h0:9100": 0.9, "h1:9100": 0.3}

    def test_dead_peer_is_data_not_an_exception(self):
        def fetch(url):
            raise OSError("connection refused")

        fleet = FleetAggregator(peers=["h9:1"], fetch=fetch).snapshot()
        assert fleet["hosts"] == {}
        assert "h9:1" in fleet["errors"]

    def test_shard_tailing_is_incremental(self, tmp_path):
        def snap_line(host, ratio, active=0):
            return json.dumps({"ts": 1.0, "host": host, "metrics": {
                "bigdl_goodput_ratio": {"kind": "gauge", "samples": [
                    {"labels": {}, "value": ratio}]},
                "bigdl_alert_active": {"kind": "gauge", "samples": [
                    {"labels": {"rule": "goodput_below_target"},
                     "value": active}]},
            }}) + "\n"

        shard = tmp_path / "metrics.h0.111.jsonl"
        shard.write_text(snap_line(0, 0.8) + snap_line(0, 0.6, active=1))
        (tmp_path / "metrics.h1.222.jsonl").write_text(snap_line(1, 0.9))
        agg = FleetAggregator(metrics_dir=str(tmp_path))
        fleet = agg.snapshot()
        assert fleet["mode"] == "shards"
        assert set(fleet["hosts"]) == {"0", "1"}
        # newest snapshot per shard wins
        assert fleet["hosts"]["0"]["goodput_ratio"] == 0.6
        assert [a["rule"] for a in fleet["alerts"]] == [
            "goodput_below_target"]
        # a torn tail line (no newline yet) is not consumed ...
        torn = snap_line(0, 0.99).rstrip("\n")[:25]
        with open(shard, "a") as fh:
            fh.write(torn)
        assert agg.snapshot()["hosts"]["0"]["goodput_ratio"] == 0.6
        # ... and a replaced (shrunk) shard is re-read from zero
        shard.write_text(snap_line(0, 0.99))
        assert agg.snapshot()["hosts"]["0"]["goodput_ratio"] == 0.99

    def test_tailer_offsets_only_advance_on_complete_lines(self,
                                                           tmp_path):
        t = ShardTailer(str(tmp_path))
        p = tmp_path / "metrics.h0.1.jsonl"
        p.write_text('{"host": 0, "metrics": {}}\n{"host": 0, "met')
        t.poll()
        assert t._offsets[p.name] == len('{"host": 0, "metrics": {}}\n')
        with open(p, "a") as fh:
            fh.write('rics": {"g": {"samples": []}}}\n')
        t.poll()
        assert t.latest[p.name]["metrics"] == {"g": {"samples": []}}


# ================================================== report --watch
class TestReportWatch:
    def _seed_dirs(self, tmp_path):
        """A minimal trace shard + metrics shard a report can read."""
        (tmp_path / "app.h0.1.0.events.jsonl").write_text("\n".join([
            json.dumps({"kind": "span", "name": "computing",
                        "wall_time": 1.0, "dur_s": 0.01, "host": 0,
                        "pid": 1, "tid": 1, "attrs": {"step": 1}}),
            json.dumps({"kind": "event", "name": "alert.firing",
                        "wall_time": 1.1, "host": 0, "pid": 1, "tid": 1,
                        "attrs": {"rule": "goodput_below_target",
                                  "severity": "warning",
                                  "metric": "bigdl_goodput_ratio",
                                  "value": 0.2}}),
        ]) + "\n")
        (tmp_path / "metrics.h0.1.jsonl").write_text(json.dumps({
            "ts": 1.0, "host": 0, "metrics": {
                "bigdl_alerts_total": {"kind": "counter", "samples": [
                    {"labels": {"rule": "goodput_below_target",
                                "severity": "warning"}, "value": 1}]},
                "bigdl_alert_active": {"kind": "gauge", "samples": [
                    {"labels": {"rule": "goodput_below_target"},
                     "value": 1}]},
            }}) + "\n")

    def test_watch_once_text_renders_fleet_and_alerts(self, tmp_path,
                                                      capsys):
        from bigdl_tpu.obs import report

        self._seed_dirs(tmp_path)
        rc = report.main([str(tmp_path), "--watch", "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "-- live fleet (shards) --" in out
        assert "host0" in out
        assert "-- alerts --" in out
        assert "FIRING goodput_below_target" in out
        assert "\x1b[2J" not in out  # --once never clears the screen

    def test_watch_once_json_carries_fleet_and_alerts(self, tmp_path,
                                                      capsys):
        from bigdl_tpu.obs import report

        self._seed_dirs(tmp_path)
        rc = report.main([str(tmp_path), "--watch", "--once", "--json"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["fleet"]["mode"] == "shards"
        assert "0" in rep["fleet"]["hosts"]
        assert rep["alerts"]["active"] == ["goodput_below_target"]
        assert rep["alerts"]["fired_total"] == {
            "goodput_below_target[warning]": 1}
        assert rep["alerts"]["events"][0]["state"] == "firing"


# ========================================== live goodput SLO signal
class TestLiveGoodputSignal:
    def test_window_ratio_sees_through_pipelined_waits(self,
                                                       monkeypatch,
                                                       tmp_path):
        """Under async pipelining a dispatch→resolve step span absorbs
        the next batch's input wait, so step/(step+wait) floors near
        0.5 in a fully starved run.  The live window gauge must use
        1 - badput/wall instead — a starved window reads starved."""
        from bigdl_tpu.obs.goodput import GoodputLedger

        monkeypatch.setenv("BIGDL_METRICS_DIR", str(tmp_path))
        monkeypatch.setenv("BIGDL_GOODPUT_WINDOW", "4")
        obs.reset()
        from bigdl_tpu.config import refresh_from_env

        refresh_from_env()
        led = GoodputLedger(str(tmp_path))
        t0 = time.perf_counter()
        # 4 pipelined iterations: each waits 30ms on input, and each
        # resolve span (50ms) OVERLAPS the following wait — the old
        # quotient would read 50/(50+30) = 0.62 "healthy"
        for n in range(1, 5):
            led.record("data_wait", t0, 0.030, step=n)
            led.record("step", t0, 0.050, step=n)
            time.sleep(0.02)  # real wall passes so win_wall > badput
        parsed = parse_prometheus(obs.get_registry().to_prometheus())
        ratio = sample_value(parsed, "bigdl_goodput_window_ratio")
        assert ratio is not None
        wall = time.perf_counter() - t0
        expect = max(0.0, 1.0 - 0.120 / wall)
        assert ratio == pytest.approx(expect, abs=0.05)
        assert ratio < 0.62, "window ratio blind to pipelined waits"
        led.close()

    def test_live_ratio_takes_the_tighter_bound(self, tmp_path):
        from bigdl_tpu.obs.goodput import GoodputLedger

        led = GoodputLedger(str(tmp_path))
        led._epoch_wall = time.time() - 10.0  # 10s elapsed
        t0 = time.perf_counter()
        led.record("step", t0, 8.0, step=1)      # absorbed waits inside
        led.record("data_wait", t0, 6.0, step=1)
        # productive bound: 8/10 = 0.8; badput bound: 1 - 6/10 = 0.4
        assert led.live_ratio() == pytest.approx(0.4, abs=0.15)
        led.close()


# ============================================ heartbeat-age satellite
class TestHeartbeatAgeGauge:
    def test_scan_publishes_age_gauges_before_peer_lost(self, tmp_path):
        from bigdl_tpu.resilience.elastic import HeartbeatMonitor

        clk = [100.0]
        mon = HeartbeatMonitor(str(tmp_path), host=0, n_hosts=3,
                               timeout_s=60.0, clock=lambda: clk[0])
        mon.beat(force=True)
        (tmp_path / "heartbeat.h1").write_text("{}")
        os.utime(tmp_path / "heartbeat.h1", (95.0, 95.0))
        clk[0] = 110.0
        mon.scan()
        parsed = parse_prometheus(obs.get_registry().to_prometheus())
        # host1 beat 15s ago; host2 never beat (counts from start)
        assert sample_value(parsed, "bigdl_heartbeat_age_seconds",
                            host=1) == pytest.approx(15.0)
        assert sample_value(parsed, "bigdl_heartbeat_age_seconds",
                            host=2) == pytest.approx(10.0)
        # staleness is data BEFORE any PeerLostError fires
        mon.check()  # under timeout: no raise
        # and the healthz census reads the same gauges
        assert server._heartbeat_census() == {"1": 15.0, "2": 10.0}
