"""Text pipeline tests (reference analogue: dataset/text specs —
Dictionary, LabeledSentence, PTB BPTT batching)."""

import numpy as np

from bigdl_tpu.dataset.text import (
    Dictionary,
    LabeledSentence,
    ptb_bptt_batches,
    synthetic_ptb_stream,
)


def test_dictionary_build_and_lookup():
    sents = [["the", "cat", "sat"], ["the", "dog", "sat", "down"]]
    d = Dictionary(sents, vocab_size=10)
    assert d.vocab_size() <= 10
    # ids are 1-based (LookupTable convention)
    for w in ("the", "cat", "sat"):
        idx = d.get_index(w)
        assert idx >= 1
        assert d.get_word(idx) == w
    # unknown word falls into the last-id bucket
    assert d.get_index("zebra") == d.vocab_size()


def test_dictionary_vocab_cap():
    sents = [["a"] * 5, ["b"] * 4, ["c"] * 3, ["d"] * 2, ["e"]]
    d = Dictionary(sents, vocab_size=3)
    assert d.vocab_size() == 3
    assert d.get_index("a") == 1  # most frequent first


def test_labeled_sentence():
    data = [1, 2, 3, 4]
    ls = LabeledSentence(data[:-1], data[1:])
    np.testing.assert_array_equal(ls.data, [1, 2, 3])
    np.testing.assert_array_equal(ls.labels, [2, 3, 4])


def test_ptb_bptt_batches_shapes_and_shift():
    tokens = np.arange(1000, dtype=np.int64)
    xs, ys = ptb_bptt_batches(tokens, batch_size=4, num_steps=10)
    assert xs.shape == ys.shape
    assert xs.shape[1:] == (4, 10)
    # target is input shifted by one within each stream
    np.testing.assert_array_equal(ys[:, :, :-1], xs[:, :, 1:])
    # stream continuity across windows (stateful BPTT, reference PTB path)
    np.testing.assert_array_equal(xs[1, :, 0], ys[0, :, -1])


def test_synthetic_ptb_stream():
    tokens = synthetic_ptb_stream(n_tokens=5000, vocab_size=50)
    assert len(tokens) == 5000
    assert tokens.min() >= 1 and tokens.max() <= 50
    # deterministic
    again = synthetic_ptb_stream(n_tokens=5000, vocab_size=50)
    np.testing.assert_array_equal(tokens, again)
