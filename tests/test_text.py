"""Text pipeline tests (reference analogue: dataset/text specs —
Dictionary, LabeledSentence, PTB BPTT batching)."""

import numpy as np
import pytest

from bigdl_tpu.dataset.text import (
    Dictionary,
    LabeledSentence,
    ptb_bptt_batches,
    synthetic_ptb_stream,
)


def test_dictionary_build_and_lookup():
    sents = [["the", "cat", "sat"], ["the", "dog", "sat", "down"]]
    d = Dictionary(sents, vocab_size=10)
    assert d.vocab_size() <= 10
    # ids are 1-based (LookupTable convention)
    for w in ("the", "cat", "sat"):
        idx = d.get_index(w)
        assert idx >= 1
        assert d.get_word(idx) == w
    # unknown word falls into the last-id bucket
    assert d.get_index("zebra") == d.vocab_size()


def test_dictionary_vocab_cap():
    sents = [["a"] * 5, ["b"] * 4, ["c"] * 3, ["d"] * 2, ["e"]]
    d = Dictionary(sents, vocab_size=3)
    assert d.vocab_size() == 3
    assert d.get_index("a") == 1  # most frequent first


def test_labeled_sentence():
    data = [1, 2, 3, 4]
    ls = LabeledSentence(data[:-1], data[1:])
    np.testing.assert_array_equal(ls.data, [1, 2, 3])
    np.testing.assert_array_equal(ls.labels, [2, 3, 4])


def test_ptb_bptt_batches_shapes_and_shift():
    tokens = np.arange(1000, dtype=np.int64)
    xs, ys = ptb_bptt_batches(tokens, batch_size=4, num_steps=10)
    assert xs.shape == ys.shape
    assert xs.shape[1:] == (4, 10)
    # target is input shifted by one within each stream
    np.testing.assert_array_equal(ys[:, :, :-1], xs[:, :, 1:])
    # stream continuity across windows (stateful BPTT, reference PTB path)
    np.testing.assert_array_equal(xs[1, :, 0], ys[0, :, -1])


def test_synthetic_ptb_stream():
    tokens = synthetic_ptb_stream(n_tokens=5000, vocab_size=50)
    assert len(tokens) == 5000
    assert tokens.min() >= 1 and tokens.max() <= 50
    # deterministic
    again = synthetic_ptb_stream(n_tokens=5000, vocab_size=50)
    np.testing.assert_array_equal(tokens, again)


# ---------------------------------------------------------------- news20
class TestNews20:
    def test_synthetic_news20_learnable_structure(self):
        from bigdl_tpu.dataset.news20 import synthetic_news20

        docs = synthetic_news20(100, class_num=4)
        assert len(docs) == 100
        labels = {label for _, label in docs}
        assert labels == {1, 2, 3, 4}
        # class-1 docs use the word0..word11 block dominantly
        text, label = docs[0]
        assert label == 1
        assert "word" in text

    def test_synthetic_glove_deterministic(self):
        from bigdl_tpu.dataset.news20 import synthetic_glove

        v1 = synthetic_glove(["alpha", "beta"], dim=16)
        v2 = synthetic_glove(["alpha"], dim=16)
        np.testing.assert_allclose(v1["alpha"], v2["alpha"])
        assert v1["alpha"].shape == (16,)

    def test_get_news20_reads_extracted_tree(self, tmp_path):
        from bigdl_tpu.dataset.news20 import get_news20

        root = tmp_path / "20news-18828"
        for group in ("alt.atheism", "sci.space"):
            d = root / group
            d.mkdir(parents=True)
            for i in range(3):
                (d / f"{i}").write_text(f"{group} post {i}")
        docs = get_news20(str(tmp_path))
        assert len(docs) == 6
        assert {label for _, label in docs} == {1, 2}

    def test_get_news20_missing_raises_with_url(self, tmp_path):
        from bigdl_tpu.dataset.news20 import get_news20

        with pytest.raises(FileNotFoundError, match="20-Newsgroups"):
            get_news20(str(tmp_path / "nope"))

    def test_get_glove_reads_txt(self, tmp_path):
        from bigdl_tpu.dataset.news20 import get_glove_w2v

        (tmp_path / "glove.6B.50d.txt").write_text(
            "hello " + " ".join(["0.1"] * 50) + "\n"
            "world " + " ".join(["0.2"] * 50) + "\n")
        w2v = get_glove_w2v(str(tmp_path), dim=50)
        assert set(w2v) == {"hello", "world"}
        np.testing.assert_allclose(w2v["hello"], 0.1)

    def test_text_cnn_example_pipeline(self):
        """The example's tokenize path over the synthetic corpus."""
        import importlib.util as iu

        spec = iu.spec_from_file_location(
            "ttc", "examples/textclassification/train_text_cnn.py")
        mod = iu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        x, y, vocab, n_classes = mod.load_corpus(None, doc_len=16)
        assert x.shape[1] == 16
        assert n_classes == 4
        assert vocab > 10
        assert x.max() <= vocab


def test_movielens_parse_and_synthetic():
    """⟦«py»/dataset/movielens.py⟧ parity: ratings.dat '::' rows ->
    (N, 3) 1-based int array; synthetic stand-in has the same shape."""
    import os
    import tempfile

    from bigdl_tpu.dataset.movielens import (
        get_id_ratings, synthetic_movielens,
    )

    with tempfile.TemporaryDirectory() as d:
        os.makedirs(os.path.join(d, "ml-1m"))
        with open(os.path.join(d, "ml-1m", "ratings.dat"), "w") as f:
            f.write("1::31::4::978300019\n7::1193::5::978300760\n")
        rows = get_id_ratings(d)
    assert rows.shape == (2, 3)
    assert rows[1].tolist() == [7, 1193, 5]

    syn = synthetic_movielens(20, 40, per_user=10)
    assert syn.shape == (200, 3)
    assert syn[:, 0].min() >= 1 and syn[:, 2].max() <= 5
    # global-quantile buckets: each rating level is populated
    assert len(set(syn[:, 2].tolist())) == 5

    import pytest

    with pytest.raises(FileNotFoundError, match="grouplens"):
        get_id_ratings("/nonexistent-dir/")
