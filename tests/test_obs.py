"""Observability-layer specs — span tracer, metrics registry, runtime
profiling, and the cross-stack instrumentation (ISSUE 2).

The acceptance gate lives here: a chaos-free 20-step DistriOptimizer
run with ``BIGDL_TRACE_DIR`` set must produce a Chrome trace JSON that
loads (nested per-phase spans), a parseable Prometheus text snapshot,
and step-time percentiles — and with observability disabled the train
loop must take the shared no-op fast path (NULL tracer, no reservoir,
no output files).
"""

import json
import os
import re
import threading

import numpy as np
import pytest

from bigdl_tpu import obs
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential
from bigdl_tpu.obs.metrics import MetricsRegistry
from bigdl_tpu.obs.runtime import Reservoir, RuntimeStats, instrument_jit
from bigdl_tpu.obs.trace import NULL_TRACER, Tracer
from bigdl_tpu.optim import DistriOptimizer, LocalOptimizer, SGD, Trigger
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.resilience import reset_injector

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    """Every spec starts with observability OFF and fresh singletons."""
    for var in ("BIGDL_OBS", "BIGDL_TRACE_DIR", "BIGDL_METRICS_DIR",
                "BIGDL_FAULT_PLAN"):
        monkeypatch.delenv(var, raising=False)
    reset_injector()
    obs.reset()
    yield
    obs.reset()
    reset_injector()


def _toy(n=256, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, k)
    x = rng.randn(n, d).astype(np.float32)
    y = (np.argmax(x @ w, axis=1) + 1).astype(np.float32)
    return x, y


def _model(d=16, k=4):
    return Sequential().add(Linear(d, 32)).add(ReLU()).add(Linear(32, k)) \
        .add(LogSoftMax())


# parity contract: the reader half (obs.metrics.parse_prometheus) must
# consume everything the writer (to_prometheus) emits — including the
# HELP/TYPE family headers real scrapers require on EVERY family
def _assert_prometheus_parses(text):
    from bigdl_tpu.obs.metrics import parse_prometheus

    assert text.strip(), "empty exposition"
    parsed = parse_prometheus(text)  # raises on any malformed line
    assert parsed["samples"], "exposition with no samples"
    # every sample's family must carry both # HELP and # TYPE lines
    # (histogram _bucket/_sum/_count samples belong to the base family)
    fams = parsed["families"]
    for s in parsed["samples"]:
        base = re.sub(r"_(bucket|sum|count)$", "", s["name"])
        fam = fams.get(s["name"]) or fams.get(base)
        assert fam is not None, f"sample {s['name']} has no family header"
        assert "help" in fam, f"family of {s['name']} missing # HELP"
        assert "type" in fam, f"family of {s['name']} missing # TYPE"
    return parsed


def _prom_value(text, name, **labels):
    """Value of the sample `name{labels}` in an exposition text."""
    from bigdl_tpu.obs.metrics import parse_prometheus, sample_value

    return sample_value(parse_prometheus(text), name, **labels)


# ------------------------------------------------------------- registry
class TestMetricsRegistry:
    def test_counter_inc_and_labels(self):
        reg = MetricsRegistry()
        fam = reg.counter("requests_total", "reqs", labels=("code",))
        fam.labels(code=200).inc()
        fam.labels(code=200).inc(2)
        fam.labels(code=500).inc()
        assert fam.labels(code=200).value == 3
        assert fam.labels(code=500).value == 1
        with pytest.raises(ValueError):
            fam.labels(code=200).inc(-1)  # counters only go up
        with pytest.raises(ValueError):
            fam.labels(status=200)        # undeclared label name

    def test_labelless_convenience(self):
        reg = MetricsRegistry()
        reg.counter("c_total").inc(5)
        reg.gauge("g").set(2.5)
        reg.histogram("h_seconds").observe(0.3)
        assert reg.counter("c_total").labels().value == 5
        assert reg.gauge("g").labels().value == 2.5
        assert reg.histogram("h_seconds").labels().count == 1
        with pytest.raises(ValueError):  # labeled family has no solo child
            reg.counter("lc_total", labels=("x",)).inc()

    def test_histogram_buckets_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(0.1, 1.0, 10.0)).labels()
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        cum = dict(h.cumulative())
        assert cum[0.1] == 1 and cum[1.0] == 2 and cum[10.0] == 3
        assert cum[float("inf")] == 4
        assert h.count == 4
        np.testing.assert_allclose(h.sum, 55.55)
        np.testing.assert_allclose(h.mean, 55.55 / 4)

    def test_registration_idempotent_and_conflict_loud(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", labels=("k",))
        assert reg.counter("x_total", labels=("k",)) is a
        with pytest.raises(ValueError):
            reg.gauge("x_total")               # kind conflict
        with pytest.raises(ValueError):
            reg.counter("x_total", labels=())  # label conflict

    def test_prometheus_exposition(self):
        reg = MetricsRegistry()
        reg.counter("bigdl_retries_total", "retries",
                    labels=("classification",)).labels(
            classification="transient").inc(3)
        reg.gauge("bigdl_rss_bytes", "rss").set(12345)
        reg.histogram("bigdl_lat_seconds", "latency",
                      buckets=(0.5, 1.0)).observe(0.7)
        text = reg.to_prometheus()
        _assert_prometheus_parses(text)
        assert "# TYPE bigdl_retries_total counter" in text
        assert _prom_value(text, "bigdl_retries_total",
                           classification="transient") == 3
        assert _prom_value(text, "bigdl_rss_bytes") == 12345
        assert _prom_value(text, "bigdl_lat_seconds_bucket", le="0.5") == 0
        assert _prom_value(text, "bigdl_lat_seconds_bucket", le="1") == 1
        assert _prom_value(text, "bigdl_lat_seconds_bucket", le="+Inf") == 1
        assert _prom_value(text, "bigdl_lat_seconds_count") == 1

    def test_snapshot_json_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("c_total").inc()
        reg.histogram("h").observe(0.2)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["metrics"]["c_total"]["samples"][0]["value"] == 1
        hsamp = snap["metrics"]["h"]["samples"][0]
        assert hsamp["count"] == 1
        assert hsamp["buckets"][-1][0] == "+Inf"

    def test_write_snapshot_files(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c_total").inc(2)
        paths = reg.write_snapshot(str(tmp_path))
        text = open(paths["prom"]).read()
        _assert_prometheus_parses(text)
        assert _prom_value(text, "c_total") == 2
        reg.write_snapshot(str(tmp_path))  # JSONL appends
        lines = open(paths["jsonl"]).read().splitlines()
        assert len(lines) == 2
        assert all(json.loads(ln)["metrics"]["c_total"] for ln in lines)

    def test_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("n_total").labels()
        h = reg.histogram("h").labels()

        def work():
            for _ in range(1000):
                c.inc()
                h.observe(0.01)

        threads = [threading.Thread(target=work) for _ in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert c.value == 8000
        assert h.count == 8000


# --------------------------------------------------------------- tracer
class TestTracer:
    def _events(self, tracer):
        tracer.close()
        with open(tracer.jsonl_path) as fh:
            return [json.loads(ln) for ln in fh if ln.strip()]

    def test_nested_spans_and_deterministic_ids(self, tmp_path):
        tr = Tracer(str(tmp_path))
        with tr.span("outer") as outer_id:
            with tr.span("inner") as inner_id:
                tr.event("mark", detail="x")
        assert (outer_id, inner_id) == (1, 2)  # counter ids, no uuids
        recs = {r["name"]: r for r in self._events(tr)}
        assert recs["inner"]["parent"] == outer_id
        assert recs["mark"]["parent"] == inner_id
        assert recs["outer"]["parent"] is None
        assert recs["mark"]["attrs"] == {"detail": "x"}
        # durations nest: outer covers inner
        assert recs["outer"]["dur_s"] >= recs["inner"]["dur_s"]

    def test_chrome_trace_loads_and_nests(self, tmp_path):
        tr = Tracer(str(tmp_path))
        with tr.span("iteration", step=1):
            with tr.span("device_put"):
                pass
        tr.close()
        doc = json.load(open(tr.trace_path))
        evs = doc["traceEvents"]
        assert isinstance(evs, list) and evs
        by_name = {e["name"]: e for e in evs if e.get("ph") == "X"}
        it, dp = by_name["iteration"], by_name["device_put"]
        for e in (it, dp):
            assert {"ts", "dur", "pid", "tid", "ph"} <= set(e)
        # timestamp containment = nesting on the Chrome timeline
        assert it["ts"] <= dp["ts"]
        assert dp["ts"] + dp["dur"] <= it["ts"] + it["dur"] + 1e-3
        assert it["args"] == {"step": 1}

    def test_same_dir_tracers_never_collide(self, tmp_path):
        a = Tracer(str(tmp_path))
        b = Tracer(str(tmp_path))  # same second, same dir
        assert a.trace_path != b.trace_path
        assert a.jsonl_path != b.jsonl_path
        a.close()
        b.close()

    def test_threads_get_own_tid(self, tmp_path):
        tr = Tracer(str(tmp_path))
        with tr.span("main"):
            pass
        t = threading.Thread(target=lambda: tr.event("bg"))
        t.start()
        t.join()
        tr.close()
        doc = json.load(open(tr.trace_path))
        tids = {e["tid"] for e in doc["traceEvents"]
                if e["ph"] in ("X", "i")}
        assert len(tids) == 2

    def test_complete_and_counter(self, tmp_path):
        import time

        tr = Tracer(str(tmp_path))
        t0 = time.perf_counter()
        tr.complete("computing", t0, 0.25, step=3)
        tr.counter("host_rss", bytes=1024)
        tr.close()
        doc = json.load(open(tr.trace_path))
        comp = next(e for e in doc["traceEvents"] if e["name"] == "computing")
        assert comp["dur"] == 250000.0  # 0.25s in us
        ctr = next(e for e in doc["traceEvents"] if e["ph"] == "C")
        assert ctr["args"] == {"bytes": 1024}

    def test_close_idempotent_and_drops_late_records(self, tmp_path):
        tr = Tracer(str(tmp_path))
        tr.event("before")
        tr.close()
        tr.close()  # idempotent
        tr.event("after")  # silently dropped, no crash
        doc = json.load(open(tr.trace_path))
        names = [e["name"] for e in doc["traceEvents"]]
        assert "before" in names and "after" not in names

    def test_flush_always_leaves_valid_json(self, tmp_path):
        tr = Tracer(str(tmp_path))
        for i in range(3):
            tr.event("e", i=i)
            tr.flush()
            assert json.load(open(tr.trace_path))["traceEvents"]
        tr.close()

    def test_disabled_fast_path_is_shared_noop(self):
        t = obs.get_tracer()
        assert t is NULL_TRACER
        # one shared context manager object — no per-span allocation
        assert t.span("a") is t.span("b", step=1)
        with t.span("a") as sid:
            assert sid is None
        t.event("x")  # all no-ops
        t.flush()


# -------------------------------------------------------------- runtime
class TestRuntime:
    def test_reservoir_percentiles_nearest_rank(self):
        r = Reservoir(size=1000)
        for v in range(1, 101):
            r.add(float(v))
        p = r.percentiles()
        assert (p[0.5], p[0.95], p[0.99]) == (50.0, 95.0, 99.0)
        s = r.summary()
        assert s["count"] == 100 and s["p50"] == 50.0
        np.testing.assert_allclose(s["mean"], 50.5)

    def test_reservoir_ring_keeps_most_recent(self):
        r = Reservoir(size=10)
        for v in range(1, 21):
            r.add(float(v))
        assert r.count == 20
        assert r.percentiles([1.0])[1.0] == 20.0
        assert r.percentiles([0.0])[0.0] == 11.0  # oldest retained

    def test_empty_reservoir(self):
        s = Reservoir().summary()
        assert s["p50"] is None and s["count"] == 0 and s["mean"] is None

    def test_instrument_jit_compile_vs_dispatch(self):
        import jax
        import jax.numpy as jnp

        stats = RuntimeStats()
        fn = instrument_jit(jax.jit(lambda a: a * 2), "mul", stats=stats)
        x4 = jnp.ones((4,), jnp.float32)
        fn(x4)
        fn(x4)
        fn(x4)
        assert stats.compile_count == 1          # one signature, one compile
        assert stats.dispatch_times.count == 2   # two cached dispatches
        fn(jnp.ones((8,), jnp.float32))          # new shape -> recompile
        assert stats.compile_count == 2
        assert stats.compile_events[0]["name"] == "mul"
        assert stats.compile_events[0]["seconds"] > 0

    def test_snapshot_shape_and_memory(self):
        stats = RuntimeStats()
        stats.record_step(0.01)
        snap = stats.snapshot()
        assert snap["step_time_s"]["count"] == 1
        assert snap["compile"]["count"] == 0
        assert snap["host_rss_bytes"] is None or snap["host_rss_bytes"] > 0

    def test_host_rss_positive_on_linux(self):
        from bigdl_tpu.obs.runtime import host_rss_bytes

        rss = host_rss_bytes()
        if os.path.exists("/proc/self/statm"):
            assert rss > 10 * 1024 * 1024  # a python+jax process is >10MB


# -------------------------------------------- Metrics delegation bridge
class TestMetricsDelegation:
    def test_value_is_mean(self):
        m = Metrics()
        m.add("computing time", 0.1)
        m.add("computing time", 0.3)
        np.testing.assert_allclose(m.value("computing time"), 0.2)
        assert m.count("computing time") == 2
        np.testing.assert_allclose(m.total("computing time"), 0.4)
        assert m.value("never seen") == 0.0

    def test_summary_reports_mean_count_total(self):
        m = Metrics()
        m.add("computing time", 0.010)
        m.add("computing time", 0.030)
        m.add("data wait time", 0.002)
        s = m.summary()
        # the reference's parseable "X average: Yms" spelling survives
        assert "computing time average: 20.00ms" in s
        assert "(n=2, total=40.0ms)" in s
        assert "data wait time average: 2.00ms" in s

    def test_snapshot_dict(self):
        m = Metrics()
        m.add("put batch time", 0.5)
        snap = m.snapshot()
        assert snap == {"put batch time":
                        {"count": 1, "total": 0.5, "mean": 0.5}}

    def test_timer_and_reset(self):
        m = Metrics()
        with m.timer("phase"):
            pass
        assert m.count("phase") == 1
        m.reset()
        assert m.count("phase") == 0 and m.value("phase") == 0.0

    def test_delegates_to_registry_exposition(self):
        m = Metrics()
        m.add("computing time", 0.25)
        text = m.registry.to_prometheus()
        _assert_prometheus_parses(text)
        assert _prom_value(text, "bigdl_phase_seconds_count",
                           phase="computing time") == 1
        np.testing.assert_allclose(
            _prom_value(text, "bigdl_phase_seconds_sum",
                        phase="computing time"), 0.25)

    def test_shared_registry_optin(self):
        reg = MetricsRegistry()
        a, b = Metrics(registry=reg), Metrics(registry=reg)
        a.add("computing time", 0.1)
        b.add("computing time", 0.3)
        assert a.count("computing time") == 2  # aggregated on purpose


# ------------------------------------------------ stack instrumentation
def _spans_by_name(jsonl_path):
    spans = {}
    with open(jsonl_path) as fh:
        for ln in fh:
            rec = json.loads(ln)
            spans.setdefault(rec["name"], []).append(rec)
    return spans


def _find_obs_files(trace_dir):
    traces = sorted(f for f in os.listdir(trace_dir)
                    if f.endswith(".trace.json"))
    jsonls = sorted(f for f in os.listdir(trace_dir)
                    if f.endswith(".events.jsonl"))
    assert traces and jsonls
    return (os.path.join(trace_dir, traces[-1]),
            os.path.join(trace_dir, jsonls[-1]))


class TestTrainingInstrumentation:
    def test_distri_20_steps_trace_prometheus_percentiles(
            self, tmp_path, monkeypatch):
        """THE acceptance gate: chaos-free 20-step DistriOptimizer run
        with BIGDL_TRACE_DIR set -> Chrome trace with nested per-phase
        spans, parseable Prometheus snapshot, step-time percentiles."""
        trace_dir = str(tmp_path / "trace")
        metrics_dir = str(tmp_path / "metrics")
        monkeypatch.setenv("BIGDL_TRACE_DIR", trace_dir)
        monkeypatch.setenv("BIGDL_METRICS_DIR", metrics_dir)
        obs.reset()
        Engine.reset()
        Engine.init()
        try:
            x, y = _toy(n=640)
            opt = DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                                  batch_size=32)
            opt.set_optim_method(SGD(learningrate=0.1))
            opt.set_end_when(Trigger.max_iteration(20))
            opt.optimize()
        finally:
            Engine.reset()
        assert opt.state["neval"] == 21  # exactly 20 steps ran

        # --- Chrome trace loads, with nested per-phase spans ---------
        trace_path, jsonl_path = _find_obs_files(trace_dir)
        doc = json.load(open(trace_path))
        evs = doc["traceEvents"]
        assert isinstance(evs, list) and evs
        names = {e["name"] for e in evs}
        for phase in ("iteration", "batch_prep", "device_put",
                      "step_dispatch", "computing", "build_train_step",
                      "engine.init"):
            assert phase in names, f"missing {phase} in trace"
        # nesting: every device_put/step_dispatch sits inside an
        # iteration span on the timeline (ts containment, same tid)
        its = [e for e in evs
               if e.get("ph") == "X" and e["name"] == "iteration"]
        assert len(its) == 20
        for child_name in ("device_put", "step_dispatch"):
            children = [e for e in evs
                        if e.get("ph") == "X" and e["name"] == child_name]
            assert len(children) == 20
            for c in children:
                assert any(
                    p["ts"] <= c["ts"] and
                    c["ts"] + c["dur"] <= p["ts"] + p["dur"] + 1e-3 and
                    p["tid"] == c["tid"]
                    for p in its), f"unnested {child_name}"
        # the structured JSONL agrees on parentage (contextvar nesting)
        spans = _spans_by_name(jsonl_path)
        iter_ids = {s["id"] for s in spans["iteration"]}
        assert all(s["parent"] in iter_ids for s in spans["step_dispatch"])
        assert all(s["parent"] in iter_ids for s in spans["batch_prep"])

        # --- Prometheus snapshot parses and carries the numbers ------
        prom = [f for f in os.listdir(metrics_dir) if f.endswith(".prom")]
        assert prom
        text = open(os.path.join(metrics_dir, prom[0])).read()
        _assert_prometheus_parses(text)
        # reference phase timers via the Metrics delegation bridge
        assert _prom_value(text, "bigdl_phase_seconds_count",
                           phase="computing time") == 20
        assert _prom_value(text, "bigdl_phase_seconds_count",
                           phase="put batch time") == 20
        # step-time percentiles from the runtime reservoir
        p50 = _prom_value(text, "bigdl_step_time_seconds", quantile="p50")
        p95 = _prom_value(text, "bigdl_step_time_seconds", quantile="p95")
        p99 = _prom_value(text, "bigdl_step_time_seconds", quantile="p99")
        assert p50 is not None and 0 < p50 <= p95 <= p99
        # compile tracking saw the first-call trace+compile
        assert _prom_value(text, "bigdl_jit_compile_count") >= 1
        assert _prom_value(text, "bigdl_engine_inits_total") == 1
        # runtime reservoir really holds 20 step samples
        snap = obs.get_runtime().snapshot(memory=False)
        assert snap["step_time_s"]["count"] == 20
        assert snap["compile"]["count"] >= 1
        # JSONL metric snapshot parses too
        jsonl = [f for f in os.listdir(metrics_dir)
                 if f.startswith("metrics.") and f.endswith(".jsonl")]
        assert jsonl
        rec = json.loads(open(
            os.path.join(metrics_dir, jsonl[0])).readline())
        assert "bigdl_step_time_seconds" in rec["metrics"]

    def test_disabled_is_noop_and_writes_nothing(self, tmp_path,
                                                 monkeypatch):
        """Observability off (the default): the loop binds the shared
        NULL tracer, no runtime reservoir is fed, and no obs files are
        written anywhere near the run."""
        x, y = _toy(n=64)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.optimize()
        assert opt._obs_tracer is NULL_TRACER
        assert opt._obs_runtime is None
        assert obs.get_runtime().step_times.count == 0
        assert os.listdir(tmp_path) == []

    def test_local_optimizer_traces_too(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        obs.reset()
        x, y = _toy(n=64)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.optimize()
        trace_path, _ = _find_obs_files(str(tmp_path))
        names = {e["name"] for e in
                 json.load(open(trace_path))["traceEvents"]}
        assert {"iteration", "step_dispatch", "computing"} <= names

    def test_checkpoint_spans_recorded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path / "trace"))
        obs.reset()
        x, y = _toy(n=64)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           Trigger.several_iteration(1))
        opt.optimize()
        trace_path, _ = _find_obs_files(str(tmp_path / "trace"))
        names = {e["name"] for e in
                 json.load(open(trace_path))["traceEvents"]}
        assert "checkpoint" in names
        assert "checkpoint.write" in names  # serializer-level span
        from bigdl_tpu.utils.serializer import verify_checkpoint, \
            checkpoint_prefixes

        prefix = os.path.join(str(tmp_path / "ckpt"),
                              checkpoint_prefixes(str(tmp_path / "ckpt"))[0])
        assert verify_checkpoint(prefix)[0]
        obs.get_tracer().flush()
        names = {e["name"] for e in
                 json.load(open(trace_path))["traceEvents"]}
        assert "checkpoint.verify" in names

    def test_nonfinite_skip_emits_structured_event(self, tmp_path,
                                                   monkeypatch):
        """resilience -> obs bridge: a poisoned batch (nan_grad fault)
        shows up as a resilience.nonfinite_skip trace event AND a
        registry counter, not only the cumulative summary scalar."""
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("BIGDL_FAULT_PLAN", "step:2:nan_grad")
        obs.reset()
        reset_injector()
        x, y = _toy(n=128)
        opt = LocalOptimizer(_model(), (x, y), ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(1))
        opt.optimize()
        assert opt.state["nonfinite_skips"] == 1
        _, jsonl_path = _find_obs_files(str(tmp_path))
        events = _spans_by_name(jsonl_path)
        skip = events["resilience.nonfinite_skip"][0]
        assert skip["attrs"]["step"] == 2
        assert skip["attrs"]["consecutive"] == 1
        text = obs.get_registry().to_prometheus()
        assert _prom_value(text, "bigdl_nonfinite_skips_total") == 1

    def test_retry_emits_structured_event(self, tmp_path, monkeypatch):
        """An injected transient step fault retried from checkpoint
        leaves a resilience.retry event with classification + attempt
        + backoff in the JSONL stream and a labeled counter."""
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path / "trace"))
        # 128 samples / batch 32 = 4 iters per epoch; the fault fires in
        # epoch 2, after the epoch-1 checkpoint the retry reloads
        monkeypatch.setenv("BIGDL_FAULT_PLAN", "step:6:raise")
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_BASE", "0")
        obs.reset()
        reset_injector()
        Engine.reset()
        Engine.init()
        try:
            x, y = _toy(n=128)
            opt = DistriOptimizer(_model(), (x, y), ClassNLLCriterion(),
                                  batch_size=32, wire_dtype="none")
            opt.set_optim_method(SGD(learningrate=0.1))
            opt.set_end_when(Trigger.max_epoch(2))
            opt.set_checkpoint(str(tmp_path / "ckpt"),
                               Trigger.every_epoch())
            opt.optimize()
        finally:
            Engine.reset()
        _, jsonl_path = _find_obs_files(str(tmp_path / "trace"))
        events = _spans_by_name(jsonl_path)
        retry = events["resilience.retry"][0]
        assert retry["attrs"]["classification"] == "transient"
        assert retry["attrs"]["error"] == "InjectedFault"
        assert retry["attrs"]["attempt"] == 1
        text = obs.get_registry().to_prometheus()
        assert _prom_value(text, "bigdl_retry_attempts_total",
                           classification="transient",
                           error="InjectedFault") == 1
        # the recovery reload is visible as checkpoint.load spans
        assert "checkpoint.load" in events


# --------------------------------------------------------------- config
class TestObsConfig:
    def test_off_by_default(self):
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().obs
        assert not cfg.active
        assert not obs.active()

    def test_trace_dir_implies_active(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        assert obs.active()
        t = obs.get_tracer()
        assert t is not NULL_TRACER
        assert t.trace_path.startswith(str(tmp_path))

    def test_enabled_without_dirs(self, monkeypatch):
        monkeypatch.setenv("BIGDL_OBS", "1")
        assert obs.active()
        assert obs.get_tracer() is NULL_TRACER  # stats only, no files
        assert obs.flush() == {}  # nothing to write, no crash

    def test_tracer_rebuilds_on_dir_change(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path / "a"))
        a = obs.get_tracer()
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path / "b"))
        b = obs.get_tracer()
        assert a is not b
        assert b.trace_path.startswith(str(tmp_path / "b"))
        # the replaced tracer was closed -> its trace file exists
        assert os.path.exists(a.trace_path)
