"""bench.py robustness-envelope tests (VERDICT r3 item 1).

r03 went blind: the driver's timeout killed bench.py before any JSON was
printed (BENCH_r03.json rc=124, empty tail).  These tests prove the
rewritten orchestration can no longer do that:

  * the default budget arithmetic fits the total deadline,
  * a HUNG TPU bring-up costs one probe timeout and still produces a
    full CPU-fallback JSON line (exercised with compressed budgets),
  * a driver SIGTERM mid-run still yields a parseable final JSON line
    and exit code 0.

All child budgets are env knobs, so the hang scenarios run in seconds.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _load_bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_default_envelope_arithmetic():
    """probe + cpu + re-probe + tpu + orchestration slop must fit the
    deadline — this is the inequality whose violation made round 3
    blind.  The r05 worst case is the probe-timeout path: probe times
    out, CPU fallback runs, the re-probe succeeds, and a full TPU
    measurement follows (VERDICT r4 item 1a)."""
    b = _load_bench_module()
    worst = (b.DEFAULT_PROBE_TIMEOUT + b.DEFAULT_CPU_TIMEOUT
             + b.DEFAULT_PROBE_TIMEOUT + b.DEFAULT_TPU_TIMEOUT + 90.0)
    assert worst <= b.DEFAULT_TIMEOUT, (
        f"worst-case child budgets {worst}s exceed BENCH_TIMEOUT "
        f"{b.DEFAULT_TIMEOUT}s")
    # and the total must sit comfortably under a 1h driver window
    assert b.DEFAULT_TIMEOUT <= 1800


def _bench_env(**over):
    env = dict(os.environ)
    for k in ("BENCH_FAKE_PROBE_HANG", "BENCH_FAKE_PROBE_ERROR",
              "BENCH_FAKE_TPU_HANG", "BENCH_FAKE_PROBE_HANG_ONCE_FILE",
              "BENCH_TPU_PLATFORM", "BENCH_ALLOW_CPU_STANDIN"):
        env.pop(k, None)
    env.update({k: str(v) for k, v in over.items()})
    return env


def _last_json_line(stdout: str):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    assert lines, f"no output: {stdout!r}"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_hung_probe_falls_back_to_cpu_json():
    """A bring-up that hangs forever must cost ONE compressed probe
    budget, then the CPU fallback must still print a full JSON line."""
    env = _bench_env(
        BENCH_FAKE_PROBE_HANG=120,      # tunnel "down": probe never returns
        BENCH_PROBE_TIMEOUT=21,         # parent floors probe budgets at 20s
        BENCH_TIMEOUT=240,
        BENCH_CPU_TIMEOUT=150,
        BENCH_CPU_BATCH=2, BENCH_CPU_IMG=32, BENCH_CPU_ITERS=2,
        BENCH_SEG_RESERVE=10_000,       # CPU child: headline segment only
        BENCH_SEC_RESERVE=10_000,       # ... and skip the secondaries
        JAX_PLATFORMS="cpu",
    )
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=REPO, timeout=235,
    )
    elapsed = time.time() - t0
    res = _last_json_line(proc.stdout)
    assert proc.returncode == 0
    # one 21s probe (no retry after a TIMEOUT) + CPU fallback only
    assert elapsed < 200, f"envelope blew up: {elapsed:.0f}s"
    assert res["platform"] == "cpu"
    assert res["value"] is not None and res["value"] > 0
    assert "timed out" in (res["error"] or "")
    # the partial mirror on disk must match the printed result
    with open(os.path.join(REPO, "BENCH_PARTIAL.json")) as f:
        disk = json.load(f)
    assert disk["value"] == res["value"]


@pytest.mark.slow
def test_tunnel_recovers_after_cpu_fallback(tmp_path):
    """VERDICT r4 item 1a: a probe timeout must no longer forfeit the
    round.  The first probe hangs (tunnel down), the CPU fallback runs,
    the re-probe succeeds (tunnel recovered), and the parent upgrades to
    a full measurement from the 'tpu' branch (stubbed onto CPU via
    BENCH_TPU_PLATFORM with tiny shapes)."""
    once = tmp_path / "probe_hung_once"
    env = _bench_env(
        BENCH_FAKE_PROBE_HANG=120,
        BENCH_FAKE_PROBE_HANG_ONCE_FILE=str(once),
        BENCH_PROBE_TIMEOUT=21,
        BENCH_TIMEOUT=420,
        BENCH_CPU_TIMEOUT=90,
        BENCH_CPU_BATCH=2, BENCH_CPU_IMG=32, BENCH_CPU_ITERS=2,
        BENCH_TPU_PLATFORM="cpu",       # stand-in chip for the test
        BENCH_ALLOW_CPU_STANDIN=1,      # both required by the guard
        BENCH_BATCHES="2", BENCH_IMG=32, BENCH_ITERS=2,
        BENCH_SEG_RESERVE=10_000,       # headline segment only
        BENCH_SEC_RESERVE=10_000,
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=REPO, timeout=460,
    )
    res = _last_json_line(proc.stdout)
    assert proc.returncode == 0
    assert once.exists(), "hang-once marker never written — hook dead"
    # the final result came from the post-fallback TPU branch, not the
    # CPU fallback: its error is cleared and the headline is measured
    assert res["error"] is None, res["error"]
    assert res["value"] is not None and res["value"] > 0
    assert res["extras"]["batch"] == 2


def test_sigterm_mid_probe_prints_json_and_exits_zero():
    """The driver's `timeout` sends SIGTERM: bench.py must trap it and
    print a parseable JSON line as its final output, rc=0."""
    env = _bench_env(
        BENCH_FAKE_PROBE_HANG=300,
        BENCH_PROBE_TIMEOUT=250,
        BENCH_TIMEOUT=400,
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=REPO,
    )
    time.sleep(3.0)  # parent is now blocked inside the probe wait
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0
    res = _last_json_line(out)
    assert res["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert "signal" in (res["error"] or "")
